"""paddle.autograd equivalent. ref: python/paddle/autograd/__init__.py"""
from ..core.autograd import backward, grad, no_grad, enable_grad  # noqa: F401
from .py_layer import PyLayer, PyLayerContext, saved_tensors_hooks  # noqa: F401
from .functional import jacobian, hessian, vjp, jvp  # noqa: F401
