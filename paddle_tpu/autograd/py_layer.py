"""PyLayer: user-defined forward/backward pairs on the eager tape.

ref: python/paddle/autograd/py_layer.py (+ C++ side paddle/fluid/eager/pylayer/).
The TPU-native version plugs a user backward directly in as a GradNode's vjp.
"""
from __future__ import annotations

from typing import Any

import jax.numpy as jnp

from ..core.autograd import GradNode, is_grad_enabled, no_grad
from ..core.tensor import Tensor


# (pack, unpack) hook stack installed by autograd.saved_tensors_hooks;
# consulted by PyLayerContext.save_for_backward / saved_tensor (ref:
# python/paddle/autograd/saved_tensors_hooks.py — same contract: pack
# runs at save time, unpack at first backward use)
_saved_tensor_hooks: list = []


class saved_tensors_hooks:
    """Context manager registering a pack/unpack hook pair for tensors
    saved for backward (ref: autograd/saved_tensors_hooks.py). pack_hook
    maps each saved tensor to stored info (e.g. a host copy); unpack_hook
    reconstructs the tensor when backward needs it. Applies to the
    PyLayer save_for_backward path — the compiled/vjp tape stores its
    residuals inside the XLA program where per-tensor hooks cannot
    reach (rematerialization is the knob there: fleet recompute /
    jax.checkpoint)."""

    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        _saved_tensor_hooks.append((self.pack_hook, self.unpack_hook))
        return self

    def __exit__(self, *exc):
        _saved_tensor_hooks.pop()
        return False


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self._saved_packed = False
        self._unpack_hook = None
        self._materialize_grads = True

    def save_for_backward(self, *tensors):
        if _saved_tensor_hooks:
            pack, unpack = _saved_tensor_hooks[-1]
            self._saved = tuple(pack(t) for t in tensors)
            self._saved_packed = True
            self._unpack_hook = unpack
        else:
            self._saved = tensors

    def saved_tensor(self):
        if self._saved_packed:
            unpacked = tuple(self._unpack_hook(p) for p in self._saved)
            # unpack once: repeated backward reads must not re-run hooks
            self._saved = unpacked
            self._saved_packed = False
            return unpacked
        return self._saved

    def mark_not_inplace(self, *args):
        pass

    def mark_non_differentiable(self, *args):
        pass

    def set_materialize_grads(self, value: bool):
        self._materialize_grads = bool(value)


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *args):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        tensor_args = [a for a in args if isinstance(a, Tensor)]
        requires = is_grad_enabled() and any(
            not t.stop_gradient for t in tensor_args)

        with no_grad():
            outs = cls.forward(ctx, *args, **kwargs)
        multi = isinstance(outs, (tuple, list))
        outs_t = tuple(outs) if multi else (outs,)

        if not requires:
            return outs

        diff_inputs = tuple(
            t for t in tensor_args
            if not t.stop_gradient and jnp.issubdtype(
                jnp.result_type(t._data), jnp.inexact))
        out_avals = tuple(
            jnp.zeros((), o.dtype) if False else
            type("A", (), {"shape": tuple(o.shape), "dtype": o.dtype})()
            for o in outs_t)

        def vjp_fn(cts):
            grads = cls.backward(ctx, *[Tensor(c) for c in cts])
            if not isinstance(grads, (tuple, list)):
                grads = (grads,)
            # positional map: backward returns one grad per tensor input
            by_tensor = {}
            for t, g in zip(tensor_args, grads):
                by_tensor[id(t)] = g
            out = []
            for t in diff_inputs:
                g = by_tensor.get(id(t))
                if g is None:
                    out.append(jnp.zeros(t._data.shape, t._data.dtype))
                else:
                    out.append(g._data if isinstance(g, Tensor) else g)
            return tuple(out)

        node = GradNode(vjp_fn, diff_inputs, out_avals, cls.__name__)
        wrapped = tuple(
            Tensor(o._data if isinstance(o, Tensor) else o,
                   stop_gradient=False, node=node, out_index=k)
            for k, o in enumerate(outs_t))
        return wrapped if multi else wrapped[0]
