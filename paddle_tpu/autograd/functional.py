"""Functional higher-order autograd: jacobian/hessian/vjp/jvp.

ref: python/paddle/incubate/autograd/functional.py. On TPU these map directly
onto jax.jacobian / jax.hessian / jax.vjp / jax.jvp over the pure function.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor


def _unwrap(xs):
    if isinstance(xs, Tensor):
        return xs._data
    if isinstance(xs, (tuple, list)):
        return type(xs)(_unwrap(x) for x in xs)
    return xs


def _wrap(xs):
    if isinstance(xs, (tuple, list)):
        return type(xs)(_wrap(x) for x in xs)
    return Tensor(xs) if not isinstance(xs, Tensor) else xs


def _pure(func):
    def f(*args):
        out = func(*[Tensor(a) for a in args])
        return _unwrap(out)
    return f


def jacobian(func, xs, is_batched=False):
    args = xs if isinstance(xs, (tuple, list)) else (xs,)
    raw = [a._data if isinstance(a, Tensor) else jnp.asarray(a) for a in args]
    jac = jax.jacobian(_pure(func), argnums=tuple(range(len(raw))))(*raw)
    if len(raw) == 1 and not isinstance(xs, (tuple, list)):
        jac = jac[0]
    return _wrap(jac)


def hessian(func, xs, is_batched=False):
    args = xs if isinstance(xs, (tuple, list)) else (xs,)
    raw = [a._data if isinstance(a, Tensor) else jnp.asarray(a) for a in args]
    hes = jax.hessian(_pure(func), argnums=tuple(range(len(raw))))(*raw)
    if len(raw) == 1 and not isinstance(xs, (tuple, list)):
        hes = hes[0][0]
    return _wrap(hes)


def vjp(func, xs, v=None):
    args = xs if isinstance(xs, (tuple, list)) else (xs,)
    raw = [a._data if isinstance(a, Tensor) else jnp.asarray(a) for a in args]
    out, vjp_fn = jax.vjp(_pure(func), *raw)
    if v is None:
        v = jnp.ones_like(out)
    else:
        v = _unwrap(v)
    grads = vjp_fn(v)
    if len(raw) == 1 and not isinstance(xs, (tuple, list)):
        grads = grads[0]
    return _wrap(out), _wrap(grads)


def jvp(func, xs, v=None):
    args = xs if isinstance(xs, (tuple, list)) else (xs,)
    raw = [a._data if isinstance(a, Tensor) else jnp.asarray(a) for a in args]
    if v is None:
        tangents = tuple(jnp.ones_like(a) for a in raw)
    else:
        vv = v if isinstance(v, (tuple, list)) else (v,)
        tangents = tuple(_unwrap(t) for t in vv)
    out, tangent_out = jax.jvp(_pure(func), tuple(raw), tangents)
    return _wrap(out), _wrap(tangent_out)
