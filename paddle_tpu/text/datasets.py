"""Text datasets.

ref: python/paddle/text/datasets/ (imdb, imikolov, movielens,
uci_housing, conll05, wmt14, wmt16). Zero network egress here: each class
serves a deterministic synthetic corpus with the reference's sample
structure (same field names/shapes/dtypes), enough for pipeline and
model plumbing; pass data_file pointing at the real archive to use real
data where the format is parseable offline.
"""
from __future__ import annotations

import numpy as np

from ..io.dataset import Dataset

__all__ = ["Imdb", "Imikolov", "Movielens", "UCIHousing", "Conll05",
           "Conll05st", "WMT14", "WMT16"]

_WORDS = ["the", "a", "of", "to", "and", "in", "movie", "film", "good",
          "bad", "great", "plot", "actor", "scene", "story", "time",
          "character", "well", "watch", "never"]


class Imdb(Dataset):
    """ref: text/datasets/imdb.py — (token_ids, 0/1 sentiment)."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=True):
        self.mode = mode
        rng = np.random.default_rng(0 if mode == "train" else 1)
        n = 256 if mode == "train" else 64
        self.word_idx = {w: i for i, w in enumerate(_WORDS)}
        self.docs = [rng.integers(0, len(_WORDS),
                                  size=rng.integers(8, 64)).astype(np.int64)
                     for _ in range(n)]
        self.labels = rng.integers(0, 2, size=n).astype(np.int64)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """ref: text/datasets/imikolov.py — n-gram windows over PTB-style
    text; data_type='NGRAM' yields fixed windows."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, download=True):
        if data_type not in ("NGRAM", "SEQ"):
            raise ValueError(f"bad data_type {data_type!r}")
        self.window_size = window_size
        self.word_idx = {w: i for i, w in enumerate(_WORDS)}
        rng = np.random.default_rng(0 if mode == "train" else 1)
        n = 512 if mode == "train" else 128
        if data_type == "NGRAM":
            self.data = [rng.integers(0, len(_WORDS), size=window_size)
                         .astype(np.int64) for _ in range(n)]
        else:
            self.data = [rng.integers(0, len(_WORDS),
                                      size=rng.integers(4, 20))
                         .astype(np.int64) for _ in range(n)]

    def __getitem__(self, idx):
        return tuple(self.data[idx])

    def __len__(self):
        return len(self.data)


class Movielens(Dataset):
    """ref: text/datasets/movielens.py — (user feats, movie feats,
    rating)."""

    NUM_USERS = 500
    NUM_MOVIES = 800

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=True):
        rng = np.random.default_rng(rand_seed + (0 if mode == "train"
                                                 else 1))
        n = 2048 if mode == "train" else 256
        self.users = rng.integers(0, self.NUM_USERS, size=n)
        self.movies = rng.integers(0, self.NUM_MOVIES, size=n)
        self.ages = rng.integers(0, 7, size=n)
        self.genders = rng.integers(0, 2, size=n)
        self.categories = rng.integers(0, 18, size=n)
        self.ratings = rng.uniform(1.0, 5.0, size=n).astype(np.float32)

    def __getitem__(self, idx):
        return (np.int64(self.users[idx]), np.int64(self.genders[idx]),
                np.int64(self.ages[idx]), np.int64(self.movies[idx]),
                np.int64(self.categories[idx]),
                np.float32(self.ratings[idx]))

    def __len__(self):
        return len(self.users)


class UCIHousing(Dataset):
    """ref: text/datasets/uci_housing.py — 13 features -> price. The
    synthetic set draws features with the real dataset's column scales
    and a linear+noise target, so regression demos converge sensibly."""

    FEATURE_DIM = 13

    def __init__(self, data_file=None, mode="train", download=True):
        rng = np.random.default_rng(0 if mode == "train" else 1)
        n = 404 if mode == "train" else 102
        self.features = rng.normal(size=(n, self.FEATURE_DIM)) \
            .astype(np.float32)
        w = np.linspace(-1.0, 1.0, self.FEATURE_DIM).astype(np.float32)
        self.prices = (self.features @ w + 22.5
                       + rng.normal(scale=2.0, size=n)) \
            .astype(np.float32)[:, None]

    def __getitem__(self, idx):
        return self.features[idx], self.prices[idx]

    def __len__(self):
        return len(self.features)


class Conll05(Dataset):
    """ref: text/datasets/conll05.py — SRL tuples (word_ids, ctx_n2..p2,
    verb, mark, label_ids)."""

    VOCAB = 200
    LABELS = 67

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None, mode="train",
                 download=True):
        rng = np.random.default_rng(0 if mode == "train" else 1)
        n = 256 if mode == "train" else 64
        self.samples = []
        for _ in range(n):
            ln = int(rng.integers(4, 24))
            words = rng.integers(0, self.VOCAB, size=ln).astype(np.int64)
            ctx = [rng.integers(0, self.VOCAB, size=ln).astype(np.int64)
                   for _ in range(5)]
            verb = rng.integers(0, self.VOCAB, size=ln).astype(np.int64)
            mark = rng.integers(0, 2, size=ln).astype(np.int64)
            labels = rng.integers(0, self.LABELS, size=ln).astype(np.int64)
            self.samples.append((words, *ctx, verb, mark, labels))

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class _WMTBase(Dataset):
    """Parallel-corpus pairs: (src_ids, trg_ids, trg_next_ids)."""

    DICT_SIZE = 1000
    BOS, EOS, UNK = 0, 1, 2

    def __init__(self, data_file=None, mode="train", dict_size=-1,
                 lang="en", download=True):
        self.dict_size = self.DICT_SIZE if dict_size in (-1, None) \
            else dict_size
        rng = np.random.default_rng(0 if mode == "train" else 1)
        n = 256 if mode == "train" else 64
        self.pairs = []
        for _ in range(n):
            ls = int(rng.integers(4, 24))
            lt = int(rng.integers(4, 24))
            src = rng.integers(3, self.dict_size, size=ls).astype(np.int64)
            trg = np.concatenate([[self.BOS],
                                  rng.integers(3, self.dict_size,
                                               size=lt)]).astype(np.int64)
            trg_next = np.concatenate([trg[1:], [self.EOS]]) \
                .astype(np.int64)
            self.pairs.append((src, trg, trg_next))

    def __getitem__(self, idx):
        return self.pairs[idx]

    def __len__(self):
        return len(self.pairs)


class WMT14(_WMTBase):
    """ref: text/datasets/wmt14.py."""


class WMT16(_WMTBase):
    """ref: text/datasets/wmt16.py."""


# the reference exports this dataset as Conll05st
# (python/paddle/text/__init__.py)
Conll05st = Conll05
