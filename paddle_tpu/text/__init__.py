"""paddle.text equivalent: sequence-labeling decode ops.

ref: python/paddle/text/viterbi_decode.py (ViterbiDecoder layer +
viterbi_decode functional over the CRF transition matrix; native op
phi/kernels/cpu/viterbi_decode_kernel.cc) + text/datasets/ (served
synthetically here — see .datasets).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.autograd import apply_op
from ..core.tensor import Tensor
from ..nn.layer import Layer

from .datasets import (  # noqa: F401
    Conll05st, Imdb, Imikolov, Movielens, UCIHousing, WMT14, WMT16)

__all__ = ["viterbi_decode", "ViterbiDecoder", "datasets", "Conll05st",
           "Imdb", "Imikolov", "Movielens", "UCIHousing", "WMT14",
           "WMT16"]


def viterbi_decode(potentials, transition, lengths=None,
                   include_bos_eos_tag: bool = True, name=None):
    """CRF Viterbi decode. potentials: [B, T, N] emission scores,
    transition: [N, N]; returns (scores [B], paths [B, T]).

    ref: text/viterbi_decode.py viterbi_decode — with include_bos_eos_tag
    the last two tags are BOS/EOS (reference convention: transition from
    BOS starts the sequence, transition to EOS ends it).
    """
    def impl(pot, trans, *len_arr):
        b, t, n = pot.shape
        if include_bos_eos_tag:
            bos, eos = n - 2, n - 1
            init = pot[:, 0] + trans[bos][None, :]
        else:
            init = pot[:, 0]
        lens = len_arr[0] if len_arr else jnp.full((b,), t, jnp.int32)

        def step(carry, xs):
            emit, t_idx = xs
            score = carry                      # [B, N]
            # [B, N_prev, N_next]
            cand = score[:, :, None] + trans[None] + emit[:, None, :]
            best = cand.max(axis=1)
            back = cand.argmax(axis=1).astype(jnp.int32)
            # padded steps (t_idx >= length) freeze the score and record
            # an identity backpointer so the path parks on the last tag
            active = (t_idx < lens)[:, None]
            best = jnp.where(active, best, score)
            ident = jnp.broadcast_to(
                jnp.arange(n, dtype=jnp.int32)[None, :], (b, n))
            back = jnp.where(active, back, ident)
            return best, back

        scores, backs = jax.lax.scan(
            step, init,
            (jnp.swapaxes(pot[:, 1:], 0, 1),
             jnp.arange(1, t, dtype=jnp.int32)))
        if include_bos_eos_tag:
            scores = scores + trans[:, eos][None, :]
        last = scores.argmax(axis=-1).astype(jnp.int32)   # [B]
        final_scores = scores.max(axis=-1)

        def backtrack(carry, back_t):
            tag = carry
            prev = jnp.take_along_axis(back_t, tag[:, None], 1)[:, 0]
            return prev, tag

        # reverse scan emits ys[t] = tag at step t+1 (stacked in forward
        # index order); the final carry is the step-0 tag
        first, ys = jax.lax.scan(backtrack, last, backs, reverse=True)
        paths = jnp.concatenate(
            [first[:, None], jnp.swapaxes(ys, 0, 1)], axis=1)
        if len_arr:  # zero the padded tail (reference masks by length)
            paths = jnp.where(
                jnp.arange(t)[None, :] < lens[:, None], paths, 0)
        return final_scores, paths

    args = (potentials, transition)
    if lengths is not None:
        args = args + (lengths,)
    return apply_op(impl, *args, op_name="viterbi_decode")


class ViterbiDecoder(Layer):
    """ref: text/viterbi_decode.py ViterbiDecoder(transitions)."""

    def __init__(self, transitions, include_bos_eos_tag: bool = True,
                 name=None):
        super().__init__()
        self.transitions = transitions if isinstance(transitions, Tensor) \
            else Tensor(jnp.asarray(transitions))
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)

from . import datasets  # noqa: F401,E402
