"""Collective launch controller.

ref: launch/main.py:23 + launch/controllers/collective.py — spawn one
worker process per device/replica with the rank env the framework reads
(PADDLE_TRAINER_ID, PADDLE_TRAINERS_NUM, PADDLE_MASTER), aggregate logs
under --log_dir, propagate the first failure, and (elastic mode) restart
workers that exit with the restart code.

TPU note: on a TPU pod each *host* is one worker (jax distributed
single-process-per-host), so --nproc_per_node defaults to 1; the CPU-mesh
test path uses --devices to emulate N single-chip workers.

Pod bootstrap (the production multi-controller regime): every launched
worker that calls ``paddle_tpu.distributed.init_parallel_env()`` brings
up the global JAX runtime via ``jax.distributed.initialize`` using the
injected env (coordinator = PADDLE_MASTER, process_id =
PADDLE_TRAINER_ID, num_processes = PADDLE_TRAINERS_NUM) BEFORE first
backend use. After that, ``jax.devices()`` spans all hosts' chips and
every collective — eager ones through the compiled one-collective
programs in ``distributed.collective``, and all collectives inside
jitted train steps — rides ICI/DCN. On the CPU backend the same path
uses gloo cross-process collectives (set automatically); this is what
tests/test_multicontroller.py exercises with real processes.
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import List, Optional

from ..elastic import ELASTIC_EXIT_CODE, ELASTIC_RESTART_CODE  # noqa: F401
# (single source of truth for the 101/102 restart protocol —
# ref: fleet/elastic/manager.py:33-34)


def _parse(argv):
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.distributed.launch",
        description="launch distributed training "
                    "(ref: paddle.distributed.launch)")
    p.add_argument("--nnodes", type=int, default=1)
    p.add_argument("--node_rank", type=int, default=0)
    p.add_argument("--nproc_per_node", type=int, default=1)
    p.add_argument("--devices", type=str, default=None,
                   help="comma list; len(devices) overrides nproc_per_node")
    p.add_argument("--master", type=str, default="127.0.0.1:29500",
                   help="host:port of the rank-0 TCPStore")
    p.add_argument("--log_dir", type=str, default="log")
    p.add_argument("--elastic_retries", type=int, default=0,
                   help="restarts allowed on exit code 101")
    p.add_argument("--elastic", action="store_true",
                   help="store-backed node membership: TTL heartbeats to "
                        "the master, rank rewrite + worker restart on "
                        "node join/leave (ref: fleet/elastic/manager.py)")
    p.add_argument("--elastic_ttl", type=float, default=6.0)
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)
    if ":" not in args.master:
        p.error(f"--master must be host:port, got {args.master!r}")
    return args


def _worker_env(args, local_rank: int, nproc: int) -> dict:
    env = dict(os.environ)
    rank = args.node_rank * nproc + local_rank
    world = args.nnodes * nproc
    env.update({
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(world),
        "PADDLE_LOCAL_RANK": str(local_rank),
        "PADDLE_MASTER": args.master,
        "MASTER_ADDR": args.master.split(":")[0],
        "MASTER_PORT": args.master.split(":")[1],
        # jax multi-host bootstrap mirrors the same coordinates
        "JAX_COORDINATOR_ADDRESS": args.master,
        "JAX_NUM_PROCESSES": str(world),
        "JAX_PROCESS_ID": str(rank),
    })
    if args.devices:
        devs = args.devices.split(",")
        env["PADDLE_VISIBLE_DEVICES"] = devs[local_rank % len(devs)]
    return env


def launch(argv: Optional[List[str]] = None) -> int:
    args = _parse(argv if argv is not None else sys.argv[1:])
    nproc = (len(args.devices.split(","))
             if args.devices else args.nproc_per_node)
    os.makedirs(args.log_dir, exist_ok=True)

    retries = {i: args.elastic_retries for i in range(nproc)}
    procs: List[Optional[subprocess.Popen]] = [None] * nproc
    logs: dict = {}  # worker index -> open log handle (reused on respawn)
    # elastic membership state: (world_nodes, my_node_index) — rewrites the
    # rank env on change (ref: fleet/elastic/manager.py rank rewrite)
    membership = {"nodes": args.nnodes, "index": args.node_rank,
                  "restart": False, "exit": False}

    def spawn(i):
        if i in logs:
            logs[i].close()
        log = open(os.path.join(args.log_dir, f"workerlog.{i}"), "ab")
        logs[i] = log
        env = _worker_env(args, i, nproc)
        if args.elastic:
            world = membership["nodes"] * nproc
            rank = membership["index"] * nproc + i
            env.update({
                "PADDLE_TRAINER_ID": str(rank),
                "PADDLE_TRAINERS_NUM": str(world),
                "JAX_NUM_PROCESSES": str(world),
                "JAX_PROCESS_ID": str(rank),
            })
        procs[i] = subprocess.Popen(
            [sys.executable, args.training_script,
             *args.training_script_args],
            env=env, stdout=log, stderr=log)

    manager = None
    if args.elastic:
        from ..elastic import ElasticManager
        from ..store import TCPStore
        host, port = args.master.rsplit(":", 1)
        store = TCPStore(host, int(port) + 2,
                         is_master=args.node_rank == 0,
                         world_size=args.nnodes, timeout=60.0)

        def on_change(alive, my_index):
            if my_index < 0:
                membership["exit"] = True
            else:
                membership["nodes"] = len(alive)
                membership["index"] = my_index
                membership["restart"] = True
            sys.stderr.write(
                f"[elastic] membership now {alive}, my_index={my_index}; "
                f"{'exiting' if my_index < 0 else 'restarting workers'}\n")

        manager = ElasticManager(
            store, str(args.node_rank), ttl=args.elastic_ttl,
            on_membership_change=on_change).start()

    for i in range(nproc):
        spawn(i)

    def _kill_workers():
        for i, p in enumerate(procs):
            if p is not None and p.poll() is None:
                p.send_signal(signal.SIGTERM)
        deadline = time.time() + 10
        for p in procs:
            if p is not None:
                while p.poll() is None and time.time() < deadline:
                    time.sleep(0.1)
                if p.poll() is None:
                    p.kill()

    exit_code = 0
    try:
        while any(p is not None for p in procs):
            time.sleep(0.2)
            if membership["exit"]:
                raise RuntimeError(
                    "elastic: this node left the alive set (heartbeat "
                    "lost); stopping workers")
            if membership["restart"]:
                membership["restart"] = False
                _kill_workers()
                for i in range(nproc):
                    spawn(i)  # rewritten rank env (elastic scale event)
                continue
            for i, p in enumerate(procs):
                if p is None:
                    continue
                rc = p.poll()
                if rc is None:
                    continue
                if rc == 0:
                    procs[i] = None
                elif rc == ELASTIC_RESTART_CODE and retries[i] > 0:
                    retries[i] -= 1
                    spawn(i)  # elastic restart (ref: manager.py protocol)
                else:
                    exit_code = rc
                    raise RuntimeError(
                        f"worker {i} failed with exit code {rc} "
                        f"(log: {args.log_dir}/workerlog.{i})")
    except RuntimeError as e:
        sys.stderr.write(str(e) + "\n")
        _kill_workers()
        exit_code = exit_code or 1
    finally:
        if manager is not None:
            manager.stop()
        for log in logs.values():
            log.close()
    return exit_code


def main():
    sys.exit(launch())
