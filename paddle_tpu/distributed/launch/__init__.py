"""paddle.distributed.launch equivalent.

ref: python/paddle/distributed/launch/main.py:23 (launch CLI), controllers/
(collective controller: per-rank proc spawn, env injection, log dir),
fleet/elastic/manager.py:125 (restart-on-failure protocol).
"""
from .main import launch, main  # noqa: F401
