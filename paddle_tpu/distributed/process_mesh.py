"""ProcessMesh: named cartesian topology of devices.

ref: paddle/phi/core/distributed/auto_parallel/process_mesh.h:34 and
python/paddle/distributed/auto_parallel/process_mesh.py. TPU-native: a thin
veneer over jax.sharding.Mesh — process ids are flattened device indices into
jax.devices(); the named dims become jax mesh axis names that pjit/shard_map
collectives ride over ICI.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh

_g_default_mesh: Optional["ProcessMesh"] = None


class ProcessMesh:
    def __init__(self, mesh: Sequence, dim_names: Optional[List[str]] = None,
                 shape=None, process_ids=None):
        if shape is not None and process_ids is not None:
            arr = np.asarray(process_ids, dtype=np.int64).reshape(shape)
        else:
            arr = np.asarray(mesh, dtype=np.int64)
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(arr.ndim)]
        if len(dim_names) != arr.ndim:
            raise ValueError(
                f"dim_names {dim_names} does not match mesh ndim {arr.ndim}")
        self._mesh = arr
        self._dim_names = list(dim_names)
        self._jax_mesh: Optional[Mesh] = None

        global _g_default_mesh
        if _g_default_mesh is None:
            _g_default_mesh = self

    # -- reference-parity accessors -----------------------------------------
    @property
    def shape(self) -> List[int]:
        return list(self._mesh.shape)

    @property
    def ndim(self) -> int:
        return self._mesh.ndim

    @property
    def dim_names(self) -> List[str]:
        return list(self._dim_names)

    @property
    def process_ids(self) -> List[int]:
        return [int(i) for i in self._mesh.flatten()]

    @property
    def mesh(self):
        return self._mesh

    @property
    def size(self) -> int:
        return int(self._mesh.size)

    def get_dim_size(self, dim_name: str) -> int:
        return self._mesh.shape[self._dim_names.index(dim_name)]

    def get_mesh_with_dim(self, dim_name: str, index=None):
        """Reorder so dim_name is leading; optionally slice one coordinate."""
        axis = self._dim_names.index(dim_name)
        order = [axis] + [i for i in range(self.ndim) if i != axis]
        new_names = [self._dim_names[i] for i in order]
        new_mesh = self._mesh.transpose(order)
        if index is not None:
            return ProcessMesh(new_mesh[index], new_names[1:] or ["d0"])
        return ProcessMesh(new_mesh, new_names)

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh)
                and self._dim_names == other._dim_names
                and np.array_equal(self._mesh, other._mesh))

    def __hash__(self):
        return hash((tuple(self._dim_names), self._mesh.tobytes()))

    def __repr__(self):
        return (f"ProcessMesh(shape={self.shape}, dim_names={self._dim_names},"
                f" process_ids={self.process_ids})")

    # -- TPU-native bridge ---------------------------------------------------
    def to_jax_mesh(self) -> Mesh:
        """Materialize as a jax.sharding.Mesh over the runtime's devices."""
        if self._jax_mesh is None:
            devices = np.asarray(jax.devices(), dtype=object)
            if self._mesh.size > devices.size:
                raise RuntimeError(
                    f"ProcessMesh needs {self._mesh.size} devices but the "
                    f"runtime exposes {devices.size}")
            dev_grid = np.empty(self._mesh.shape, dtype=object)
            for idx, pid in np.ndenumerate(self._mesh):
                dev_grid[idx] = devices[int(pid)]
            self._jax_mesh = Mesh(dev_grid, axis_names=tuple(self._dim_names))
        return self._jax_mesh


def get_default_mesh() -> Optional[ProcessMesh]:
    return _g_default_mesh


def set_default_mesh(mesh: ProcessMesh):
    global _g_default_mesh
    _g_default_mesh = mesh


def init_process_mesh(shape: Sequence[int], dim_names: List[str]) -> ProcessMesh:
    """Build a mesh over all visible devices in default order."""
    n = int(np.prod(shape))
    return ProcessMesh(np.arange(n).reshape(shape), dim_names)
