"""paddle.distributed.io: persistable-variable save/load.

ref: python/paddle/distributed/io.py (save_persistables /
load_persistables / is_persistable over static Programs). Here the
persistable set is a Layer's parameters + buffers; the on-disk format is
the framework's .pdparams state-dict, so artifacts interoperate with
paddle_tpu.save/load.
"""
from __future__ import annotations

import os

__all__ = ["save_persistables", "load_persistables", "is_persistable"]


def is_persistable(var) -> bool:
    """ref: distributed/io.py is_persistable — parameters and buffers
    persist; activations don't."""
    from ..core.tensor import Parameter
    return isinstance(var, Parameter) or getattr(var, "persistable", False)


def save_persistables(executor, dirname, main_program=None, filename=None):
    """ref: distributed/io.py save_persistables. ``main_program`` here is
    the Layer holding the persistables (the static-Program form has no
    TPU analog — the jitted step owns no variables)."""
    import paddle_tpu as paddle
    layer = main_program if main_program is not None else executor
    if not hasattr(layer, "state_dict"):
        raise TypeError(
            "save_persistables needs a Layer (parameters + buffers); "
            f"got {type(layer).__name__}")
    os.makedirs(dirname, exist_ok=True)
    path = os.path.join(dirname, filename or "__paddle_tpu_persistables__")
    paddle.save(layer.state_dict(), path + ".pdparams")


def load_persistables(executor, dirname, main_program=None, filename=None):
    """ref: distributed/io.py load_persistables."""
    import paddle_tpu as paddle
    layer = main_program if main_program is not None else executor
    path = os.path.join(dirname, filename or "__paddle_tpu_persistables__")
    state = paddle.load(path + ".pdparams")
    layer.set_state_dict(state)
    return layer
