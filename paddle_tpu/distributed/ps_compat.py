"""Parameter-server dataset/entry API surface.

ref: python/paddle/distributed/entry_attr.py (ProbabilityEntry,
CountFilterEntry, ShowClickEntry) and fleet InMemoryDataset/QueueDataset
(python/paddle/distributed/fleet/dataset/dataset.py). The brpc PS *runtime*
is a documented non-goal (SURVEY.md §7 — sparse-CTR stack); these classes
cover the configuration surface and a minimal host-side slot-file
pipeline so data-side code written against the reference API runs.
"""
from __future__ import annotations

import os
from typing import List, Optional, Sequence

import numpy as np

__all__ = ["ProbabilityEntry", "CountFilterEntry", "ShowClickEntry",
           "InMemoryDataset", "QueueDataset"]


class EntryAttr:
    """ref: entry_attr.py EntryAttr base."""

    def _to_attr(self) -> str:
        raise NotImplementedError


class ProbabilityEntry(EntryAttr):
    """ref: entry_attr.py ProbabilityEntry(probability)."""

    def __init__(self, probability: float):
        if not 0 < probability <= 1:
            raise ValueError("probability must be in (0, 1]")
        self._name = "probability_entry"
        self._probability = probability

    def _to_attr(self) -> str:
        return f"{self._name}:{self._probability}"


class CountFilterEntry(EntryAttr):
    """ref: entry_attr.py CountFilterEntry(count_filter)."""

    def __init__(self, count_filter: int):
        if count_filter < 0:
            raise ValueError("count_filter must be >= 0")
        self._name = "count_filter_entry"
        self._count_filter = count_filter

    def _to_attr(self) -> str:
        return f"{self._name}:{self._count_filter}"


class ShowClickEntry(EntryAttr):
    """ref: entry_attr.py ShowClickEntry(show_name, click_name)."""

    def __init__(self, show_name: str, click_name: str):
        self._name = "show_click_entry"
        self._show_name = show_name
        self._click_name = click_name

    def _to_attr(self) -> str:
        return f"{self._name}:{self._show_name}:{self._click_name}"


class _SlotDataset:
    """Shared minimal slot-file pipeline: whitespace 'slot:value' lines ->
    per-slot numpy arrays, batched."""

    def __init__(self):
        self._filelist: List[str] = []
        self._use_vars: List[str] = []
        self._batch_size = 1
        self._thread_num = 1
        self._pipe_command = ""
        self._samples: List[dict] = []

    def init(self, batch_size=1, thread_num=1, use_var=None,
             pipe_command="cat", input_type=0, fs_name="", fs_ugi="",
             **kwargs):
        self._batch_size = batch_size
        self._thread_num = thread_num
        self._use_vars = [getattr(v, "name", str(v))
                          for v in (use_var or [])]
        self._pipe_command = pipe_command
        return self

    def set_filelist(self, filelist: Sequence[str]):
        self._filelist = list(filelist)

    def get_filelist(self) -> List[str]:
        return self._filelist

    def _parse(self):
        self._samples = []
        for path in self._filelist:
            if not os.path.exists(path):
                raise FileNotFoundError(path)
            with open(path) as f:
                for line in f:
                    parts = line.split()
                    if not parts:
                        continue
                    sample: dict = {}
                    for tok in parts:
                        if ":" in tok:
                            slot, val = tok.split(":", 1)
                            sample.setdefault(slot, []).append(float(val))
                    self._samples.append(sample)

    def __iter__(self):
        keys = self._use_vars or sorted(
            {k for s in self._samples for k in s})
        for i in range(0, len(self._samples), self._batch_size):
            chunk = self._samples[i:i + self._batch_size]
            batch = {}
            for k in keys:
                rows = [s.get(k, [0.0]) for s in chunk]
                width = max(len(r) for r in rows)  # pad ragged slots
                batch[k] = np.asarray(
                    [r + [0.0] * (width - len(r)) for r in rows],
                    dtype=np.float32)
            yield batch


class InMemoryDataset(_SlotDataset):
    """ref: fleet/dataset InMemoryDataset — loads slot files into host
    memory with shuffle support."""

    def load_into_memory(self):
        self._parse()

    def get_memory_data_size(self) -> int:
        return len(self._samples)

    def local_shuffle(self, seed: Optional[int] = None):
        rng = np.random.default_rng(seed)
        rng.shuffle(self._samples)

    def global_shuffle(self, fleet=None, thread_num=12):
        self.local_shuffle()

    def release_memory(self):
        self._samples = []

    def get_shuffle_data_size(self, fleet=None) -> int:
        return len(self._samples)


class QueueDataset(_SlotDataset):
    """ref: fleet/dataset QueueDataset — streaming variant (files parsed
    lazily per epoch)."""

    def __iter__(self):
        self._parse()
        return super().__iter__()
