"""Collective/step watchdog: timeout detection for enqueued device work.

ref: paddle/phi/core/distributed/comm_task_manager.h:37-57 (CommTaskManager
background loop: per-collective start/end events, timeout detection, error
propagation, async trace dump enabled by FLAGS_enable_async_trace,
process_group_nccl.cc:156). TPU mapping: the unit of watching is the
compiled program (collectives live inside it), so the watchdog monitors
host-observed completion of each enqueued step; on timeout it dumps the
native host-tracer buffer and invokes the abort callback — the role the
reference fills by aborting NCCL comms.
"""
from __future__ import annotations

import contextlib
import threading
import time
from collections import deque
from typing import Callable, Optional

from ..observability import flight as _flight
from ..observability import metrics as _om

__all__ = ["Watchdog", "WatchdogTimeout", "WatchdogBusy",
           "collective_span", "install_watchdog", "uninstall_watchdog"]

# span completions feed the process registry (the reference's
# comm_task_manager per-collective attribution, now queryable without a
# trace dump): latency histogram per span name + timeout counters
_M_span_s = _om.histogram(
    "watchdog.span_seconds",
    "Completed watchdog span durations (collectives, steps) by name")
_M_timeouts = _om.counter(
    "watchdog.timeouts_total", "Spans/steps that exceeded the timeout")


def _flight_dump(note: str):
    """A hung collective/step must leave forensics behind, not just a
    counter bump: freeze the flight ring next to the host-trace dump
    (counted in observability.dumps_total{trigger="watchdog"}).
    Best-effort — a failing dump must not mask the timeout itself."""
    try:
        return _flight.dump(trigger="watchdog", note=note)
    except Exception:  # noqa: BLE001
        return None


class WatchdogTimeout(RuntimeError):
    pass


class WatchdogBusy(WatchdogTimeout):
    """A previous timed-out step is still running. Subclasses
    WatchdogTimeout so existing handlers still fire, but lets retry logic
    distinguish 'refused to start' from a fresh hang."""


class Watchdog:
    """Wrap blocking step executions with a timeout monitor.

        wd = Watchdog(timeout=300.0)
        loss = wd.run(lambda: float(step(x, y)))     # raises on hang

    The callable must block until device completion (a host value
    transfer — see the tunnel-timing contract used by bench.py)."""

    def __init__(self, timeout: float = 600.0,
                 on_timeout: Optional[Callable[[], None]] = None,
                 trace_path: Optional[str] = None):
        self.timeout = timeout
        self.on_timeout = on_timeout
        self.trace_path = trace_path
        self._task_counter = 0
        self._stuck_thread: Optional[threading.Thread] = None
        # named spans (ref: comm_task_manager.h CommTask start/end events):
        # open spans keyed by id, completed spans in a ring for attribution
        self._span_lock = threading.Lock()
        self._open_spans: dict = {}
        self._span_counter = 0
        self._recent_spans: deque = deque(maxlen=32)
        self._monitor: Optional[threading.Thread] = None
        self._monitor_stop = threading.Event()
        self.timed_out_spans: list = []

    # -- named spans --------------------------------------------------------
    @contextlib.contextmanager
    def span(self, name: str):
        """Track one named operation (a collective, a step). On timeout
        the monitor names it, dumps the host trace, and fires on_timeout
        — the reference's per-CommTask attribution
        (ref: comm_task_manager.h:37-57)."""
        with self._span_lock:
            self._span_counter += 1
            sid = self._span_counter
            # [name, start, timed_out_flag] — a timed-out span stays OPEN
            # (the thread is still blocked) and is merely flagged, so
            # open_span_report keeps showing the hang until it resolves
            self._open_spans[sid] = [name, time.monotonic(), False]
        try:
            yield
        finally:
            with self._span_lock:
                entry = self._open_spans.pop(sid, None)
            if entry is not None:
                name_, t0, flagged = entry
                dt = time.monotonic() - t0
                _M_span_s.observe(dt, name=name_)
                with self._span_lock:
                    self._recent_spans.append(
                        (name_ + (" [timed out]" if flagged else ""), dt))

    def open_span_report(self) -> str:
        with self._span_lock:
            now = time.monotonic()
            opens = [f"{n}{' [TIMED OUT]' if flagged else ''} "
                     f"({now - t0:.1f}s open)"
                     for n, t0, flagged in self._open_spans.values()]
            recent = [f"{n} ({dt * 1e3:.0f}ms)"
                      for n, dt in list(self._recent_spans)[-5:]]
        return (f"open spans: {opens or ['<none>']}; "
                f"recent: {recent or ['<none>']}")

    def start_monitor(self, interval: float = 1.0):
        """Background loop that attributes hangs to the oldest open span
        (a blocked collective cannot raise for itself)."""
        if self._monitor is not None:
            return self
        self._monitor_stop.clear()

        def loop():
            while not self._monitor_stop.wait(interval):
                with self._span_lock:
                    now = time.monotonic()
                    expired = [(sid, e[0], now - e[1]) for sid, e
                               in self._open_spans.items()
                               if now - e[1] > self.timeout and not e[2]]
                for sid, name, age in expired:
                    with self._span_lock:
                        entry = self._open_spans.get(sid)
                        if entry is None or entry[2]:
                            continue
                        entry[2] = True  # flag in place; span stays open
                    _M_timeouts.inc()
                    _flight.record("watchdog", "timeout", span=name,
                                   open_s=round(age, 1))
                    dump = self._dump_trace()
                    fdump = _flight_dump(
                        f"span {name!r} open {age:.0f}s")
                    self.timed_out_spans.append((name, age, dump))
                    import sys
                    sys.stderr.write(
                        f"[watchdog] operation {name!r} exceeded "
                        f"{self.timeout:.0f}s (open {age:.0f}s)"
                        + (f"; trace dumped to {dump}" if dump else "")
                        + (f"; flight dump {fdump}" if fdump else "")
                        + "\n")
                    if self.on_timeout is not None:
                        try:
                            self.on_timeout()
                        except BaseException:
                            pass
        self._monitor = threading.Thread(target=loop, daemon=True)
        self._monitor.start()
        return self

    def stop_monitor(self):
        self._monitor_stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5)
            self._monitor = None

    def _dump_trace(self):
        """Async trace dump on failure (ref: FLAGS_enable_async_trace)."""
        try:
            from .._native import lib
            if lib is not None and self.trace_path:
                with open(self.trace_path, "w") as f:
                    f.write(lib.tracer_dump())
                return self.trace_path
        except Exception:
            pass
        return None

    def run(self, fn: Callable, *args, **kwargs):
        """NOTE a Python thread cannot be killed: on timeout the worker may
        STILL complete later and land its side effects (the reference
        aborts the NCCL comm from on_timeout — do the equivalent abort in
        your callback). A subsequent run() while the timed-out worker is
        still alive refuses to start, so a retry can never double-apply an
        update on top of a late-finishing one."""
        if self._stuck_thread is not None:
            if self._stuck_thread.is_alive():
                raise WatchdogBusy(
                    "previous timed-out step is still running; refusing "
                    "to launch another (restart the process or abort the "
                    "device work from on_timeout)")
            self._stuck_thread = None
        self._task_counter += 1
        task_id = self._task_counter
        result = {}
        done = threading.Event()

        def worker():
            try:
                result["value"] = fn(*args, **kwargs)
            except BaseException as e:  # propagate into the caller
                result["error"] = e
            finally:
                done.set()

        t = threading.Thread(target=worker, daemon=True)
        start = time.monotonic()
        t.start()
        if not done.wait(self.timeout):
            self._stuck_thread = t
            _M_timeouts.inc()
            _flight.record("watchdog", "timeout", task=task_id,
                           timeout_s=self.timeout)
            dump = self._dump_trace()
            fdump = _flight_dump(f"step {task_id} exceeded "
                                 f"{self.timeout:.0f}s")
            abort_err = None
            if self.on_timeout is not None:
                try:
                    self.on_timeout()
                except BaseException as e:  # the timeout must still surface
                    abort_err = e
            raise WatchdogTimeout(
                f"step {task_id} exceeded {self.timeout:.0f}s "
                f"(started {time.monotonic() - start:.0f}s ago)"
                + (f"; host trace dumped to {dump}" if dump else "")
                + (f"; flight dump {fdump}" if fdump else "")
                + (f"; on_timeout callback itself failed: {abort_err!r}"
                   if abort_err is not None else "")) from abort_err
        if "error" in result:
            raise result["error"]
        return result["value"]


# -- global collective instrumentation ---------------------------------------
# collective.py wraps every eager collective in collective_span(); with no
# installed watchdog the wrapper is free (nullcontext).

_installed: Optional[Watchdog] = None


def install_watchdog(timeout: float = 600.0,
                     on_timeout: Optional[Callable[[], None]] = None,
                     trace_path: Optional[str] = None) -> Watchdog:
    """Install a process-wide watchdog whose monitor attributes hangs to
    the named collective/step spans (ref: FLAGS_enable_async_trace +
    CommTaskManager background loop)."""
    global _installed
    if _installed is not None:
        _installed.stop_monitor()
    _installed = Watchdog(timeout, on_timeout, trace_path).start_monitor()
    return _installed


def uninstall_watchdog():
    global _installed
    if _installed is not None:
        _installed.stop_monitor()
        _installed = None


def collective_span(name: str):
    if _installed is None:
        return contextlib.nullcontext()
    return _installed.span(name)
