"""Collective/step watchdog: timeout detection for enqueued device work.

ref: paddle/phi/core/distributed/comm_task_manager.h:37-57 (CommTaskManager
background loop: per-collective start/end events, timeout detection, error
propagation, async trace dump enabled by FLAGS_enable_async_trace,
process_group_nccl.cc:156). TPU mapping: the unit of watching is the
compiled program (collectives live inside it), so the watchdog monitors
host-observed completion of each enqueued step; on timeout it dumps the
native host-tracer buffer and invokes the abort callback — the role the
reference fills by aborting NCCL comms.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Optional

__all__ = ["Watchdog", "WatchdogTimeout", "WatchdogBusy"]


class WatchdogTimeout(RuntimeError):
    pass


class WatchdogBusy(WatchdogTimeout):
    """A previous timed-out step is still running. Subclasses
    WatchdogTimeout so existing handlers still fire, but lets retry logic
    distinguish 'refused to start' from a fresh hang."""


class Watchdog:
    """Wrap blocking step executions with a timeout monitor.

        wd = Watchdog(timeout=300.0)
        loss = wd.run(lambda: float(step(x, y)))     # raises on hang

    The callable must block until device completion (a host value
    transfer — see the tunnel-timing contract used by bench.py)."""

    def __init__(self, timeout: float = 600.0,
                 on_timeout: Optional[Callable[[], None]] = None,
                 trace_path: Optional[str] = None):
        self.timeout = timeout
        self.on_timeout = on_timeout
        self.trace_path = trace_path
        self._task_counter = 0
        self._stuck_thread: Optional[threading.Thread] = None

    def _dump_trace(self):
        """Async trace dump on failure (ref: FLAGS_enable_async_trace)."""
        try:
            from .._native import lib
            if lib is not None and self.trace_path:
                with open(self.trace_path, "w") as f:
                    f.write(lib.tracer_dump())
                return self.trace_path
        except Exception:
            pass
        return None

    def run(self, fn: Callable, *args, **kwargs):
        """NOTE a Python thread cannot be killed: on timeout the worker may
        STILL complete later and land its side effects (the reference
        aborts the NCCL comm from on_timeout — do the equivalent abort in
        your callback). A subsequent run() while the timed-out worker is
        still alive refuses to start, so a retry can never double-apply an
        update on top of a late-finishing one."""
        if self._stuck_thread is not None:
            if self._stuck_thread.is_alive():
                raise WatchdogBusy(
                    "previous timed-out step is still running; refusing "
                    "to launch another (restart the process or abort the "
                    "device work from on_timeout)")
            self._stuck_thread = None
        self._task_counter += 1
        task_id = self._task_counter
        result = {}
        done = threading.Event()

        def worker():
            try:
                result["value"] = fn(*args, **kwargs)
            except BaseException as e:  # propagate into the caller
                result["error"] = e
            finally:
                done.set()

        t = threading.Thread(target=worker, daemon=True)
        start = time.monotonic()
        t.start()
        if not done.wait(self.timeout):
            self._stuck_thread = t
            dump = self._dump_trace()
            abort_err = None
            if self.on_timeout is not None:
                try:
                    self.on_timeout()
                except BaseException as e:  # the timeout must still surface
                    abort_err = e
            raise WatchdogTimeout(
                f"step {task_id} exceeded {self.timeout:.0f}s "
                f"(started {time.monotonic() - start:.0f}s ago)"
                + (f"; host trace dumped to {dump}" if dump else "")
                + (f"; on_timeout callback itself failed: {abort_err!r}"
                   if abort_err is not None else "")) from abort_err
        if "error" in result:
            raise result["error"]
        return result["value"]
