"""Eager collective communication API + Group bookkeeping.

ref: python/paddle/distributed/communication/ (all_reduce.py etc.) and
paddle/fluid/distributed/collective/process_group_nccl.cc. TPU-native design
(SURVEY.md §5 "Distributed communication backend"): instead of NCCL comms on
a side stream, each collective is a tiny cached XLA executable over the
group's device mesh — the collective rides ICI inside the compiled program.

Three operating regimes:
- single-controller (default, incl. tests with 8 virtual CPU devices): one
  Python process drives all chips; "ranks" are devices. Eager collectives on
  replicated host values are identity-like (world through jit is the real
  path); collectives on device-sharded DistTensors run compiled psum etc.
- multi-process with a global jax runtime (jax.distributed.initialize):
  compiled one-collective XLA executables span hosts (ICI/DCN).
- multi-process without a global jax runtime (launch CLI on CPU, or eager
  p2p/object exchange): a TCPStore channel transport
  (ref: process_group_nccl.cc:834 + store/tcp_store.h:121 — the reference
  likewise bootstraps every comm ring through its store). Tensors are
  host-staged through the store; this is the correctness path — the
  bandwidth path is always the compiled collective inside jit.
"""
from __future__ import annotations

import functools
import os
import pickle
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.flags import define_flag
from ..core.tensor import Tensor

__all__ = [
    "ReduceOp", "Group", "new_group", "get_group", "all_reduce", "all_gather",
    "all_gather_object", "broadcast", "broadcast_object_list", "reduce",
    "scatter", "scatter_object_list", "alltoall", "alltoall_single", "send",
    "recv", "isend", "irecv", "barrier", "reduce_scatter", "stream",
    "P2POp", "batch_isend_irecv", "get_backend", "destroy_process_group",
    "is_available", "bucket_assignment", "bucketed_grad_sync",
]

define_flag(
    "dist_grad_bucket_bytes", 4 << 20,
    "Gradient-bucket byte target for the captured distributed train "
    "step (DistTrainStep): grads group into buckets of ~this many "
    "bytes in reverse-backward order and each bucket's all-reduce/"
    "reduce-scatter is emitted as its own first-class node in the "
    "captured program (an optimization_barrier chain pins bucket "
    "order), so XLA's async collectives overlap gradient sync with "
    "remaining backward compute instead of running one serial "
    "epilogue. 0 disables bucketing (pre-T3 program shape: sharding "
    "propagation places the collectives)")


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Task:
    """Async collective handle (ref: process_group.h Task). XLA dispatch is
    already async; wait() blocks on the result buffer."""

    def __init__(self, arrays):
        self._arrays = arrays

    def wait(self):
        for a in self._arrays:
            if hasattr(a, "block_until_ready"):
                a.block_until_ready()

    def is_completed(self):
        return True


class Group:
    """ref: python/paddle/distributed/communication/group.py Group."""

    def __init__(self, gid: int, ranks: List[int]):
        self.id = gid
        self.ranks = list(ranks)
        self.nranks = len(ranks)
        # per-group collective sequence numbers (all members call group
        # collectives in the same order, so local counters agree — the same
        # invariant NCCL imposes on its rings)
        self._seq: Dict[str, int] = {}

    @property
    def world_size(self):
        return self.nranks

    @property
    def rank(self):
        """This process's rank within the group (-1 if not a member)."""
        grank = _global_rank()
        return self.ranks.index(grank) if grank in self.ranks else -1

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1

    @property
    def process_group(self):
        return self

    def __repr__(self):
        return f"Group(id={self.id}, ranks={self.ranks})"


_group_map = {}
_group_counter = 0


def _global_rank() -> int:
    """Env-aware: launched CPU workers have jax.process_count()==1 but a
    real rank from the launcher (PADDLE_TRAINER_ID)."""
    if jax.process_count() > 1:
        return jax.process_index()
    return int(os.environ.get("PADDLE_TRAINER_ID", "0"))


def _world_size() -> int:
    if jax.process_count() > 1:
        return jax.process_count()
    return int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))


def _ensure_default_group() -> Group:
    if 0 not in _group_map:
        _group_map[0] = Group(0, list(range(max(_world_size(), 1))))
    return _group_map[0]


def get_group(gid: int = 0) -> Group:
    if gid == 0:
        return _ensure_default_group()
    return _group_map[gid]


def _get_group(group: Optional[Group]) -> Group:
    return group if group is not None else _ensure_default_group()


def new_group(ranks: Optional[List[int]] = None, backend=None, timeout=None) -> Group:
    """ref: communication/group.py new_group."""
    global _group_counter
    _group_counter += 1
    if ranks is None:
        ranks = list(range(max(_world_size(), 1)))
    g = Group(_group_counter, sorted(ranks))
    _group_map[g.id] = g
    return g


def _unwrap(t):
    return t._data if isinstance(t, Tensor) else jnp.asarray(t)


def _mode(g: Group) -> str:
    """Pick the execution regime for a collective on group ``g``."""
    if g.nranks <= 1:
        return "local"
    if jax.process_count() > 1:
        return "compiled"
    if _world_size() > 1:
        return "store"
    return "local"


# -- TCPStore channel transport ----------------------------------------------
# Host-staged tensor/object exchange for eager p2p and for collectives in
# launched multi-process jobs that don't bring up a global jax runtime.
# ref: the reference's ProcessGroup bootstraps every ring through its store
# (process_group_nccl.cc CreateNCCLEnvCache); here the store IS the eager
# transport — the fast path is always the compiled collective inside jit.

_store = None


def _comm_store():
    global _store
    if _store is None:
        from .store import TCPStore
        master = os.environ.get("PADDLE_MASTER",
                                os.environ.get("MASTER_ADDR", ""))
        if not master:
            raise RuntimeError(
                "cross-process eager collectives need PADDLE_MASTER "
                "(set by paddle_tpu.distributed.launch)")
        if ":" in master:
            host, port = master.rsplit(":", 1)
            port = int(port)
        else:
            host, port = master, int(os.environ.get("MASTER_PORT", "29500"))
        # comm store lives next to the coordinator port
        _store = TCPStore(host, port + 1, is_master=_global_rank() == 0,
                          world_size=_world_size(),
                          timeout=float(os.environ.get(
                              "PADDLE_STORE_TIMEOUT", "120")))
    return _store


def _store_available() -> bool:
    return _store is not None or bool(
        os.environ.get("PADDLE_MASTER", os.environ.get("MASTER_ADDR", "")))


def _allgather_bytes(g: Group, payload: bytes, tag: str) -> List[bytes]:
    """Gather one bytes payload per rank. Uses the TCPStore when the
    launcher env provides one; in a compiled multi-process regime without
    a store (e.g. TPU auto-bootstrap), falls back to a size-exchange +
    padded uint8 compiled all_gather."""
    if _store_available():
        st = _comm_store()
        base = f"c{g.id}/{tag}/{_next_seq(g, tag)}"
        st.set(f"{base}/{g.rank}", payload)
        parts = [st.get(f"{base}/{i}") for i in range(g.nranks)]
        if st.add(f"{base}/rc", 1) == g.nranks:
            for i in range(g.nranks):
                st.delete(f"{base}/{i}")
            st.delete(f"{base}/rc")
        return parts
    buf = np.frombuffer(payload, dtype=np.uint8)
    sizes = _cross_process(
        "all_gather", jnp.asarray(np.array([buf.size], np.int32)),
        g).reshape(g.nranks)
    maxlen = int(sizes.max())
    padded = np.zeros(maxlen, np.uint8)
    padded[:buf.size] = buf
    gathered = _cross_process("all_gather", jnp.asarray(padded), g)
    return [gathered[i][:sizes[i]].tobytes() for i in range(g.nranks)]


def _pack(arr) -> bytes:
    return pickle.dumps(np.asarray(arr), protocol=4)


def _unpack(b: bytes):
    return jnp.asarray(pickle.loads(b))


def _next_seq(g: Group, tag: str) -> int:
    n = g._seq.get(tag, 0)
    g._seq[tag] = n + 1
    return n


def _reduce_parts(parts, op, nranks):
    out = parts[0]
    for p in parts[1:]:
        if op in (ReduceOp.SUM, ReduceOp.AVG):
            out = out + p
        elif op == ReduceOp.MAX:
            out = np.maximum(out, p)
        elif op == ReduceOp.MIN:
            out = np.minimum(out, p)
        elif op == ReduceOp.PROD:
            out = out * p
        else:
            raise NotImplementedError(op)
    if op == ReduceOp.AVG:
        out = out / nranks
    return out


def _store_gather_all(g: Group, arr, tag: str):
    """Every member contributes its array; every member reads all parts
    (host numpy). Shares the set/read-all/refcounted-delete protocol with
    _allgather_bytes."""
    return [pickle.loads(p) for p in _allgather_bytes(g, _pack(arr), tag)]


def _store_bcast_bytes(g: Group, payload: Optional[bytes], src_rank: int,
                       tag: str) -> bytes:
    st = _comm_store()
    base = f"c{g.id}/{tag}/{_next_seq(g, tag)}"
    if g.rank == src_rank:
        st.set(base, payload)
        out = payload
    else:
        out = st.get(base)
    if st.add(f"{base}/rc", 1) == g.nranks:
        st.delete(base)
        st.delete(f"{base}/rc")
    return out


def _store_barrier(g: Group):
    st = _comm_store()
    base = f"c{g.id}/bar/{_next_seq(g, 'bar')}"
    if st.add(f"{base}/cnt", 1) == g.nranks:
        st.set(f"{base}/done", b"1")
    st.wait(f"{base}/done")
    if st.add(f"{base}/rc", 1) == g.nranks:
        st.delete(f"{base}/cnt")
        st.delete(f"{base}/done")
        st.delete(f"{base}/rc")


# Single-process emulation mailbox for send/recv, keyed by
# (group_id, src, dst) so interleaved channels can't cross wires
# (each directed edge is its own FIFO).
_mailbox: Dict[Tuple[int, int, int], List] = {}


# -- multi-process compiled collectives --------------------------------------
# The production (regime-2) transport: a one-collective XLA program over a
# mesh of one device per participating process — psum/all_gather ride the
# interconnect (ICI/DCN on TPU pods, gloo on the CPU test backend) inside
# the compiled program, exactly like the reference's per-ring NCCL comm
# (ref: process_group_nccl.cc:732 CreateNCCLEnvCache per place). Every
# group member must call in (same SPMD contract as NCCL).

@functools.lru_cache(maxsize=None)
def _rank_device(rank: int):
    """The device owned by global rank ``rank`` (multi-controller: one
    process per rank, first local device of that process)."""
    for d in jax.devices():
        if d.process_index == rank:
            return d
    raise RuntimeError(
        f"no device owned by process {rank}; "
        f"process_count={jax.process_count()}")


@functools.lru_cache(maxsize=None)
def _group_mesh(ranks: tuple):
    from jax.sharding import Mesh
    devs = np.asarray([_rank_device(r) for r in ranks], dtype=object)
    return Mesh(devs, axis_names=("r",))


def _cross_process(op_name, arr, group: Group, **kw):
    """Run a one-collective compiled program over the group's ranks and
    return this rank's result as a host numpy array
    (all_reduce -> arr.shape, all_gather -> (nranks,) + arr.shape)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ._mesh_axes import shard_map

    mesh = _group_mesh(tuple(group.ranks))
    arr = jnp.asarray(arr)
    x = jax.make_array_from_single_device_arrays(
        (group.nranks,) + arr.shape,
        NamedSharding(mesh, P("r")),
        [jax.device_put(arr[None], jax.local_devices()[0])])

    if op_name == "all_reduce":
        red = kw.get("op", ReduceOp.SUM)
        def f(v):
            v = v[0]
            if red in (ReduceOp.SUM, ReduceOp.AVG):
                out = jax.lax.psum(v, "r")
                if red == ReduceOp.AVG:
                    out = out / group.nranks
            elif red == ReduceOp.MAX:
                out = jax.lax.pmax(v, "r")
            elif red == ReduceOp.MIN:
                out = jax.lax.pmin(v, "r")
            else:
                raise NotImplementedError(red)
            return out[None]
    elif op_name == "all_gather":
        def f(v):
            return jax.lax.all_gather(v[0], "r")
    else:
        raise NotImplementedError(op_name)

    fn = jax.jit(shard_map(f, mesh=mesh, in_specs=(P("r"),),
                           out_specs=P("r")))
    out = fn(x)
    # this rank's shard IS its result; a global np.asarray would need
    # non-addressable remote shards and fail in multi-controller mode
    local = np.asarray(out.addressable_shards[0].data)
    return local[0] if op_name == "all_reduce" else local


# -- public API ---------------------------------------------------------------

def all_reduce(tensor, op=ReduceOp.SUM, group: Optional[Group] = None,
               sync_op: bool = True) -> Task:
    """ref: communication/all_reduce.py:29. In-place on `tensor`."""
    g = _get_group(group)
    m = _mode(g)
    if m == "local":
        # single-controller: value already holds the full contribution
        if op == ReduceOp.AVG and g.nranks > 1:
            tensor._data = _unwrap(tensor) / g.nranks
        return Task([_unwrap(tensor)])
    if m == "store":
        parts = _store_gather_all(g, _unwrap(tensor), "ar")
        tensor._data = jnp.asarray(_reduce_parts(parts, op, g.nranks))
        return Task([tensor._data])
    out = _cross_process("all_reduce", _unwrap(tensor), g, op=op)
    tensor._data = jnp.asarray(out)
    return Task([tensor._data])


def all_gather(tensor_list: List, tensor, group: Optional[Group] = None,
               sync_op: bool = True) -> Task:
    """ref: communication/all_gather.py."""
    g = _get_group(group)
    arr = _unwrap(tensor)
    m = _mode(g)
    if m == "local":
        for _ in range(g.nranks):
            tensor_list.append(Tensor(jnp.asarray(arr)))
        return Task([arr])
    if m == "store":
        parts = _store_gather_all(g, arr, "ag")
        tensor_list.extend(Tensor(jnp.asarray(p)) for p in parts)
        return Task([arr])
    host = _cross_process("all_gather", arr, g)
    for i in range(g.nranks):
        tensor_list.append(Tensor(jnp.asarray(host[i])))
    return Task([arr])


def all_gather_object(object_list: List, obj, group: Optional[Group] = None):
    g = _get_group(group)
    if _mode(g) == "local":
        object_list.extend(obj for _ in range(g.nranks))
        return
    parts = _allgather_bytes(g, pickle.dumps(obj, protocol=4), "ago")
    object_list.extend(pickle.loads(p) for p in parts)


def broadcast(tensor, src: int = 0, group: Optional[Group] = None,
              sync_op: bool = True) -> Task:
    """ref: communication/broadcast.py. Single-controller: identity."""
    g = _get_group(group)
    m = _mode(g)
    if m == "local":
        return Task([_unwrap(tensor)])
    if m == "store":
        sr = g.get_group_rank(src)
        payload = _pack(_unwrap(tensor)) if g.rank == sr else None
        out = _store_bcast_bytes(g, payload, sr, "bc")
        if g.rank != sr:
            tensor._data = _unpack(out)
        return Task([_unwrap(tensor)])
    # compiled regime: psum of (value if rank==src else zeros). Costs one
    # allreduce (~2x a tree broadcast's bytes) but stays on ICI and fuses
    # under jit; the store path above is the host-staged alternative.
    arr = _unwrap(tensor)
    if g.rank != g.get_group_rank(src):
        arr = jnp.zeros_like(arr)
    t = Tensor(arr)
    task = all_reduce(t, ReduceOp.SUM, g)
    tensor._data = t._data
    return task


def broadcast_object_list(object_list: List, src: int = 0,
                          group: Optional[Group] = None):
    """ref: communication/broadcast.py broadcast_object_list — in-place."""
    g = _get_group(group)
    if _mode(g) == "local":
        return
    sr = g.get_group_rank(src)
    if _store_available():
        payload = (pickle.dumps(list(object_list), protocol=4)
                   if g.rank == sr else None)
        out = _store_bcast_bytes(g, payload, sr, "bco")
    else:  # compiled regime without a store: gather, keep src's payload
        mine = pickle.dumps(list(object_list) if g.rank == sr else None,
                            protocol=4)
        out = _allgather_bytes(g, mine, "bco")[sr]
    if g.rank != sr:
        object_list[:] = pickle.loads(out)


def reduce(tensor, dst: int = 0, op=ReduceOp.SUM,
           group: Optional[Group] = None, sync_op: bool = True) -> Task:
    """ref: communication/reduce.py — only ``dst`` holds the reduced value
    afterwards; other ranks' tensors are left untouched."""
    g = _get_group(group)
    m = _mode(g)
    if m == "local":
        return all_reduce(tensor, op, group)
    if m == "store":
        st = _comm_store()
        dr = g.get_group_rank(dst)
        base = f"c{g.id}/rd/{_next_seq(g, 'rd')}"
        if g.rank == dr:
            parts = [np.asarray(_unwrap(tensor))]
            parts += [pickle.loads(st.take(f"{base}/{i}"))
                      for i in range(g.nranks) if i != dr]
            tensor._data = jnp.asarray(_reduce_parts(parts, op, g.nranks))
        else:
            st.set(f"{base}/{g.rank}", _pack(_unwrap(tensor)))
        return Task([_unwrap(tensor)])
    # compiled regime: allreduce, then non-dst ranks restore their input
    # (dst-selectivity is semantic, not a bandwidth saving, on a ring)
    orig = _unwrap(tensor)
    task = all_reduce(tensor, op, group)
    if g.rank != g.get_group_rank(dst):
        tensor._data = orig
    return task


def scatter(tensor, tensor_list=None, src: int = 0,
            group: Optional[Group] = None, sync_op: bool = True) -> Task:
    """ref: communication/scatter.py — ``src`` distributes tensor_list[i]
    to group rank i."""
    g = _get_group(group)
    m = _mode(g)
    if m == "local":
        if tensor_list:
            tensor._data = _unwrap(tensor_list[0])
        return Task([_unwrap(tensor)])
    st = _comm_store()
    sr = g.get_group_rank(src)
    base = f"c{g.id}/sc/{_next_seq(g, 'sc')}"
    if g.rank == sr:
        if not tensor_list or len(tensor_list) != g.nranks:
            raise ValueError(
                f"scatter src needs tensor_list of len {g.nranks}")
        for i in range(g.nranks):
            if i == sr:
                tensor._data = _unwrap(tensor_list[i])
            else:
                st.set(f"{base}/{i}", _pack(_unwrap(tensor_list[i])))
    else:
        tensor._data = _unpack(st.take(f"{base}/{g.rank}"))
    return Task([_unwrap(tensor)])


def scatter_object_list(out_object_list: List, in_object_list=None,
                        src: int = 0, group: Optional[Group] = None):
    """ref: communication/scatter.py scatter_object_list."""
    g = _get_group(group)
    if _mode(g) == "local":
        if in_object_list:
            out_object_list[:] = [in_object_list[0]]
        return
    sr = g.get_group_rank(src)
    if g.rank == sr and (in_object_list is None or
                         len(in_object_list) != g.nranks):
        raise ValueError(
            f"scatter src needs in_object_list of len {g.nranks}")
    if _store_available():
        st = _comm_store()
        base = f"c{g.id}/sco/{_next_seq(g, 'sco')}"
        if g.rank == sr:
            for i in range(g.nranks):
                if i != sr:
                    st.set(f"{base}/{i}",
                           pickle.dumps(in_object_list[i], protocol=4))
            out_object_list[:] = [in_object_list[sr]]
        else:
            out_object_list[:] = [pickle.loads(st.take(f"{base}/{g.rank}"))]
    else:  # compiled regime without a store: gather src's list, pick own
        mine = pickle.dumps(in_object_list if g.rank == sr else None,
                            protocol=4)
        full = pickle.loads(_allgather_bytes(g, mine, "sco")[sr])
        out_object_list[:] = [full[g.rank]]


def reduce_scatter(tensor, tensor_list, op=ReduceOp.SUM,
                   group: Optional[Group] = None, sync_op: bool = True) -> Task:
    g = _get_group(group)
    m = _mode(g)
    if m == "local":
        idx = max(g.rank, 0)
        t = Tensor(_unwrap(tensor_list[idx]))
        all_reduce(t, op, g)
        tensor._data = t._data
        return Task([tensor._data])
    stacked = jnp.stack([_unwrap(t) for t in tensor_list])
    if m == "store":
        parts = _store_gather_all(g, stacked, "rs")
        summed = _reduce_parts(parts, op, g.nranks)
        tensor._data = jnp.asarray(summed[g.rank])
        return Task([tensor._data])
    summed = _cross_process("all_reduce", stacked, g, op=op)
    tensor._data = jnp.asarray(summed)[g.rank]
    return Task([tensor._data])


def alltoall(out_tensor_list: List, in_tensor_list: List,
             group: Optional[Group] = None, sync_op: bool = True) -> Task:
    g = _get_group(group)
    m = _mode(g)
    if m == "local":
        out_tensor_list.extend(Tensor(_unwrap(t)) for t in in_tensor_list)
        return Task([])
    if m == "store":
        st = _comm_store()
        base = f"c{g.id}/a2a/{_next_seq(g, 'a2a')}"
        r = g.rank
        for d in range(g.nranks):
            if d != r:
                st.set(f"{base}/{r}>{d}", _pack(_unwrap(in_tensor_list[d])))
        for s in range(g.nranks):
            if s == r:
                out_tensor_list.append(Tensor(_unwrap(in_tensor_list[r])))
            else:
                out_tensor_list.append(Tensor(_unpack(
                    st.take(f"{base}/{s}>{r}"))))
        return Task([])
    stacked = jnp.stack([_unwrap(t) for t in in_tensor_list])
    gathered = _cross_process("all_gather", stacked, g)
    r = g.rank
    for i in range(g.nranks):
        out_tensor_list.append(Tensor(jnp.asarray(gathered[i][r])))
    return Task([])


def alltoall_single(out_tensor, in_tensor, in_split_sizes=None,
                    out_split_sizes=None, group: Optional[Group] = None,
                    sync_op: bool = True) -> Task:
    """ref: communication/all_to_all.py alltoall_single — axis-0 splits of
    one tensor exchanged pairwise."""
    g = _get_group(group)
    m = _mode(g)
    if m == "local":
        out_tensor._data = _unwrap(in_tensor)
        return Task([out_tensor._data])
    arr = _unwrap(in_tensor)
    n = g.nranks
    if in_split_sizes is None:
        if arr.shape[0] % n:
            raise ValueError(
                f"alltoall_single dim0 {arr.shape[0]} not divisible by "
                f"group size {n}")
        in_split_sizes = [arr.shape[0] // n] * n
    offs = np.cumsum([0] + list(in_split_sizes))
    chunks = [arr[offs[i]:offs[i + 1]] for i in range(n)]
    ins, outs = [Tensor(c) for c in chunks], []
    alltoall(outs, ins, group=g, sync_op=sync_op)
    out_tensor._data = jnp.concatenate([_unwrap(t) for t in outs], axis=0)
    return Task([out_tensor._data])


def send(tensor, dst: int = 0, group: Optional[Group] = None,
         sync_op: bool = True) -> Task:
    """ref: communication/send.py + process_group_nccl.cc:252 Send. Cross-
    process transport is the TCPStore channel (host-staged); per-directed-
    edge FIFO sequence numbers pair each send with its recv."""
    g = _get_group(group)
    if _mode(g) == "local":
        key = (g.id, _global_rank(), dst)
        _mailbox.setdefault(key, []).append(jnp.asarray(_unwrap(tensor)))
        return Task([])
    st = _comm_store()
    me = _global_rank()  # dst/src are GLOBAL ranks (paddle contract)
    seq = _next_seq(g, f"p2p/{me}>{dst}")
    st.set(f"c{g.id}/p2p/{me}>{dst}/{seq}", _pack(_unwrap(tensor)))
    return Task([])


def recv(tensor, src: int = 0, group: Optional[Group] = None,
         sync_op: bool = True) -> Task:
    g = _get_group(group)
    if _mode(g) == "local":
        key = (g.id, src, _global_rank())
        q = _mailbox.get(key)
        if not q:
            raise RuntimeError(
                f"recv(src={src}) has no pending message on channel "
                f"{key} (single-process mode cannot block)")
        tensor._data = q.pop(0)
        return Task([])
    st = _comm_store()
    me = _global_rank()
    seq = _next_seq(g, f"p2p/{src}>{me}")
    tensor._data = _unpack(st.take(f"c{g.id}/p2p/{src}>{me}/{seq}"))
    return Task([tensor._data])


def isend(tensor, dst=0, group=None):
    return send(tensor, dst, group, sync_op=False)


def irecv(tensor, src=0, group=None):
    return recv(tensor, src, group, sync_op=False)


class P2POp:
    """ref: communication/batch_isend_irecv.py P2POp."""

    def __init__(self, op, tensor, peer, group=None):
        if op not in (isend, irecv, send, recv):
            raise ValueError("P2POp op must be paddle.distributed.isend/irecv")
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group


def batch_isend_irecv(p2p_op_list: List[P2POp]) -> List[Task]:
    """ref: communication/batch_isend_irecv.py. Sends are issued before
    recvs so the host-staged transport cannot deadlock on ordering."""
    sends = [p for p in p2p_op_list if p.op in (isend, send)]
    recvs = [p for p in p2p_op_list if p.op in (irecv, recv)]
    tasks = [p.op(p.tensor, p.peer, p.group) for p in sends]
    tasks += [p.op(p.tensor, p.peer, p.group) for p in recvs]
    return tasks


def barrier(group: Optional[Group] = None):
    g = _get_group(group)
    m = _mode(g)
    if m == "local":
        return
    if m == "store":
        _store_barrier(g)
        return
    t = Tensor(jnp.zeros((1,), jnp.float32))
    all_reduce(t, ReduceOp.SUM, g).wait()


def get_backend(group: Optional[Group] = None) -> str:
    """ref: communication/group.py get_backend (NCCL/GLOO there)."""
    dev = jax.devices()[0].platform
    return "XCCL" if dev == "tpu" else "GLOO"


def is_available() -> bool:
    return True


def destroy_process_group(group: Optional[Group] = None):
    """ref: communication/group.py destroy_process_group."""
    global _store
    if group is None or group.id == 0:
        _group_map.clear()
        _mailbox.clear()
        if _store is not None:
            _store.shutdown()
            _store = None
    else:
        _group_map.pop(group.id, None)


# -- bucketed gradient synchronization (T3 compute–collective overlap) --------
# The captured distributed train step (dist_train.DistTrainStep over
# jit/sot.CapturedStep) syncs gradients through these instead of leaving
# ONE sharding-propagation-placed collective epilogue after the full
# backward: grads group into size-targeted buckets in REVERSE-backward
# order (the last layers' grads retire first while earlier layers are
# still differentiating), each bucket's reduce materializes at its own
# pinned program point (with_sharding_constraint to the parameter's
# placement — reduce-scatter under ZeRO/fsdp, all-reduce under pure dp),
# and an optimization_barrier chain keeps XLA from collapsing the
# buckets back into a tail. Bucket k's collective depends ONLY on its
# own grads, so the latency-hiding scheduler can launch it while the
# remaining backward computes — the DDP/T3 tracking-and-triggering
# structure as a first-class piece of the captured DAG.

def bucket_assignment(named_sizes, target_bytes: int):
    """Greedy in-order bucketing: ``named_sizes`` is [(key, nbytes)]
    ALREADY in reverse-backward order; returns a list of buckets (each
    a list of keys) such that every key lands in exactly one bucket,
    order is preserved, each bucket closes once it reaches
    ``target_bytes`` (a single grad larger than the target gets its
    own bucket). ``target_bytes <= 0`` puts everything in one bucket."""
    if target_bytes <= 0:
        return [[k for k, _ in named_sizes]] if named_sizes else []
    buckets: List[List[str]] = []
    cur: List[str] = []
    cur_bytes = 0
    for key, nbytes in named_sizes:
        if cur and cur_bytes + int(nbytes) > target_bytes:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(key)
        cur_bytes += int(nbytes)
    if cur:
        buckets.append(cur)
    return buckets


def bucketed_grad_sync(grads: Dict[str, Any], buckets, shardings):
    """Trace-time: emit each bucket's gradient synchronization as its
    own program node. ``grads`` maps key -> grad array (tracers under
    jit), ``buckets`` is bucket_assignment's output, ``shardings``
    maps key -> the parameter's NamedSharding (keys without one pass
    through un-constrained — single-device runs). Returns
    ``(synced_grads, plan)`` where plan is
    [{"bucket", "grads", "bytes", "keys"}] for telemetry."""
    from jax import lax

    synced = dict(grads)
    plan: List[Dict[str, Any]] = []
    token = None
    for i, bucket in enumerate(buckets):
        leaves = [synced[k] for k in bucket]
        if token is not None:
            # pin: this bucket's sync cannot be hoisted before the
            # previous bucket's (reverse-backward issue order, the
            # same in-order guarantee DDP buckets give NCCL)
            barred = lax.optimization_barrier(tuple(leaves) + (token,))
            leaves = list(barred[:-1])
        out = []
        nbytes = 0
        for k, g in zip(bucket, leaves):
            sh = shardings.get(k)
            if sh is not None:
                # materialize the REDUCED, placement-correct grad HERE:
                # the partitioner lands the bucket's collective at this
                # program point instead of wherever the epilogue sits
                g = lax.with_sharding_constraint(g, sh)
            out.append(g)
            nbytes += int(np.prod(g.shape)) * np.dtype(g.dtype).itemsize
        token = out[0]
        plan.append({"bucket": i, "grads": len(bucket), "bytes": nbytes,
                     "keys": list(bucket)})
        for k, g in zip(bucket, out):
            synced[k] = g
    return synced, plan


def journal_grad_buckets(plan, dur_us=None) -> None:
    """Host-side: land one flight-recorder ``collective`` event per
    bucket (payload bytes + grad count — the T3 overlap-efficiency
    numerator next to PR 8's eager-collective events) plus a
    ``dist_step`` summary carrying the step's host dispatch duration.
    Flight-gated: the off path pays one flag read."""
    if not plan or not _flight.enabled():
        return
    for b in plan:
        _flight.record("collective", "grad_bucket", bucket=b["bucket"],
                       bytes=b["bytes"], grads=b["grads"])
    attrs = {"buckets": len(plan),
             "bytes": sum(b["bytes"] for b in plan)}
    if dur_us is not None:
        attrs["dur_us"] = round(dur_us, 1)
    _flight.record("collective", "dist_step", **attrs)


# -- watchdog + telemetry instrumentation -------------------------------------
# every eager collective runs inside a named span so an installed watchdog
# (watchdog.install_watchdog) attributes hangs to the exact operation —
# the reference's per-CommTask start/end tracking
# (ref: comm_task_manager.h:37-57). Free when no watchdog is installed.
# The registry additionally gets per-collective call + payload-byte
# counters (the comm_task_manager bytes attribution); span latency lands
# in watchdog.span_seconds when a watchdog is installed.

import time as _time  # noqa: E402

from ..observability import flight as _flight  # noqa: E402
from ..observability import metrics as _om  # noqa: E402

_M_coll_calls = _om.counter(
    "collectives.calls_total", "Eager collective invocations by op")
_M_coll_bytes = _om.counter(
    "collectives.bytes_total",
    "Input tensor payload bytes entering eager collectives by op "
    "(best-effort: positional payload args only)")

# which positional arg(s) carry the INPUT payload per op — several
# collectives take their output buffer first (all_gather, scatter,
# reduce_scatter, alltoall), and counting that would inflate bytes with
# buffers no payload entered through
_PAYLOAD_ARGS = {
    "all_reduce": (0,), "all_gather": (1,), "broadcast": (0,),
    "reduce": (0,), "scatter": (1,), "reduce_scatter": (1,),
    "alltoall": (1,), "alltoall_single": (1,), "send": (0,),
}


def _payload_bytes(opname, args) -> int:
    """Concrete input-tensor bytes for one collective call (lists of
    tensors included — scatter/alltoall take them). Lazy
    (unmaterialized) fusion handles and payloads passed as kwargs are
    skipped rather than forced/guessed."""
    n = 0
    for i in _PAYLOAD_ARGS.get(opname, ()):
        if i >= len(args):
            continue
        a = args[i]
        for t in (a if isinstance(a, (list, tuple)) else (a,)):
            buf = getattr(t, "_buf", None)
            if buf is not None:
                n += int(getattr(buf, "nbytes", 0) or 0)
    return n


def _spanned(fn):
    opname = fn.__name__

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        from .watchdog import collective_span
        g = kwargs.get("group")
        if not isinstance(g, Group):  # group may be passed positionally
            g = next((a for a in args if isinstance(a, Group)), None)
        gid = g.id if isinstance(g, Group) else 0
        want_flight = _flight.enabled()
        nbytes = 0
        if _om.enabled() or want_flight:
            nbytes = _payload_bytes(opname, args)
        if _om.enabled():
            _M_coll_calls.inc(op=opname)
            if nbytes:
                _M_coll_bytes.inc(nbytes, op=opname)
        if not want_flight:
            with collective_span(f"{opname}(group={gid})"):
                return fn(*args, **kwargs)
        # flight trail: op, payload bytes, host-observed duration — the
        # T3 overlap-efficiency input (ROADMAP item 3). NOTE duration is
        # dispatch-to-return on the host; device completion may lag.
        t0 = _time.perf_counter()
        with collective_span(f"{opname}(group={gid})"):
            out = fn(*args, **kwargs)
        _flight.record(
            "collective", opname, group=gid, bytes=nbytes,
            dur_us=round((_time.perf_counter() - t0) * 1e6, 1))
        return out
    return wrapper


all_reduce = _spanned(all_reduce)
all_gather = _spanned(all_gather)
all_gather_object = _spanned(all_gather_object)
broadcast = _spanned(broadcast)
broadcast_object_list = _spanned(broadcast_object_list)
reduce = _spanned(reduce)
scatter = _spanned(scatter)
scatter_object_list = _spanned(scatter_object_list)
reduce_scatter = _spanned(reduce_scatter)
alltoall = _spanned(alltoall)
alltoall_single = _spanned(alltoall_single)
send = _spanned(send)
recv = _spanned(recv)
barrier = _spanned(barrier)


class stream:
    """paddle.distributed.stream.* namespace parity (sync/calc-stream
    variants collapse on TPU: XLA owns scheduling)."""

    all_reduce = staticmethod(all_reduce)
    all_gather = staticmethod(all_gather)
    broadcast = staticmethod(broadcast)
    reduce = staticmethod(reduce)
    scatter = staticmethod(scatter)
    alltoall = staticmethod(alltoall)
    reduce_scatter = staticmethod(reduce_scatter)
    send = staticmethod(send)
    recv = staticmethod(recv)
