"""Eager collective communication API + Group bookkeeping.

ref: python/paddle/distributed/communication/ (all_reduce.py etc.) and
paddle/fluid/distributed/collective/process_group_nccl.cc. TPU-native design
(SURVEY.md §5 "Distributed communication backend"): instead of NCCL comms on
a side stream, each collective is a tiny cached XLA executable over the
group's device mesh — the collective rides ICI inside the compiled program.

Two operating regimes:
- single-controller (default, incl. tests with 8 virtual CPU devices): one
  Python process drives all chips; "ranks" are devices. Eager collectives on
  replicated host values are identity-like (world through jit is the real
  path); collectives on device-sharded DistTensors run compiled psum etc.
- multi-process (jax.distributed.initialize via launch CLI): rank ==
  process_index, and the same compiled-collective cache spans hosts (DCN).
"""
from __future__ import annotations

import functools
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor

__all__ = [
    "ReduceOp", "Group", "new_group", "get_group", "all_reduce", "all_gather",
    "all_gather_object", "broadcast", "reduce", "scatter", "alltoall",
    "alltoall_single", "send", "recv", "isend", "irecv", "barrier",
    "reduce_scatter", "stream",
]


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Task:
    """Async collective handle (ref: process_group.h Task). XLA dispatch is
    already async; wait() blocks on the result buffer."""

    def __init__(self, arrays):
        self._arrays = arrays

    def wait(self):
        for a in self._arrays:
            if hasattr(a, "block_until_ready"):
                a.block_until_ready()

    def is_completed(self):
        return True


class Group:
    """ref: python/paddle/distributed/communication/group.py Group."""

    def __init__(self, gid: int, ranks: List[int]):
        self.id = gid
        self.ranks = list(ranks)
        self.nranks = len(ranks)

    @property
    def world_size(self):
        return self.nranks

    @property
    def rank(self):
        """This process's rank within the group (-1 if not a member)."""
        grank = _global_rank()
        return self.ranks.index(grank) if grank in self.ranks else -1

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1

    @property
    def process_group(self):
        return self

    def __repr__(self):
        return f"Group(id={self.id}, ranks={self.ranks})"


_group_map = {}
_group_counter = 0


def _global_rank() -> int:
    return jax.process_index()


def _world_size() -> int:
    return jax.process_count()


def _ensure_default_group() -> Group:
    if 0 not in _group_map:
        _group_map[0] = Group(0, list(range(max(_world_size(), 1))))
    return _group_map[0]


def get_group(gid: int = 0) -> Group:
    if gid == 0:
        return _ensure_default_group()
    return _group_map[gid]


def _get_group(group: Optional[Group]) -> Group:
    return group if group is not None else _ensure_default_group()


def new_group(ranks: Optional[List[int]] = None, backend=None, timeout=None) -> Group:
    """ref: communication/group.py new_group."""
    global _group_counter
    _group_counter += 1
    if ranks is None:
        ranks = list(range(max(_world_size(), 1)))
    g = Group(_group_counter, sorted(ranks))
    _group_map[g.id] = g
    return g


def _unwrap(t):
    return t._data if isinstance(t, Tensor) else jnp.asarray(t)


# -- multi-process compiled collectives --------------------------------------
# One device per process is assumed for the cross-process eager path (the
# launch CLI sets this up); a global 1-D mesh over process-local device 0 of
# every process carries the collective.

@functools.lru_cache(maxsize=None)
def _proc_mesh(nranks: int):
    from jax.sharding import Mesh
    devs = np.asarray(jax.devices()[:nranks], dtype=object)
    return Mesh(devs, axis_names=("r",))


def _cross_process(op_name, arr, group: Group, **kw):
    """Run a one-collective compiled program over the group's ranks."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    mesh = _proc_mesh(group.nranks)
    x = jax.make_array_from_single_device_arrays(
        (group.nranks,) + arr.shape,
        NamedSharding(mesh, P("r")),
        [jax.device_put(arr[None], jax.devices()[0])])

    if op_name == "all_reduce":
        red = kw.get("op", ReduceOp.SUM)
        def f(v):
            v = v[0]
            if red in (ReduceOp.SUM, ReduceOp.AVG):
                out = jax.lax.psum(v, "r")
                if red == ReduceOp.AVG:
                    out = out / group.nranks
            elif red == ReduceOp.MAX:
                out = jax.lax.pmax(v, "r")
            elif red == ReduceOp.MIN:
                out = jax.lax.pmin(v, "r")
            else:
                raise NotImplementedError(red)
            return out[None]
    elif op_name == "all_gather":
        def f(v):
            return jax.lax.all_gather(v[0], "r")
    else:
        raise NotImplementedError(op_name)

    spec = P("r")
    fn = jax.jit(shard_map(f, mesh=mesh, in_specs=(spec,),
                           out_specs=spec if op_name == "all_reduce" else P("r")))
    return fn(x)


# -- public API ---------------------------------------------------------------

def all_reduce(tensor, op=ReduceOp.SUM, group: Optional[Group] = None,
               sync_op: bool = True) -> Task:
    """ref: communication/all_reduce.py:29. In-place on `tensor`."""
    g = _get_group(group)
    if g.nranks <= 1 or _world_size() <= 1:
        # single-controller: value already holds the full contribution
        if op == ReduceOp.AVG and g.nranks > 1:
            tensor._data = _unwrap(tensor) / g.nranks
        return Task([_unwrap(tensor)])
    out = _cross_process("all_reduce", _unwrap(tensor), g, op=op)
    local = out[jax.process_index() % out.shape[0]] if out.ndim > _unwrap(tensor).ndim else out
    tensor._data = jnp.asarray(local)
    return Task([tensor._data])


def all_gather(tensor_list: List, tensor, group: Optional[Group] = None,
               sync_op: bool = True) -> Task:
    """ref: communication/all_gather.py."""
    g = _get_group(group)
    arr = _unwrap(tensor)
    if g.nranks <= 1 or _world_size() <= 1:
        for _ in range(g.nranks):
            tensor_list.append(Tensor(jnp.asarray(arr)))
        return Task([arr])
    out = _cross_process("all_gather", arr, g)
    host = np.asarray(out)
    for i in range(g.nranks):
        tensor_list.append(Tensor(jnp.asarray(host[i])))
    return Task([arr])


def all_gather_object(object_list: List, obj, group: Optional[Group] = None):
    g = _get_group(group)
    if g.nranks <= 1 or _world_size() <= 1:
        object_list.extend(obj for _ in range(g.nranks))
        return
    import pickle
    payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    size = np.array([payload.size], dtype=np.int32)
    sizes = np.asarray(_cross_process("all_gather", jnp.asarray(size),
                                      g)).reshape(g.nranks)
    maxlen = int(sizes.max())
    padded = np.zeros(maxlen, dtype=np.uint8)
    padded[:payload.size] = payload
    gathered = np.asarray(
        _cross_process("all_gather", jnp.asarray(padded), g))
    for i in range(g.nranks):
        object_list.append(pickle.loads(gathered[i][:sizes[i]].tobytes()))


def broadcast(tensor, src: int = 0, group: Optional[Group] = None,
              sync_op: bool = True) -> Task:
    """ref: communication/broadcast.py. Single-controller: identity."""
    g = _get_group(group)
    if g.nranks <= 1 or _world_size() <= 1:
        return Task([_unwrap(tensor)])
    # broadcast == all_reduce of (value if rank==src else zeros)
    arr = _unwrap(tensor)
    if g.rank != g.get_group_rank(src):
        arr = jnp.zeros_like(arr)
    t = Tensor(arr)
    task = all_reduce(t, ReduceOp.SUM, g)
    tensor._data = t._data
    return task


def reduce(tensor, dst: int = 0, op=ReduceOp.SUM,
           group: Optional[Group] = None, sync_op: bool = True) -> Task:
    task = all_reduce(tensor, op, group)
    return task


def scatter(tensor, tensor_list=None, src: int = 0,
            group: Optional[Group] = None, sync_op: bool = True) -> Task:
    g = _get_group(group)
    if g.nranks <= 1 or _world_size() <= 1:
        if tensor_list:
            tensor._data = _unwrap(tensor_list[0])
        return Task([_unwrap(tensor)])
    raise NotImplementedError(
        "cross-process scatter requires the launch runtime")


def reduce_scatter(tensor, tensor_list, op=ReduceOp.SUM,
                   group: Optional[Group] = None, sync_op: bool = True) -> Task:
    g = _get_group(group)
    if g.nranks <= 1 or _world_size() <= 1:
        idx = max(g.rank, 0)
        t = Tensor(_unwrap(tensor_list[idx]))
        all_reduce(t, op, g)
        tensor._data = t._data
        return Task([tensor._data])
    stacked = jnp.stack([_unwrap(t) for t in tensor_list])
    summed = _cross_process("all_reduce", stacked, g, op=op)
    tensor._data = jnp.asarray(summed)[g.rank]
    return Task([tensor._data])


def alltoall(out_tensor_list: List, in_tensor_list: List,
             group: Optional[Group] = None, sync_op: bool = True) -> Task:
    g = _get_group(group)
    if g.nranks <= 1 or _world_size() <= 1:
        out_tensor_list.extend(Tensor(_unwrap(t)) for t in in_tensor_list)
        return Task([])
    stacked = jnp.stack([_unwrap(t) for t in in_tensor_list])
    gathered = np.asarray(_cross_process("all_gather", stacked, g))
    r = g.rank
    for i in range(g.nranks):
        out_tensor_list.append(Tensor(jnp.asarray(gathered[i][r])))
    return Task([])


def alltoall_single(out_tensor, in_tensor, in_split_sizes=None,
                    out_split_sizes=None, group: Optional[Group] = None,
                    sync_op: bool = True) -> Task:
    g = _get_group(group)
    if g.nranks <= 1 or _world_size() <= 1:
        out_tensor._data = _unwrap(in_tensor)
        return Task([out_tensor._data])
    raise NotImplementedError(
        "cross-process alltoall_single requires the launch runtime")


def send(tensor, dst: int = 0, group: Optional[Group] = None,
         sync_op: bool = True) -> Task:
    if _world_size() <= 1:
        _p2p_buf.append(jnp.asarray(_unwrap(tensor)))
        return Task([])
    raise NotImplementedError("cross-process send requires the launch runtime")


def recv(tensor, src: int = 0, group: Optional[Group] = None,
         sync_op: bool = True) -> Task:
    if _world_size() <= 1:
        if _p2p_buf:
            tensor._data = _p2p_buf.pop(0)
        return Task([])
    raise NotImplementedError("cross-process recv requires the launch runtime")


_p2p_buf: List = []


def isend(tensor, dst=0, group=None):
    return send(tensor, dst, group, sync_op=False)


def irecv(tensor, src=0, group=None):
    return recv(tensor, src, group, sync_op=False)


def barrier(group: Optional[Group] = None):
    g = _get_group(group)
    if g.nranks <= 1 or _world_size() <= 1:
        return
    t = Tensor(jnp.zeros((1,), jnp.float32))
    all_reduce(t, ReduceOp.SUM, g).wait()


class stream:
    """paddle.distributed.stream.* namespace parity (sync/calc-stream
    variants collapse on TPU: XLA owns scheduling)."""

    all_reduce = staticmethod(all_reduce)
    all_gather = staticmethod(all_gather)
    broadcast = staticmethod(broadcast)
    reduce = staticmethod(reduce)
    scatter = staticmethod(scatter)
    alltoall = staticmethod(alltoall)
    reduce_scatter = staticmethod(reduce_scatter)
    send = staticmethod(send)
    recv = staticmethod(recv)
