"""Per-op SPMD sharding rules: the explicit propagation table.

ref: paddle/phi/infermeta/spmd_rules/ (~60 per-op rules, e.g.
matmul.cc:116 MatmulInferSpmd, flash_attention.cc, moe_gate_dispatch.cc)
and the registry in phi/core/distributed/auto_parallel/inferspmd_utils.h.
The TPU build leans on GSPMD for most propagation, but GSPMD cannot see
through Pallas kernels: a pallas_call under pjit with sharded operands
would be replicated (or mis-sharded). The rules here produce the
`shard_map` in/out PartitionSpecs that pin the intended decomposition —
the direct analog of the reference's InferSpmd (input dist_attrs ->
output dist_attrs + required reshards).

Two consumers:
- ops.yaml `spmd:` entries name a rule per op; the native OpRegistry
  carries the name and `get_rule(name)` resolves it (tested so every
  named rule exists).
- `shard_*` helpers below apply the three custom-kernel rules (flash
  attention, grouped matmul, MoE dispatch) through shard_map, asserting
  the collectives the rule implies (HLO-inspected in tests).

A rule is `fn(*arg_specs, **shape_kwargs) -> (in_specs, out_specs)`
over jax.sharding.PartitionSpec. Unknown/unsupported input placements
raise — the caller falls back to replicate-with-GSPMD, never a silent
wrong decomposition (SURVEY §7 hard-parts list: "missing rules must fall
back to replicate-with-warning, not crash").
"""
from __future__ import annotations

from typing import Callable, Dict

from jax.sharding import PartitionSpec as P

__all__ = ["get_rule", "register_rule", "list_rules",
           "shard_map_flash_attention", "shard_map_grouped_matmul",
           "shard_map_moe_dispatch"]

_RULES: Dict[str, Callable] = {}


def register_rule(name: str):
    def deco(fn):
        _RULES[name] = fn
        return fn
    return deco


def get_rule(name: str) -> Callable:
    if name not in _RULES:
        raise KeyError(
            f"no SPMD rule {name!r} (known: {sorted(_RULES)}); GSPMD "
            f"propagation is the fallback")
    return _RULES[name]


def list_rules():
    return sorted(_RULES)


# -- generic families -----------------------------------------------------

@register_rule("elementwise")
def elementwise(*in_specs):
    """Same-rank elementwise: dims merge across inputs; two inputs
    sharded DIFFERENTLY on the same dim conflict and raise (never a
    silent drop). ref: spmd_rules/elementwise.cc."""
    real = [s for s in in_specs if s is not None and len(s)]
    if not real:
        return tuple(in_specs), P()
    rank = max(len(s) for s in real)
    merged = [None] * rank
    for s in real:
        off = rank - len(s)  # right-align for broadcasting
        for i, d in enumerate(s):
            if d is None:
                continue
            j = off + i
            if merged[j] is not None and merged[j] != d:
                raise ValueError(
                    f"elementwise dim {j} sharded differently across "
                    f"inputs: {merged[j]} vs {d}")
            merged[j] = d
    return tuple(in_specs), P(*merged)


@register_rule("broadcast")
def broadcast(x_spec, *rest):
    return (x_spec, *rest), x_spec


@register_rule("reduction")
def reduction(x_spec, axis=None, keepdims=False):
    """Reduce: reduced dims' sharding drops (implies a psum when the
    reduced dim was sharded). ref: spmd_rules/reduction.cc."""
    if x_spec is None:
        return (None,), None
    dims = list(x_spec)
    if axis is None:
        return (x_spec,), P()
    ax = axis if isinstance(axis, (list, tuple)) else [axis]
    out = [d for i, d in enumerate(dims) if i not in
           [a % len(dims) for a in ax]]
    if keepdims:
        out = [None if i in [a % len(dims) for a in ax] else d
               for i, d in enumerate(dims)]
    return (x_spec,), P(*out)


@register_rule("matmul")
def matmul(x_spec, y_spec):
    """[.., M, K] @ [.., K, N]: K sharded on both -> partial (psum);
    M/N pass through; batch dims merge across operands (conflict
    raises). ref: spmd_rules/matmul.cc:116."""
    xs = list(x_spec) if x_spec is not None else [None, None]
    ys = list(y_spec) if y_spec is not None else [None, None]
    if len(xs) < 2 or len(ys) < 2:
        raise ValueError(
            "matmul rule covers rank>=2 operands; annotate 1-D "
            "operands replicated (GSPMD handles the vector forms)")
    bx, by = xs[:-2], ys[:-2]
    rank = max(len(bx), len(by))
    batch = [None] * rank
    for bs in (bx, by):
        off = rank - len(bs)
        for i, d in enumerate(bs):
            if d is None:
                continue
            j = off + i
            if batch[j] is not None and batch[j] != d:
                raise ValueError(
                    f"matmul batch dim {j} sharded differently: "
                    f"{batch[j]} vs {d}")
            batch[j] = d
    m, kx = xs[-2], xs[-1]
    ky, n = ys[-2], ys[-1]
    if kx is not None and ky is not None and kx != ky:
        raise ValueError(
            f"matmul contraction dim sharded differently: {kx} vs {ky}")
    return (x_spec, y_spec), P(*batch, m, n)


@register_rule("transpose")
def transpose(x_spec, perm=None):
    if x_spec is None or perm is None:
        return (x_spec,), x_spec
    dims = list(x_spec) + [None] * (len(perm) - len(x_spec))
    return (x_spec,), P(*[dims[p] for p in perm])


@register_rule("reshape")
def reshape(x_spec):
    """Reshape keeps only the leading-dim sharding (general dim-mapping
    reshape propagation is GSPMD's job). ref: spmd_rules/reshape.cc."""
    if x_spec is None or not len(x_spec):
        return (x_spec,), x_spec
    return (x_spec,), P(x_spec[0])


@register_rule("concat")
def concat(*in_specs, axis=0):
    base = next((s for s in in_specs if s is not None), P())
    dims = list(base)
    if len(dims) > axis:
        dims[axis] = None  # concat dim cannot stay sharded
    return tuple(in_specs), P(*dims)


@register_rule("split")
def split(x_spec, axis=0):
    if x_spec is None:
        return (None,), None
    dims = list(x_spec)
    if len(dims) > axis:
        dims[axis] = None
    return (x_spec,), P(*dims)


@register_rule("softmax")
def softmax(x_spec):
    """Softmax dim (last) must be unsharded; leading dims pass through.
    ref: spmd_rules/softmax.cc."""
    if x_spec is None:
        return (None,), None
    dims = list(x_spec)
    if dims and dims[-1] is not None:
        raise ValueError("softmax axis cannot be sharded")
    return (x_spec,), x_spec


@register_rule("embedding")
def embedding(ids_spec, w_spec):
    """Gather: ids batch sharding passes through; row-sharded tables
    need the mp allreduce the reference's c_embedding does.
    ref: spmd_rules/embedding.cc."""
    out = list(ids_spec) if ids_spec is not None else []
    hidden = None
    if w_spec is not None and len(w_spec) == 2:
        if w_spec[0] is not None:
            raise ValueError(
                "row-sharded embedding table needs VocabParallelEmbedding "
                "(masked gather + psum), not plain embedding")
        hidden = w_spec[1]
    return (ids_spec, w_spec), P(*out, hidden)


@register_rule("layer_norm")
def layer_norm(x_spec, *param_specs):
    """Normalized (trailing) dim unsharded; batch/seq pass through.
    ref: spmd_rules/layer_norm.cc."""
    if x_spec is not None and len(x_spec) and x_spec[-1] is not None:
        raise ValueError("layer_norm normalized dim cannot be sharded")
    return (x_spec, *param_specs), x_spec


@register_rule("rms_norm")
def rms_norm(x_spec, *param_specs):
    return layer_norm(x_spec, *param_specs)


@register_rule("batch_norm")
def batch_norm(x_spec, *rest):
    """Batch dims reduce into the channel stats: sharded batch implies a
    cross-device psum of the per-shard stats (data-parallel BN here
    computes per-shard batch stats, the DataParallel contract)."""
    return (x_spec, *rest), x_spec


@register_rule("dropout")
def dropout(x_spec, *rest):
    return (x_spec, *rest), x_spec


@register_rule("conv")
def conv(x_spec, w_spec, data_format="NCHW"):
    """Conv: batch sharding passes through, weights replicated, spatial
    dims unsharded (halo exchange is future work), input-channel
    sharding rejected (it would leave partial sums). data_format
    defaults to NCHW, matching the conv ops' own default — pass
    "NHWC"/"NLC"/"NDHWC" explicitly for channel-last layouts. Ranks 3-5
    (conv1d/2d/3d) are all validated."""
    if x_spec is not None and len(x_spec) >= 3:
        dims = list(x_spec)
        channel_last = data_format in ("NHWC", "NLC", "NWC", "NDHWC")
        ndim = len(dims)
        if channel_last:
            ch = ndim - 1
            spatial = tuple(range(1, ndim - 1))
        else:
            ch = 1
            spatial = tuple(range(2, ndim))
        if any(dims[i] is not None for i in spatial):
            raise ValueError(
                "spatially-sharded conv needs halo exchange — "
                "unsupported")
        if dims[ch] is not None:
            raise ValueError(
                "input-channel-sharded conv leaves partial sums "
                "(needs psum); reshard the channel dim first")
    if w_spec is not None and any(d is not None for d in w_spec):
        raise ValueError("conv weights must be replicated in this rule")
    out = list(x_spec) if x_spec is not None else [None] * 4
    return (x_spec, w_spec), P(*out)


@register_rule("cross_entropy")
def cross_entropy(logits_spec, label_spec):
    """Class dim unsharded (the mp-sharded variant is
    ParallelCrossEntropy); batch sharding implies psum of the mean."""
    if logits_spec is not None and len(logits_spec) and \
            logits_spec[-1] is not None:
        raise ValueError(
            "class-dim-sharded CE needs ParallelCrossEntropy "
            "(fleet.mp_layers), not plain cross_entropy")
    return (logits_spec, label_spec), P()


@register_rule("fused_ce")
def fused_ce(logits_spec, label_spec, *rest):
    return cross_entropy(logits_spec, label_spec)


@register_rule("rope")
def rope(x_spec, *rest):
    """Rotary embedding is positionwise over (seq, head_dim): any batch/
    head sharding passes; head_dim must be whole."""
    if x_spec is not None and len(x_spec) and x_spec[-1] is not None:
        raise ValueError("rope head_dim cannot be sharded")
    return (x_spec, *rest), x_spec


@register_rule("bias_act")
def bias_act(x_spec, *rest):
    return (x_spec, *rest), x_spec


@register_rule("scale")
def scale(x_spec, *rest):
    return (x_spec, *rest), x_spec


@register_rule("arg_reduce")
def arg_reduce(x_spec, axis=-1):
    if x_spec is None:
        return (None,), None
    dims = list(x_spec)
    if dims and dims[axis] is not None:
        raise ValueError("arg-reduce axis cannot be sharded")
    out = [d for i, d in enumerate(dims) if i != axis % len(dims)]
    return (x_spec,), P(*out)


# -- custom-kernel rules (the Pallas ops GSPMD cannot see through) --------

@register_rule("flash_attention")
def flash_attention(q_spec, k_spec, v_spec):
    """[B, L, H, D]: batch and head sharding decompose freely (each
    shard runs full attention over its rows); L-sharded inputs must go
    to ring attention (distributed.ring_attention) and D-sharded is
    invalid. ref: spmd_rules/flash_attention.cc."""
    for s in (q_spec, k_spec, v_spec):
        if s is None or len(s) != 4:
            continue
        if s[1] is not None:
            raise ValueError(
                "sequence-sharded flash attention must use "
                "ring_attention (context parallelism), not the dense "
                "kernel")
        if s[3] is not None:
            raise ValueError("head_dim cannot be sharded")
    base = q_spec if q_spec is not None else P(None, None, None, None)
    return (base, base, base), base


@register_rule("grouped_matmul")
def grouped_matmul(lhs_spec, rhs_spec, gs_spec=None):
    """lhs [T, K] x rhs [E, K, N]: expert-sharded rhs requires
    token-resharding by expert (the ep alltoall) BEFORE the kernel, so
    inside the kernel rhs must be whole per shard; token rows shard
    freely when every shard sees all experts. ref: the CUTLASS grouped
    GEMM's dispatch contract (fused_moe_kernel.cu)."""
    if rhs_spec is not None and len(rhs_spec) == 3:
        if rhs_spec[1] is not None or rhs_spec[2] is not None:
            raise ValueError("grouped_matmul K/N dims cannot be sharded")
        if rhs_spec[0] is not None and lhs_spec is not None and \
                lhs_spec[0] is not None:
            raise ValueError(
                "tokens and experts sharded together: dispatch tokens "
                "to their expert shard first (moe_dispatch alltoall)")
    out = P(lhs_spec[0] if lhs_spec is not None and len(lhs_spec)
            else None, None)
    return (lhs_spec, rhs_spec, gs_spec), out


@register_rule("moe_dispatch")
def moe_dispatch(tokens_spec, gate_spec=None):
    """Token-sharded input + expert-sharded FFN: the dispatch is an
    all-to-all over the ep axis (the reference's global_scatter), the
    combine its inverse. ref: spmd_rules/moe_gate_dispatch.cc."""
    return (tokens_spec, gate_spec), tokens_spec


# -- shard_map appliers for the custom kernels ----------------------------

def shard_map_flash_attention(mesh, q, k, v, *, batch_axis=None,
                              head_axis=None, causal=False, scale=None,
                              dropout_p=0.0, seed=None):
    """Run flash attention decomposed per the `flash_attention` rule:
    batch on ``batch_axis``, heads on ``head_axis`` — zero collectives
    in the forward (each shard is a full attention over its slice),
    which the HLO test asserts."""
    import jax

    from ..ops.pallas.flash_attention import flash_attention as _fa

    spec = P(batch_axis, None, head_axis, None)
    in_specs, out_spec = get_rule("flash_attention")(spec, spec, spec)

    def local(q_, k_, v_):
        return _fa(q_, k_, v_, causal, scale, dropout_p, seed)

    return jax.shard_map(local, mesh=mesh, in_specs=in_specs,
                         out_specs=out_spec, check_vma=False)(q, k, v)


def shard_map_grouped_matmul(mesh, lhs, rhs, group_sizes, *,
                             token_axis=None):
    """Grouped matmul with token rows sharded over ``token_axis`` and
    experts replicated (the `grouped_matmul` rule's collective-free
    decomposition). group_sizes must be per-shard counts."""
    from ..ops.pallas.grouped_matmul import grouped_matmul as _gmm

    lhs_spec = P(token_axis, None)
    in_specs, out_spec = get_rule("grouped_matmul")(
        lhs_spec, P(None, None, None), P(None))

    def local(l_, r_, gs_):
        return _gmm(l_, r_, gs_)

    import jax
    return jax.shard_map(local, mesh=mesh, in_specs=in_specs,
                         out_specs=out_spec, check_vma=False)(
        lhs, rhs, group_sizes)


def shard_map_moe_dispatch(mesh, tokens, gate_w, w_in, w_out, *, top_k,
                           capacity, act, ep_axis):
    """MoE forward with experts sharded over ``ep_axis``: tokens
    re-shard to their expert's device via the alltoall the rule implies
    (tested by HLO inspection for all-to-all, matching the reference's
    global_scatter contract)."""
    import jax

    from ..incubate.moe_dispatch import moe_forward_indices

    # pin expert-sharded weights AND token-sharded input/output per the
    # registered moe_dispatch rule: with both ends fixed, either GSPMD
    # moves tokens (all-to-all, the global_scatter contract) or it would
    # have to all-gather the full expert weights — the HLO test forbids
    # weight-shaped all-gathers, so the memory-saving decomposition is
    # what ships. (Unlike the other appliers this one constrains a
    # GSPMD program rather than shard_map-ing: the dispatch gather is
    # data-dependent, which GSPMD lowers to the alltoall directly.)
    from jax.sharding import NamedSharding
    (tok_spec, _), out_spec = get_rule("moe_dispatch")(P(ep_axis, None))
    tok = jax.lax.with_sharding_constraint(
        tokens, NamedSharding(mesh, tok_spec))
    wi = jax.lax.with_sharding_constraint(
        w_in, NamedSharding(mesh, P(ep_axis, None, None)))
    wo = jax.lax.with_sharding_constraint(
        w_out, NamedSharding(mesh, P(ep_axis, None, None)))
    out = moe_forward_indices(tok, gate_w, wi, wo, top_k, capacity, act)
    y = out[0] if isinstance(out, tuple) else out
    y = jax.lax.with_sharding_constraint(
        y, NamedSharding(mesh, out_spec))
    return (y,) + tuple(out[1:]) if isinstance(out, tuple) else y
