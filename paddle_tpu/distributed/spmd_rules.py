"""Per-op SPMD sharding rules: the explicit propagation table.

ref: paddle/phi/infermeta/spmd_rules/ (~60 per-op rules, e.g.
matmul.cc:116 MatmulInferSpmd, flash_attention.cc, moe_gate_dispatch.cc)
and the registry in phi/core/distributed/auto_parallel/inferspmd_utils.h.
The TPU build leans on GSPMD for most propagation, but GSPMD cannot see
through Pallas kernels: a pallas_call under pjit with sharded operands
would be replicated (or mis-sharded). The rules here produce the
`shard_map` in/out PartitionSpecs that pin the intended decomposition —
the direct analog of the reference's InferSpmd (input dist_attrs ->
output dist_attrs + required reshards).

Two consumers:
- ops.yaml `spmd:` entries name a rule per op; the native OpRegistry
  carries the name and `get_rule(name)` resolves it (tested so every
  named rule exists).
- `shard_*` helpers below apply the three custom-kernel rules (flash
  attention, grouped matmul, MoE dispatch) through shard_map, asserting
  the collectives the rule implies (HLO-inspected in tests).

A rule is `fn(*arg_specs, **shape_kwargs) -> (in_specs, out_specs)`
over jax.sharding.PartitionSpec. Unknown/unsupported input placements
raise — the caller falls back to replicate-with-GSPMD, never a silent
wrong decomposition (SURVEY §7 hard-parts list: "missing rules must fall
back to replicate-with-warning, not crash").
"""
from __future__ import annotations

from typing import Callable, Dict

from jax.sharding import PartitionSpec as P

__all__ = ["get_rule", "register_rule", "list_rules",
           "shard_map_flash_attention", "shard_map_grouped_matmul",
           "shard_map_moe_dispatch"]

_RULES: Dict[str, Callable] = {}


def register_rule(name: str):
    def deco(fn):
        _RULES[name] = fn
        return fn
    return deco


def get_rule(name: str) -> Callable:
    if name not in _RULES:
        raise KeyError(
            f"no SPMD rule {name!r} (known: {sorted(_RULES)}); GSPMD "
            f"propagation is the fallback")
    return _RULES[name]


def list_rules():
    return sorted(_RULES)


# -- generic families -----------------------------------------------------

@register_rule("elementwise")
def elementwise(*in_specs):
    """Same-rank elementwise: dims merge across inputs; two inputs
    sharded DIFFERENTLY on the same dim conflict and raise (never a
    silent drop). ref: spmd_rules/elementwise.cc."""
    real = [s for s in in_specs if s is not None and len(s)]
    if not real:
        return tuple(in_specs), P()
    rank = max(len(s) for s in real)
    merged = [None] * rank
    for s in real:
        off = rank - len(s)  # right-align for broadcasting
        for i, d in enumerate(s):
            if d is None:
                continue
            j = off + i
            if merged[j] is not None and merged[j] != d:
                raise ValueError(
                    f"elementwise dim {j} sharded differently across "
                    f"inputs: {merged[j]} vs {d}")
            merged[j] = d
    return tuple(in_specs), P(*merged)


@register_rule("broadcast")
def broadcast(x_spec, *rest):
    return (x_spec, *rest), x_spec


@register_rule("reduction")
def reduction(x_spec, axis=None, keepdims=False):
    """Reduce: reduced dims' sharding drops (implies a psum when the
    reduced dim was sharded). ref: spmd_rules/reduction.cc."""
    if x_spec is None:
        return (None,), None
    dims = list(x_spec)
    if axis is None:
        return (x_spec,), P()
    ax = axis if isinstance(axis, (list, tuple)) else [axis]
    out = [d for i, d in enumerate(dims) if i not in
           [a % len(dims) for a in ax]]
    if keepdims:
        out = [None if i in [a % len(dims) for a in ax] else d
               for i, d in enumerate(dims)]
    return (x_spec,), P(*out)


@register_rule("matmul")
def matmul(x_spec, y_spec):
    """[.., M, K] @ [.., K, N]: K sharded on both -> partial (psum);
    M/N pass through; batch dims merge across operands (conflict
    raises). ref: spmd_rules/matmul.cc:116."""
    xs = list(x_spec) if x_spec is not None else [None, None]
    ys = list(y_spec) if y_spec is not None else [None, None]
    if len(xs) < 2 or len(ys) < 2:
        raise ValueError(
            "matmul rule covers rank>=2 operands; annotate 1-D "
            "operands replicated (GSPMD handles the vector forms)")
    bx, by = xs[:-2], ys[:-2]
    rank = max(len(bx), len(by))
    batch = [None] * rank
    for bs in (bx, by):
        off = rank - len(bs)
        for i, d in enumerate(bs):
            if d is None:
                continue
            j = off + i
            if batch[j] is not None and batch[j] != d:
                raise ValueError(
                    f"matmul batch dim {j} sharded differently: "
                    f"{batch[j]} vs {d}")
            batch[j] = d
    m, kx = xs[-2], xs[-1]
    ky, n = ys[-2], ys[-1]
    if kx is not None and ky is not None and kx != ky:
        raise ValueError(
            f"matmul contraction dim sharded differently: {kx} vs {ky}")
    return (x_spec, y_spec), P(*batch, m, n)


@register_rule("transpose")
def transpose(x_spec, perm=None):
    if x_spec is None or perm is None:
        return (x_spec,), x_spec
    dims = list(x_spec) + [None] * (len(perm) - len(x_spec))
    return (x_spec,), P(*[dims[p] for p in perm])


@register_rule("reshape")
def reshape(x_spec):
    """Reshape keeps only the leading-dim sharding (general dim-mapping
    reshape propagation is GSPMD's job). ref: spmd_rules/reshape.cc."""
    if x_spec is None or not len(x_spec):
        return (x_spec,), x_spec
    return (x_spec,), P(x_spec[0])


@register_rule("concat")
def concat(*in_specs, axis=0):
    base = next((s for s in in_specs if s is not None), P())
    dims = list(base)
    if len(dims) > axis:
        dims[axis] = None  # concat dim cannot stay sharded
    return tuple(in_specs), P(*dims)


@register_rule("split")
def split(x_spec, axis=0):
    if x_spec is None:
        return (None,), None
    dims = list(x_spec)
    if len(dims) > axis:
        dims[axis] = None
    return (x_spec,), P(*dims)


@register_rule("softmax")
def softmax(x_spec):
    """Softmax dim (last) must be unsharded; leading dims pass through.
    ref: spmd_rules/softmax.cc."""
    if x_spec is None:
        return (None,), None
    dims = list(x_spec)
    if dims and dims[-1] is not None:
        raise ValueError("softmax axis cannot be sharded")
    return (x_spec,), x_spec


@register_rule("embedding")
def embedding(ids_spec, w_spec):
    """Gather: ids batch sharding passes through; row-sharded tables
    need the mp allreduce the reference's c_embedding does.
    ref: spmd_rules/embedding.cc."""
    out = list(ids_spec) if ids_spec is not None else []
    hidden = None
    if w_spec is not None and len(w_spec) == 2:
        if w_spec[0] is not None:
            raise ValueError(
                "row-sharded embedding table needs VocabParallelEmbedding "
                "(masked gather + psum), not plain embedding")
        hidden = w_spec[1]
    return (ids_spec, w_spec), P(*out, hidden)


@register_rule("layer_norm")
def layer_norm(x_spec, *param_specs):
    """Normalized (trailing) dim unsharded; batch/seq pass through.
    ref: spmd_rules/layer_norm.cc."""
    if x_spec is not None and len(x_spec) and x_spec[-1] is not None:
        raise ValueError("layer_norm normalized dim cannot be sharded")
    return (x_spec, *param_specs), x_spec


@register_rule("rms_norm")
def rms_norm(x_spec, *param_specs):
    return layer_norm(x_spec, *param_specs)


@register_rule("batch_norm")
def batch_norm(x_spec, *rest):
    """Batch dims reduce into the channel stats: sharded batch implies a
    cross-device psum of the per-shard stats (data-parallel BN here
    computes per-shard batch stats, the DataParallel contract)."""
    return (x_spec, *rest), x_spec


@register_rule("dropout")
def dropout(x_spec, *rest):
    return (x_spec, *rest), x_spec


@register_rule("conv")
def conv(x_spec, w_spec, data_format="NCHW"):
    """Conv: batch sharding passes through, weights replicated, spatial
    dims unsharded (halo exchange is future work), input-channel
    sharding rejected (it would leave partial sums). data_format
    defaults to NCHW, matching the conv ops' own default — pass
    "NHWC"/"NLC"/"NDHWC" explicitly for channel-last layouts. Ranks 3-5
    (conv1d/2d/3d) are all validated."""
    if x_spec is not None and len(x_spec) >= 3:
        dims = list(x_spec)
        channel_last = data_format in ("NHWC", "NLC", "NWC", "NDHWC")
        ndim = len(dims)
        if channel_last:
            ch = ndim - 1
            spatial = tuple(range(1, ndim - 1))
        else:
            ch = 1
            spatial = tuple(range(2, ndim))
        if any(dims[i] is not None for i in spatial):
            raise ValueError(
                "spatially-sharded conv needs halo exchange — "
                "unsupported")
        if dims[ch] is not None:
            raise ValueError(
                "input-channel-sharded conv leaves partial sums "
                "(needs psum); reshard the channel dim first")
    if w_spec is not None and any(d is not None for d in w_spec):
        raise ValueError("conv weights must be replicated in this rule")
    out = list(x_spec) if x_spec is not None else [None] * 4
    return (x_spec, w_spec), P(*out)


@register_rule("cross_entropy")
def cross_entropy(logits_spec, label_spec):
    """Class dim unsharded (the mp-sharded variant is
    ParallelCrossEntropy); batch sharding implies psum of the mean."""
    if logits_spec is not None and len(logits_spec) and \
            logits_spec[-1] is not None:
        raise ValueError(
            "class-dim-sharded CE needs ParallelCrossEntropy "
            "(fleet.mp_layers), not plain cross_entropy")
    return (logits_spec, label_spec), P()


@register_rule("fused_ce")
def fused_ce(logits_spec, label_spec, *rest):
    return cross_entropy(logits_spec, label_spec)


@register_rule("rope")
def rope(x_spec, *rest):
    """Rotary embedding is positionwise over (seq, head_dim): any batch/
    head sharding passes; head_dim must be whole."""
    if x_spec is not None and len(x_spec) and x_spec[-1] is not None:
        raise ValueError("rope head_dim cannot be sharded")
    return (x_spec, *rest), x_spec


@register_rule("bias_act")
def bias_act(x_spec, *rest):
    return (x_spec, *rest), x_spec


@register_rule("scale")
def scale(x_spec, *rest):
    return (x_spec, *rest), x_spec


@register_rule("arg_reduce")
def arg_reduce(x_spec, axis=-1):
    if x_spec is None:
        return (None,), None
    dims = list(x_spec)
    if dims and dims[axis] is not None:
        raise ValueError("arg-reduce axis cannot be sharded")
    out = [d for i, d in enumerate(dims) if i != axis % len(dims)]
    return (x_spec,), P(*out)


# -- indexing / gather-scatter family -------------------------------------
# These return CORRECTED in_specs where a cheap local reshard makes the
# decomposition valid (the reference's InferSpmd contract: input
# dist_attrs -> required reshards + output dist_attrs); they raise only
# when the right answer is a different op.

@register_rule("gather")
def gather(x_spec, index_spec, axis=0):
    """Gather rows along `axis`: the gathered dim must be whole on every
    shard (a row-sharded table would need the masked-gather+psum path);
    index sharding lands on the output at the axis position, x's other
    dims pass through. ref: spmd_rules/gather.cc."""
    xs = list(x_spec) if x_spec is not None else []
    idx = list(index_spec) if index_spec is not None else [None]
    if xs:
        ax = axis % len(xs)
        if xs[ax] is not None:
            raise ValueError(
                "gather axis is sharded: use the masked-gather+psum "
                "decomposition (VocabParallelEmbedding pattern) or "
                "reshard the table first")
        out = xs[:ax] + idx + xs[ax + 1:]
    else:
        out = idx
    return (x_spec, index_spec), P(*out)


@register_rule("gather_nd")
def gather_nd(x_spec, index_spec, index_depth=1):
    """x's first `index_depth` dims are pointed into and must be whole;
    out = index batch dims + x trailing dims.
    ref: spmd_rules/gather_nd.cc."""
    xs = list(x_spec) if x_spec is not None else []
    idx = list(index_spec) if index_spec is not None else [None]
    fixed = list(xs)
    for d in range(min(index_depth, len(fixed))):
        fixed[d] = None  # indexed dims: reshard to whole
    # the coordinate-depth (last) dim of the index must be whole too —
    # a shard holding half of every coordinate tuple gathers garbage
    fixed_idx = P(*idx[:-1], None) if idx else index_spec
    out = idx[:-1] + fixed[index_depth:]
    return (P(*fixed) if xs else x_spec, fixed_idx), P(*out)


@register_rule("scatter")
def scatter(x_spec, index_spec, updates_spec=None, axis=0):
    """Scatter along `axis`: the written dim is whole per shard, and —
    since every shard then holds the FULL axis — each shard must apply
    ALL writes: index and the updates' axis dim reshard whole too;
    non-axis update dims follow x's. ref: spmd_rules/scatter.cc."""
    xs = list(x_spec) if x_spec is not None else []
    if not xs:
        return (x_spec, index_spec, updates_spec), x_spec
    ax = axis % len(xs)
    fixed = list(xs)
    fixed[ax] = None
    fixed_idx = P(*([None] * len(index_spec))) \
        if index_spec is not None else None
    fixed_upd = None
    if updates_spec is not None:
        ud = list(fixed)  # non-axis dims co-sharded with x
        ud[ax] = None
        fixed_upd = P(*ud[:len(updates_spec)])
    return (P(*fixed), fixed_idx, fixed_upd), P(*fixed)


@register_rule("one_hot")
def one_hot(ids_spec, depth=None):
    """Output appends an UNSHARDED class dim to the index dims.
    ref: spmd_rules/one_hot.cc."""
    out = list(ids_spec) if ids_spec is not None else []
    return (ids_spec,), P(*out, None)


# -- shape-manipulation family --------------------------------------------

@register_rule("slice")
def slice_rule(x_spec, axes=()):
    """Sliced dims lose their sharding (a shard can't know which rows of
    a sliced range it owns without a gather); untouched dims pass.
    ref: spmd_rules/slice.cc sets sliced dims_mapping to -1."""
    if x_spec is None:
        return (None,), None
    dims = list(x_spec)
    for a in axes:
        if len(dims):
            dims[a % len(dims)] = None
    fixed = P(*dims)
    return (fixed,), fixed


@register_rule("stack")
def stack(*in_specs, axis=0):
    """Inputs merge elementwise-style; the new stack dim is unsharded.
    ref: spmd_rules/stack.cc."""
    _, merged = elementwise(*in_specs)
    dims = list(merged) if merged is not None else []
    ax = axis % (len(dims) + 1)
    return tuple(in_specs), P(*dims[:ax], None, *dims[ax:])


@register_rule("tile")
def tile(x_spec, repeats=()):
    """Tiled dims (repeat>1) lose sharding — each shard would need its
    neighbours' rows to build the repetition; repeat==1 dims pass.
    numpy/paddle semantics: a short `repeats` aligns to the TRAILING
    dims (jnp.tile pads repeats with leading 1s); extra repeats prepend
    new dims. ref: spmd_rules/tile.cc."""
    if x_spec is None:
        return (None,), None
    dims = list(x_spec)
    reps = list(repeats)
    rank = max(len(reps), len(dims))
    out = [None] * (rank - len(dims)) + dims          # right-align x
    reps_full = [1] * (rank - len(reps)) + reps       # right-align reps
    for i, r in enumerate(reps_full):
        if r != 1:
            out[i] = None
    fixed_in = P(*out[rank - len(dims):]) if dims else x_spec
    return (fixed_in,), P(*out)


@register_rule("pad")
def pad(x_spec, padded_dims=()):
    """Padded dims lose sharding (the shard holding the edge would need
    to know it's the global edge); others pass.
    ref: spmd_rules/pad.cc."""
    if x_spec is None:
        return (None,), None
    dims = list(x_spec)
    for d in padded_dims:
        if len(dims):
            dims[d % len(dims)] = None
    fixed = P(*dims)
    return (fixed,), fixed


@register_rule("squeeze")
def squeeze(x_spec, axis=None):
    """Removed size-1 dims can never be sharded; remaining shardings
    keep their dims. ref: spmd_rules/squeeze.cc."""
    if x_spec is None:
        return (None,), None
    dims = list(x_spec)
    if axis is None:
        return (x_spec,), x_spec  # shape-dependent: GSPMD handles
    ax = axis if isinstance(axis, (tuple, list)) else [axis]
    drop = {a % len(dims) for a in ax}
    return (x_spec,), P(*[d for i, d in enumerate(dims)
                          if i not in drop])


@register_rule("unsqueeze")
def unsqueeze(x_spec, axis=0):
    """New size-1 dim is unsharded; existing shardings shift.
    ref: spmd_rules/unsqueeze.cc."""
    if x_spec is None:
        return (None,), None
    dims = list(x_spec)
    ax = axis % (len(dims) + 1)
    return (x_spec,), P(*dims[:ax], None, *dims[ax:])


@register_rule("flatten")
def flatten(x_spec, start_axis=0, stop_axis=-1):
    """A collapsed [a, b, c] group keeps the LEADING dim's sharding iff
    the trailing members are unsharded (rows stay contiguous per shard);
    otherwise the group replicates. ref: spmd_rules/flatten.cc."""
    if x_spec is None:
        return (None,), None
    dims = list(x_spec)
    n = len(dims)
    lo, hi = start_axis % n, stop_axis % n
    group = dims[lo:hi + 1]
    keep = group[0] if all(d is None for d in group[1:]) else None
    fixed_in = dims[:lo] + [group[0] if keep is not None else None] \
        + [None] * (len(group) - 1) + dims[hi + 1:]
    out = dims[:lo] + [keep] + dims[hi + 1:]
    return (P(*fixed_in),), P(*out)


@register_rule("expand_as")
def expand_as(x_spec, y_spec=None, target_rank=None):
    """Right-align x into the target rank; broadcast (new) dims take the
    target's sharding — each shard materializes only its slice of the
    broadcast, which is free. ref: spmd_rules/expand_as.cc."""
    xs = list(x_spec) if x_spec is not None else []
    if y_spec is not None:
        out = list(y_spec)
    elif target_rank is not None:
        out = [None] * target_rank
    else:
        raise ValueError(
            "expand_as rule needs the target's spec or rank: returning "
            "x's spec unchanged would shard the wrong dims after a "
            "rank-growing broadcast (specs bind leading dims; "
            "broadcasting aligns trailing) — fall back to GSPMD")
    off = len(out) - len(xs)
    for i, d in enumerate(xs):
        if d is not None:
            out[off + i] = d  # x's sharding wins on shared dims
    return (x_spec, y_spec), P(*out)


@register_rule("cast")
def cast(x_spec):
    """Dtype-only: placement passes through untouched.
    ref: spmd_rules/cast.cc."""
    return (x_spec,), x_spec


@register_rule("add_n")
def add_n(*in_specs):
    """Sum of same-shape tensors: elementwise merge.
    ref: spmd_rules/add_n.cc."""
    return elementwise(*in_specs)


@register_rule("where")
def where(c_spec, x_spec=None, y_spec=None):
    """Three-way elementwise merge. ref: spmd_rules/where.cc."""
    return elementwise(c_spec, x_spec, y_spec)


@register_rule("triu")
def triu(x_spec):
    """Positionwise mask over the last two dims: any sharding passes
    (the iota offset is shard-local arithmetic). ref:
    spmd_rules/triu.cc."""
    return (x_spec,), x_spec


# -- scan / norm family ----------------------------------------------------

@register_rule("cumsum")
def cumsum(x_spec, axis=0):
    """The scanned dim carries a prefix dependency across shards: it
    must be whole (reshard in), other dims pass.
    ref: spmd_rules/cumsum.cc."""
    if x_spec is None:
        return (None,), None
    dims = list(x_spec)
    if dims:
        dims[axis % len(dims)] = None
    fixed = P(*dims)
    return (fixed,), fixed


@register_rule("p_norm")
def p_norm(x_spec, axis=None, keepdims=False):
    """Reduction semantics: reduced dims drop (partial per-shard norms
    combine via the psum GSPMD inserts — valid because sum-of-powers
    composes). ref: spmd_rules/p_norm.cc."""
    return reduction(x_spec, axis=axis, keepdims=keepdims)


@register_rule("logsumexp")
def logsumexp(x_spec, axis=None, keepdims=False):
    """ref: spmd_rules/logsumexp.cc — reduction-shaped propagation."""
    return reduction(x_spec, axis=axis, keepdims=keepdims)


@register_rule("squared_l2_norm")
def squared_l2_norm(x_spec):
    """Full reduce to a replicated scalar, any input sharding legal (the
    per-shard partial sums psum) — the grad-clip hot path the reference
    gives an explicit rule (spmd_rules/squared_l2_norm.cc) precisely so
    clip never forces a parameter all-gather."""
    return (x_spec,), P()


@register_rule("swiglu")
def swiglu(x_spec, y_spec=None):
    """Paired form silu(x)*y: elementwise merge (tp-sharded last dim is
    the mp_layers decomposition and passes). Packed single-input form
    splits the last dim in half, so ITS last dim must be whole.
    ref: spmd_rules/swiglu.cc."""
    if y_spec is not None:
        return elementwise(x_spec, y_spec)
    if x_spec is not None and len(x_spec) and x_spec[-1] is not None:
        raise ValueError(
            "packed swiglu halves its last dim: a sharded last dim "
            "interleaves gate/up across shards — pass gate and up "
            "separately (paired form) for tp")
    return (x_spec, None), x_spec


@register_rule("normalize")
def normalize(x_spec, axis=1):
    """F.normalize divides by the p-norm reduced along `axis`: that dim
    must be whole per shard (per-shard norms would be wrong); other
    dims pass. Same shape in/out."""
    if x_spec is None:
        return (None,), None
    dims = list(x_spec)
    if dims:
        dims[axis % len(dims)] = None
    fixed = P(*dims)
    return (fixed,), fixed


@register_rule("glu")
def glu(x_spec, axis=-1):
    """glu splits `axis` in half (a·sigmoid(b)): a sharded split dim
    would interleave the halves across shards — reshard it whole; the
    output halves the dim but keeps the other shardings."""
    if x_spec is None:
        return (None,), None
    dims = list(x_spec)
    if dims:
        dims[axis % len(dims)] = None
    fixed = P(*dims)
    return (fixed,), fixed


@register_rule("c_softmax_with_cross_entropy")
def c_softmax_with_cross_entropy(logits_spec, label_spec=None):
    """The CLASS-SHARDED softmax CE (the reference's mp collective op,
    fluid/operators/collective/c_softmax_with_cross_entropy_op.cu):
    class dim MAY be sharded — the max/sum reduce over the mp axis —
    and the loss keeps only the batch dims' sharding."""
    dims = list(logits_spec) if logits_spec is not None else [None]
    return (logits_spec, label_spec), P(*dims[:-1])


@register_rule("moe_combine")
def moe_combine(tokens_spec, gate_spec=None):
    """Inverse of moe_dispatch: the all-to-all returning expert outputs
    to their source rank; token sharding passes through.
    ref: spmd_rules/moe_combine.cc."""
    return (tokens_spec, gate_spec), tokens_spec


@register_rule("topk")
def topk(x_spec, axis=-1):
    """Selection along `axis` needs the whole dim per shard; other dims
    pass; values and indices share the output spec.
    ref: spmd_rules/topk.cc."""
    if x_spec is None:
        return (None,), (None, None)
    dims = list(x_spec)
    if dims:
        dims[axis % len(dims)] = None
    fixed = P(*dims)
    return (fixed,), (fixed, fixed)


@register_rule("argsort")
def argsort(x_spec, axis=-1):
    """Sorting a sharded dim would need a distributed sort network:
    reshard the axis whole; others pass. ref: spmd_rules/argsort.cc."""
    if x_spec is None:
        return (None,), None
    dims = list(x_spec)
    if dims:
        dims[axis % len(dims)] = None
    fixed = P(*dims)
    return (fixed,), fixed


@register_rule("take_along_axis")
def take_along_axis(x_spec, index_spec, axis=0):
    """Pointwise gather along `axis`: x's axis dim must be whole (any
    index row may point anywhere in it); the output has the INDEX's
    shape and inherits the index's sharding wholesale — an axis-sharded
    index is fine, each shard computes its own slice of the output.
    ref: spmd_rules/take_along_axis.cc."""
    xs = list(x_spec) if x_spec is not None else []
    idx = list(index_spec) if index_spec is not None else []
    if not xs:
        return (x_spec, index_spec), index_spec
    ax = axis % len(xs)
    # consistency: non-axis dims of x CO-SHARD with the index (each
    # shard must hold exactly the x rows its index rows point into);
    # the axis dim of x is whole; the output has the index's shape and
    # sharding
    fixed = [None if i == ax else (idx[i] if i < len(idx) else None)
             for i in range(len(xs))]
    return (P(*fixed), index_spec), index_spec


@register_rule("roll")
def roll(x_spec, axes=()):
    """Rolled dims wrap across shard boundaries: reshard them whole;
    untouched dims pass. ref: spmd_rules/... (roll ships in the
    reference's rule set as a shifted-layout op)."""
    return slice_rule(x_spec, axes=axes)


@register_rule("unbind")
def unbind(x_spec, axis=0):
    """Split into per-index views along `axis`: the unbound dim must be
    whole; each output drops it. ref: spmd_rules/unbind.cc."""
    if x_spec is None:
        return (None,), None
    dims = list(x_spec)
    ax = axis % len(dims) if dims else 0
    fixed = list(dims)
    if fixed:
        fixed[ax] = None
    out = [d for i, d in enumerate(fixed) if i != ax]
    return (P(*fixed),), P(*out)


# -- custom-kernel rules (the Pallas ops GSPMD cannot see through) --------

@register_rule("flash_attention")
def flash_attention(q_spec, k_spec, v_spec):
    """[B, L, H, D]: batch and head sharding decompose freely (each
    shard runs full attention over its rows); L-sharded inputs must go
    to ring attention (distributed.ring_attention) and D-sharded is
    invalid. ref: spmd_rules/flash_attention.cc."""
    for s in (q_spec, k_spec, v_spec):
        if s is None or len(s) != 4:
            continue
        if s[1] is not None:
            raise ValueError(
                "sequence-sharded flash attention must use "
                "ring_attention (context parallelism), not the dense "
                "kernel")
        if s[3] is not None:
            raise ValueError("head_dim cannot be sharded")
    base = q_spec if q_spec is not None else P(None, None, None, None)
    return (base, base, base), base


@register_rule("grouped_matmul")
def grouped_matmul(lhs_spec, rhs_spec, gs_spec=None):
    """lhs [T, K] x rhs [E, K, N]: expert-sharded rhs requires
    token-resharding by expert (the ep alltoall) BEFORE the kernel, so
    inside the kernel rhs must be whole per shard; token rows shard
    freely when every shard sees all experts. ref: the CUTLASS grouped
    GEMM's dispatch contract (fused_moe_kernel.cu)."""
    if rhs_spec is not None and len(rhs_spec) == 3:
        if rhs_spec[1] is not None or rhs_spec[2] is not None:
            raise ValueError("grouped_matmul K/N dims cannot be sharded")
        if rhs_spec[0] is not None and lhs_spec is not None and \
                lhs_spec[0] is not None:
            raise ValueError(
                "tokens and experts sharded together: dispatch tokens "
                "to their expert shard first (moe_dispatch alltoall)")
    out = P(lhs_spec[0] if lhs_spec is not None and len(lhs_spec)
            else None, None)
    return (lhs_spec, rhs_spec, gs_spec), out


@register_rule("moe_dispatch")
def moe_dispatch(tokens_spec, gate_spec=None):
    """Token-sharded input + expert-sharded FFN: the dispatch is an
    all-to-all over the ep axis (the reference's global_scatter), the
    combine its inverse. ref: spmd_rules/moe_gate_dispatch.cc."""
    return (tokens_spec, gate_spec), tokens_spec


# -- shard_map appliers for the custom kernels ----------------------------

def shard_map_flash_attention(mesh, q, k, v, *, batch_axis=None,
                              head_axis=None, causal=False, scale=None,
                              dropout_p=0.0, seed=None):
    """Run flash attention decomposed per the `flash_attention` rule:
    batch on ``batch_axis``, heads on ``head_axis`` — zero collectives
    in the forward (each shard is a full attention over its slice),
    which the HLO test asserts."""
    import jax

    from ..ops.pallas.flash_attention import flash_attention as _fa

    spec = P(batch_axis, None, head_axis, None)
    in_specs, out_spec = get_rule("flash_attention")(spec, spec, spec)

    def local(q_, k_, v_):
        return _fa(q_, k_, v_, causal, scale, dropout_p, seed)

    from ._mesh_axes import shard_map
    return shard_map(local, mesh=mesh, in_specs=in_specs,
                     out_specs=out_spec, check_vma=False)(q, k, v)


def shard_map_grouped_matmul(mesh, lhs, rhs, group_sizes, *,
                             token_axis=None):
    """Grouped matmul with token rows sharded over ``token_axis`` and
    experts replicated (the `grouped_matmul` rule's collective-free
    decomposition). group_sizes must be per-shard counts."""
    from ..ops.pallas.grouped_matmul import grouped_matmul as _gmm

    lhs_spec = P(token_axis, None)
    in_specs, out_spec = get_rule("grouped_matmul")(
        lhs_spec, P(None, None, None), P(None))

    def local(l_, r_, gs_):
        return _gmm(l_, r_, gs_)

    from ._mesh_axes import shard_map
    return shard_map(local, mesh=mesh, in_specs=in_specs,
                     out_specs=out_spec, check_vma=False)(
        lhs, rhs, group_sizes)


def shard_map_moe_dispatch(mesh, tokens, gate_w, w_in, w_out, *, top_k,
                           capacity, act, ep_axis):
    """MoE forward with experts sharded over ``ep_axis``: tokens
    re-shard to their expert's device via the alltoall the rule implies
    (tested by HLO inspection for all-to-all, matching the reference's
    global_scatter contract)."""
    import jax

    from ..incubate.moe_dispatch import moe_forward_indices

    # pin expert-sharded weights AND token-sharded input/output per the
    # registered moe_dispatch rule: with both ends fixed, either GSPMD
    # moves tokens (all-to-all, the global_scatter contract) or it would
    # have to all-gather the full expert weights — the HLO test forbids
    # weight-shaped all-gathers, so the memory-saving decomposition is
    # what ships. (Unlike the other appliers this one constrains a
    # GSPMD program rather than shard_map-ing: the dispatch gather is
    # data-dependent, which GSPMD lowers to the alltoall directly.)
    from jax.sharding import NamedSharding
    (tok_spec, _), out_spec = get_rule("moe_dispatch")(P(ep_axis, None))
    tok = jax.lax.with_sharding_constraint(
        tokens, NamedSharding(mesh, tok_spec))
    wi = jax.lax.with_sharding_constraint(
        w_in, NamedSharding(mesh, P(ep_axis, None, None)))
    wo = jax.lax.with_sharding_constraint(
        w_out, NamedSharding(mesh, P(ep_axis, None, None)))
    out = moe_forward_indices(tok, gate_w, wi, wo, top_k, capacity, act)
    y = out[0] if isinstance(out, tuple) else out
    y = jax.lax.with_sharding_constraint(
        y, NamedSharding(mesh, out_spec))
    return (y,) + tuple(out[1:]) if isinstance(out, tuple) else y
