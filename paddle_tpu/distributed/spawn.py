"""paddle.distributed.spawn: programmatic multi-process launch.

ref: python/paddle/distributed/spawn.py — starts nprocs worker processes
running ``func(*args)`` with the same rank environment the launch CLI
injects (PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_MASTER), and
joins them. Uses the multiprocessing "spawn" start method (fork is unsafe
once the XLA runtime is up).
"""
from __future__ import annotations

import multiprocessing as mp
import os
import socket
from typing import Optional, Sequence

__all__ = ["spawn", "ProcessContext"]


def _free_port_pair() -> int:
    """Pick P with P and P+1 both currently bindable (the collective
    TCPStore lives on master_port + 1). Best effort — the OS can still
    race us between probe and bind, but adjacent-pair probing removes
    the common collision with a sibling spawn's store port."""
    for _ in range(64):
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            p = s.getsockname()[1]
        try:
            with socket.socket() as s2:
                s2.bind(("127.0.0.1", p + 1))
            return p
        except OSError:
            continue
    raise RuntimeError("could not find a free adjacent port pair")


def _worker(func, args, rank, nprocs, master, env_extra, backend):
    os.environ["PADDLE_TRAINER_ID"] = str(rank)
    os.environ["PADDLE_TRAINERS_NUM"] = str(nprocs)
    os.environ["PADDLE_LOCAL_RANK"] = str(rank)
    os.environ["PADDLE_MASTER"] = master
    os.environ["MASTER_ADDR"], os.environ["MASTER_PORT"] = \
        master.rsplit(":", 1)
    for k, v in (env_extra or {}).items():
        os.environ[k] = str(v)
    func(*args)


class ProcessContext:
    """ref: spawn.py MultiprocessContext — join()/processes accessors."""

    def __init__(self, processes):
        self.processes = processes

    def join(self, timeout: Optional[float] = None) -> bool:
        for p in self.processes:
            p.join(timeout)
        failed = [p for p in self.processes
                  if p.exitcode not in (0, None)]
        if failed:
            raise RuntimeError(
                f"{len(failed)} spawned process(es) failed with exit "
                f"codes {[p.exitcode for p in failed]}")
        return all(p.exitcode == 0 for p in self.processes)


def spawn(func, args: Sequence = (), nprocs: int = -1, join: bool = True,
          daemon: bool = False, **options):
    """ref: spawn.py spawn(func, args, nprocs, join, daemon)."""
    if nprocs == -1:
        import sys
        nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", "0"))
        if nprocs <= 0 and "jax" in sys.modules:
            # only consult jax if the runtime is ALREADY up — importing
            # it here would acquire the accelerator in the parent and
            # starve every spawned worker
            nprocs = max(sys.modules["jax"].local_device_count(), 1)
        if nprocs <= 0:
            raise ValueError(
                "spawn(nprocs=-1) cannot infer the process count before "
                "the runtime is initialized; pass nprocs= explicitly or "
                "set PADDLE_TRAINERS_NUM")
    master = options.get(
        "master", f"127.0.0.1:{options.get('port', _free_port_pair())}")
    ctx = mp.get_context("spawn")
    procs = []
    for rank in range(nprocs):
        p = ctx.Process(
            target=_worker,
            args=(func, tuple(args), rank, nprocs, master,
                  options.get("env"), options.get("backend")),
            daemon=daemon)
        p.start()
        procs.append(p)
    context = ProcessContext(procs)
    if join:
        context.join()
    return context
