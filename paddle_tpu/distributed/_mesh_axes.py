"""Shared mesh-axis classification for the context-parallel attention
paths (ring_attention / ulysses): conventional batch-like and head-like
axis names pass through shard_map untouched on their natural dims.

Also the ONE home of the ``shard_map`` symbol: jax moved it from
``jax.experimental.shard_map`` to ``jax.shard_map`` and 0.4.37 ships a
window where only the experimental spelling exists — every caller in
this package (and the tests) imports the alias from here instead of
betting on a jax version."""
from __future__ import annotations

try:
    from jax import shard_map as _shard_map_impl
except ImportError:
    from jax.experimental.shard_map import shard_map as _shard_map_impl


def _detect_check_kw():
    """Which replication-check kwarg the resolved shard_map accepts —
    decided by signature, not import location: some jax releases expose
    the top-level name while still spelling the kwarg check_rep."""
    import inspect
    try:
        params = inspect.signature(_shard_map_impl).parameters
    except (TypeError, ValueError):
        return "check_rep"  # conservative: the 0.4.x spelling
    for kw in ("check_vma", "check_rep"):
        if kw in params:
            return kw
    return None  # neither: drop the kwarg (it only tunes a safety check)


_CHECK_KW = _detect_check_kw()


def shard_map(f, *args, **kwargs):
    """jax.shard_map / jax.experimental.shard_map compat shim: accepts
    either spelling of the replication-check kwarg and forwards the one
    the resident jax understands."""
    if "check_vma" in kwargs:
        check = kwargs.pop("check_vma")
    elif "check_rep" in kwargs:
        check = kwargs.pop("check_rep")
    else:
        check = None
    if check is not None and _CHECK_KW is not None:
        kwargs[_CHECK_KW] = check
    return _shard_map_impl(f, *args, **kwargs)

BATCH_AXIS_NAMES = ("dp", "fsdp", "data", "sharding")
HEAD_AXIS_NAMES = ("mp", "tp", "model")


def classify_axes(jmesh, seq_axis: str):
    """Returns (batch_axes, head_axes) among the mesh axes != seq_axis."""
    others = [a for a in jmesh.axis_names if a != seq_axis]
    batch_axes = tuple(a for a in others if a in BATCH_AXIS_NAMES)
    head_axes = tuple(a for a in others if a in HEAD_AXIS_NAMES)
    return batch_axes, head_axes
