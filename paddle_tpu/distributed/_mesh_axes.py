"""Shared mesh-axis classification for the context-parallel attention
paths (ring_attention / ulysses): conventional batch-like and head-like
axis names pass through shard_map untouched on their natural dims."""
from __future__ import annotations

BATCH_AXIS_NAMES = ("dp", "fsdp", "data", "sharding")
HEAD_AXIS_NAMES = ("mp", "tp", "model")


def classify_axes(jmesh, seq_axis: str):
    """Returns (batch_axes, head_axes) among the mesh axes != seq_axis."""
    others = [a for a in jmesh.axis_names if a != seq_axis]
    batch_axes = tuple(a for a in others if a in BATCH_AXIS_NAMES)
    head_axes = tuple(a for a in others if a in HEAD_AXIS_NAMES)
    return batch_axes, head_axes
