"""Distributed API odds and ends: ParallelMode / ReduceType enums,
gather, wait, gloo_* CPU-rendezvous helpers.

ref: python/paddle/distributed/fleet/base/topology.py:42 (ParallelMode),
paddle/phi/core/distributed/auto_parallel/dist_attr.h ReduceType,
python/paddle/distributed/communication/gather.py, parallel.py
(gloo_init_parallel_env / gloo_barrier / gloo_release — here the TCPStore
plays gloo's rendezvous role).
"""
from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from . import collective as coll

__all__ = ["ParallelMode", "ReduceType", "gather", "wait",
           "gloo_init_parallel_env", "gloo_barrier", "gloo_release"]


class ParallelMode:
    """ref: fleet/base/topology.py:42."""

    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3


class ReduceType:
    """ref: phi ReduceType (dist_attr.h) — partial-placement reductions."""

    kRedSum = 0
    kRedMax = 1
    kRedMin = 2
    kRedProd = 3
    kRedAvg = 4
    kRedAny = 5
    kRedAll = 6


def gather(tensor, gather_list: Optional[List] = None, dst: int = 0,
           group=None, sync_op: bool = True):
    """ref: communication/gather.py — dst collects every rank's tensor
    into gather_list; other ranks pass gather_list=None."""
    g = coll._get_group(group)
    m = coll._mode(g)
    if m == "local":
        if gather_list is not None:
            for _ in range(g.nranks):
                gather_list.append(Tensor(jnp.asarray(coll._unwrap(tensor))))
        return coll.Task([])
    dr = g.get_group_rank(dst)
    if m == "store":
        st = coll._comm_store()
        base = f"c{g.id}/ga/{coll._next_seq(g, 'ga')}"
        if g.rank == dr:
            parts = []
            for i in range(g.nranks):
                if i == dr:
                    parts.append(np.asarray(coll._unwrap(tensor)))
                else:
                    import pickle
                    parts.append(pickle.loads(st.take(f"{base}/{i}")))
            if gather_list is not None:
                gather_list.extend(Tensor(jnp.asarray(p)) for p in parts)
        else:
            st.set(f"{base}/{g.rank}", coll._pack(coll._unwrap(tensor)))
        return coll.Task([])
    tmp: List = []
    coll.all_gather(tmp, tensor, group=g)
    if g.rank == dr and gather_list is not None:
        gather_list.extend(tmp)
    return coll.Task([])


def wait(tensor, group=None, use_calc_stream: bool = True):
    """ref: communication/wait.py — barrier on a tensor's readiness. On
    TPU a host value fetch is the only trustworthy barrier."""
    arr = coll._unwrap(tensor)
    if hasattr(arr, "block_until_ready"):
        arr.block_until_ready()
    return None


_gloo_ready = False


def gloo_init_parallel_env(rank_id: int, rank_num: int,
                           server_endpoint: str):
    """ref: parallel.py gloo_init_parallel_env — CPU-only rendezvous; the
    TCPStore is the gloo-equivalent coordinator here."""
    import os
    global _gloo_ready
    # explicit arguments WIN over whatever is in the environment — a
    # leaked PADDLE_TRAINER_ID must not silently alias two ranks
    os.environ["PADDLE_TRAINER_ID"] = str(rank_id)
    os.environ["PADDLE_TRAINERS_NUM"] = str(rank_num)
    os.environ["PADDLE_MASTER"] = server_endpoint
    coll._comm_store()  # brings up / connects the store
    _gloo_ready = True


def gloo_barrier():
    """ref: parallel.py gloo_barrier."""
    coll.barrier()


def gloo_release():
    """ref: parallel.py gloo_release."""
    global _gloo_ready
    coll.destroy_process_group()
    _gloo_ready = False
