"""Built-in trial runner: the auto-tuner actually builds, compiles,
memory-gates, and times each parallel config.

ref: python/paddle/distributed/auto_tuner/tuner.py:21 + prune.py — the
reference spawns launch jobs per trial and prunes by recorded OOM
signatures. TPU-native: a trial is one compiled DistTrainStep over the
candidate mesh; XLA's compile-time memory analysis gives the OOM verdict
BEFORE paying for execution (chipless — the compiler knows peak bytes),
then a few timed steps produce the throughput metric.
"""
from __future__ import annotations

import time
from typing import Callable, Dict, Optional

import numpy as np

__all__ = ["build_trial_runner", "MemoryBudgetExceeded"]


class MemoryBudgetExceeded(RuntimeError):
    """Config pruned by the compile-time memory model."""


def build_trial_runner(make_model: Callable[[], object],
                       shard_model: Callable,
                       make_optimizer: Callable,
                       loss_fn: Callable,
                       make_batch: Callable[[Dict], tuple],
                       mesh_axes=("dp", "mp"),
                       steps: int = 3,
                       hbm_bytes: Optional[int] = None,
                       devices=None) -> Callable[[Dict], float]:
    """Returns trial_fn(config) -> tokens-or-items per second.

    make_model() -> Layer (fresh per trial);
    shard_model(model, mesh, config) applies the candidate's placements;
    make_optimizer(model) -> optimizer;
    make_batch(config) -> tuple of arrays (inputs..., labels);
    config keys "<axis>_degree" shape the mesh over `devices`.
    A config whose compiled peak (args + temps) exceeds ``hbm_bytes``
    raises MemoryBudgetExceeded — recorded as a failed trial, exactly how
    the reference records OOM trials.
    """
    import jax

    from ..dist_train import DistTrainStep
    from ..process_mesh import ProcessMesh

    devs = list(devices if devices is not None else jax.devices())

    def trial(config: Dict) -> float:
        degrees = [int(config.get(f"{a}_degree", 1)) for a in mesh_axes]
        pp = int(config.get("pp_degree", 1))
        n = int(np.prod(degrees)) * pp
        if n > len(devs):
            raise ValueError(
                f"config needs {n} devices, have {len(devs)}")
        if pp > 1:
            # pipeline candidate (planner v2): time the compiled-GPipe
            # executor the Engine would realize it with. Same
            # realizability contract as the Engine — a config this
            # executor can't faithfully run records as a FAILED trial
            # rather than a mislabeled measurement.
            bad = [a for a in mesh_axes
                   if a != "dp" and int(config.get(f"{a}_degree", 1)) > 1]
            if bad:
                raise ValueError(
                    f"pipeline trials run non-pp axes as pure data "
                    f"parallel; config also asks for {bad} — "
                    f"unrealizable, recording as failed")
            sched = config.get("pp_schedule", "gpipe")
            if sched != "gpipe":
                raise ValueError(
                    f"pipeline executor runs the GPipe schedule; "
                    f"cannot measure {sched!r} — price the planner "
                    f"with schedules=('gpipe',)")
            from ..auto_parallel.engine_pp import PipelineTrainStep
            model = make_model()
            pstep = PipelineTrainStep(model, loss_fn,
                                      make_optimizer(model), pp=pp,
                                      n_devices=n, devices=devs[:n])
            batch = make_batch(config)
            if hbm_bytes is not None:
                est = pstep.estimate_peak_bytes(*batch)
                if est > hbm_bytes:
                    raise MemoryBudgetExceeded(
                        f"estimated peak {est / 1e6:.1f}MB exceeds "
                        f"budget {hbm_bytes / 1e6:.1f}MB "
                        f"(jaxpr-liveness model; pipeline trial)")
            # time the bare jitted step (threaded donated state), the
            # same footing as the flat branch — __call__'s per-step
            # host upload + write-back would bias the comparison
            import jax.numpy as jnp
            pstep._build()
            pstate = pstep._init_opt_state()
            pparams = pstep._params
            raw = [jnp.asarray(np.asarray(b)) for b in batch]
            lr = jnp.float32(0.0)

            def pone(params, state):
                return pstep._jitted(params, state, lr, raw[0],
                                     tuple(raw[1:]))

            loss, pparams, pstate = pone(pparams, pstate)
            float(loss)
            t0 = time.perf_counter()
            for _ in range(steps):
                loss, pparams, pstate = pone(pparams, pstate)
            float(loss)
            dt = (time.perf_counter() - t0) / steps
            return int(np.asarray(batch[0]).shape[0]) / dt
        mesh = ProcessMesh(
            np.arange(n).reshape(degrees), dim_names=list(mesh_axes))
        model = make_model()
        shard_model(model, mesh, config)
        step = DistTrainStep(model, loss_fn, make_optimizer(model))
        batch = make_batch(config)

        mem, compiled, (params, buffers, opt_state, raw) = \
            step.compile_stats(*batch, return_compiled=True)
        # donated outputs (new params/opt state) alias their argument
        # buffers at runtime — count the aliased bytes once
        peak = (mem.argument_size_in_bytes + mem.temp_size_in_bytes +
                max(mem.output_size_in_bytes - mem.alias_size_in_bytes, 0))
        if hbm_bytes is not None and peak > hbm_bytes:
            raise MemoryBudgetExceeded(
                f"compiled peak {peak / 1e6:.1f}MB exceeds budget "
                f"{hbm_bytes / 1e6:.1f}MB "
                f"(args {mem.argument_size_in_bytes}, "
                f"temps {mem.temp_size_in_bytes}, "
                f"aliased {mem.alias_size_in_bytes})")

        # time through the SAME executable (no second compile); donated
        # buffers force threading the state forward between calls
        import jax
        import jax.numpy as jnp
        lr = jnp.float32(0.0)
        rng = (jax.random.key(0), jnp.uint32(0))

        def one(params, buffers, opt_state, rng):
            return compiled(params, buffers, opt_state, lr, rng, *raw)

        loss, params, buffers, opt_state, rng = one(params, buffers,
                                                    opt_state, rng)
        float(loss)  # warm + barrier
        t0 = time.perf_counter()
        for _ in range(steps):
            loss, params, buffers, opt_state, rng = one(
                params, buffers, opt_state, rng)
        float(loss)
        dt = (time.perf_counter() - t0) / steps
        # donation consumed the step's original param/buffer/opt-state
        # buffers — re-sync the threaded-through state so the step (and
        # the model it wraps) stays usable after the trial
        step._resync(params, buffers, opt_state)
        items = int(np.asarray(batch[0]).shape[0])
        return items / dt

    return trial
