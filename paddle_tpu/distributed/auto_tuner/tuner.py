"""Auto-tuner core.

ref: auto_tuner/tuner.py:21 (AutoTuner: search_once/get_best loop),
search.py (GridSearch over the cartesian candidate space), prune.py
(registered prune rules), recorder.py (sorted history + best).
"""
from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

__all__ = ["SearchSpace", "Prune", "Recorder", "AutoTuner"]


@dataclass
class SearchSpace:
    """Candidate axes (ref: the tuner's default space over hybrid dims)."""
    num_devices: int = 8
    dp_degree: Sequence[int] = (1, 2, 4, 8)
    mp_degree: Sequence[int] = (1, 2, 4, 8)
    pp_degree: Sequence[int] = (1, 2, 4)
    sharding_degree: Sequence[int] = (1, 2, 4, 8)
    sharding_stage: Sequence[int] = (1, 2, 3)
    micro_batch_size: Sequence[int] = (1, 2, 4, 8)
    global_batch_size: int = 8
    num_layers: int = 24

    def candidates(self) -> List[Dict]:
        out = []
        for dp, mp, pp, sh_deg, sh_st, mbs in itertools.product(
                self.dp_degree, self.mp_degree, self.pp_degree,
                self.sharding_degree, self.sharding_stage,
                self.micro_batch_size):
            out.append({
                "dp_degree": dp, "mp_degree": mp, "pp_degree": pp,
                "sharding_degree": sh_deg, "sharding_stage": sh_st,
                "micro_batch_size": mbs,
            })
        return out


class Prune:
    """Registered prune rules (ref: prune.py @register_prune functions).
    Each rule returns True if the candidate should be DROPPED."""

    def __init__(self, space: SearchSpace):
        self.space = space
        self.rules: List[Callable[[Dict], bool]] = [
            self._prune_by_device_product,
            self._prune_by_batch_divisibility,
            self._prune_by_layer_divisibility,
            self._prune_sharding_with_dp,
            self._prune_degenerate_sharding_stage,
        ]

    def _prune_by_device_product(self, c) -> bool:
        # dp*mp*pp*sharding must cover exactly the device count
        return (c["dp_degree"] * c["mp_degree"] * c["pp_degree"]
                * c["sharding_degree"]) != self.space.num_devices

    def _prune_by_batch_divisibility(self, c) -> bool:
        per_dp = self.space.global_batch_size / (
            c["dp_degree"] * c["sharding_degree"])
        if per_dp != int(per_dp) or per_dp < 1:
            return True
        return int(per_dp) % c["micro_batch_size"] != 0

    def _prune_by_layer_divisibility(self, c) -> bool:
        return self.space.num_layers % c["pp_degree"] != 0

    def _prune_degenerate_sharding_stage(self, c) -> bool:
        # stages are indistinguishable at sharding_degree 1: keep only
        # stage 1 so duplicate configs aren't trialed repeatedly
        return c["sharding_degree"] == 1 and c["sharding_stage"] > 1

    def _prune_sharding_with_dp(self, c) -> bool:
        # stage-3 with plain dp>1 duplicates params per dp replica for no
        # benefit (ref prune rule: prefer folding dp into sharding)
        return c["sharding_stage"] == 3 and c["dp_degree"] > 1

    def keep(self, c: Dict) -> bool:
        return not any(rule(c) for rule in self.rules)


@dataclass
class Recorder:
    """ref: recorder.py — history sorted by the metric (lower=better time
    or higher=better throughput)."""
    higher_is_better: bool = True
    history: List[Dict] = field(default_factory=list)

    def add(self, cfg: Dict, metric: Optional[float], error: str = ""):
        self.history.append({"config": cfg, "metric": metric,
                             "error": error})

    def best(self) -> Optional[Dict]:
        ok = [h for h in self.history if h["metric"] is not None]
        if not ok:
            return None
        return (max if self.higher_is_better else min)(
            ok, key=lambda h: h["metric"])


class AutoTuner:
    """ref: tuner.py:21. trial_fn(config) -> metric (throughput); raise to
    mark the config failed (e.g. OOM)."""

    def __init__(self, space: SearchSpace,
                 trial_fn: Callable[[Dict], float],
                 higher_is_better: bool = True,
                 max_trials: Optional[int] = None):
        self.space = space
        self.trial_fn = trial_fn
        self.prune = Prune(space)
        self.recorder = Recorder(higher_is_better)
        self.max_trials = max_trials
        self._pending = [c for c in space.candidates()
                         if self.prune.keep(c)]

    @property
    def pending(self) -> List[Dict]:
        return list(self._pending)

    def search_once(self) -> Optional[Dict]:
        """Run the next candidate; returns its record or None when done."""
        if not self._pending:
            return None
        if self.max_trials is not None and \
                len(self.recorder.history) >= self.max_trials:
            return None
        cfg = self._pending.pop(0)
        try:
            metric = self.trial_fn(cfg)
            self.recorder.add(cfg, float(metric))
        except Exception as e:  # trial failure (OOM...) is data, not fatal
            self.recorder.add(cfg, None, error=f"{type(e).__name__}: {e}")
        return self.recorder.history[-1]

    def tune(self) -> Optional[Dict]:
        """Run all candidates (up to max_trials); returns the best record."""
        while self.search_once() is not None:
            pass
        return self.recorder.best()
