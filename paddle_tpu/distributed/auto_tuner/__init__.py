"""Auto-tuner: black-box search over parallel configurations.

ref: python/paddle/distributed/auto_tuner/{tuner,search,prune,recorder}.py
— enumerate (dp, mp, pp, sharding-stage, micro-batch) candidates, prune
infeasible ones (divisibility, memory model), run timed trials, record and
rank. The TPU build reuses the same harness shape with a mesh-aware
candidate space; trials are callables so tests can stub the runner.
"""
from .tuner import AutoTuner, Prune, Recorder, SearchSpace  # noqa: F401
from .runner import MemoryBudgetExceeded, build_trial_runner  # noqa: F401
