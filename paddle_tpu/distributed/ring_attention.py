"""Ring attention: context parallelism over the ICI ring.

ABSENT in the reference (SURVEY.md §2.2 flags no ring/Ulysses/blockwise CP
in the snapshot — its long-context story stops at flash attention + Megatron
SP). This is the TPU-native fill: sequence-sharded Q/K/V, with K/V blocks
rotated around the mesh axis via jax.lax.ppermute while each device
accumulates its queries' online softmax — compute and ICI transfer overlap,
memory per chip stays O(L/n), total sequence scales with the ring size.

Layout [B, L, H, D], L sharded on the `axis` mesh dim. Causality is
enforced with global position ids, so the result is bit-for-bit the same
math as full causal attention over the unsharded sequence.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ["ring_attention", "ring_self_attention"]

_NEG = -1e30


def _ring_attn_local(q, k, v, axis: str, scale: float, causal: bool):
    """Runs inside shard_map: q/k/v are the local sequence shards."""
    n = jax.lax.psum(1, axis)
    idx = jax.lax.axis_index(axis)
    b, lq, h, d = q.shape
    lk = k.shape[1]

    qf = q.astype(jnp.float32) * scale
    q_pos = idx * lq + jax.lax.broadcasted_iota(jnp.int32, (lq, lk), 0)

    def step(i, carry):
        k_cur, v_cur, m, l, acc = carry
        # the kv block this device holds at step i originated on rank idx-i
        src = (idx - i) % n
        logits = jnp.einsum("blhd,bkhd->bhlk", qf,
                            k_cur.astype(jnp.float32))
        if causal:
            k_pos = src * lk + jax.lax.broadcasted_iota(
                jnp.int32, (lq, lk), 1)
            keep = (q_pos >= k_pos)[None, None]
            logits = jnp.where(keep, logits, _NEG)
        m_new = jnp.maximum(m, logits.max(axis=-1, keepdims=True))
        # guard: a fully-masked block must contribute zero probability even
        # when m_new is still the -inf sentinel
        p = jnp.where(logits > _NEG / 2, jnp.exp(logits - m_new), 0.0)
        alpha = jnp.exp(jnp.maximum(m, _NEG) -
                        jnp.maximum(m_new, _NEG))
        alpha = jnp.where(m > _NEG / 2, alpha, 0.0)
        l_new = alpha * l + p.sum(axis=-1, keepdims=True)
        acc_new = alpha * acc + jnp.einsum(
            "bhlk,bkhd->bhld", p, v_cur.astype(jnp.float32))
        # rotate kv one hop around the ring for the next step
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_nxt = jax.lax.ppermute(k_cur, axis, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis, perm)
        return k_nxt, v_nxt, m_new, l_new, acc_new

    m0 = jnp.full((b, h, lq, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((b, h, lq, 1), jnp.float32)
    acc0 = jnp.zeros((b, h, lq, d), jnp.float32)
    _, _, m, l, acc = jax.lax.fori_loop(
        0, n, step, (k, v, m0, l0, acc0))
    out = acc / jnp.maximum(l, 1e-30)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)  # [B, Lq, H, D]


def ring_attention(q, k, v, mesh, axis: str = "sp", causal: bool = True,
                   scale: Optional[float] = None):
    """q/k/v: [B, L, H, D] jax arrays (or already seq-sharded on `axis`).
    Returns attention output with the same sharding. Other mesh axes may
    shard batch/heads; they pass through untouched.
    """
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    jmesh = mesh.to_jax_mesh() if hasattr(mesh, "to_jax_mesh") else mesh
    # full-manual shard_map: map the other mesh axes onto their
    # conventional dims (data axes -> batch, model axes -> heads) so dp/tp
    # shardings ride through instead of being all-gathered per device
    from ._mesh_axes import classify_axes, shard_map
    batch_axes, head_axes = classify_axes(jmesh, axis)
    spec = P(batch_axes or None, axis, head_axes or None, None)
    fn = shard_map(
        functools.partial(_ring_attn_local, axis=axis, scale=s,
                          causal=causal),
        mesh=jmesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)


def ring_self_attention(q, k, v, mesh, axis: str = "sp", causal: bool = True,
                        scale: Optional[float] = None):
    """Tensor-level wrapper recording one autograd node (eager API)."""
    from ..core.autograd import apply_op
    return apply_op(
        lambda a, b, c: ring_attention(a, b, c, mesh, axis, causal, scale),
        q, k, v, op_name="ring_attention")


# analysis-plane aval registration (the flash_attention pattern, see
# ops/pallas/flash_attention.py): ring attention computes EXACT causal
# attention — the ring is a memory/comm schedule, not a different
# function — so its aval reference is the sdpa oracle cast back to the
# query dtype, exactly what the sharded entry point returns.
def _ring_attention_aval_ref(q, k, v):
    from ..ops.pallas.flash_attention import _sdpa_xla
    return _sdpa_xla(q, k, v, causal=True).astype(q.dtype)


def _register_aval_impls() -> None:
    from ..core.fusion import register_param_impl
    register_param_impl("ring_attention", _ring_attention_aval_ref)


_register_aval_impls()
