"""Sharded state_dict save/load.

ref: python/paddle/distributed/checkpoint/save_state_dict.py:145
(save_state_dict: local shard write + metadata, cross-rank dedup of
replicated tensors at :117 dedup_tensor) and load_state_dict.py (reshard
on read). TPU-native: a jax.Array's addressable_shards give exactly the
(global_offset, local_shape) pairs the reference records; load assembles
the global value from shard files then device_puts with the target
sharding — changed mesh/placement works by construction.
"""
from __future__ import annotations

import json
import os
import pickle
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

from ...core.tensor import Tensor

__all__ = ["save_state_dict", "load_state_dict", "Metadata",
           "LocalTensorMetadata"]


@dataclass
class LocalTensorMetadata:
    """ref: checkpoint/metadata.py:20."""
    global_offset: Tuple[int, ...]
    local_shape: Tuple[int, ...]
    dtype: str
    file_name: str


@dataclass
class Metadata:
    """ref: checkpoint/metadata.py:41."""
    state_dict_metadata: Dict[str, List[LocalTensorMetadata]] = \
        field(default_factory=dict)
    flat_mapping: Dict[str, str] = field(default_factory=dict)


def _process_id() -> int:
    return jax.process_index()


def _barrier_if_multiprocess(process_group=None):
    """Synchronize save phases across the SAVING group's controllers.
    Without it the coordinator can merge metadata parts other ranks
    haven't written yet (the rank-0 metadata race), and a fast rank
    could return from save — and immediately load — before
    metadata.json exists (writer collision with the previous save's
    file). The caller's process_group is honored: barriering the whole
    world from a subgroup save would hang on the non-participants."""
    if jax.process_count() <= 1:
        return
    from ..collective import barrier
    barrier(group=process_group)


def _participants(process_group) -> List[int]:
    """Process ids taking part in this save (the rank set whose
    metadata parts the coordinator merges — stale parts from an earlier
    larger-world save into the same path must NOT leak in)."""
    ranks = getattr(process_group, "ranks", None)
    if ranks:
        return sorted(int(x) for x in ranks)
    return list(range(jax.process_count()))


def save_state_dict(state_dict: Dict, path: str,
                    process_group=None, coordinator_rank: int = 0):
    """ref: save_state_dict.py:145. Layout on disk:
    path/{key}__{shard_idx}.npy per local shard + path/metadata.json
    (written by the coordinator; single-controller writes everything).

    Multi-controller contract (each process writes ONLY its addressable
    shards): cross-rank dedup of replicated copies picks the
    replica_id==0 shard — exactly one process on the mesh owns each
    (key, offset) — matching the reference's dedup_tensor assignment of
    replicated tensors to a single writer (ref: save_state_dict.py:117);
    two barriers order shard-writes < metadata merge < return."""
    os.makedirs(path, exist_ok=True)
    meta = Metadata()
    rank = _process_id()
    # A local failure (ENOSPC in a shard write, pickle error) must not
    # strand the other ranks in the barriers below — capture, keep
    # participating in every synchronization point, re-raise at the end.
    err: Optional[BaseException] = None
    marker = os.path.join(path, f"metadata_rank{rank}.failed")
    try:
        if os.path.exists(marker):
            os.remove(marker)  # stale marker from an earlier save
        for key, value in _flatten(state_dict).items():
            arr = (value._data if isinstance(value, Tensor)
                   else np.asarray(value))
            entries = []
            is_dist = isinstance(arr, jax.Array) and (
                len(arr.sharding.device_set) > 1
                or not arr.is_fully_addressable)
            if is_dist:
                seen_offsets = set()
                for i, shard in enumerate(arr.addressable_shards):
                    if shard.replica_id != 0:
                        continue  # another device/process owns this copy
                    offset = tuple(s.start or 0 for s in shard.index) \
                        if shard.index else ()
                    if offset in seen_offsets:
                        continue  # dedup replicated shards (ref: :117)
                    seen_offsets.add(offset)
                    fname = f"{_safe(key)}__r{rank}s{i}.npy"
                    np.save(os.path.join(path, fname),
                            np.asarray(shard.data))
                    entries.append(LocalTensorMetadata(
                        offset, tuple(shard.data.shape), str(arr.dtype),
                        fname))
            else:
                if rank == coordinator_rank:
                    fname = f"{_safe(key)}__full.npy"
                    np.save(os.path.join(path, fname), np.asarray(arr))
                    entries.append(LocalTensorMetadata(
                        tuple(0 for _ in np.shape(arr)),
                        tuple(np.shape(arr)),
                        str(np.asarray(arr).dtype), fname))
            if entries:
                meta.state_dict_metadata[key] = entries
        # merge metadata across processes via the filesystem (each process
        # owns distinct keys' shard files; coordinator merges)
        part = os.path.join(path, f"metadata_rank{rank}.pkl")
        with open(part + ".tmp", "wb") as f:
            pickle.dump(meta, f)
        os.replace(part + ".tmp", part)
    except BaseException as e:  # noqa: BLE001 — re-raised below
        err = e
        try:  # tell the coordinator this rank's shards are incomplete
            with open(marker, "w") as f:
                f.write(f"{type(e).__name__}: {e}")
        except OSError:
            pass
    _barrier_if_multiprocess(process_group)  # parts on disk before merge
    if err is None and rank == coordinator_rank:
        try:
            failed = [r for r in _participants(process_group)
                      if os.path.exists(
                          os.path.join(path, f"metadata_rank{r}.failed"))]
            if failed:
                raise RuntimeError(
                    f"checkpoint save failed on rank(s) {failed}; "
                    f"metadata.json withheld (a partial checkpoint must "
                    f"not look loadable)")
            merged = Metadata()
            # merge ONLY this save's participants: stale parts from an
            # earlier larger-world save into the same path would mix
            # old-topology shards into metadata.json
            for r in _participants(process_group):
                fn = os.path.join(path, f"metadata_rank{r}.pkl")
                if not os.path.exists(fn):
                    continue  # rank r had nothing to write
                with open(fn, "rb") as f:
                    m = pickle.load(f)
                for k, v in m.state_dict_metadata.items():
                    merged.state_dict_metadata.setdefault(k, []).extend(v)
            tmp = os.path.join(path, "metadata.json.tmp")
            with open(tmp, "w") as f:
                json.dump({k: [vars(e) for e in v]
                           for k, v in merged.state_dict_metadata.items()},
                          f)
            os.replace(tmp, os.path.join(path, "metadata.json"))
        except BaseException as e:  # noqa: BLE001 — re-raised below
            err = e
    _barrier_if_multiprocess(process_group)  # no early return
    if err is not None:
        raise err


def load_state_dict(state_dict: Dict, path: str,
                    process_group=None, coordinator_rank: int = 0):
    """ref: load_state_dict.py — fills `state_dict` values in place,
    resharding to each destination tensor's current sharding."""
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    flat = _flatten(state_dict)
    for key, dest in flat.items():
        if key not in meta:
            continue
        entries = meta[key]
        global_arr = _assemble(path, entries)
        if isinstance(dest, Tensor):
            target = dest._data
            if isinstance(target, jax.Array) and hasattr(target, "sharding"):
                arr = jax.device_put(
                    global_arr.astype(target.dtype), target.sharding)
            else:
                arr = jax.numpy.asarray(global_arr)
            dest._data = arr
        else:
            raise TypeError(f"load_state_dict target for {key} must be Tensor")


def _assemble(path: str, entries: List[dict]) -> np.ndarray:
    if len(entries) == 1 and all(o == 0 for o in entries[0]["global_offset"]):
        return np.load(os.path.join(path, entries[0]["file_name"]))
    # compute global shape as max(offset + local_shape) per dim
    ndim = len(entries[0]["local_shape"])
    gshape = [0] * ndim
    for e in entries:
        for d in range(ndim):
            gshape[d] = max(gshape[d],
                            e["global_offset"][d] + e["local_shape"][d])
    out = np.zeros(gshape, dtype=entries[0]["dtype"])
    for e in entries:
        sl = tuple(slice(o, o + s) for o, s in
                   zip(e["global_offset"], e["local_shape"]))
        out[sl] = np.load(os.path.join(path, e["file_name"]))
    return out


def _flatten(d: Dict, prefix: str = "") -> Dict[str, object]:
    out = {}
    for k, v in d.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(_flatten(v, key))
        else:
            out[key] = v
    return out


def _safe(key: str) -> str:
    return key.replace("/", "_").replace(".", "_")
