"""Sharded state_dict save/load.

ref: python/paddle/distributed/checkpoint/save_state_dict.py:145
(save_state_dict: local shard write + metadata, cross-rank dedup of
replicated tensors at :117 dedup_tensor) and load_state_dict.py (reshard
on read). TPU-native: a jax.Array's addressable_shards give exactly the
(global_offset, local_shape) pairs the reference records; load assembles
the global value from shard files then device_puts with the target
sharding — changed mesh/placement works by construction.
"""
from __future__ import annotations

import json
import os
import pickle
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

from ...core.tensor import Tensor

__all__ = ["save_state_dict", "load_state_dict", "Metadata",
           "LocalTensorMetadata"]


@dataclass
class LocalTensorMetadata:
    """ref: checkpoint/metadata.py:20."""
    global_offset: Tuple[int, ...]
    local_shape: Tuple[int, ...]
    dtype: str
    file_name: str


@dataclass
class Metadata:
    """ref: checkpoint/metadata.py:41."""
    state_dict_metadata: Dict[str, List[LocalTensorMetadata]] = \
        field(default_factory=dict)
    flat_mapping: Dict[str, str] = field(default_factory=dict)


def _process_id() -> int:
    return jax.process_index()


def save_state_dict(state_dict: Dict, path: str,
                    process_group=None, coordinator_rank: int = 0):
    """ref: save_state_dict.py:145. Layout on disk:
    path/{key}__{shard_idx}.npy per local shard + path/metadata.json
    (written by the coordinator; single-controller writes everything)."""
    os.makedirs(path, exist_ok=True)
    meta = Metadata()
    rank = _process_id()
    for key, value in _flatten(state_dict).items():
        arr = value._data if isinstance(value, Tensor) else np.asarray(value)
        entries = []
        if isinstance(arr, jax.Array) and len(arr.sharding.device_set) > 1:
            seen_offsets = set()
            for i, shard in enumerate(arr.addressable_shards):
                offset = tuple(s.start or 0 for s in shard.index) \
                    if shard.index else ()
                if offset in seen_offsets:
                    continue  # dedup replicated shards (ref: :117)
                seen_offsets.add(offset)
                fname = f"{_safe(key)}__r{rank}s{i}.npy"
                np.save(os.path.join(path, fname), np.asarray(shard.data))
                entries.append(LocalTensorMetadata(
                    offset, tuple(shard.data.shape), str(arr.dtype), fname))
        else:
            if rank == coordinator_rank:
                fname = f"{_safe(key)}__full.npy"
                np.save(os.path.join(path, fname), np.asarray(arr))
                entries.append(LocalTensorMetadata(
                    tuple(0 for _ in np.shape(arr)),
                    tuple(np.shape(arr)), str(np.asarray(arr).dtype), fname))
        if entries:
            meta.state_dict_metadata[key] = entries
    # merge metadata across processes via the filesystem (each process owns
    # distinct keys' shard files; coordinator merges)
    part = os.path.join(path, f"metadata_rank{rank}.pkl")
    with open(part, "wb") as f:
        pickle.dump(meta, f)
    if rank == coordinator_rank:
        merged = Metadata()
        for fn in sorted(os.listdir(path)):
            if fn.startswith("metadata_rank") and fn.endswith(".pkl"):
                with open(os.path.join(path, fn), "rb") as f:
                    m = pickle.load(f)
                for k, v in m.state_dict_metadata.items():
                    merged.state_dict_metadata.setdefault(k, []).extend(v)
        with open(os.path.join(path, "metadata.json"), "w") as f:
            json.dump({k: [vars(e) for e in v]
                       for k, v in merged.state_dict_metadata.items()}, f)


def load_state_dict(state_dict: Dict, path: str,
                    process_group=None, coordinator_rank: int = 0):
    """ref: load_state_dict.py — fills `state_dict` values in place,
    resharding to each destination tensor's current sharding."""
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    flat = _flatten(state_dict)
    for key, dest in flat.items():
        if key not in meta:
            continue
        entries = meta[key]
        global_arr = _assemble(path, entries)
        if isinstance(dest, Tensor):
            target = dest._data
            if isinstance(target, jax.Array) and hasattr(target, "sharding"):
                arr = jax.device_put(
                    global_arr.astype(target.dtype), target.sharding)
            else:
                arr = jax.numpy.asarray(global_arr)
            dest._data = arr
        else:
            raise TypeError(f"load_state_dict target for {key} must be Tensor")


def _assemble(path: str, entries: List[dict]) -> np.ndarray:
    if len(entries) == 1 and all(o == 0 for o in entries[0]["global_offset"]):
        return np.load(os.path.join(path, entries[0]["file_name"]))
    # compute global shape as max(offset + local_shape) per dim
    ndim = len(entries[0]["local_shape"])
    gshape = [0] * ndim
    for e in entries:
        for d in range(ndim):
            gshape[d] = max(gshape[d],
                            e["global_offset"][d] + e["local_shape"][d])
    out = np.zeros(gshape, dtype=entries[0]["dtype"])
    for e in entries:
        sl = tuple(slice(o, o + s) for o, s in
                   zip(e["global_offset"], e["local_shape"]))
        out[sl] = np.load(os.path.join(path, e["file_name"]))
    return out


def _flatten(d: Dict, prefix: str = "") -> Dict[str, object]:
    out = {}
    for k, v in d.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(_flatten(v, key))
        else:
            out[key] = v
    return out


def _safe(key: str) -> str:
    return key.replace("/", "_").replace(".", "_")
