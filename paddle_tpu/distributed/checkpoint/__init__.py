"""Distributed checkpoint: sharded save/load with reshard-on-load.

ref: python/paddle/distributed/checkpoint/save_state_dict.py:145 and
metadata.py:20-41 (Metadata{LocalTensorMetadata(global_offset,
local_shape)}), load_state_dict.py. Design contract preserved: each rank
writes only its local shards plus a global metadata index; load reshards
when the target mesh/placements differ (SURVEY.md §5 Checkpoint/resume).
"""
from .save_load import (  # noqa: F401
    save_state_dict, load_state_dict, LocalTensorMetadata, Metadata,
)
