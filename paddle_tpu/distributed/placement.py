"""Placement types for the semi-auto-parallel (DTensor) API.

ref: paddle/phi/core/distributed/auto_parallel/placement_types.h:68,108,132
(Shard / Replicate / Partial). On TPU these map onto jax.sharding
PartitionSpec entries: Shard(d) puts a mesh axis on tensor dim d,
Replicate leaves the axis unused, Partial marks a pending cross-axis
reduction (tracked framework-side; XLA's NamedSharding has no native
partial, so reshard materializes it with a psum).
"""
from __future__ import annotations


class Placement:
    def is_shard(self, dim=None):
        return False

    def is_replicated(self):
        return False

    def is_partial(self):
        return False


class Replicate(Placement):
    def is_replicated(self):
        return True

    def __repr__(self):
        return "Replicate()"

    def __eq__(self, other):
        return isinstance(other, Replicate)

    def __hash__(self):
        return hash("Replicate")


class Shard(Placement):
    def __init__(self, dim: int):
        self.dim = int(dim)

    def is_shard(self, dim=None):
        return dim is None or dim == self.dim

    def get_dim(self):
        return self.dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def __eq__(self, other):
        return isinstance(other, Shard) and other.dim == self.dim

    def __hash__(self):
        return hash(("Shard", self.dim))


class Partial(Placement):
    """Pending reduction over a mesh axis (ref: placement_types.h:132).

    reduce_type: 'sum' | 'avg' | 'max' | 'min' (ReduceType subset).
    """

    def __init__(self, reduce_type: str = "sum"):
        self.reduce_type = reduce_type

    def is_partial(self):
        return True

    def __repr__(self):
        return f"Partial(reduce_type={self.reduce_type})"

    def __eq__(self, other):
        return isinstance(other, Partial) and other.reduce_type == self.reduce_type

    def __hash__(self):
        return hash(("Partial", self.reduce_type))
