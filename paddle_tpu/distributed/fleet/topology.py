"""Hybrid-parallel communication topology.

ref: python/paddle/distributed/fleet/base/topology.py:70 (CommunicateTopology)
and :189 (HybridCommunicateGroup): a product topology over the axes
[dp, pp, sharding, sep, mp] with per-axis communicator groups. The math is
hardware-agnostic and ports directly; on TPU the per-axis "comm groups"
double as named mesh axes — get_mesh() returns the jax-backed ProcessMesh
whose axis names carry pjit collectives over ICI.
"""
from __future__ import annotations

import itertools
from functools import reduce
from typing import Dict, List, Optional

import numpy as np

from ..collective import Group, new_group
from ..process_mesh import ProcessMesh

__all__ = ["CommunicateTopology", "HybridCommunicateGroup"]

_HYBRID_PARALLEL_ORDER = ["dp", "pp", "sharding", "sep", "mp"]


class CommunicateTopology:
    """ref: topology.py:70 — rank <-> coordinate bookkeeping on a dense
    cartesian product of parallel axes."""

    def __init__(self, hybrid_group_names: Optional[List[str]] = None,
                 dims: Optional[List[int]] = None):
        self._parallel_names = list(hybrid_group_names or _HYBRID_PARALLEL_ORDER)
        self._dims = list(dims or [1] * len(self._parallel_names))
        self.coordinate = list(itertools.product(
            *(range(d) for d in self._dims)))
        self._coord2rank = {c: i for i, c in enumerate(self.coordinate)}
        self._rank2coord = {i: c for i, c in enumerate(self.coordinate)}
        self._world = np.arange(self.world_size()).reshape(self._dims)

    def get_hybrid_group_names(self) -> List[str]:
        return self._parallel_names

    def get_dim(self, axis_name: str) -> int:
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self) -> int:
        return int(reduce(lambda a, b: a * b, self._dims, 1))

    def get_rank(self, **args) -> int:
        coord = tuple(args[name] for name in self._parallel_names)
        return self._coord2rank[coord]

    def get_coord(self, rank: int):
        return self._rank2coord[rank]

    def get_axis_list(self, axis_name: str, index: int) -> List[int]:
        """All ranks whose coordinate on axis_name equals index."""
        axis = self._parallel_names.index(axis_name)
        return sorted(r for c, r in self._coord2rank.items()
                      if c[axis] == index)

    def get_dim_num(self, axis_name: str) -> int:
        return self.get_dim(axis_name)

    def get_comm_list(self, axis_name: str) -> List[List[int]]:
        """ref: topology.py get_comm_list — for each combination of the other
        axes, the rank list varying along axis_name (one comm ring each)."""
        axis = self._parallel_names.index(axis_name)
        other_dims = [d for i, d in enumerate(self._dims) if i != axis]
        comm_list = []
        for other_coord in itertools.product(*(range(d) for d in other_dims)):
            ranks = []
            for i in range(self._dims[axis]):
                coord = list(other_coord)
                coord.insert(axis, i)
                ranks.append(self._coord2rank[tuple(coord)])
            comm_list.append(ranks)
        return comm_list

    def get_rank_from_stage(self, global_rank: int, **kwargs) -> int:
        coord = list(self.get_coord(global_rank))
        for name, val in kwargs.items():
            coord[self._parallel_names.index(name)] = val
        return self._coord2rank[tuple(coord)]


class HybridCommunicateGroup:
    """ref: topology.py:189 — builds per-axis groups (dp/mp/pp/sharding/sep)
    plus fused groups (e.g. dp+sep for gradient sync) and exposes
    rank/degree accessors used throughout fleet."""

    def __init__(self, topology: CommunicateTopology, global_rank: int = 0):
        self._topo = topology
        self.global_rank = global_rank
        self.nranks = topology.world_size()

        self._dp_degree = topology.get_dim("dp")
        self._mp_degree = topology.get_dim("mp")
        self._pp_degree = topology.get_dim("pp")
        self._sharding_degree = topology.get_dim("sharding")
        self._sep_degree = (topology.get_dim("sep")
                            if "sep" in topology.get_hybrid_group_names() else 1)

        self._groups: Dict[str, Group] = {}
        self._group_ranks: Dict[str, List[int]] = {}
        for axis in topology.get_hybrid_group_names():
            self._groups[axis], self._group_ranks[axis] = \
                self._build_group(axis)

        # fused data-parallel group (dp+sep behave DP-like for grads;
        # ref: topology.py _set_p2p_prev_next + hybrid_parallel_util.py:265)
        self._dp_sep_group = self._groups["dp"]

    def _build_group(self, axis_name: str):
        comm_lists = self._topo.get_comm_list(axis_name)
        my_ranks = next(rl for rl in comm_lists if self.global_rank in rl)
        return new_group(my_ranks), my_ranks

    # -- degree / rank accessors (ref: topology.py:220-292) -----------------
    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sep_parallel_world_size(self):
        return self._sep_degree

    def _axis_rank(self, axis):
        coord = self._topo.get_coord(self.global_rank)
        return coord[self._topo.get_hybrid_group_names().index(axis)]

    def get_data_parallel_rank(self):
        return self._axis_rank("dp")

    def get_model_parallel_rank(self):
        return self._axis_rank("mp")

    def get_stage_id(self):
        return self._axis_rank("pp")

    get_pipe_parallel_rank = get_stage_id

    def get_sharding_parallel_rank(self):
        return self._axis_rank("sharding")

    def get_sep_parallel_rank(self):
        return self._axis_rank("sep")

    def get_data_parallel_group(self) -> Group:
        return self._groups["dp"]

    def get_model_parallel_group(self) -> Group:
        return self._groups["mp"]

    def get_pipe_parallel_group(self) -> Group:
        return self._groups["pp"]

    def get_sharding_parallel_group(self) -> Group:
        return self._groups["sharding"]

    def get_sep_parallel_group(self) -> Group:
        return self._groups.get("sep", self._groups["dp"])

    def get_data_parallel_group_src_rank(self):
        return self._group_ranks["dp"][0]

    def get_model_parallel_group_src_rank(self):
        return self._group_ranks["mp"][0]

    def get_p2p_groups(self):
        return None

    def is_first_stage(self):
        return self.get_stage_id() == 0

    def is_last_stage(self):
        return self.get_stage_id() == self._pp_degree - 1

    # -- TPU-native bridge ---------------------------------------------------
    def get_mesh(self) -> ProcessMesh:
        """The whole hybrid topology as one named device mesh — the idiomatic
        TPU form: every per-axis comm group above is a named axis here."""
        names = self._topo.get_hybrid_group_names()
        dims = [self._topo.get_dim(n) for n in names]
        keep = [i for i, d in enumerate(dims) if d > 1] or [0]
        shape = [dims[i] for i in keep]
        kept_names = [names[i] for i in keep]
        n = int(np.prod(shape))
        return ProcessMesh(np.arange(n).reshape(shape), kept_names)
