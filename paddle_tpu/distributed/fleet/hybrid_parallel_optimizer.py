"""HybridParallelOptimizer: optimizer wrapper for hybrid parallelism.

ref: python/paddle/distributed/fleet/meta_optimizers/dygraph_optimizer/
hybrid_parallel_optimizer.py:42 (HybridParallelClipGrad — global-norm clip
allreduced across mp/pp/sharding groups) and :266 (HybridParallelOptimizer).
Delegates to DygraphShardingOptimizer when sharding degree > 1.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor
from ..collective import ReduceOp, all_reduce
from ..parallel import get_world_size

__all__ = ["HybridParallelOptimizer", "HybridParallelClipGrad"]


class HybridParallelClipGrad:
    """ref: hybrid_parallel_optimizer.py:42 — the local sq-norm of each
    param group is summed across the hybrid groups before clipping so the
    clip factor is identical on all ranks."""

    def __init__(self, clip, hcg):
        self._clip = clip
        self._hcg = hcg

    def __call__(self, params_grads):
        sq = None
        for p, g in params_grads:
            if g is None:
                continue
            s = jnp.sum(jnp.square(g._data.astype(jnp.float32)))
            sq = s if sq is None else sq + s
        if sq is None:
            return params_grads
        t = Tensor(sq)
        if get_world_size() > 1:
            all_reduce(t, ReduceOp.SUM, self._hcg.get_model_parallel_group())
        global_norm = jnp.sqrt(t._data)
        max_norm = getattr(self._clip, "clip_norm", None) or \
            getattr(self._clip, "max_global_norm", 1.0)
        factor = jnp.minimum(max_norm / (global_norm + 1e-6), 1.0)
        return [(p, None if g is None else Tensor(g._data * factor))
                for p, g in params_grads]


class HybridParallelOptimizer:
    """ref: hybrid_parallel_optimizer.py:266."""

    def __init__(self, optimizer, hcg, strategy):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy
        self._sharding = hcg.get_sharding_parallel_world_size() > 1
        if self._sharding:
            from .sharding_optimizer import DygraphShardingOptimizer
            stage = int((getattr(strategy, "sharding_configs", {}) or {})
                        .get("stage", 1))
            self._inner_opt = DygraphShardingOptimizer(
                optimizer, hcg, stage=stage)
        clip = getattr(optimizer, "_grad_clip", None)
        if clip is not None:
            optimizer._grad_clip = HybridParallelClipGrad(clip, hcg)

    def step(self):
        # dp(+sep) gradient sync before the update
        # (ref: hybrid_parallel_util.py:249 fused_allreduce_gradients)
        if get_world_size() > 1 and \
                self._hcg.get_data_parallel_world_size() > 1:
            n = self._hcg.get_data_parallel_world_size()
            group = self._hcg.get_data_parallel_group()
            for p in self._inner_opt._parameter_list:
                if p.grad is not None:
                    all_reduce(p.grad, ReduceOp.SUM, group)
                    p.grad._data = p.grad._data / n
        self._inner_opt.step()

    def clear_grad(self, set_to_zero: bool = False):
        self._inner_opt.clear_grad()

    def set_state_dict(self, sd):
        return self._inner_opt.set_state_dict(sd)

    def state_dict(self):
        return self._inner_opt.state_dict()

    def __getattr__(self, name):
        return getattr(self._inner_opt, name)
