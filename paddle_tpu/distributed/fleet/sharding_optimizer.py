"""Sharding (ZeRO) optimizers, stages 1-3.

ref: python/paddle/distributed/fleet/meta_optimizers/dygraph_optimizer/
dygraph_sharding_optimizer.py:53 (DygraphShardingOptimizer, stage 1: split
params across the sharding group, reduce each grad to its owner, update the
owned shard, broadcast updated params) and fleet/meta_parallel/sharding/
group_sharded_stage2.py:46 / group_sharded_stage3.py:85 (grad + param
sharding).

TPU-native: on a single controller, "rank owns param i" becomes "optimizer
state for param i is placed Shard(0) on the sharding mesh axis" — the
compiled update reads/writes only the local shard, which is exactly ZeRO's
memory win without any of the hook machinery. The class below implements
the reference's rank-cyclic assignment so multi-process behavior and
state_dicts line up, and additionally annotates optimizer-state shardings
when a hybrid mesh is active.
"""
from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ..api import shard_tensor
from ..collective import ReduceOp, all_reduce, broadcast
from ..parallel import get_world_size
from ..placement import Replicate, Shard

__all__ = ["DygraphShardingOptimizer"]


class DygraphShardingOptimizer:
    """Stage 1/2/3 unified driver (ref: dygraph_sharding_optimizer.py:53).

    _rank2params: greedy by-size partition of the parameter list so each
    sharding rank's shard is balanced (ref: :319 _partition_parameters).
    """

    def __init__(self, optimizer, hcg, stage: int = 1):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._stage = stage
        self._sharding_world_size = hcg.get_sharding_parallel_world_size()
        self._sharding_rank = hcg.get_sharding_parallel_rank()
        self._parameter_list = list(optimizer._parameter_list)
        self._rank2params = self._partition_parameters()
        self._param2rank = {}
        for r, plist in enumerate(self._rank2params):
            for p in plist:
                self._param2rank[id(p)] = r
        self._shard_optimizer_states()

    def _partition_parameters(self) -> List[List]:
        """Greedy smallest-heap partition (ref: :319)."""
        sizes = [0.0] * self._sharding_world_size
        mapping: List[List] = [[] for _ in range(self._sharding_world_size)]
        for p in sorted(self._parameter_list,
                        key=lambda q: -float(q.size)):
            r = sizes.index(min(sizes))
            mapping[r].append(p)
            sizes[r] += float(p.size)
        return mapping

    def _shard_optimizer_states(self):
        """Annotate moment buffers Shard(0) over the sharding mesh axis so
        XLA keeps only 1/N of optimizer state resident (the ZeRO-1 memory
        contract, verified by tests/test_sharding.py)."""
        from .fleet import get_hybrid_communicate_group
        hcg = get_hybrid_communicate_group()
        if hcg is None:
            return
        mesh = hcg.get_mesh()
        if "sharding" not in mesh.dim_names:
            return
        placements = [Shard(0) if n == "sharding" else Replicate()
                      for n in mesh.dim_names]
        init_state = getattr(self._inner_opt, "_init_state", None)
        if init_state is None:
            return
        orig = init_state

        def sharded_init(p):
            state = orig(p)
            for k, v in state.items():
                if isinstance(v, Tensor) and v._data.ndim >= 1 and \
                        v._data.shape[0] % mesh.get_dim_size("sharding") == 0:
                    state[k] = shard_tensor(v, mesh, placements)
            return state

        self._inner_opt._init_state = sharded_init

    # -- the step (ref: :585 step / :319 reduce_gradients / :377 sync) ------
    def reduce_gradients(self):
        if get_world_size() <= 1:
            return
        for p in self._parameter_list:
            if p.grad is not None:
                all_reduce(p.grad, ReduceOp.SUM,
                           self._hcg.get_sharding_parallel_group())
                p.grad._data = p.grad._data / self._sharding_world_size

    def _sharding_sync_parameters(self):
        """Broadcast each param from its owner after the update (ref: :377)."""
        if get_world_size() <= 1:
            return
        group = self._hcg.get_sharding_parallel_group()
        for r, plist in enumerate(self._rank2params):
            src = group.ranks[r]
            for p in plist:
                broadcast(p, src=src, group=group)

    def step(self):
        self.reduce_gradients()
        if get_world_size() > 1:
            # update only the owned shard (other grads dropped), then sync
            owned = set(id(p) for p in
                        self._rank2params[self._sharding_rank])
            saved = []
            for p in self._parameter_list:
                if id(p) not in owned and p.grad is not None:
                    saved.append((p, p.grad))
                    p.grad = None
            self._inner_opt.step()
            for p, g in saved:
                p.grad = g
            self._sharding_sync_parameters()
        else:
            self._inner_opt.step()

    def clear_grad(self, set_to_zero: bool = False):
        self._inner_opt.clear_grad(set_to_zero)

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        return self._inner_opt.set_state_dict(sd)

    def __getattr__(self, name):
        return getattr(self._inner_opt, name)
