"""Pipeline-parallel runtime: 1F1B schedule over micro-batches.

ref: python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py:255
(PipelineParallel), :575-720 (forward_backward_pipeline: warmup
recv_forward/_forward_step/send_forward, steady 1F1B, cooldown), :928
(_forward_step), :994 (_backward_step); p2p meta handshake
pp_utils/p2p_communication.py:52,576.

TPU-native note (SURVEY.md §7 "hard parts"): a host-driven per-micro-batch
loop serializes on dispatch latency. This runtime therefore (a) keeps the
reference's 1F1B order so memory high-water matches, and (b) under a
single controller the stage programs are jit-cached so the host loop only
enqueues. The fully-compiled alternative (stage axis on the mesh +
collective_permute) lives in paddle_tpu.parallel.pipeline_spmd and is what
dryrun_multichip exercises.
"""
from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp

from ...core.tensor import Tensor
from ...nn.layer import Layer
from ..collective import recv, send
from .pp_layers import PipelineLayer

__all__ = ["PipelineParallel", "PipelineParallelWithInterleave"]


class PipelineParallel(Layer):
    def __init__(self, layers: PipelineLayer, hcg, strategy):
        super().__init__()
        if not isinstance(layers, PipelineLayer):
            raise TypeError(
                "fleet.distributed_model with pp_degree>1 expects a "
                "PipelineLayer (ref: fleet/model.py:134)")
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        pcfg = getattr(strategy, "pipeline_configs", {}) or {}
        self.accumulate_steps = int(pcfg.get("accumulate_steps", 1))
        self.micro_batch_size = int(pcfg.get("micro_batch_size", 1))
        self.num_stages = hcg.get_pipe_parallel_world_size()
        self.stage_id = hcg.get_stage_id()
        self.is_first_stage = self.stage_id == 0
        self.is_last_stage = self.stage_id == self.num_stages - 1
        self.total_loss = None

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    # -- schedule -----------------------------------------------------------
    def _split_micro(self, data):
        """Split the global batch into accumulate_steps micro-batches."""
        if data is None:
            return [None] * self.accumulate_steps
        if isinstance(data, (tuple, list)):
            parts = [self._split_micro(d) for d in data]
            return list(zip(*parts))
        n = self.accumulate_steps
        arrs = jnp.split(data._data if isinstance(data, Tensor) else
                         jnp.asarray(data), n, axis=0)
        return [Tensor(a) for a in arrs]

    def _forward_step(self, micro_input, micro_label):
        """ref: pipeline_parallel.py:928."""
        out = micro_input
        if not self.is_first_stage:
            # out arrived from the previous stage via recv
            pass
        out = self._layers(out) if not isinstance(out, (tuple, list)) \
            else self._layers(*out)
        if self.is_last_stage and self._layers._loss_fn is not None:
            loss = self._layers._loss_fn(out, micro_label)
            if isinstance(loss, Tensor) and loss._data.ndim > 0:
                loss = loss.mean() if hasattr(loss, "mean") else loss
            return loss
        return out

    def _backward_step(self, out, out_grad=None):
        """ref: pipeline_parallel.py:994 — paddle.autograd.backward on the
        chunk with received output grads."""
        out.backward(out_grad)

    def forward_backward_pipeline(self, data, scaler=None):
        """ref: :575 — on a single controller all stages are local, so 1F1B
        degenerates to looped fwd+bwd per micro-batch with grad
        accumulation (identical numerics and memory shape). Across launched
        processes (one stage per rank) this runs the real 1F1B schedule
        with p2p activations/grads over the pp group."""
        from ..parallel import get_world_size
        if self.num_stages > 1 and get_world_size() > 1:
            return self._forward_backward_1f1b_multiproc(data, scaler)
        inputs, labels = data if isinstance(data, (tuple, list)) and \
            len(data) == 2 else (data, None)
        micro_inputs = self._split_micro(inputs)
        micro_labels = self._split_micro(labels)
        self.total_loss = None
        for mi, ml in zip(micro_inputs, micro_labels):
            loss = self._forward_step(mi, ml)
            scaled = loss
            if scaler is not None:
                scaled = scaler.scale(loss)
            div = apply_scale(scaled, 1.0 / self.accumulate_steps)
            self._backward_step(div)
            self.total_loss = (loss if self.total_loss is None else
                               Tensor(self.total_loss._data + loss._data))
        return Tensor(self.total_loss._data / self.accumulate_steps)

    def _forward_backward_1f1b_multiproc(self, data, scaler):
        """Cross-process 1F1B (ref: pipeline_parallel.py:575-720 — warmup
        forwards, steady interleaved fwd/bwd, cooldown backwards).
        Activations/grads are exchanged with the eager p2p channel
        (ref: pp_utils/p2p_communication.py:576 _p2p_helper; shapes ride
        inside the message, so no separate meta handshake is needed)."""
        import jax.numpy as jnp
        from ..collective import broadcast, recv, send

        g = self._hcg.get_pipe_parallel_group()
        pp_ranks = g.ranks
        s, S, M = self.stage_id, self.num_stages, self.accumulate_steps
        prev_rank = pp_ranks[s - 1] if s > 0 else None
        next_rank = pp_ranks[s + 1] if s < S - 1 else None

        inputs, labels = data if isinstance(data, (tuple, list)) and \
            len(data) == 2 else (data, None)
        micro_inputs = self._split_micro(inputs) if self.is_first_stage \
            else [None] * M
        micro_labels = self._split_micro(labels) if self.is_last_stage \
            else [None] * M
        self.total_loss = None

        def do_forward(m):
            if self.is_first_stage:
                x = micro_inputs[m]
            else:
                x = Tensor(jnp.zeros((1,), jnp.float32))
                recv(x, src=prev_rank, group=g)
                x.stop_gradient = False  # leaf: backward fills x.grad
            out = self._layers(x) if not isinstance(x, (tuple, list)) \
                else self._layers(*x)
            if self.is_last_stage:
                loss = self._layers._loss_fn(out, micro_labels[m])
                if isinstance(loss, Tensor) and loss._data.ndim > 0:
                    loss = loss.mean()
                self.total_loss = (loss if self.total_loss is None else
                                   Tensor(self.total_loss._data +
                                          loss._data))
                return x, loss
            send(out, dst=next_rank, group=g)
            return x, out

        def do_backward(x, out):
            if self.is_last_stage:
                scaled = scaler.scale(out) if scaler is not None else out
                self._backward_step(apply_scale(scaled, 1.0 / M))
            else:
                og = Tensor(jnp.zeros((1,), jnp.float32))
                recv(og, src=next_rank, group=g)
                self._backward_step(out, og)
            if not self.is_first_stage:
                send(x.grad, dst=prev_rank, group=g)

        warmup = min(S - 1 - s, M)
        queue = []
        m_fwd = 0
        for _ in range(warmup):
            queue.append(do_forward(m_fwd))
            m_fwd += 1
        for _ in range(M - warmup):          # steady 1F1B
            queue.append(do_forward(m_fwd))
            m_fwd += 1
            do_backward(*queue.pop(0))
        while queue:                         # cooldown
            do_backward(*queue.pop(0))

        # surface the last stage's mean loss on every rank
        loss_t = Tensor(
            (self.total_loss._data / M) if self.total_loss is not None
            else jnp.zeros((), jnp.float32))
        broadcast(loss_t, src=pp_ranks[-1], group=g)
        return loss_t

    def train_batch(self, data, optimizer=None, lr_scheduler=None,
                    scaler=None):
        """ref: pipeline_parallel.py:820."""
        self._layers.train()
        loss = self.forward_backward_pipeline(data, scaler)
        if optimizer is not None:
            if scaler is not None:
                scaler.step(optimizer)
                scaler.update()
            else:
                optimizer.step()
            optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def eval_batch(self, data, compute_loss: bool = True):
        self._layers.eval()
        inputs, labels = data if isinstance(data, (tuple, list)) and \
            len(data) == 2 else (data, None)
        out = self._layers(inputs)
        if compute_loss and self._layers._loss_fn is not None:
            return self._layers._loss_fn(out, labels)
        return out

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)


class PipelineParallelWithInterleave(PipelineParallel):
    """Virtual-pipeline (VPP) runtime.

    ref: pipeline_parallel.py:1174 PipelineParallelWithInterleave — each
    stage owns num_virtual_pipeline_stages round-robin model chunks and
    the 1F1B schedule interleaves their micro-batches, cutting the bubble
    by the virtual factor. Under this framework's single controller the
    chunk visitation order degenerates to serial execution (numerics
    identical); the bubble reduction on a real pp mesh comes from the
    compiled schedule in paddle_tpu.parallel.spmd_pipeline_interleaved,
    which this wrapper fronts API-wise. The reference's constraint
    accumulate_steps % pp_degree == 0 is enforced for config parity.
    """

    def __init__(self, layers: PipelineLayer, hcg, strategy):
        super().__init__(layers, hcg, strategy)
        vpp = getattr(layers, "_num_virtual_stages", 1) or 1
        if vpp <= 1:
            raise ValueError(
                "PipelineParallelWithInterleave requires a PipelineLayer "
                "built with num_virtual_pipeline_stages > 1")
        self.num_model_chunks = vpp
        if self.accumulate_steps % max(self.num_stages, 1) != 0:
            raise ValueError(
                f"accumulate_steps ({self.accumulate_steps}) must be "
                f"divisible by pp degree ({self.num_stages}) for the "
                f"interleaved schedule (ref: :1174)")


def apply_scale(loss: Tensor, factor: float) -> Tensor:
    from ...core.autograd import apply_op
    return apply_op(lambda x: x * factor, loss, op_name="scale")
