"""Activation recomputation for eager/taped training.

ref: python/paddle/distributed/fleet/utils/recompute (recompute(function,
*args) — forward runs normally, activations inside are re-computed in
backward instead of stored). TPU-native: the segment's functionalized
forward is wrapped in jax.checkpoint and dispatched through apply_op —
the tape's jax.vjp then stores only the segment INPUTS as residuals and
re-runs the forward during backward. Inside a compiled train step
(DistTrainStep / jit) the same wrapper lowers to XLA remat.
"""
from __future__ import annotations

import jax

from ....core.autograd import apply_op
from ....core.tensor import Tensor
from ....nn.layer import Layer

__all__ = ["recompute"]


def recompute(function, *args, preserve_rng_state: bool = True,
              forward_fn=None, **kwargs):
    """Run ``function(*args)`` with recompute-in-backward semantics.

    function: a Layer (its parameters keep receiving gradients — they are
    threaded through the checkpointed program, not captured as constants)
    or a pure callable over Tensors. ``forward_fn`` overrides the
    Layer's callable (used by Engine's auto-recompute pass, which
    replaces ``layer.forward`` with a recompute wrapper and must hand
    the ORIGINAL forward here to avoid recursing into itself).
    """
    if isinstance(function, Layer):
        from ....jit.api import functionalize
        apply, params0, buffers0 = functionalize(function, forward_fn)
        names = list(params0)
        named = dict(function.named_parameters())
        param_tensors = [named[n] for n in names]
        buffer_names = list(buffers0)
        buffer_tensors = dict(function.named_buffers())

        def fn(*flat):
            ps = dict(zip(names, flat[:len(names)]))
            out, new_buffers = apply(ps, buffers0, *flat[len(names):],
                                     **kwargs)
            if isinstance(out, (tuple, list)):
                raise NotImplementedError(
                    "recompute over a multi-output segment: wrap the "
                    "segment so it returns one tensor")
            # thread buffer updates (e.g. BN running stats) out as extra
            # outputs so they are not lost to the recompute wrapper
            return (out, *[new_buffers[n] for n in buffer_names])

        ck = jax.checkpoint(fn)
        res = apply_op(ck, *param_tensors, *args, op_name="recompute")
        if buffer_names:
            out = res[0]
            for n, new_b in zip(buffer_names, res[1:]):
                buffer_tensors[n]._data = new_b._data
            return out
        return res if not isinstance(res, tuple) else res[0]

    def fn(*flat):
        out = function(*[Tensor(a) for a in flat], **kwargs)
        return out._data if isinstance(out, Tensor) else out

    return apply_op(jax.checkpoint(fn), *args, op_name="recompute")
