"""fleet.utils: recompute + hybrid-parallel helpers.

ref: python/paddle/distributed/fleet/utils/__init__.py (recompute,
hybrid_parallel_util helpers).
"""
from .recompute import recompute  # noqa: F401

__all__ = ["recompute"]
