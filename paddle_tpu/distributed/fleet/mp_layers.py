"""Megatron-style tensor-parallel layers.

ref: python/paddle/distributed/fleet/layers/mpu/mp_layers.py:47
(VocabParallelEmbedding), :334 (ColumnParallelLinear), :541
(RowParallelLinear), :742 (ParallelCrossEntropy). TPU-native design: the
weights carry Shard placements on the "mp" mesh axis; the forward is the
plain dense math. Under pjit/shard_map over the hybrid mesh, GSPMD
partitions the matmul and inserts the same collectives the reference
issues by hand (identity/allreduce pairs) — over ICI. Eager on one
controller the math is exact (weights logically global), so numerics are
identical to the single-card reference.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor
from ...core.autograd import apply_op
from ...nn.layer import Layer
from ..api import shard_tensor
from ..placement import Replicate, Shard
from ..process_mesh import ProcessMesh
from .mp_ops import _c_concat, _c_identity, _c_split, _mp_allreduce

__all__ = [
    "VocabParallelEmbedding", "ColumnParallelLinear", "RowParallelLinear",
    "ParallelCrossEntropy",
]


def _current_mp_mesh() -> Optional[ProcessMesh]:
    """The active hybrid mesh, if fleet was initialized with mp degree > 1."""
    from .fleet import get_hybrid_communicate_group
    hcg = get_hybrid_communicate_group()
    if hcg is not None and hcg.get_model_parallel_world_size() > 1:
        mesh = hcg.get_mesh()
        if "mp" in mesh.dim_names:
            return mesh
    return None


def _shard_param(param, dim: int):
    """Annotate a parameter as Shard(dim) on the mp axis of the hybrid mesh."""
    mesh = _current_mp_mesh()
    if mesh is None:
        return
    placements = [Shard(dim) if n == "mp" else Replicate()
                  for n in mesh.dim_names]
    sharded = shard_tensor(param, mesh, placements)
    param._data = sharded._data
    param._dist_attr = sharded._dist_attr


class VocabParallelEmbedding(Layer):
    """ref: mp_layers.py:47 — vocab dim sharded across mp ranks; out-of-range
    ids masked locally, partial outputs allreduced. GSPMD derives exactly
    this from Shard(0) on the weight."""

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 weight_attr=None, mp_group=None, name=None):
        super().__init__()
        self._size = [num_embeddings, embedding_dim]
        self.weight = self.create_parameter(
            shape=self._size, attr=weight_attr,
            default_initializer=None)
        _shard_param(self.weight, 0)
        self.mp_group = mp_group

    def forward(self, x):
        def f(ids, w):
            return jnp.take(w, ids.astype(jnp.int32), axis=0)
        return apply_op(f, x, self.weight, op_name="vocab_parallel_embedding")


class ColumnParallelLinear(Layer):
    """ref: mp_layers.py:334 — weight [in, out] Shard(1); input identity-
    broadcast in, output optionally gathered."""

    def __init__(self, in_features: int, out_features: int, weight_attr=None,
                 has_bias: bool = True, gather_output: bool = True,
                 fuse_matmul_bias: bool = False, mp_group=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=None)
        _shard_param(self.weight, 1)
        self.bias = None
        if has_bias:
            self.bias = self.create_parameter(
                shape=[out_features], attr=None, is_bias=True)
            _shard_param(self.bias, 0)
        self.gather_output = gather_output
        self.mp_group = mp_group

    def forward(self, x):
        x = _c_identity(x, self.mp_group)
        if self.bias is not None:
            out = apply_op(lambda a, w, b: a @ w + b, x, self.weight,
                           self.bias, op_name="column_parallel_linear")
        else:
            out = apply_op(lambda a, w: a @ w, x, self.weight,
                           op_name="column_parallel_linear")
        if self.gather_output:
            out = _c_concat(out, self.mp_group)
        return out


class RowParallelLinear(Layer):
    """ref: mp_layers.py:541 — weight [in, out] Shard(0); input expected
    already split on last dim, partial products allreduced."""

    def __init__(self, in_features: int, out_features: int, weight_attr=None,
                 has_bias: bool = True, input_is_parallel: bool = False,
                 fuse_matmul_bias: bool = False, mp_group=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=None)
        _shard_param(self.weight, 0)
        self.bias = None
        if has_bias:
            self.bias = self.create_parameter(
                shape=[out_features], attr=None, is_bias=True)
        self.input_is_parallel = input_is_parallel
        self.mp_group = mp_group

    def forward(self, x):
        if not self.input_is_parallel:
            x = _c_split(x, self.mp_group)
        out = apply_op(lambda a, w: a @ w, x, self.weight,
                       op_name="row_parallel_linear")
        out = _mp_allreduce(out, group=self.mp_group)
        if self.bias is not None:
            out = apply_op(lambda a, b: a + b, out, self.bias,
                           op_name="row_parallel_bias")
        return out


class ParallelCrossEntropy(Layer):
    """ref: mp_layers.py:742 — softmax cross-entropy over vocab sharded
    logits (c_softmax_with_cross_entropy). GSPMD form: plain logsumexp CE;
    the vocab-axis reduction lowers to a psum over mp."""

    def __init__(self, mp_group=None, name=None, ignore_index: int = -100):
        super().__init__()
        self.mp_group = mp_group
        self.ignore_index = ignore_index

    def forward(self, input, label):
        ignore = self.ignore_index

        def f(logits, lab):
            lse = jnp.log(jnp.sum(jnp.exp(
                logits - jnp.max(logits, axis=-1, keepdims=True)),
                axis=-1, keepdims=True)) + jnp.max(
                logits, axis=-1, keepdims=True)
            lab_i = lab.astype(jnp.int32)
            squeeze = lab_i.ndim == logits.ndim
            idx = lab_i[..., 0] if squeeze else lab_i
            picked = jnp.take_along_axis(
                logits, idx[..., None], axis=-1)
            loss = (lse - picked)
            mask = (idx != ignore)[..., None]
            return jnp.where(mask, loss, 0.0)

        return apply_op(f, input, label, op_name="parallel_cross_entropy")
