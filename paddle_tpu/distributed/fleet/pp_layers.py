"""Pipeline-parallel layer description and segmentation.

ref: python/paddle/distributed/fleet/meta_parallel/parallel_layers/
pp_layers.py:56 (LayerDesc), :92 (SharedLayerDesc), :257 (PipelineLayer —
segmentation of a layer list into stages). The segmentation math is
hardware-agnostic and ports as semantics; on TPU each stage's chunk is a
separately jit-compiled program and activations cross stages over ICI
send/recv (or, in single-controller SPMD mode, the whole pipeline lives in
one program and the stage dim is a mesh axis).
"""
from __future__ import annotations

from typing import Callable, List, Optional

import numpy as np

from ...nn.layer import Layer
from ...nn.container import LayerList

__all__ = ["LayerDesc", "SharedLayerDesc", "PipelineLayer"]


class LayerDesc:
    """ref: pp_layers.py:56 — lazy layer constructor so only the owning
    stage materializes parameters."""

    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_func, Layer):
            raise TypeError("LayerDesc expects a Layer subclass")

    def build_layer(self) -> Layer:
        return self.layer_func(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_func.__name__})"


class SharedLayerDesc(LayerDesc):
    """ref: pp_layers.py:92 — layer shared between stages (e.g. tied
    embedding/lm-head); grads for shared params are allreduced over the
    owning stages' comm group."""

    def __init__(self, key, layer_func, forward_func=None,
                 shared_weight_attr="weight", *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


def _uniform_partition(num_items: int, num_parts: int) -> List[int]:
    """ref: pp_layers.py segment_uniform — bounds[i] is first index of part i."""
    base = num_items // num_parts
    extra = num_items % num_parts
    bounds = [0]
    for i in range(num_parts):
        bounds.append(bounds[-1] + base + (1 if i < extra else 0))
    return bounds


class PipelineLayer(Layer):
    """ref: pp_layers.py:257 — takes a flat list of LayerDesc/Layer/callable,
    segments into num_stages parts, builds only this stage's segment."""

    def __init__(self, layers, num_stages: Optional[int] = None,
                 topology=None, loss_fn=None, seg_method="uniform",
                 recompute_interval: int = 0, num_virtual_pipeline_stages=None):
        super().__init__()
        self._layers_desc = list(layers)
        self._loss_fn = loss_fn
        self._topo = topology
        self._recompute_interval = recompute_interval
        self._num_virtual_stages = num_virtual_pipeline_stages or 1

        if topology is not None:
            self._num_stages = topology.get_dim("pipe") if hasattr(
                topology, "get_dim") else num_stages
            self._stage_id = 0
        else:
            self._num_stages = num_stages or 1
            self._stage_id = 0

        from .fleet import get_hybrid_communicate_group
        hcg = get_hybrid_communicate_group()
        if hcg is not None and hcg.get_pipe_parallel_world_size() > 1:
            self._num_stages = hcg.get_pipe_parallel_world_size()
            self._stage_id = hcg.get_stage_id()

        n = len(self._layers_desc)
        if self._num_virtual_stages > 1:
            # validate against the RESOLVED stage count (topology/hcg may
            # have overridden the constructor arg above)
            total = self._num_stages * self._num_virtual_stages
            if n % total != 0:
                raise ValueError(
                    f"layer count {n} must be a multiple of "
                    f"num_stages*num_virtual_pipeline_stages = "
                    f"{self._num_stages}*{self._num_virtual_stages} "
                    f"(ref: pp_layers.py interleave segmentation)")
        self.segment_parts = _uniform_partition(n, self._num_stages)
        self._start = self.segment_parts[self._stage_id]
        self._end = self.segment_parts[self._stage_id + 1]

        self.run_function: List = []
        built = []
        self.shared_layers = {}
        for i in range(self._start, self._end):
            desc = self._layers_desc[i]
            if isinstance(desc, SharedLayerDesc):
                if desc.layer_name not in self.shared_layers:
                    self.shared_layers[desc.layer_name] = desc.build_layer()
                layer = self.shared_layers[desc.layer_name]
                if desc.forward_func is not None:
                    fwd = desc.forward_func
                    self.run_function.append(
                        lambda x, _l=layer, _f=fwd: _f(_l, x))
                else:
                    self.run_function.append(layer)
                built.append(layer)
            elif isinstance(desc, LayerDesc):
                layer = desc.build_layer()
                self.run_function.append(layer)
                built.append(layer)
            elif isinstance(desc, Layer):
                self.run_function.append(desc)
                built.append(desc)
            elif callable(desc):
                self.run_function.append(desc)
            else:
                raise TypeError(f"bad pipeline item {desc!r}")
        self._stage_layers = LayerList(built)

    # -- accessors ----------------------------------------------------------
    def get_num_stages(self):
        return self._num_stages

    def get_stage_id(self):
        return self._stage_id

    @property
    def parameters_in_stage(self):
        return self.parameters()

    def forward(self, input):
        x = input
        for fn in self.run_function:
            x = fn(x)
        return x

    def forward_segment(self, x, start: int, end: int):
        for fn in self.run_function[start:end]:
            x = fn(x)
        return x
