"""Tensor-parallel communication primitives.

ref: python/paddle/distributed/fleet/layers/mpu/mp_ops.py:91-341
(_c_identity / _c_split / _c_concat / _mp_allreduce) and :706
(paddle.distributed.split). TPU-native: under jit these are pure sharding
annotations (with_sharding_constraint) and XLA inserts the collective; the
eager fallbacks below act on replicated values on a single controller.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...core.autograd import apply_op
from ..collective import Group, ReduceOp, all_reduce, get_group

__all__ = ["_c_identity", "_c_split", "_c_concat", "_mp_allreduce", "split"]


def _nranks(group: Optional[Group]):
    g = group if group is not None else get_group(0)
    return max(g.nranks, 1), max(g.rank, 0)


def _c_identity(tensor: Tensor, group: Optional[Group] = None) -> Tensor:
    """Forward identity, backward allreduce over the mp group
    (ref: mp_ops.py:91 c_identity). Under trace the backward psum comes from
    the sharding of the consumer; eager single-controller returns as-is."""
    return apply_op(lambda x: x, tensor, op_name="c_identity")


def _mp_allreduce(tensor: Tensor, op=ReduceOp.SUM,
                  group: Optional[Group] = None) -> Tensor:
    """Forward allreduce, backward identity (ref: mp_ops.py:241)."""
    out = apply_op(lambda x: x, tensor, op_name="mp_allreduce")
    all_reduce(out, op, group)
    return out


def _c_split(tensor: Tensor, group: Optional[Group] = None) -> Tensor:
    """Split last dim, keep local rank's slice (ref: mp_ops.py:141)."""
    n, r = _nranks(group)
    if n == 1:
        return tensor
    def f(x):
        return jnp.split(x, n, axis=-1)[r]
    return apply_op(f, tensor, op_name="c_split")


def _c_concat(tensor: Tensor, group: Optional[Group] = None) -> Tensor:
    """All-gather along last dim (ref: mp_ops.py:176). Single-controller:
    identity (the value is already global)."""
    return apply_op(lambda x: x, tensor, op_name="c_concat")


def split(x, size, operation: str, axis: int = 0, num_partitions: int = 1,
          gather_out: bool = True, weight_attr=None, bias_attr=None,
          name=None):
    """ref: mp_ops.py:706 paddle.distributed.split — sugar constructing a
    row/column-parallel linear or vocab-parallel embedding."""
    from .mp_layers import (ColumnParallelLinear, RowParallelLinear,
                            VocabParallelEmbedding)
    if operation == "linear":
        in_f, out_f = size
        if axis == 0:
            layer = RowParallelLinear(
                in_f, out_f, weight_attr=weight_attr,
                has_bias=bias_attr is not False, name=name)
        else:
            layer = ColumnParallelLinear(
                in_f, out_f, weight_attr=weight_attr,
                has_bias=bias_attr is not False,
                gather_output=gather_out, name=name)
        return layer(x)
    elif operation == "embedding":
        vocab, dim = size
        layer = VocabParallelEmbedding(vocab, dim, weight_attr=weight_attr,
                                       name=name)
        return layer(x)
    raise ValueError(f"unknown split operation {operation}")
