"""TensorParallel model wrapper.

ref: python/paddle/distributed/fleet/meta_parallel/tensor_parallel.py —
broadcasts non-mp params within the mp group at wrap time and syncs
gradients of sequence-parallel params. Single-controller TPU: parameters
are logically global (replicated or mp-sharded jax.Arrays), so broadcast
is structural, not a comm.
"""
from __future__ import annotations

from ...nn.layer import Layer
from ..collective import broadcast
from ..parallel import get_world_size

__all__ = ["TensorParallel"]


class TensorParallel(Layer):
    def __init__(self, layers: Layer, hcg, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        if get_world_size() > 1:
            src = hcg.get_model_parallel_group_src_rank()
            group = hcg.get_model_parallel_group()
            for p in layers.parameters():
                if getattr(p, "_dist_attr", None) is None:
                    broadcast(p, src=src, group=group)

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)

    def __getattr__(self, name):
        try:
            return super().__getattr__(name)
        except AttributeError:
            return getattr(self._layers, name)
