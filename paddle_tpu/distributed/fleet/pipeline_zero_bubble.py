"""Zero-bubble pipeline schedule (ZB-H1): backward split into B and W.

ref: python/paddle/distributed/passes/pipeline_scheduler_pass/
pipeline_zero_bubble.py — the reference implements ZB as a static-graph
schedule pass splitting each micro-batch's backward into B (activation
/ input gradients, which unblock the upstream stage) and W (weight
gradients, deferrable). B runs where 1F1B ran its full backward; W fills
what would otherwise be cooldown bubble. With unit costs t_F=t_B=t_W,
per-stage bubble drops from (S-1)(t_F + t_B + t_W) to
(S-1)(t_F + t_B) — a third less (Qi et al., "Zero Bubble Pipeline
Parallelism", H1 variant: no extra activation memory vs 1F1B).

TPU-native decomposition: a stage's B and W are two separately compiled
programs — B = grad of the stage output w.r.t. its INPUT, W = grad
w.r.t. its PARAMS (both jitted once per shape; XLA rematerializes the
stage forward inside each, the standard remat trade for schedule
freedom). The host-driven runtime executes the per-stage event list from
``zb_h1_schedule`` with p2p sends issued right after B — upstream gets
its output grad t_W earlier than under 1F1B, which is where the bubble
goes.

``simulate_schedule`` replays event lists under a dependency-respecting
clock so tests can assert the bubble reduction exactly
(tests/test_pipeline_zero_bubble.py).
"""
from __future__ import annotations

from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from .pipeline_parallel import PipelineParallel

__all__ = ["PipelineParallelZeroBubble", "zb_h1_schedule",
           "one_f_one_b_schedule", "simulate_schedule"]


# -- schedules (event lists: ("F"|"B"|"W", microbatch)) -------------------

def one_f_one_b_schedule(num_stages: int, stage: int, micro: int
                         ) -> List[Tuple[str, int]]:
    """The 1F1B order with B meaning the FULL backward (B+W fused) —
    the baseline the ZB simulator compares against. W events carry the
    same micro id immediately after their B (fused => same slot)."""
    w = min(num_stages - 1 - stage, micro)
    ev: List[Tuple[str, int]] = [("F", m) for m in range(w)]
    b = 0
    for m in range(w, micro):
        ev.append(("F", m))
        ev.append(("B", b))
        ev.append(("W", b))  # fused with B in 1F1B
        b += 1
    while b < micro:
        ev.append(("B", b))
        ev.append(("W", b))
        b += 1
    return ev


def zb_h1_schedule(num_stages: int, stage: int, micro: int
                   ) -> List[Tuple[str, int]]:
    """ZB-H1 per-stage order: warmup and steady match 1F1B exactly
    (F,B,W per steady slot — same activation high-water), but the
    COOLDOWN runs its remaining B's back-to-back with every W deferred
    to the tail. The cross-stage B dependency chain is what serializes
    the cooldown; taking the W's off that critical path is the
    zero-bubble trick — upstream stages receive their output grads
    t_W earlier per hop. ref: pipeline_zero_bubble.py
    _split_matmul_grad_to_matmul + schedule assembly."""
    w = min(num_stages - 1 - stage, micro)
    ev: List[Tuple[str, int]] = [("F", m) for m in range(w)]
    b = 0
    for m in range(w, micro):          # steady: F,B,W (1F1B memory)
        ev.append(("F", m))
        ev.append(("B", b))
        ev.append(("W", b))
        b += 1
    pending: List[int] = []
    while b < micro:                   # cooldown: B-chain only
        ev.append(("B", b))
        pending.append(b)
        b += 1
    for m in pending:                  # tail: deferred W's fill the idle
        ev.append(("W", m))
    return ev


def simulate_schedule(schedules: Dict[int, List[Tuple[str, int]]],
                      t_f: int = 1, t_b: int = 1, t_w: int = 1,
                      fused_bw: bool = False) -> Dict[int, int]:
    """Dependency-respecting clock replay. F(m,s) needs F(m,s-1);
    B(m,s) needs F(m,s) and B(m,s+1); W(m,s) needs B(m,s). Returns
    per-stage idle time (bubble) up to each stage's last event."""
    S = len(schedules)
    done: Dict[Tuple[str, int, int], int] = {}
    clock = {s: 0 for s in range(S)}
    idle = {s: 0 for s in range(S)}
    pos = {s: 0 for s in range(S)}
    total = sum(len(v) for v in schedules.values())
    n_done = 0
    while n_done < total:
        progressed = False
        for s in range(S):
            if pos[s] >= len(schedules[s]):
                continue
            kind, m = schedules[s][pos[s]]
            deps = []
            if kind == "F" and s > 0:
                deps.append(("F", m, s - 1))
            if kind == "B":
                deps.append(("F", m, s))
                if s < S - 1:
                    deps.append(("B", m, s + 1))
            if kind == "W":
                deps.append(("B", m, s))
            if any(d not in done for d in deps):
                continue
            ready = max([done[d] for d in deps], default=0)
            start = max(clock[s], ready)
            idle[s] += start - clock[s]
            cost = {"F": t_f, "B": t_b + (t_w if fused_bw else 0),
                    "W": 0 if fused_bw else t_w}[kind]
            clock[s] = start + cost
            done[(kind, m, s)] = clock[s]
            pos[s] += 1
            n_done += 1
            progressed = True
        if not progressed:
            raise RuntimeError("schedule deadlock (bad event order)")
    return idle


# -- runtime --------------------------------------------------------------

class PipelineParallelZeroBubble(PipelineParallel):
    """Host-driven ZB-H1 runtime: B unblocks upstream immediately, W
    drains into the bubble. Single-controller runs F/B/W per micro-batch
    with W genuinely deferred (numerics identical to 1F1B, asserted in
    tests); across launched processes the per-stage ``zb_h1_schedule``
    order runs with p2p exchanges placed right after each B."""

    def __init__(self, layers, hcg, strategy):
        super().__init__(layers, hcg, strategy)
        self._progs = None
        self.peak_stash = 0
        self.last_schedule: List[Tuple[str, int]] = []

    # B/W programs: two jitted grads per stage (see module docstring)
    def _build_progs(self):
        if self._layers._loss_fn is None:
            raise ValueError(
                "the zero-bubble schedule needs a PipelineLayer loss_fn "
                "(the split B/W grad programs differentiate the loss); "
                "build PipelineLayer(..., loss_fn=...) or use the 1F1B "
                "runtime for loss-less forward pipelines")
        from ...jit.api import functionalize
        apply, params0, buffers0 = functionalize(self._layers)

        def out_of(params, x):
            return apply(params, buffers0, x)[0]

        def loss_of(params, x, label):
            out = out_of(params, x)
            loss = self._layers._loss_fn(Tensor(out), Tensor(label))
            val = loss._data if isinstance(loss, Tensor) else loss
            return (val.mean() if val.ndim > 0 else val)

        fwd = jax.jit(out_of)

        def b_mid(params, x, g):
            _, vjp = jax.vjp(lambda xx: out_of(params, xx), x)
            return vjp(g)[0]

        def w_mid(params, x, g):
            _, vjp = jax.vjp(lambda pp: out_of(pp, x), params)
            return vjp(g)[0]

        b_last = jax.jit(jax.grad(loss_of, argnums=1))
        w_last = jax.jit(jax.grad(loss_of, argnums=0))
        self._progs = {
            "params0": params0, "fwd": fwd,
            "b_mid": jax.jit(b_mid), "w_mid": jax.jit(w_mid),
            "b_last": b_last, "w_last": w_last,
            "loss": jax.jit(loss_of),
        }

    def _accumulate_param_grads(self, dparams, scale):
        named = dict(self._layers.named_parameters())
        for k, g in dparams.items():
            p = named.get(k)
            if p is None or p.stop_gradient:
                continue
            g = g * scale
            if p.grad is None:
                p.grad = Tensor(g.astype(p._data.dtype))
            else:
                p.grad._data = p.grad._data + g.astype(p._data.dtype)

    def forward_backward_pipeline(self, data, scaler=None):
        from ..parallel import get_world_size
        if self.num_stages > 1 and get_world_size() > 1:
            return self._zb_multiproc(data, scaler)
        return self._zb_single(data, scaler)

    def _zb_single(self, data, scaler):
        """Single controller: F all + B all + deferred W all, through the
        same split programs the distributed schedule uses — identical
        numerics to 1F1B (the W deferral is real: no weight grad exists
        until the W phase)."""
        if self._progs is None:
            self._build_progs()
        P_ = self._progs
        inputs, labels = data if isinstance(data, (tuple, list)) and \
            len(data) == 2 else (data, None)
        micro_inputs = self._split_micro(inputs)
        micro_labels = self._split_micro(labels)
        M = self.accumulate_steps
        params = {k: p._data for k, p in
                  dict(self._layers.named_parameters()).items()}
        scale = jnp.float32(1.0 / M)
        if scaler is not None:
            scale = scale * scaler._scale._data.astype(jnp.float32)
        stash = []
        total = None
        self.last_schedule = []
        for m, (mi, ml) in enumerate(zip(micro_inputs, micro_labels)):
            x = mi._data if isinstance(mi, Tensor) else jnp.asarray(mi)
            lb = ml._data if isinstance(ml, Tensor) else ml
            loss = P_["loss"](params, x, lb)
            total = loss if total is None else total + loss
            stash.append((m, x, lb))
            self.peak_stash = max(self.peak_stash, len(stash))
            self.last_schedule.append(("F", m))
            self.last_schedule.append(("B", m))  # dx of the first stage
            # (single stage owns the whole model: B has no consumer)
        for m, x, lb in stash:                    # deferred W drain
            dparams = P_["w_last"](params, x, lb)
            self._accumulate_param_grads(dparams, scale)
            self.last_schedule.append(("W", m))
        self.total_loss = Tensor(total / M)
        return self.total_loss

    def _zb_multiproc(self, data, scaler):
        """Cross-process ZB-H1: per-stage event list from
        zb_h1_schedule; dx is sent the moment B finishes (the W that
        1F1B would have run first is deferred into the bubble)."""
        from ..collective import broadcast, recv, send
        if self._progs is None:
            self._build_progs()
        P_ = self._progs
        g = self._hcg.get_pipe_parallel_group()
        pp_ranks = g.ranks
        s, S, M = self.stage_id, self.num_stages, self.accumulate_steps
        prev_rank = pp_ranks[s - 1] if s > 0 else None
        next_rank = pp_ranks[s + 1] if s < S - 1 else None

        inputs, labels = data if isinstance(data, (tuple, list)) and \
            len(data) == 2 else (data, None)
        micro_inputs = self._split_micro(inputs) if self.is_first_stage \
            else [None] * M
        micro_labels = self._split_micro(labels) if self.is_last_stage \
            else [None] * M
        params = {k: p._data for k, p in
                  dict(self._layers.named_parameters()).items()}
        scale = jnp.float32(1.0 / M)
        if scaler is not None:
            scale = scale * scaler._scale._data.astype(jnp.float32)

        xs: Dict[int, jnp.ndarray] = {}       # stage input per micro
        gs: Dict[int, jnp.ndarray] = {}       # output grad per micro
        total = None
        sched = zb_h1_schedule(S, s, M)
        self.last_schedule = sched
        for kind, m in sched:
            if kind == "F":
                if self.is_first_stage:
                    mi = micro_inputs[m]
                    x = mi._data if isinstance(mi, Tensor) else \
                        jnp.asarray(mi)
                else:
                    t = Tensor(jnp.zeros((1,), jnp.float32))
                    recv(t, src=prev_rank, group=g)
                    x = t._data
                xs[m] = x
                self.peak_stash = max(self.peak_stash, len(xs))
                if self.is_last_stage:
                    ml = micro_labels[m]
                    lb = ml._data if isinstance(ml, Tensor) else ml
                    loss = P_["loss"](params, x, lb)
                    total = loss if total is None else total + loss
                    gs[m] = lb  # stash the label for B/W
                else:
                    out = P_["fwd"](params, x)
                    send(Tensor(out), dst=next_rank, group=g)
            elif kind == "B":
                if self.is_last_stage:
                    dx = P_["b_last"](params, xs[m], gs[m])
                else:
                    t = Tensor(jnp.zeros((1,), jnp.float32))
                    recv(t, src=next_rank, group=g)
                    gs[m] = t._data
                    # the first stage has no upstream consumer for dx —
                    # skip the whole input-grad program, keep the recv
                    dx = (P_["b_mid"](params, xs[m], gs[m])
                          if not self.is_first_stage else None)
                if not self.is_first_stage:
                    send(Tensor(dx), dst=prev_rank, group=g)
            else:  # W — deferred weight grads from the stashed (x, g)
                if self.is_last_stage:
                    dparams = P_["w_last"](params, xs[m], gs[m])
                else:
                    dparams = P_["w_mid"](params, xs[m], gs[m])
                self._accumulate_param_grads(dparams, scale)
                xs.pop(m, None)
                gs.pop(m, None)

        loss_t = Tensor((total / M) if total is not None
                        else jnp.zeros((), jnp.float32))
        broadcast(loss_t, src=pp_ranks[-1], group=g)
        self.total_loss = loss_t
        return loss_t
