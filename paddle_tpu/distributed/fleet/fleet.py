"""Fleet: the hybrid-parallel orchestration singleton.

ref: python/paddle/distributed/fleet/fleet.py:218 (fleet.init) and :674
(_init_hybrid_parallel_env); fleet/model.py:32 (distributed_model);
DistributedStrategy (framework/distributed_strategy.proto exposed as
fleet/base/distributed_strategy.py). TPU-native: init builds the
CommunicateTopology + HybridCommunicateGroup whose product mesh is one
jax Mesh; wrappers choose DataParallel / TensorParallel / PipelineParallel
by strategy exactly as the reference does.
"""
from __future__ import annotations

import os
from typing import Optional

from ..collective import Group
from ..parallel import DataParallel, get_rank, get_world_size, init_parallel_env
from .topology import CommunicateTopology, HybridCommunicateGroup

__all__ = [
    "DistributedStrategy", "init", "fleet", "distributed_model",
    "distributed_optimizer", "get_hybrid_communicate_group",
]

_hcg: Optional[HybridCommunicateGroup] = None
_strategy = None


class DistributedStrategy:
    """ref: fleet/base/distributed_strategy.py — dataclass stand-in for the
    protobuf strategy; hybrid_configs drives topology construction."""

    def __init__(self):
        self.hybrid_configs = {
            "dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
            "sharding_degree": 1, "sep_degree": 1,
        }
        self.pipeline_configs = {
            "accumulate_steps": 1, "micro_batch_size": 1,
        }
        self.sharding_configs = {"stage": 1}
        self.amp = False
        self.amp_configs = {}
        self.recompute = False
        self.recompute_configs = {}
        self.gradient_merge = False
        self.gradient_merge_configs = {}
        self.find_unused_parameters = False

    def __repr__(self):
        return f"DistributedStrategy(hybrid={self.hybrid_configs})"


class _Fleet:
    """Module-level singleton mirroring `paddle.distributed.fleet`."""

    def __init__(self):
        self._is_initialized = False

    # -- init ---------------------------------------------------------------
    def init(self, role_maker=None, is_collective: bool = True,
             strategy: Optional[DistributedStrategy] = None, log_level="INFO"):
        global _hcg, _strategy
        _strategy = strategy or DistributedStrategy()
        init_parallel_env()
        hc = _strategy.hybrid_configs
        world = get_world_size()
        degrees = {
            "dp": int(hc.get("dp_degree", 1)),
            "pp": int(hc.get("pp_degree", 1)),
            "sharding": int(hc.get("sharding_degree", 1)),
            "sep": int(hc.get("sep_degree", 1)),
            "mp": int(hc.get("mp_degree", 1)),
        }
        # reference infers dp_degree as the remainder (fleet.py hybrid init)
        prod_non_dp = (degrees["pp"] * degrees["sharding"] * degrees["sep"]
                       * degrees["mp"])
        if degrees["dp"] * prod_non_dp != world and world % prod_non_dp == 0:
            degrees["dp"] = world // prod_non_dp
        topo = CommunicateTopology(
            ["dp", "pp", "sharding", "sep", "mp"],
            [degrees["dp"], degrees["pp"], degrees["sharding"],
             degrees["sep"], degrees["mp"]])
        _hcg = HybridCommunicateGroup(topo, get_rank())
        self._is_initialized = True
        return self

    def is_initialized(self):
        return self._is_initialized

    # -- accessors ----------------------------------------------------------
    def get_hybrid_communicate_group(self) -> HybridCommunicateGroup:
        return _hcg

    @property
    def worker_num(self):
        return get_world_size()

    def worker_index(self):
        return get_rank()

    # -- wrappers (ref: fleet/model.py:32, fleet/fleet.py distributed_*) ----
    def distributed_model(self, model):
        strategy = _strategy or DistributedStrategy()
        hcg = _hcg
        if hcg is not None and hcg.get_pipe_parallel_world_size() > 1:
            from .pipeline_parallel import (PipelineParallel,
                                            PipelineParallelWithInterleave)
            pcfg = getattr(strategy, "pipeline_configs", {}) or {}
            if str(pcfg.get("schedule_mode", "")).upper() in (
                    "ZB", "ZB-H1", "ZBH1"):
                if getattr(model, "_num_virtual_stages", 1) > 1:
                    raise ValueError(
                        "schedule_mode='ZB-H1' assumes one contiguous "
                        "stage per rank; it cannot drive a PipelineLayer "
                        "with num_virtual_pipeline_stages > 1 (use the "
                        "interleaved 1F1B runtime for VPP)")
                # ref: passes/pipeline_scheduler_pass/pipeline_zero_bubble
                # selected via pipeline_configs schedule_mode
                from .pipeline_zero_bubble import PipelineParallelZeroBubble
                return PipelineParallelZeroBubble(model, hcg, strategy)
            if getattr(model, "_num_virtual_stages", 1) > 1:
                # ref: fleet/model.py:162-172 picks the interleave runtime
                # when the PipelineLayer declares virtual stages
                return PipelineParallelWithInterleave(model, hcg, strategy)
            return PipelineParallel(model, hcg, strategy)
        if hcg is not None and hcg.get_model_parallel_world_size() > 1:
            from .tensor_parallel import TensorParallel
            return TensorParallel(model, hcg, strategy)
        if get_world_size() > 1:
            return DataParallel(
                model,
                find_unused_parameters=strategy.find_unused_parameters)
        return model

    def distributed_optimizer(self, optimizer, strategy=None):
        from .hybrid_parallel_optimizer import HybridParallelOptimizer
        st = strategy or _strategy or DistributedStrategy()
        if _hcg is not None:
            return HybridParallelOptimizer(optimizer, _hcg, st)
        return optimizer

    # PS-mode API surface kept for signature parity (non-goal per SURVEY §7)
    def is_first_worker(self):
        return get_rank() == 0

    def barrier_worker(self):
        from ..collective import barrier
        barrier()


fleet = _Fleet()
init = fleet.init
distributed_model = fleet.distributed_model
distributed_optimizer = fleet.distributed_optimizer
get_hybrid_communicate_group = fleet.get_hybrid_communicate_group


def _reset_for_tests():
    """Clear the global hybrid-parallel state so one test's fleet.init
    cannot leak an active mesh into later tests."""
    global _hcg, _strategy
    _hcg = None
    _strategy = None
    fleet._is_initialized = False
