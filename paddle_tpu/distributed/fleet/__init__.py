"""paddle.distributed.fleet equivalent.

ref: python/paddle/distributed/fleet/__init__.py — hybrid-parallel
orchestration: topology, TP/PP/sharding wrappers, meta-optimizers.
"""
from .fleet import (  # noqa: F401
    DistributedStrategy, init, fleet, distributed_model,
    distributed_optimizer, get_hybrid_communicate_group,
)
from . import utils  # noqa: F401
from .topology import CommunicateTopology, HybridCommunicateGroup  # noqa: F401
from . import mp_ops  # noqa: F401
from .mp_layers import (  # noqa: F401
    VocabParallelEmbedding, ColumnParallelLinear, RowParallelLinear,
    ParallelCrossEntropy,
)
from .pp_layers import LayerDesc, SharedLayerDesc, PipelineLayer  # noqa: F401
from .pipeline_parallel import (  # noqa: F401
    PipelineParallel, PipelineParallelWithInterleave,
)
from .pipeline_zero_bubble import (  # noqa: F401
    PipelineParallelZeroBubble, zb_h1_schedule, one_f_one_b_schedule,
    simulate_schedule,
)
from .tensor_parallel import TensorParallel  # noqa: F401
from .hybrid_parallel_optimizer import HybridParallelOptimizer  # noqa: F401
from .sharding_optimizer import DygraphShardingOptimizer  # noqa: F401

# meta_parallel namespace parity (ref: fleet/meta_parallel/__init__.py)
from . import mp_layers as meta_parallel  # noqa: F401

worker_num = None  # populated via fleet singleton accessors
is_first_worker = fleet.is_first_worker
barrier_worker = fleet.barrier_worker
worker_index = fleet.worker_index
is_initialized = fleet.is_initialized
