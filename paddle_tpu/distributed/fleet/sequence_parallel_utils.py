"""Megatron sequence parallelism: activations sharded along the sequence
axis inside the TP group.

ref: python/paddle/distributed/fleet/utils/sequence_parallel_utils.py —
ScatterOp/GatherOp (:42-127), ColumnSequenceParallelLinear (:427),
RowSequenceParallelLinear (:562), register_sequence_parallel_allreduce
(:192). TPU-native: the scatter/all-gather/reduce-scatter choreography is
*placement* — a with_sharding_constraint on the sequence dim before/after
the sharded matmuls; GSPMD inserts the same collectives the reference
issues manually, and fuses them with the matmuls where profitable.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ...core.autograd import apply_op
from ...core.tensor import Tensor
from ...nn import functional as F
from ...nn import initializer as I
from ...nn.layer import Layer
from ..api import shard_parameter
from .mp_layers import _current_mp_mesh

__all__ = [
    "ScatterOp", "GatherOp", "AllGatherOp", "ReduceScatterOp",
    "ColumnSequenceParallelLinear", "RowSequenceParallelLinear",
    "mark_as_sequence_parallel_parameter",
]


def _seq_axis_name() -> Optional[str]:
    mesh = _current_mp_mesh()
    if mesh is None:
        return None
    if "sp" in mesh.dim_names:
        return "sp"
    if "mp" in mesh.dim_names:
        return "mp"  # reference: SP reuses the TP group
    return None


def _constrain(x, dim: Optional[int], axis: Optional[str]):
    """Sharding constraint on one dim (None axis or no trace: identity).
    Errors inside a traced program (bad axis name etc.) surface — a
    swallowed constraint would make SP a silent no-op."""
    if axis is None:
        return x
    mesh = _current_mp_mesh()
    if mesh is None:
        return x
    from jax.sharding import NamedSharding
    jmesh = mesh.to_jax_mesh()

    def f(a):
        if not isinstance(a, jax.core.Tracer):
            return a  # eager arrays already have a concrete placement
        spec = [None] * a.ndim
        if dim is not None and a.ndim > dim:
            spec[dim] = axis
        return jax.lax.with_sharding_constraint(
            a, NamedSharding(jmesh, P(*spec)))
    return apply_op(f, x, op_name="sharding_constraint")


def _constrain_seq(x, shard: bool):
    """Constrain activation sharding along dim 1 (sequence)."""
    return _constrain(x, 1 if shard else None, _seq_axis_name())


class ScatterOp:
    """ref: sequence_parallel_utils.py ScatterOp — split along seq."""

    @staticmethod
    def apply(x):
        return _constrain_seq(x, shard=True)


class GatherOp:
    """ref: GatherOp — all-gather along seq."""

    @staticmethod
    def apply(x):
        return _constrain_seq(x, shard=False)


AllGatherOp = GatherOp
ReduceScatterOp = ScatterOp


def mark_as_sequence_parallel_parameter(param):
    """ref: :192 register_sequence_parallel_allreduce — under GSPMD the
    gradient reduction falls out of the sharded program; the mark is kept
    for API parity."""
    param._sequence_parallel = True
    return param


class ColumnSequenceParallelLinear(Layer):
    """ref: :427 — input arrives seq-sharded, is (implicitly) gathered for
    the column-parallel matmul; output stays TP-sharded on features."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=False, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.bias = self.create_parameter(
            [out_features], is_bias=True) if has_bias else None
        mesh = _current_mp_mesh()
        if mesh is not None:
            shard_parameter(self.weight, mesh, tp_axis="mp", tp_dim=1)
            if self.bias is not None:
                shard_parameter(self.bias, mesh, tp_axis="mp", tp_dim=0)
        self.gather_output = gather_output

    def forward(self, x):
        x = GatherOp.apply(x)          # [B, L/sp, H] -> full seq
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            # all-gather the TP-sharded feature dim (ref: gather_output)
            mesh = _current_mp_mesh()
            if mesh is not None and "mp" in mesh.dim_names:
                out = _constrain(out, None, "mp")
        return out


class RowSequenceParallelLinear(Layer):
    """ref: :562 — row-parallel matmul whose partial outputs reduce-scatter
    back onto the sequence axis."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=True, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.bias = self.create_parameter(
            [out_features], is_bias=True) if has_bias else None
        mesh = _current_mp_mesh()
        if mesh is not None:
            shard_parameter(self.weight, mesh, tp_axis="mp", tp_dim=0)
        self.input_is_parallel = input_is_parallel

    def forward(self, x):
        if not self.input_is_parallel:
            # split the full input's feature dim across the TP group
            # (ref: input_is_parallel=False path)
            mesh = _current_mp_mesh()
            if mesh is not None and "mp" in mesh.dim_names:
                x = _constrain(x, x.ndim - 1, "mp")
        out = F.linear(x, self.weight, self.bias)
        return ScatterOp.apply(out)    # reduce-scatter onto seq axis
