"""Semi-auto-parallel DTensor API: shard_tensor / reshard / shard_layer.

ref: python/paddle/distributed/auto_parallel/api.py:727 (reshard),
paddle/phi/core/distributed/auto_parallel/dist_tensor.h:39 (DistTensor =
local shard + TensorDistAttr{mesh, placements}). TPU-native mapping: the
"DistTensor" is simply a Tensor whose jax.Array carries a NamedSharding
(GSPMD); the reference's pairwise reshard-function lattice
(ref: auto_parallel/reshard/*_reshard_function.cc) collapses to
jax.device_put with a new sharding — XLA inserts the all-gather /
slice / all-to-all — except Partial, which we materialize with a psum
via shard_map before re-placing.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor
from .placement import Partial, Placement, Replicate, Shard
from .process_mesh import ProcessMesh

__all__ = [
    "DistAttr", "shard_tensor", "dtensor_from_fn", "reshard", "shard_layer",
    "unshard_dtensor", "placements_to_spec", "shard_parameter",
    "shard_batch",
]


class DistAttr:
    """TensorDistAttr analog (ref: dist_tensor.h:39): mesh + placements."""

    def __init__(self, mesh: ProcessMesh, placements: Sequence[Placement]):
        self.process_mesh = mesh
        self.placements = list(placements)

    def __repr__(self):
        return f"DistAttr(mesh={self.process_mesh.shape}, placements={self.placements})"


def placements_to_spec(mesh: ProcessMesh, placements: Sequence[Placement],
                       ndim: int) -> P:
    """[Shard(0), Replicate()] on mesh axes -> PartitionSpec per tensor dim.

    Mirrors dims_mapping in the reference (ref: process_mesh + dims_mapping in
    phi/core/distributed/auto_parallel/dist_attr.h): mesh axis i shards tensor
    dim placements[i].dim. Multiple mesh axes on one tensor dim stack into a
    tuple spec entry (the GSPMD composite-axes form).
    """
    dim_axes: List[Optional[object]] = [None] * ndim
    for axis_name, placement in zip(mesh.dim_names, placements):
        if isinstance(placement, Shard):
            d = placement.dim % ndim
            if dim_axes[d] is None:
                dim_axes[d] = axis_name
            elif isinstance(dim_axes[d], tuple):
                dim_axes[d] = dim_axes[d] + (axis_name,)
            else:
                dim_axes[d] = (dim_axes[d], axis_name)
    return P(*dim_axes)


def _named_sharding(mesh: ProcessMesh, placements: Sequence[Placement],
                    ndim: int) -> NamedSharding:
    return NamedSharding(mesh.to_jax_mesh(),
                         placements_to_spec(mesh, placements, ndim))


def _normalize_placements(mesh: ProcessMesh,
                          placements: Optional[Sequence[Placement]]):
    if placements is None:
        return [Replicate() for _ in range(mesh.ndim)]
    placements = list(placements)
    while len(placements) < mesh.ndim:
        placements.append(Replicate())
    return placements


def shard_tensor(data, mesh: ProcessMesh,
                 placements: Optional[Sequence[Placement]] = None,
                 dtype=None, stop_gradient=None) -> Tensor:
    """ref: python/paddle/distributed/auto_parallel/api.py shard_tensor."""
    from ..core.tensor import to_tensor
    t = data if isinstance(data, Tensor) else to_tensor(data, dtype=dtype)
    placements = _normalize_placements(mesh, placements)
    sharding = _named_sharding(mesh, placements, t._data.ndim)
    arr = jax.device_put(t._data, sharding)
    sg = t.stop_gradient if stop_gradient is None else stop_gradient
    out = Tensor(arr, stop_gradient=sg)
    out._dist_attr = DistAttr(mesh, placements)
    if isinstance(data, Tensor):
        out.name = data.name
    return out


def shard_batch(data, mesh: ProcessMesh,
                placements: Optional[Sequence[Placement]] = None,
                dtype=None) -> Tensor:
    """Assemble each process's LOCAL batch shard into one global
    DistTensor — the multi-controller data-feeding contract: every rank's
    DataLoader yields only ITS OWN rows (the reference's
    DistributedBatchSampler split, ref: python/paddle/io/dataloader —
    each NCCL rank feeds its local batch), and the global array spanning
    the mesh is assembled from those per-process pieces without any rank
    ever holding the full batch.

    Default placement shards dim 0 along the mesh's FIRST axis. On a
    single controller this degenerates to shard_tensor (local == global).
    """
    import numpy as np
    placements = _normalize_placements(
        mesh, placements if placements is not None else [Shard(0)])
    local = data._data if isinstance(data, Tensor) else data
    local = np.asarray(local, dtype=dtype)
    sharding = _named_sharding(mesh, placements, local.ndim)
    if jax.process_count() == 1:
        arr = jax.device_put(local, sharding)
    else:
        arr = jax.make_array_from_process_local_data(sharding, local)
    out = Tensor(arr, stop_gradient=True)
    out._dist_attr = DistAttr(mesh, placements)
    return out


def dtensor_from_fn(fn, mesh: ProcessMesh,
                    placements: Sequence[Placement], *args, **kwargs) -> Tensor:
    """ref: auto_parallel/api.py dtensor_from_fn."""
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def _materialize_partial(t: Tensor, mesh: ProcessMesh,
                         placements: List[Placement]) -> Tensor:
    """psum away Partial placements so only Shard/Replicate remain."""
    from ._mesh_axes import shard_map

    partial_axes = [mesh.dim_names[i] for i, p in enumerate(placements)
                    if isinstance(p, Partial)]
    if not partial_axes:
        return t
    jmesh = mesh.to_jax_mesh()
    in_spec = placements_to_spec(mesh, placements, t._data.ndim)

    def _reduce(x):
        return jax.lax.psum(x, tuple(partial_axes))

    fn = shard_map(_reduce, mesh=jmesh, in_specs=(in_spec,), out_specs=in_spec)
    arr = jax.jit(fn)(t._data)
    new_placements = [Replicate() if isinstance(p, Partial) else p
                      for p in placements]
    out = Tensor(arr, stop_gradient=t.stop_gradient)
    out._dist_attr = DistAttr(mesh, new_placements)
    return out


def reshard(t: Tensor, mesh: ProcessMesh,
            placements: Sequence[Placement]) -> Tensor:
    """ref: auto_parallel/api.py:727. All lattice transitions (r<->s, s<->s
    alltoall, p->r, p->s, cross-mesh) reduce to: psum partials, then
    device_put with the target NamedSharding (XLA emits the collective)."""
    placements = _normalize_placements(mesh, placements)
    src_attr = getattr(t, "_dist_attr", None)
    if src_attr is not None and any(isinstance(p, Partial)
                                    for p in src_attr.placements):
        t = _materialize_partial(t, src_attr.process_mesh, src_attr.placements)
    if any(isinstance(p, Partial) for p in placements):
        raise ValueError("reshard target placements cannot be Partial")
    sharding = _named_sharding(mesh, placements, t._data.ndim)
    arr = jax.device_put(t._data, sharding)
    out = Tensor(arr, stop_gradient=t.stop_gradient)
    out._dist_attr = DistAttr(mesh, list(placements))
    return out


def shard_layer(layer, process_mesh: ProcessMesh,
                shard_fn=None, input_fn=None, output_fn=None):
    """ref: auto_parallel/api.py shard_layer — apply shard_fn(name, layer,
    mesh) to every sublayer to re-place its params; default replicates."""
    def _default_shard_fn(name, sublayer, mesh):
        for pname, param in list(sublayer._parameters.items()):
            if param is not None:
                sharded = shard_tensor(
                    param, mesh, [Replicate() for _ in range(mesh.ndim)])
                param._data = sharded._data
                param._dist_attr = sharded._dist_attr

    fn = shard_fn or _default_shard_fn
    for name, sublayer in layer.named_sublayers(include_self=True):
        fn(name, sublayer, process_mesh)
    if input_fn is not None:
        layer.register_forward_pre_hook(
            lambda _layer, inputs: input_fn(inputs, process_mesh))
    if output_fn is not None:
        layer.register_forward_post_hook(
            lambda _layer, inputs, outputs: output_fn(outputs, process_mesh))
    return layer


def shard_parameter(param, mesh: ProcessMesh, tp_axis: Optional[str] = None,
                    fsdp_axis: Optional[str] = None,
                    tp_dim: Optional[int] = None,
                    fsdp_dim: Optional[int] = None) -> None:
    """In-place tp/fsdp placement for one parameter — the shared placement
    algebra behind the model zoo's shard_* rule tables (ref: the per-weight
    shard_tensor calls in semi_auto_parallel_llama_model.py).

    tp_dim shards on tp_axis (column=out dim, row=in dim for [in, out]
    weights); fsdp_dim shards the remaining dim on fsdp_axis unless it
    would collide with the tp split. Dims beyond the param's rank are
    ignored.
    """
    axis_names = list(mesh.dim_names)
    placements: List[Placement] = [Replicate() for _ in axis_names]
    ndim = param._data.ndim
    if tp_axis in axis_names and tp_dim is not None and tp_dim < ndim:
        placements[axis_names.index(tp_axis)] = Shard(tp_dim)
    else:
        tp_dim = None
    if (fsdp_axis in axis_names and fsdp_dim is not None
            and fsdp_dim < ndim and fsdp_dim != tp_dim):
        placements[axis_names.index(fsdp_axis)] = Shard(fsdp_dim)
    sharded = shard_tensor(param, mesh, placements,
                           stop_gradient=param.stop_gradient)
    param._data = sharded._data
    param._dist_attr = sharded._dist_attr


def unshard_dtensor(t: Tensor) -> Tensor:
    """Gather a DistTensor to a fully-replicated dense tensor.

    ref: auto_parallel/api.py unshard_dtensor."""
    attr = getattr(t, "_dist_attr", None)
    if attr is None:
        return t
    if any(isinstance(p, Partial) for p in attr.placements):
        t = _materialize_partial(t, attr.process_mesh, attr.placements)
        attr = t._dist_attr
    mesh = attr.process_mesh
    sharding = _named_sharding(
        mesh, [Replicate()] * mesh.ndim, t._data.ndim)
    out = Tensor(jax.device_put(t._data, sharding),
                 stop_gradient=t.stop_gradient)
    out._dist_attr = None
    return out
