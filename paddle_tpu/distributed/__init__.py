"""paddle.distributed equivalent, TPU-native.

ref: python/paddle/distributed/__init__.py. Three API tiers, as in the
reference: (1) eager collectives + groups (communication/), (2) semi-auto
parallel DTensor (auto_parallel/api.py), (3) fleet hybrid-parallel
orchestration (fleet/). All three ride jax.sharding + XLA collectives.
"""
from .placement import Placement, Replicate, Shard, Partial  # noqa: F401
from .process_mesh import (  # noqa: F401
    ProcessMesh, get_default_mesh, set_default_mesh, init_process_mesh,
)
from .api import (  # noqa: F401
    DistAttr, shard_tensor, dtensor_from_fn, reshard, shard_layer,
    unshard_dtensor, placements_to_spec, shard_batch,
)
from .collective import (  # noqa: F401
    ReduceOp, Group, new_group, get_group, all_reduce, all_gather,
    all_gather_object, broadcast, broadcast_object_list, reduce, scatter,
    scatter_object_list, alltoall, alltoall_single, send, recv, isend,
    irecv, barrier, reduce_scatter, stream, P2POp, batch_isend_irecv,
    get_backend, destroy_process_group, is_available,
)
from .parallel import (  # noqa: F401
    init_parallel_env, get_rank, get_world_size, is_initialized,
    ParallelEnv, DataParallel,
)

from . import fleet  # noqa: F401
from . import checkpoint  # noqa: F401
from . import io  # noqa: F401
from . import launch  # noqa: F401
from .auto_parallel.api_ext import (  # noqa: F401
    shard_optimizer, shard_scaler, shard_dataloader, ShardDataloader,
    ShardingStage1, ShardingStage2, ShardingStage3, Strategy, DistModel,
    to_static,
)
from .misc import (  # noqa: F401
    ParallelMode, ReduceType, gather, wait, gloo_init_parallel_env,
    gloo_barrier, gloo_release,
)
from .spawn import spawn  # noqa: F401
from .ps_compat import (  # noqa: F401
    ProbabilityEntry, CountFilterEntry, ShowClickEntry, InMemoryDataset,
    QueueDataset,
)
from .checkpoint import save_state_dict, load_state_dict  # noqa: F401
from .store import TCPStore  # noqa: F401
from .watchdog import (  # noqa: F401
    Watchdog, WatchdogBusy, WatchdogTimeout, install_watchdog,
    uninstall_watchdog,
)
from .elastic import ElasticManager  # noqa: F401
from .ring_attention import ring_attention, ring_self_attention  # noqa: F401
from .ulysses import ulysses_attention, ulysses_self_attention  # noqa: F401
from .dist_train import DistTrainStep  # noqa: F401

# paddle.distributed.split (TP sugar) lives in fleet.mp_ops
from .fleet.mp_ops import split  # noqa: F401

__all__ = [n for n in dir() if not n.startswith("_")]
