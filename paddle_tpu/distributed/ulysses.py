"""Ulysses (DeepSpeed-style) all-to-all sequence-parallel attention.

ABSENT in the reference (SURVEY.md §2.2: no Ulysses all-to-all attention
in the snapshot) — the second TPU-native context-parallel fill alongside
ring_attention. Instead of rotating K/V around the ring, ONE all-to-all
re-shards activations from sequence-sharded [B, L/n, H, D] to
head-sharded [B, L, H/n, D]; each device then runs ordinary (flash)
attention over the FULL sequence for its head subset; a second all-to-all
restores sequence sharding. Two collectives per layer, so it wins over
ring attention when heads >> mesh axis and per-hop latency dominates.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ["ulysses_attention", "ulysses_self_attention"]


def _ulysses_local(q, k, v, axis: str, scale: float, causal: bool):
    """Runs inside shard_map with seq-sharded inputs [B, l=L/n, H, D]."""
    from ..ops.pallas.flash_attention import flash_attention

    def seq2head(x):
        # [B, l, H, D] -> [B, L, H/n, D]: scatter head chunks across the
        # axis, gather the sequence shards (rank order = sequence order)
        return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                  tiled=True)

    def head2seq(x):
        # [B, L, H/n, D] -> [B, l, H, D]: the inverse all-to-all
        return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                  tiled=True)

    qh, kh, vh = seq2head(q), seq2head(k), seq2head(v)
    # flash path: Pallas kernel on TPU, XLA sdpa fallback elsewhere — the
    # full-sequence O(L) memory profile is the point of Ulysses
    out = flash_attention(qh, kh, vh, causal=causal, scale=scale)
    return head2seq(out)


def ulysses_attention(q, k, v, mesh, axis: str = "sp",
                      causal: bool = True,
                      scale: Optional[float] = None):
    """q/k/v: [B, L, H, D] (global view), L sharded on `axis`; H must be
    divisible by the axis size. Same contract as ring_attention."""
    d = q.shape[-1]
    h = q.shape[2]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    jmesh = mesh.to_jax_mesh() if hasattr(mesh, "to_jax_mesh") else mesh
    sizes = dict(zip(jmesh.axis_names, jmesh.devices.shape))
    n = sizes[axis]
    from ._mesh_axes import classify_axes, shard_map
    batch_axes, head_axes = classify_axes(jmesh, axis)
    mp = 1
    for a in head_axes:
        mp *= sizes[a]
    if (h // mp) % n != 0:
        raise ValueError(
            f"the '{axis}' axis size {n} must divide the per-shard head "
            f"count {h}//{mp}={h // mp} (Ulysses scatters heads across "
            f"the sequence axis during attention)")
    spec = P(batch_axes or None, axis, head_axes or None, None)
    fn = shard_map(
        functools.partial(_ulysses_local, axis=axis, scale=s,
                          causal=causal),
        mesh=jmesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)


def ulysses_self_attention(q, k, v, mesh, axis: str = "sp",
                           causal: bool = True,
                           scale: Optional[float] = None):
    """Tensor-level wrapper recording one autograd node (eager API)."""
    from ..core.autograd import apply_op
    return apply_op(
        lambda a, b, c: ulysses_attention(a, b, c, mesh, axis, causal,
                                          scale),
        q, k, v, op_name="ulysses_attention")
