"""Elastic node membership: TTL heartbeats over the TCPStore driving
rank rewrite + restart.

ref: python/paddle/distributed/fleet/elastic/manager.py:125 — the
reference keeps an etcd registry with TTL leases and watchers; node
join/leave rewrites the rank environment and restarts training through
the exit-code protocol (101 restart / 102 stop, manager.py:33-34). Here
the registry is the rank-0 TCPStore (the same coordinator that
bootstraps collectives): each node heartbeats a key, a watcher computes
the alive set from heartbeat ages, and a stable membership change fires
the rewrite callback. The launcher consumes this with --elastic to kill
and respawn its workers under the new (world_size, rank_offset); the
TPU deployment note from SURVEY §5 — preemption-aware restart — is this
watcher plus resharded checkpoint restore on the training side
(dist.load_state_dict reshard-on-load).
"""
from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

from ..observability import flight as _flight

__all__ = ["ElasticManager", "ELASTIC_RESTART_CODE", "ELASTIC_EXIT_CODE"]

ELASTIC_RESTART_CODE = 101  # ref: elastic/manager.py:33
ELASTIC_EXIT_CODE = 102     # ref: elastic/manager.py:34


class ElasticManager:
    """Store-backed node registry.

    node_id: stable identity of this node (e.g. "host:port" or node_rank).
    on_membership_change(alive_ids: sorted list, my_index: int) is called
    from the watcher thread when the alive set changes and stays stable
    for `stability_ticks` scan intervals (debounces flapping nodes).
    """

    PREFIX = "elastic/hb"

    # consecutive store failures a beat/watch thread tolerates before
    # concluding the job is over (transient flakes below this are
    # absorbed — on top of the store's own per-op retry)
    MAX_CONSECUTIVE_FAILURES = 5

    def __init__(self, store, node_id: str, ttl: float = 6.0,
                 interval: float = 1.5, stability_ticks: int = 2,
                 on_membership_change: Optional[Callable] = None,
                 max_nodes: int = 64):
        self._store = store
        self.node_id = str(node_id)
        self.ttl = ttl
        self.interval = interval
        self.stability_ticks = stability_ticks
        self.on_membership_change = on_membership_change
        self.max_nodes = max_nodes
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._known: Optional[List[str]] = None
        self._pending: Optional[List[str]] = None
        self._pending_ticks = 0
        # serializes the debounce state (_known/_pending/_pending_ticks)
        # between the watch thread and user/test-driven _watch_tick
        # calls — interleaved ticks could double-fire the rewrite
        # callback or reset a half-counted debounce (lock-checker
        # hardening, PR 6). The membership callback deliberately runs
        # UNDER this lock: a second membership change must wait out an
        # in-flight re-bootstrap, not race it. Reentrant so a callback
        # that drives its own _watch_tick cannot self-deadlock.
        from ..analysis.locks import make_lock
        self._tick_lock = make_lock("elastic.watch_tick", rlock=True)
        # nid -> (last beat value, monotonic time the value last changed)
        self._beat_seen: dict = {}
        self.store_faults_survived = 0

    # -- registry ----------------------------------------------------------
    def _register(self):
        # the id joins a roster enumerable by slot index (the store has no
        # key listing, mirroring etcd prefix watches with one entry per
        # node); slots are allocated with the store's ATOMIC add so two
        # nodes starting together can never claim the same slot
        for nid in self.roster():
            if nid == self.node_id:
                return  # restart of a known node keeps its slot
        idx = self._store.add(f"{self.PREFIX}/roster_next", 1) - 1
        if idx >= self.max_nodes:
            raise RuntimeError(
                f"elastic roster full (max_nodes={self.max_nodes})")
        self._store.set(f"{self.PREFIX}/roster/{idx}",
                        self.node_id.encode())

    def _heartbeat_once(self):
        # heartbeat = atomic counter bump: liveness is judged by whether
        # the VALUE changed recently as observed on the watcher's own
        # monotonic clock — no cross-host wall-clock comparison, so clock
        # skew/NTP steps cannot fake a death
        self._store.add(f"{self.PREFIX}/beat/{self.node_id}", 1)

    def roster(self) -> List[str]:
        out = []
        for i in range(self.max_nodes):
            v = self._store.get_nowait(f"{self.PREFIX}/roster/{i}")
            if v is None:
                break
            if v.decode() not in out:
                out.append(v.decode())
        return out

    @staticmethod
    def _sort(ids: List[str]) -> List[str]:
        try:
            return sorted(ids, key=int)  # numeric node ranks keep their
        except ValueError:               # numeric order past 10 nodes
            return sorted(ids)

    def alive_nodes(self) -> List[str]:
        now = time.monotonic()
        alive = []
        for nid in self.roster():
            v = self._store.get_nowait(f"{self.PREFIX}/beat/{nid}")
            if v is None:
                self._beat_seen.pop(nid, None)  # graceful leave
                continue
            last_val, last_change = self._beat_seen.get(nid, (None, None))
            if v != last_val:
                self._beat_seen[nid] = (v, now)
                alive.append(nid)
            elif now - last_change <= self.ttl:
                alive.append(nid)
        return self._sort(alive)

    # -- watcher core ------------------------------------------------------
    def _watch_tick(self, alive: Optional[List[str]] = None):
        """One debounced membership scan (the watch thread's body,
        extracted so tests can drive it deterministically). A changed
        alive set must repeat for ``stability_ticks`` consecutive scans
        before the rewrite callback fires — a node flapping around its
        TTL (slow beat, GC pause) never triggers a restart. Returns the
        new alive list when a stable change was committed, else None."""
        with self._tick_lock:
            if alive is None:
                # snapshot INSIDE the lock: a tick that read the store
                # before a concurrent tick committed would otherwise
                # debounce (and with stability_ticks=1, fire) on stale
                # membership
                alive = self.alive_nodes()
            if alive == self._known:
                self._pending = None
                self._pending_ticks = 0
                return None
            if alive == self._pending:
                self._pending_ticks += 1
            else:
                self._pending = alive
                self._pending_ticks = 1
            if self._pending_ticks < self.stability_ticks:
                return None
            self._pending = None
            self._pending_ticks = 0
            # fire BEFORE committing _known: if the rewrite callback
            # raises (and the resilient wrapper absorbs it), the next
            # scans still see a changed set, re-debounce, and re-fire —
            # the membership change cannot be silently lost
            my = alive.index(self.node_id) \
                if self.node_id in alive else -1
            _flight.record("elastic", "membership_change",
                           n_alive=len(alive), my_index=my,
                           was=len(self._known or ()))
            if self.on_membership_change is not None:
                self.on_membership_change(alive, my)
            self._known = alive
            return alive

    # -- threads -----------------------------------------------------------
    def start(self):
        self._register()
        self._heartbeat_once()
        self._known = self.alive_nodes()

        def resilient(step):
            # transient store errors (coordinator restarting, network
            # flake) must not silently kill the thread — that turns one
            # dropped packet into a false node death. Tolerate a bounded
            # run of consecutive failures, then conclude the job ended.
            failures = 0
            while not self._stop.wait(self.interval):
                try:
                    step()
                    failures = 0
                except Exception:  # noqa: BLE001 — bounded tolerance
                    failures += 1
                    self.store_faults_survived += 1
                    _flight.record("elastic", "store_fault",
                                   node=self.node_id, streak=failures)
                    if failures >= self.MAX_CONSECUTIVE_FAILURES:
                        _flight.record("elastic", "thread_gave_up",
                                       node=self.node_id,
                                       after=failures)
                        return  # store gone for good: the job is ending

        for step in (self._heartbeat_once, self._watch_tick):
            t = threading.Thread(target=resilient, args=(step,),
                                 daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def stop(self):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)
        self._threads = []

    def leave(self):
        """Graceful departure: drop the heartbeat so peers rebalance."""
        self.stop()
        self._store.delete(f"{self.PREFIX}/beat/{self.node_id}")
