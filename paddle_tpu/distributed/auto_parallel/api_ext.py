"""Semi-auto parallel API completion: shard_optimizer / shard_scaler /
shard_dataloader, sharding-stage shard_fns, and dist.to_static
(Strategy + DistModel).

ref: python/paddle/distributed/auto_parallel/api.py:1613 (shard_optimizer
+ ShardingStage1/2/3), :2132 (shard_scaler), :2715 (shard_dataloader),
and the to_static/DistModel machinery in the same file. TPU-native: a
"distributed view" of the optimizer means optimizer-state arrays carry
the placements the shard_fn decides (GSPMD then keeps every update local
to the shard owner — the ZeRO contract); to_static compiles the whole
train step with DistTrainStep instead of building a static Program.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import numpy as np

from ...core.tensor import Tensor
from ..api import DistAttr, _named_sharding, shard_tensor
from ..placement import Partial, Replicate, Shard
from ..process_mesh import ProcessMesh

__all__ = [
    "shard_optimizer", "shard_scaler", "shard_dataloader",
    "ShardingStage1", "ShardingStage2", "ShardingStage3",
    "Strategy", "DistModel", "to_static", "ShardDataloader",
]


# ---------------------------------------------------------------------------
# sharding-stage shard_fns (ref: api.py _ShardingStageBase and subclasses)
# ---------------------------------------------------------------------------

class _ShardingStageBase:
    def __init__(self, mesh: Optional[ProcessMesh] = None):
        self._mesh = mesh
        self._sharding_mesh_axis = 0

    def _set_sharding_mesh_axis(self, axis: int):
        self._sharding_mesh_axis = axis

    def _mesh_of(self, param: Tensor) -> Optional[ProcessMesh]:
        if param._dist_attr is not None:
            return param._dist_attr.process_mesh
        return self._mesh

    def _param_placements(self, param: Tensor,
                          mesh: ProcessMesh) -> List:
        if param._dist_attr is not None:
            return list(param._dist_attr.placements)
        return [Replicate() for _ in range(mesh.ndim)]


def _apply_placements(arr, mesh: ProcessMesh, placements) -> Any:
    return jax.device_put(
        arr, _named_sharding(mesh, placements, np.ndim(arr)))


class ShardingStage1(_ShardingStageBase):
    """Builtin shard_fn: optimizer momenta sharded along the sharding mesh
    axis, scalar betas replicated (ref: api.py ShardingStage1)."""

    def __call__(self, key: str, param: Tensor, accumulator: Tensor):
        mesh = self._mesh_of(param)
        if mesh is None:
            return accumulator
        acc = accumulator._data if isinstance(accumulator, Tensor) \
            else accumulator
        placements = self._param_placements(param, mesh)
        if "beta" not in key and np.ndim(acc) > 0:
            # add sharding on dim 0 via the sharding mesh axis unless some
            # axis already shards it
            if not any(isinstance(p, Shard) for p in placements):
                placements[self._sharding_mesh_axis] = Shard(0)
        else:
            placements = [Replicate() for _ in range(mesh.ndim)]
        out = Tensor(_apply_placements(acc, mesh, placements))
        out._dist_attr = DistAttr(mesh, placements)
        return out


class ShardingStage2(ShardingStage1):
    """Stage 2 == stage 1 for optimizer-state placement purposes under
    GSPMD (gradient sharding comes from the compiled reduce-scatter —
    ref: api.py ShardingStage2 shares stage 1's accumulator rule)."""


class ShardingStage3(_ShardingStageBase):
    """Builtin shard_fn: accumulators inherit the (fully sharded) param
    placements (ref: api.py ShardingStage3)."""

    def __call__(self, key: str, param: Tensor, accumulator: Tensor):
        mesh = self._mesh_of(param)
        if mesh is None:
            return accumulator
        acc = accumulator._data if isinstance(accumulator, Tensor) \
            else accumulator
        placements = self._param_placements(param, mesh)
        if np.ndim(acc) == 0 or "beta" in key:
            placements = [Replicate() for _ in range(mesh.ndim)]
        out = Tensor(_apply_placements(acc, mesh, placements))
        out._dist_attr = DistAttr(mesh, placements)
        return out


# ---------------------------------------------------------------------------
# shard_optimizer / shard_scaler / shard_dataloader
# ---------------------------------------------------------------------------

class _ShardOptimizer:
    """Distributed view of an optimizer: every state slot created by
    _init_state is placed by shard_fn (or inherits its param's sharding).
    Everything else delegates, so it drops into both the eager step() path
    and DistTrainStep."""

    def __init__(self, optimizer, shard_fn=None,
                 gradient_accumulation_steps: int = 1):
        self.__dict__["_inner"] = optimizer
        self.__dict__["_shard_fn"] = shard_fn
        self.__dict__["gradient_accumulation_steps"] = \
            gradient_accumulation_steps
        # the wrapper must also intercept the INNER's own calls (step()
        # uses self._state_for -> self._init_state), so patch the instance
        orig = optimizer._init_state

        def sharded_init(p, _orig=orig, _self=self):
            slots = dict(_orig(p))
            for name, v in slots.items():
                slots[name] = _self._place_slot(name, p, v)
            return slots

        optimizer._init_state = sharded_init

    def _place_slot(self, name, p, v):
        if not hasattr(v, "shape"):
            return v
        if self._shard_fn is not None:
            out = self._shard_fn(name, p, Tensor(v))
            return out._data if isinstance(out, Tensor) else out
        # default: pass down the param's own placements to same-shaped
        # slots (ref: shard_optimizer docstring)
        arr = getattr(p, "_data", p)
        if hasattr(arr, "sharding") and getattr(v, "shape", None) == \
                arr.shape:
            return jax.device_put(v, arr.sharding)
        return v

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def __setattr__(self, name, value):
        setattr(self._inner, name, value)


def shard_optimizer(optimizer, shard_fn=None,
                    gradient_accumulation_steps: int = 1):
    """ref: auto_parallel/api.py:1613 shard_optimizer."""
    return _ShardOptimizer(optimizer, shard_fn,
                           gradient_accumulation_steps)


def shard_scaler(scaler):
    """ref: auto_parallel/api.py:2132 shard_scaler — the found-inf flag is
    agreed across ranks so every rank skips the same steps. On a single
    controller the grads are already global; the cross-process eager path
    ORs the flag over the default group."""
    orig_unscale = scaler.unscale_

    def unscale_(optimizer, _orig=orig_unscale, _s=scaler):
        _orig(optimizer)
        from .. import collective as coll
        g = coll._get_group(None)
        if coll._mode(g) != "local":
            flag = Tensor(np.asarray([1.0 if _s._found_inf else 0.0],
                                     np.float32))
            coll.all_reduce(flag, coll.ReduceOp.MAX, g)
            _s._found_inf = bool(np.asarray(flag._data)[0] > 0)

    scaler.unscale_ = unscale_
    return scaler


class ShardDataloader:
    """ref: auto_parallel/api.py ShardDataloader — wraps a DataLoader so
    every batch element comes out as a DistTensor on the mesh, sharded on
    the batch dim along ``shard_dims`` (data parallel)."""

    def __init__(self, dataloader, meshes, input_keys=None,
                 shard_dims=None, is_dataset_splitted: bool = False):
        self._loader = dataloader
        self._meshes = meshes if isinstance(meshes, (list, tuple)) \
            else [meshes]
        self._input_keys = input_keys
        self._shard_dims = shard_dims
        self._is_split = is_dataset_splitted

    def _mesh_for(self, i: int) -> ProcessMesh:
        return self._meshes[min(i, len(self._meshes) - 1)]

    def _placements_for(self, i: int, ndim: int):
        mesh = self._mesh_for(i)
        placements = [Replicate() for _ in range(mesh.ndim)]
        sd = self._shard_dims
        if isinstance(sd, (list, tuple)):
            sd = sd[min(i, len(sd) - 1)]
        if sd is not None:
            axis = sd if isinstance(sd, int) else \
                mesh.dim_names.index(sd)
            placements[axis] = Shard(0)
        return placements

    def _shard_item(self, i, item):
        if isinstance(item, (list, tuple)):
            return type(item)(self._shard_item(i, v) for v in item)
        if isinstance(item, dict):
            return {k: self._shard_item(i, v) for k, v in item.items()}
        t = item if isinstance(item, Tensor) else Tensor(
            jax.numpy.asarray(np.asarray(item)))
        mesh = self._mesh_for(i)
        return shard_tensor(t, mesh,
                            self._placements_for(i, t._data.ndim))

    def __iter__(self):
        for batch in self._loader:
            if isinstance(batch, dict):
                keys = self._input_keys or list(batch.keys())
                yield {k: self._shard_item(j, batch[k])
                       for j, k in enumerate(keys)}
            elif isinstance(batch, (list, tuple)):
                yield type(batch)(
                    self._shard_item(j, v) for j, v in enumerate(batch))
            else:
                yield self._shard_item(0, batch)

    def __len__(self):
        return len(self._loader)


def shard_dataloader(dataloader, meshes, input_keys=None, shard_dims=None,
                     is_dataset_splitted: bool = False) -> ShardDataloader:
    """ref: auto_parallel/api.py:2715 shard_dataloader."""
    return ShardDataloader(dataloader, meshes, input_keys, shard_dims,
                           is_dataset_splitted)


# ---------------------------------------------------------------------------
# Strategy + DistModel + dist.to_static
# ---------------------------------------------------------------------------

class _Config:
    def __init__(self, **defaults):
        self.__dict__.update(defaults)

    def __repr__(self):
        return f"{type(self).__name__}({self.__dict__})"


class Strategy:
    """ref: auto_parallel/api.py Strategy — sharding / fused_passes /
    gradient_merge / pipeline / amp knobs for the compiled program."""

    def __init__(self, config: Optional[Dict] = None):
        cfg = config or {}

        def sub(name, **defaults):
            defaults.update(cfg.get(name, {}))
            return _Config(**defaults)

        self.sharding = sub("sharding", enable=False, stage=1, degree=8)
        self.fused_passes = sub("fused_passes", enable=False,
                                fused_passes_list=[])
        self.gradient_merge = sub("gradient_merge", enable=False,
                                  k_steps=1, avg=True)
        self.pipeline = sub("pipeline", enable=False,
                            schedule_mode="1F1B", micro_batch_size=1,
                            accumulate_steps=1)
        self.amp = sub("amp", enable=False, dtype="bfloat16", level="O1")

    def __repr__(self):
        return (f"Strategy(sharding={self.sharding}, "
                f"pipeline={self.pipeline}, amp={self.amp})")


class DistModel:
    """ref: auto_parallel/api.py DistModel — the compiled distributed
    program with train/eval/predict modes. Here the 'static graph' is the
    jitted whole-train-step (DistTrainStep) / jitted forward."""

    def __init__(self, layer, loader=None, loss=None, optimizer=None,
                 strategy: Optional[Strategy] = None, metrics=None):
        from ..dist_train import DistTrainStep
        self.network = layer
        self._loss = loss
        self._optimizer = optimizer
        self._strategy = strategy or Strategy()
        self._mode = None
        self._step = None
        if loss is not None and optimizer is not None:
            self._step = DistTrainStep(layer, loss, optimizer)
            self.train()
        else:
            self.predict()

    # -- modes (ref: DistModel.train/eval/predict) -------------------------
    def train(self):
        if self._step is None:
            raise RuntimeError(
                "DistModel needs loss and optimizer for train mode "
                "(pass them to dist.to_static)")
        self._mode = "train"
        self.network.train()
        return self

    def eval(self):
        if self._loss is None:
            raise RuntimeError("DistModel needs a loss for eval mode")
        self._mode = "eval"
        self.network.eval()
        return self

    def predict(self):
        self._mode = "predict"
        self.network.eval()
        return self

    @property
    def mode(self):
        return self._mode

    def __call__(self, *batch):
        if self._mode == "train":
            return self._step(*batch)
        inputs = [b if isinstance(b, Tensor) else Tensor(
            jax.numpy.asarray(np.asarray(b))) for b in batch]
        if self._mode == "eval":
            *xs, label = inputs
            out = self.network(*xs)
            return self._loss(out, label)
        return self.network(*inputs)

    # -- state (ref: DistModel.state_dict / dist_main_program) -------------
    def state_dict(self, mode: str = "all") -> Dict[str, Tensor]:
        out = {}
        if mode in ("all", "param"):
            out.update(self.network.state_dict())
        if mode in ("all", "opt") and self._step is not None:
            out.update(self._step.state_dict())
        return out

    def set_state_dict(self, state_dict):
        params = {k: v for k, v in state_dict.items() if "#" not in k}
        opt = {k: v for k, v in state_dict.items() if "#" in k}
        if params:
            self.network.set_state_dict(params)
        if opt and self._step is not None:
            self._step.set_state_dict(opt)

    def dist_main_program(self, mode=None):
        return None  # no Program IR: the program is the jitted step

    def dist_startup_program(self, mode=None):
        return None


def to_static(layer, loader=None, loss=None, optimizer=None,
              strategy: Optional[Strategy] = None) -> DistModel:
    """ref: auto_parallel/api.py to_static -> DistModel."""
    inner = optimizer._inner if isinstance(optimizer, _ShardOptimizer) \
        else optimizer
    return DistModel(layer, loader, loss, inner, strategy)
