"""Static peak-memory estimator over a traced program (jaxpr liveness).

The reference's static auto-parallel prices recompute candidates against
a memory model over its IR (ref: python/paddle/distributed/passes/
auto_parallel_recompute.py + auto_parallel/static/cost/), not against a
compiled binary. This is the jaxpr analog: a linear liveness scan —
every value is born at its producer and dies after its last consumer;
the peak is the largest concurrently-live byte count. Call-like
equations (pjit, checkpoint/remat, cond branches) are handled
recursively: a region's internals are transient, so only its boundary
values stay live outside — which is exactly how ``jax.checkpoint``
saves memory, and why this estimator sees remat savings that XLA CPU's
schedule-agnostic ``temp_size_in_bytes`` does not.

This is a MODEL, not ground truth: XLA fusion/scheduling moves the real
number (the TPU compiled ``memory_analysis()`` is the deployment
truth); the model's job is backend-neutral, compile-free RANKING of
program variants — e.g. with/without recompute segments.
"""
from __future__ import annotations

from collections import defaultdict

from jax._src import core as jcore

__all__ = ["estimate_peak_bytes"]


def _aval_bytes(v) -> int:
    aval = getattr(v, "aval", None)
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    size = 1
    for d in shape:
        if not isinstance(d, int):
            return 0  # symbolic dim: unpriceable, skip
        size *= d
    return size * dtype.itemsize


def _inner_jaxprs(eqn):
    out = []
    for val in eqn.params.values():
        vals = val if isinstance(val, (list, tuple)) else [val]
        for v in vals:
            if isinstance(v, jcore.ClosedJaxpr):
                out.append(v.jaxpr)
            elif isinstance(v, jcore.Jaxpr):
                out.append(v)
    return out


def _peak(jaxpr) -> int:
    boundary = sum(_aval_bytes(v)
                   for v in (*jaxpr.invars, *jaxpr.constvars))
    last_use: dict = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if isinstance(v, jcore.Var):
                last_use[v] = i
    n = len(jaxpr.eqns)
    for v in jaxpr.outvars:
        if isinstance(v, jcore.Var):
            last_use[v] = n
    deaths = defaultdict(list)
    for v, i in last_use.items():
        deaths[i].append(v)

    inputs = set(v for v in (*jaxpr.invars, *jaxpr.constvars)
                 if isinstance(v, jcore.Var))
    current = boundary  # inputs counted live throughout (constant term)
    peak = current
    for i, eqn in enumerate(jaxpr.eqns):
        out_b = sum(_aval_bytes(v) for v in eqn.outvars)
        io_b = out_b + sum(_aval_bytes(v) for v in eqn.invars
                           if isinstance(v, jcore.Var))
        current += out_b
        # a region's internal peak beyond its boundary values is
        # transient extra memory at this program point
        internal_extra = 0
        for inner in _inner_jaxprs(eqn):
            internal_extra = max(internal_extra,
                                 _peak(inner) - io_b)
        peak = max(peak, current + max(internal_extra, 0))
        for v in deaths.get(i, []):
            if v not in inputs:
                current -= _aval_bytes(v)
        # outputs with no consumer (DropVars, dead outvars) die here
        # too — without this they'd inflate `current` forever
        for v in eqn.outvars:
            if v not in last_use:
                current -= _aval_bytes(v)
    return peak


def estimate_peak_bytes(traced_or_jaxpr) -> int:
    """Estimated peak live bytes of a traced program.

    Accepts a ``jax.stages.Traced`` (``jitted.trace(*args)``), a
    ``ClosedJaxpr`` (``jax.make_jaxpr(f)(*args)``), or a raw Jaxpr.
    """
    obj = traced_or_jaxpr
    if hasattr(obj, "jaxpr"):
        obj = obj.jaxpr
    if isinstance(obj, jcore.ClosedJaxpr):
        obj = obj.jaxpr
    return _peak(obj)
