"""Semi-auto / static auto-parallel.

ref: python/paddle/distributed/auto_parallel/ — the dygraph API
(shard_tensor/reshard, re-exported from distributed.api) + the static
Engine (static/engine.py:100). Under XLA the "static" pipeline is the
same jit; Engine is the orchestration wrapper.
"""
from ..api import (  # noqa: F401
    DistAttr, dtensor_from_fn, reshard, shard_layer, shard_parameter,
    shard_tensor, unshard_dtensor,
)
from .engine import Engine, Strategy  # noqa: F401
