"""Engine: whole-program auto-parallel training orchestration.

ref: python/paddle/distributed/auto_parallel/static/engine.py:100
(Engine(model, loss, optimizer, metrics, strategy): .fit :1544 /
.evaluate / .predict; internally completion -> partition -> reshard ->
pass pipeline). The TPU analog: placements come from the model's
parameter shardings (or a shard_fn), and "partition + reshard insertion"
is GSPMD inside one jit — Engine drives data feeding, the compiled step,
eval loops, and checkpoints.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional

import numpy as np

from ...core.tensor import Tensor
from ..dist_train import DistTrainStep

__all__ = ["Engine", "Strategy"]


@dataclass
class Strategy:
    """ref: auto_parallel/strategy.py Strategy (amp/recompute/sharding
    sub-configs as attribute bags). ``auto`` turns on the planner
    (ref: static engine auto_mode + static/cost planner): with
    enable=True and no mesh given, Engine prices every (dp, fsdp, mp)
    factorization with the roofline cost model and shards the model on
    the winner before compiling."""
    amp: dict = field(default_factory=dict)
    recompute: dict = field(default_factory=dict)
    sharding: dict = field(default_factory=dict)
    pipeline: dict = field(default_factory=dict)
    gradient_merge: dict = field(default_factory=dict)
    auto: dict = field(default_factory=dict)


class Engine:
    def __init__(self, model=None, loss=None, optimizer=None, metrics=None,
                 strategy: Optional[Strategy] = None, mesh=None,
                 shard_fn: Optional[Callable] = None,
                 data_sharding=None):
        self.model = model
        self.loss = loss
        self.optimizer = optimizer
        self.metrics = metrics or []
        self.strategy = strategy or Strategy()
        self.mesh = mesh
        self._data_sharding = data_sharding
        self._shard_fn = shard_fn
        if shard_fn is not None and mesh is not None:
            shard_fn(model, mesh)
        self._step: Optional[DistTrainStep] = None
        self._pending_plan_batch = None
        self.plan_choice = None
        self.recompute_report: Optional[dict] = None
        self.history: dict = {"loss": []}

    def _apply_strategy(self):
        """Strategy-driven passes (ref: passes/auto_parallel_{amp,
        sharding,gradient_merge}.py — completion/partition is GSPMD here;
        these knobs configure what the one compiled program does):
        amp -> bf16 weights (O2); sharding -> shard_optimizer with the
        configured stage; gradient_merge -> on-device micro-batch scan.
        recompute is the explicit fleet.utils.recompute segment wrapper
        (the reference's auto segment picker is a pass on its static IR;
        here segments are marked in model code)."""
        s = self.strategy
        amp = s.amp if isinstance(s.amp, dict) else vars(s.amp)
        if amp.get("enable"):
            dtype = str(amp.get("dtype", "bfloat16"))
            if dtype not in ("bfloat16", "bf16"):
                raise ValueError(
                    f"Engine amp dtype {dtype!r} is not supported on "
                    f"TPU — bfloat16 is the native fast dtype (fp16 "
                    f"has no hardware advantage here)")
            level = str(amp.get("level", "O1")).upper()
            if level == "O2":
                # O2 = master-weight cast (ref: passes/auto_parallel_fp16)
                self.model.bfloat16()
            else:
                # O1 keeps fp32 weights and autocasts per-op through the
                # white/black lists (ref: passes/auto_parallel_amp.py) —
                # the autocast context wraps forward so it applies both
                # eagerly and while the compiled step traces
                from ...amp import auto_cast
                inner_forward = self.model.forward

                def _amp_forward(*a, **kw):
                    with auto_cast(True, level="O1", dtype="bfloat16"):
                        return inner_forward(*a, **kw)

                self.model.forward = _amp_forward
        sh = s.sharding if isinstance(s.sharding, dict) else vars(s.sharding)
        if sh.get("enable") and self.mesh is not None:
            from ..api import shard_parameter
            from .api_ext import (ShardingStage1, ShardingStage2,
                                  ShardingStage3, shard_optimizer,
                                  _ShardOptimizer)
            # params must live on the same mesh as the sharded opt state
            for p in self.model.parameters():
                if p._dist_attr is None:
                    shard_parameter(p, self.mesh)
            if not isinstance(self.optimizer, _ShardOptimizer):
                stage = {1: ShardingStage1, 2: ShardingStage2,
                         3: ShardingStage3}[int(sh.get("stage", 1))]
                self.optimizer = shard_optimizer(self.optimizer,
                                                 stage(self.mesh))
        # gradient merge parsed BEFORE recompute: the memory probe must
        # model the k-way micro-batched program that actually runs
        gm = (s.gradient_merge if isinstance(s.gradient_merge, dict)
              else vars(s.gradient_merge))
        self._acc = int(gm.get("k_steps", 1)) if gm.get("enable") else 1
        rc = (s.recompute if isinstance(s.recompute, dict)
              else vars(s.recompute))
        if rc.get("enable"):
            target = rc.get("target_peak_bytes")
            min_repeat = int(rc.get("min_repeat", 2))
            if target is not None:
                self._memory_aware_recompute(int(target),
                                             min_repeat=min_repeat)
            else:
                self._auto_recompute(min_repeat=min_repeat)

    def _loss_fn(self):
        loss_fn = self.loss
        if hasattr(loss_fn, "forward"):  # a Layer criterion
            crit = loss_fn
            return lambda out, *labels: crit(out, *labels)
        return loss_fn

    def _probe_peak_bytes(self, batch) -> int:
        """Modeled peak live bytes of the train step for this batch:
        jaxpr liveness over a shape-only TRACE of the step (no XLA
        compile, no device allocation) via the static estimator — the
        decision metric for the memory-aware recompute pass (ref: the
        reference prices recompute candidates with its static memory
        cost model, not compiled binaries). The compiled
        ``memory_analysis()`` remains the deployment truth (bench
        peak_hbm_bytes); XLA CPU's schedule-agnostic temp figure cannot
        see remat savings, the model can.

        Shape basis is GLOBAL: jaxpr avals carry unpartitioned logical
        shapes, so on an N-device mesh this is the whole-program figure
        (the target budget is interpreted on the same global basis; the
        report records the basis + mesh size for conversion)."""
        from .mem_estimator import estimate_peak_bytes
        opt = self.optimizer
        if hasattr(opt, "_inner"):
            opt = opt._inner
        probe = DistTrainStep(self.model, self._loss_fn(), opt,
                              data_sharding=self._data_sharding,
                              accumulate_steps=getattr(self, "_acc", 1))
        return int(estimate_peak_bytes(
            probe.trace_jaxpr(*batch, abstract=True)))

    def _memory_aware_recompute(self, target_peak_bytes: int,
                                min_repeat: int = 2):
        """Memory-model-driven segment picking (ref: passes/
        auto_parallel_recompute.py selects segments against a memory
        model, not a repeat-count heuristic): estimate the step's
        global-shape peak WITHOUT recompute; only when it exceeds the
        target are the repeated segments wrapped, and the peak is
        re-estimated to confirm the drop. Decision + both measurements
        land in ``self.recompute_report``."""
        n_dev = (self.mesh.to_jax_mesh().size
                 if self.mesh is not None else 1)
        basis = {"shape_basis": "global", "mesh_devices": n_dev,
                 "target_peak_bytes": int(target_peak_bytes)}
        batch = self._pending_plan_batch
        if batch is None:
            # no sample batch to measure against (explicit load()/
            # evaluate() path): fall back to the heuristic picker
            self._auto_recompute(min_repeat=min_repeat)
            self.recompute_report = {"mode": "heuristic-fallback",
                                     "reason": "no sample batch",
                                     **basis}
            return
        before = self._probe_peak_bytes(batch)
        if before <= target_peak_bytes:
            self.recompute_report = {
                "mode": "skipped", "peak_bytes": before, **basis}
            return
        wrapped = self._auto_recompute(min_repeat=min_repeat)
        if not wrapped:
            # nothing to wrap (no repeated block family): don't claim a
            # pass was applied, and don't pay a second trace
            self.recompute_report = {
                "mode": "no-segments", "peak_bytes": before, **basis}
            return
        after = self._probe_peak_bytes(batch)
        self.recompute_report = {
            "mode": "applied", "segments": len(wrapped),
            "peak_bytes_before": before, "peak_bytes_after": after,
            "met_target": after <= target_peak_bytes, **basis}

    def _auto_recompute(self, min_repeat: int = 2):
        """Auto segment picking (ref: passes/auto_parallel_recompute.py,
        which selects segments on the static IR): the largest-parameter
        family of repeated same-class sibling blocks (transformer
        layers, Sequential stages) becomes the recompute segment set;
        each member's forward is wrapped so its activations
        re-materialize during backward (jax.checkpoint under the
        compiled step). Returns the wrapped layers."""
        from ..fleet.utils.recompute import recompute as rc_fn

        best = None
        parents = [self.model] + [l for _, l in
                                  self.model.named_sublayers()]
        for parent in parents:
            groups: dict = {}
            for _, child in parent.named_children():
                groups.setdefault(type(child).__name__, []).append(child)
            for members in groups.values():
                if len(members) < min_repeat:
                    continue
                pc = sum(int(np.prod(p.shape)) for m in members
                         for p in m.parameters())
                if pc and (best is None or pc > best[0]):
                    best = (pc, members)
        if best is None:
            return []
        for layer in best[1]:
            if getattr(layer, "_recompute_wrapped", False):
                continue
            inner = layer.forward

            def fwd(*a, __inner=inner, __layer=layer, **kw):
                return rc_fn(__layer, *a, forward_fn=__inner, **kw)

            layer.forward = fwd
            layer._recompute_wrapped = True
        return best[1]

    def plan(self, sample_batch, n_devices: Optional[int] = None,
             cluster=None, trial_fn: Optional[Callable] = None):
        """Choose the parallel config (ref: static engine planner,
        static/cost/): profile the model, search mesh factorizations,
        build the winning mesh, and shard the model onto it. Called
        automatically by fit() when strategy.auto.enable and no mesh
        was given; callable directly for inspection (returns the
        chosen PlanCandidate). ``cluster``/``n_devices``/``trial_fn``
        may also be supplied through the strategy.auto dict so the
        fit() path can reach them. With a ``trial_fn(config_dict) ->
        items/s`` the analytic top-3 are timed and the measured winner
        is taken (ref: static engine's tuning mode)."""
        import jax
        import numpy as np

        from ..process_mesh import ProcessMesh
        from .planner import Planner, profile_model

        auto = (self.strategy.auto if isinstance(self.strategy.auto, dict)
                else vars(self.strategy.auto))
        n = n_devices or auto.get("n_devices") or len(jax.devices())
        cluster = cluster if cluster is not None else auto.get("cluster")
        if cluster is None:
            # no manual spec: detect from the live runtime (device-kind
            # table + PJRT memory stats; ref: static/cluster.py)
            from .planner import detect_cluster
            cluster = detect_cluster()
        trial_fn = trial_fn if trial_fn is not None \
            else auto.get("trial_fn")
        first = sample_batch[0] if isinstance(
            sample_batch, (tuple, list)) else sample_batch
        # shape only — np.asarray would pull the whole (possibly
        # device-resident) batch to the host
        shape = (first._data.shape if isinstance(first, Tensor)
                 else np.shape(first))
        batch_tokens = int(np.prod(shape[:2])) if len(shape) >= 2 \
            else int(shape[0])
        prof = profile_model(self.model, batch_tokens,
                             layer_count=auto.get("layer_count"))
        shard_fn = auto.get("shard_fn") or self._shard_fn
        # tensor parallelism needs model knowledge (column/row splits):
        # without a shard_fn the fallback only shards along fsdp, so an
        # mp>1 plan would be priced against memory it cannot realize
        max_mp = (auto.get("max_mp") if shard_fn is not None else 1)
        # the pipeline axis opens only when the model is realizable as
        # a pipeline (PipelineLayer segmentation contract) — a pp plan
        # the executor can't run would be worse than no plan
        max_pp = int(auto.get("max_pp", 1))
        fam_len = 0
        if max_pp > 1:
            from .engine_pp import detect_pipeline_split
            split = detect_pipeline_split(self.model)
            if split is None:
                max_pp = 1
            else:
                fam_len = len(split[1])
        planner = Planner(n, cluster=cluster, max_mp=max_mp,
                          max_pp=max_pp,
                          schedules=("gpipe",) if max_pp > 1 else None)
        def realizable(c):
            # v1 pipeline realization runs the non-pp axes as pure
            # data parallel (a pp plan that also assumed fsdp/mp
            # sharding would claim memory the executor can't
            # deliver), and the block family must split evenly
            # across the stages
            return c.pp == 1 or (c.fsdp == 1 and c.mp == 1
                                 and fam_len % c.pp == 0)

        # realizability filtering lives in Planner.plan (the single home
        # of the contract) so the analytic and measured paths can never
        # diverge; plan() ranks EVERY feasible candidate before the cut,
        # so a realizable pp=1 plan below the cheapest-16 is still found
        if trial_fn is not None:
            best = planner.plan_measured(prof, trial_fn,
                                         realizable_fn=realizable)
        else:
            best = planner.plan(prof, top_k=1,
                                realizable_fn=realizable)[0]
        self.plan_choice = best
        if best.pp > 1:
            # pipeline realization builds its own ("dp", "pp") mesh in
            # _ensure_step; no per-param shardings (blocks stack on pp)
            self.mesh = ProcessMesh(
                np.arange(n).reshape(n // best.pp, best.pp),
                dim_names=["dp", "pp"])
            return best
        dims = [d for d in best.mesh_shape]
        mesh = ProcessMesh(
            np.arange(n).reshape(dims), dim_names=["dp", "fsdp", "mp"])
        self.mesh = mesh
        if shard_fn is not None:
            # model-aware placements (tp column/row splits need model
            # knowledge, e.g. models.llama.shard_llama)
            shard_fn(self.model, mesh)
        else:
            from ..api import shard_parameter
            for p in self.model.parameters():
                shard_parameter(p, mesh, fsdp_axis="fsdp", fsdp_dim=0)
        return best

    def _ensure_step(self):
        if self._step is None:
            auto = (self.strategy.auto
                    if isinstance(self.strategy.auto, dict)
                    else vars(self.strategy.auto))
            if auto.get("enable") and self.mesh is None:
                if self._pending_plan_batch is None:
                    # building (and caching) an unplanned step here would
                    # silently disable auto sharding for the whole run
                    raise RuntimeError(
                        "strategy.auto needs a sample batch before the "
                        "step builds: call fit() first, or "
                        "Engine.plan(sample_batch) explicitly before "
                        "load()/evaluate()")
                self.plan(self._pending_plan_batch)
                # NOT cleared here: the memory-aware recompute pass in
                # _apply_strategy also probes against it; fit()/callers
                # clear it after _ensure_step returns
            self._apply_strategy()
            loss_fn = self._loss_fn()
            opt = self.optimizer
            if hasattr(opt, "_inner"):  # _ShardOptimizer: unwrap for step
                opt = opt._inner
            if self.plan_choice is not None and self.plan_choice.pp > 1:
                # realize the pipeline plan: compiled GPipe over the
                # ("dp", "pp") mesh (ref: static engine +
                # pipeline_scheduler_pass; the plan was also PRICED with
                # the GPipe fill-drain bubble — see plan()'s schedules
                # argument — so plan_choice.schedule tells the truth)
                if getattr(self, "_acc", 1) > 1:
                    raise NotImplementedError(
                        "gradient_merge with a pipeline plan is not "
                        "supported (v1): the pipeline already "
                        "micro-batches inside the step — drop "
                        "gradient_merge or cap max_pp to 1")
                from .engine_pp import PipelineTrainStep
                self._step = PipelineTrainStep(
                    self.model, loss_fn, opt, pp=self.plan_choice.pp,
                    n_devices=self.mesh.to_jax_mesh().size)
            else:
                self._step = DistTrainStep(
                    self.model, loss_fn, opt,
                    data_sharding=self._data_sharding,
                    accumulate_steps=getattr(self, "_acc", 1))
        return self._step

    # -- training (ref: engine.py fit :1544) --------------------------------
    def fit(self, train_data, epochs=1, steps_per_epoch=None, verbose=0,
            log_freq=10):
        step = None
        for epoch in range(epochs):
            for i, batch in enumerate(train_data):
                if steps_per_epoch is not None and i >= steps_per_epoch:
                    break
                batch = batch if isinstance(batch, (tuple, list)) else \
                    (batch,)
                if step is None:
                    # the planner needs a sample batch for its token
                    # count, so the step builds lazily at first batch
                    self._pending_plan_batch = batch
                    step = self._ensure_step()
                    self._pending_plan_batch = None  # don't pin the batch
                loss = step(*batch)
                self.history["loss"].append(float(loss))
                if verbose and i % log_freq == 0:
                    print(f"epoch {epoch} step {i}: "
                          f"loss {float(loss):.4f}")
        return self.history

    def evaluate(self, eval_data, steps=None):
        """Mean loss over eval batches (model in eval mode, no updates)."""
        was_training = self.model.training
        self.model.eval()
        losses = []
        try:
            for i, batch in enumerate(eval_data):
                if steps is not None and i >= steps:
                    break
                batch = batch if isinstance(batch, (tuple, list)) else \
                    (batch,)
                out = self.model(*[b if isinstance(b, Tensor) else
                                   _to_tensor(b) for b in batch[:-1]])
                loss = self.loss(out, _to_tensor(batch[-1]))
                losses.append(float(loss))
        finally:
            if was_training:
                self.model.train()
        return {"loss": float(np.mean(losses)) if losses else None}

    def predict(self, data, steps=None):
        was_training = self.model.training
        self.model.eval()
        outs = []
        try:
            for i, batch in enumerate(data):
                if steps is not None and i >= steps:
                    break
                batch = batch if isinstance(batch, (tuple, list)) else \
                    (batch,)
                outs.append(self.model(*[_to_tensor(b) for b in batch]))
        finally:
            if was_training:
                self.model.train()
        return outs

    # -- checkpoints (ref: engine save/load -> dist ckpt) -------------------
    def save(self, path: str):
        from ..checkpoint import save_state_dict
        state = {"model": self.model.state_dict()}
        if self._step is not None:
            state["opt"] = self._step.state_dict()
        save_state_dict(state, path)

    def load(self, path: str):
        from ..checkpoint import load_state_dict
        step = self._ensure_step()
        state = {"model": self.model.state_dict(),
                 "opt": step.state_dict()}
        load_state_dict(state, path)
        step.set_state_dict(state["opt"])


def _to_tensor(x):
    if isinstance(x, Tensor):
        return x
    import jax.numpy as jnp
    return Tensor(jnp.asarray(np.asarray(x)))
