"""Static auto-parallel planner v1: cost model + mesh/strategy search.

ref: python/paddle/distributed/auto_parallel/static/engine.py:100 (the
Engine's completion -> partition -> reshard pipeline is GSPMD here), and
static/cost/ + static/cluster.py — the reference prices each candidate
distributed program with per-op FLOPs/bytes models over a cluster
description, prunes infeasible ones, and picks the cheapest. This
planner does the TPU-native equivalent:

1. enumerate mesh factorizations of n_devices over (dp, fsdp, mp) and —
   when ``max_pp`` allows — a pipeline axis pp (the reference prices
   pipeline candidates through its schedule passes,
   ref: passes/pipeline_scheduler_pass/ + static/cost/);
2. price each with a roofline model — MXU time from model FLOPs,
   ICI time per axis from the collective bytes its sharding implies
   (dp: grad allreduce; fsdp: param allgather fwd+bwd + grad
   reduce-scatter; mp: per-layer activation allreduces; pp: boundary
   p2p bytes plus a bubble factor REPLAYED from the repo's own
   1F1B / ZB-H1 schedule simulators — the cheaper schedule wins and is
   recorded on the candidate);
3. prune configs whose per-chip memory (params + grads + optimizer
   state + activation checkpoints, with pipeline in-flight accounting)
   exceeds the HBM budget — the compile-free OOM verdict (the
   reference's prune-by-memory, auto_tuner/prune.py);
4. (optional) hand the top-k survivors to the auto_tuner trial runner,
   which compiles and TIMES each candidate (distributed/auto_tuner/
   runner.py) — measurement beats modeling for the final pick.

The cluster description (chip FLOP/s, ICI GB/s, HBM bytes) defaults to
v5e and is overridable — the analog of static/cluster.py.
"""
from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple

__all__ = ["Cluster", "ModelProfile", "PlanCandidate", "Planner",
           "profile_model"]


@dataclass
class Cluster:
    """ref: auto_parallel/static/cluster.py — the device description the
    cost model prices against. Defaults: one TPU v5e pod slice."""
    chip_flops: float = 197e12          # bf16 peak per chip
    ici_bandwidth: float = 45e9         # bytes/s per link direction
    hbm_bytes: float = 16e9
    mfu_ceiling: float = 0.6            # realistic matmul efficiency
    ici_latency: float = 5e-6           # per-collective launch latency
    mp_min_width: int = 512             # hidden/mp below this starves
    # the MXU (128-wide systolic tiles + pipelining need fat matmuls);
    # compute efficiency scales ~ linearly with shard width under it


@dataclass
class ModelProfile:
    """What the cost model needs to know about one training step."""
    param_bytes: int                    # total parameter bytes
    flops_per_step: float               # fwd+bwd+update FLOPs
    batch_tokens: int = 1
    hidden: int = 1                     # activation width (mp comm unit)
    layer_count: int = 1                # mp comm multiplier
    act_dtype_bytes: int = 2
    bytes_per_param_state: float = 10.0  # grad + opt state per param byte
    # (bf16 grads 1x + f32 moments 8 bytes/2-byte-param => ~10x is AdamW
    # with fp32 state; SGD-momentum would be ~4)

    @property
    def activation_bytes(self) -> float:
        """Standard transformer footprint ~12 tensors of
        [tokens, hidden] live per layer."""
        return (12.0 * self.layer_count * self.batch_tokens *
                self.hidden * self.act_dtype_bytes)


def profile_model(model, batch_tokens: int,
                  layer_count: Optional[int] = None) -> ModelProfile:
    """Build a ModelProfile from a live Layer: params from the module
    tree, FLOPs from the 6·N·tokens transformer estimate (the standard
    fwd+bwd accounting; ref static_op_benchmark.json's role is pricing
    sanity, not exactness), activations ~ 12·tokens·hidden guess."""
    import numpy as np
    n_params = 0
    p_bytes = 0
    widths: List[int] = []
    for p in model.parameters():
        size = int(np.prod(p.shape)) if len(p.shape) else 1
        n_params += size
        p_bytes += size * p._data.dtype.itemsize
        if len(p.shape) >= 2:
            widths.append(int(p.shape[-1]))
    hidden = int(np.median(widths)) if widths else 1
    layers = layer_count
    if layers is None:
        # count distinct numbered blocks in param names as the proxy
        import re
        idx = {m.group(1) for n, _ in model.named_parameters()
               for m in [re.search(r"(?:^|\.)(\d+)\.", n)] if m}
        layers = max(len(idx), 1)
    return ModelProfile(
        param_bytes=p_bytes,
        flops_per_step=6.0 * n_params * batch_tokens,
        batch_tokens=batch_tokens,
        hidden=hidden,
        layer_count=layers,
    )


@dataclass
class PlanCandidate:
    dp: int
    fsdp: int
    mp: int
    pp: int = 1
    schedule: str = ""            # "1f1b" | "zb_h1" when pp > 1
    bubble_fraction: float = 0.0
    est_step_time: float = 0.0
    est_mem_bytes: float = 0.0
    feasible: bool = True
    reason: str = ""
    measured_items_per_s: Optional[float] = None

    @property
    def mesh_shape(self) -> Tuple[int, int, int]:
        return (self.dp, self.fsdp, self.mp)

    @property
    def full_shape(self) -> Tuple[int, int, int, int]:
        return (self.dp, self.fsdp, self.mp, self.pp)


def _ring_factor(n: int) -> float:
    return (n - 1) / n if n > 1 else 0.0


@functools.lru_cache(maxsize=None)
def _bubble_fractions(pp: int, micro: int) -> Tuple[float, float]:
    """(1F1B, ZB-H1) bubble fractions for a pp-stage pipeline with
    ``micro`` micro-batches, replayed through the repo's own schedule
    simulator (fleet/pipeline_zero_bubble.py) — the same event/dependency
    model the real schedules execute, not a closed-form guess."""
    from ..fleet.pipeline_zero_bubble import (
        one_f_one_b_schedule, simulate_schedule, zb_h1_schedule)

    busy = 3 * micro  # per-stage work slots: micro * (t_f + t_b + t_w)

    def frac(idle_by_stage):
        worst = max(idle_by_stage.values())
        return worst / (worst + busy)

    f1b = frac(simulate_schedule(
        {s: one_f_one_b_schedule(pp, s, micro) for s in range(pp)},
        fused_bw=True))
    zb = frac(simulate_schedule(
        {s: zb_h1_schedule(pp, s, micro) for s in range(pp)}))
    return f1b, zb


class Planner:
    """Search over (dp, fsdp, mp) factorizations of n_devices.

    ``plan()`` = analytic rank (+ memory prune); ``plan_measured()``
    additionally times the top-k with the auto_tuner trial runner and
    returns the measured winner — the reference's two-phase
    cost-model-then-trials flow (auto_tuner/tuner.py)."""

    def __init__(self, n_devices: int, cluster: Optional[Cluster] = None,
                 max_mp: Optional[int] = None, max_pp: int = 1,
                 micro_batches: Optional[int] = None,
                 schedules=None):
        self.n = n_devices
        self.cluster = cluster or Cluster()
        self.max_mp = max_mp or n_devices
        # pp candidates are enumerated only up to max_pp: the caller must
        # be able to REALIZE a pipeline plan (Engine gates this on its
        # pipeline executor's segmentation contract)
        self.max_pp = max(int(max_pp), 1)
        self.micro_batches = micro_batches  # default: 2*pp per candidate
        # which schedules the CALLER can execute: pp candidates are
        # priced with the best bubble among these and record the pick.
        # Default = the fleet's executable split-B/W schedules; the
        # Engine's compiled-GPipe executor passes ("gpipe",) so the plan
        # is priced with the fill-drain bubble it will actually get.
        self.schedules = tuple(schedules or ("1f1b", "zb_h1"))

    def candidates(self) -> List[PlanCandidate]:
        out = []
        n = self.n
        for pp in range(1, min(self.max_pp, n) + 1):
            if n % pp:
                continue
            nn = n // pp
            for dp in range(1, nn + 1):
                if nn % dp:
                    continue
                rem = nn // dp
                for fsdp in range(1, rem + 1):
                    if rem % fsdp:
                        continue
                    mp = rem // fsdp
                    if mp > self.max_mp:
                        continue
                    out.append(PlanCandidate(dp=dp, fsdp=fsdp, mp=mp,
                                             pp=pp))
        return out

    def _pick_schedule(self, pp: int, micro: int):
        """Best executable schedule for (pp, micro): replay 1F1B/ZB-H1
        through the repo's own simulator (the executable schedules in
        fleet/pipeline_zero_bubble.py); GPipe fill-drain closed form
        is (pp-1) idle slots around micro working slots per stage."""
        f1b, zb = _bubble_fractions(pp, micro)
        gp = (pp - 1) / (micro + pp - 1)
        options = {"1f1b": f1b, "zb_h1": zb, "gpipe": gp}
        return min(((s, options[s]) for s in self.schedules
                    if s in options), key=lambda kv: kv[1])

    def price(self, cand: PlanCandidate, prof: ModelProfile
              ) -> PlanCandidate:
        c = self.cluster
        micro = self.micro_batches or max(2 * cand.pp, 1)
        n_shard = cand.fsdp * cand.mp * cand.pp
        # -- memory: params+grads+opt sharded by fsdp*mp, and by pp too
        # (each stage owns only its layers). Activations: per-layer
        # rematerialization keeps ONE layer's working set live, but the
        # remat CHECKPOINTS (one [tokens, hidden] boundary per layer,
        # batch split over dp*fsdp) are stored — pipeline stages store
        # them only for their own layers and in-flight micro-batches,
        # which is the memory lever pp has that fsdp doesn't: fsdp can
        # never shard a batch it can't split, pp shards the LAYERS.
        state_bytes = prof.param_bytes * (1 + prof.bytes_per_param_state)
        act_live = prof.activation_bytes / max(prof.layer_count, 1)
        ckpt_all = (prof.layer_count * prof.batch_tokens * prof.hidden *
                    prof.act_dtype_bytes)
        ckpt = ckpt_all / (cand.dp * cand.fsdp)
        live = act_live / self.n
        if cand.pp > 1:
            # Pick the schedule FIRST (bubble replay needs only pp and
            # micro) so memory is priced with the schedule that will
            # actually run: 1F1B/ZB cap live checkpoints at the stage
            # depth, but GPipe's fill-drain holds every micro-batch's
            # stage checkpoints until backward starts — pricing a
            # gpipe-executed plan with min(pp, micro) under-counts ~2x
            # and the HBM prune admits plans the executor OOMs on.
            cand.schedule, cand.bubble_fraction = self._pick_schedule(
                cand.pp, micro)
            if cand.schedule == "gpipe":
                in_flight = micro
            else:
                in_flight = min(cand.pp, micro)
            ckpt = ckpt * in_flight / (micro * cand.pp)
            # the pipeline computes ONE micro-batch at a time per stage,
            # so the live working set shrinks with the micro count
            live = live / micro
        mem = state_bytes / n_shard + live + ckpt
        cand.est_mem_bytes = mem
        if mem > c.hbm_bytes:
            cand.feasible = False
            cand.reason = (f"est {mem/1e9:.1f}GB > HBM "
                           f"{c.hbm_bytes/1e9:.1f}GB")
        # -- compute: data/model-parallel FLOPs, degraded when mp
        # shards the hidden dim below the MXU-efficient width (the
        # known physics that makes tiny-model mp lose to dp even though
        # its comm bytes look small)
        width = max(prof.hidden / cand.mp, 1.0)
        mp_eff = min(1.0, width / c.mp_min_width)
        t_compute = prof.flops_per_step / self.n / \
            (c.chip_flops * c.mfu_ceiling * mp_eff)
        # -- pipeline bubble: schedule + fraction were picked in the
        # memory pass above (so memory matches the executed schedule)
        if cand.pp > 1:
            t_compute = t_compute / max(1.0 - cand.bubble_fraction, 1e-3)
        # -- communication per step (ring costs over ICI):
        bw = c.ici_bandwidth
        shard_param_bytes = prof.param_bytes / n_shard
        t_dp = 2 * shard_param_bytes * _ring_factor(cand.dp) / bw
        t_fsdp = 3 * (prof.param_bytes / (cand.mp * cand.pp)) * \
            _ring_factor(cand.fsdp) / bw
        # Megatron mp: two activation allreduces fwd + two bwd per layer
        # over this dp-shard's [tokens, hidden] tensor
        mp_bytes = (4 * prof.layer_count *
                    (prof.batch_tokens / (cand.dp * cand.fsdp)) *
                    prof.hidden * prof.act_dtype_bytes)
        t_mp = mp_bytes * _ring_factor(cand.mp) / bw
        # pp boundary p2p: one [tokens_micro, hidden] activation fwd and
        # one grad bwd per stage boundary per micro-batch
        t_pp = 0.0
        if cand.pp > 1:
            tokens_micro = prof.batch_tokens / (cand.dp * cand.fsdp *
                                                micro)
            hop_bytes = tokens_micro * prof.hidden * prof.act_dtype_bytes
            t_pp = 2 * (cand.pp - 1) * micro * hop_bytes / bw
        # per-COLLECTIVE launch latency (ring transfers pipeline, so
        # the launch cost is ~independent of ring length): dp's grad
        # allreduce is one fused pair; fsdp gathers/scatters and mp
        # allreduces fire per layer — at toy scale this fixed cost is
        # why pure dp measures fastest
        lat = c.ici_latency
        t_lat = ((2 * lat if cand.dp > 1 else 0.0) +
                 (3 * prof.layer_count * lat if cand.fsdp > 1 else 0.0) +
                 (4 * prof.layer_count * lat if cand.mp > 1 else 0.0) +
                 (2 * (cand.pp - 1) * micro * lat if cand.pp > 1
                  else 0.0))
        cand.est_step_time = (t_compute + t_dp + t_fsdp + t_mp + t_pp +
                              t_lat)
        return cand

    def plan(self, prof: ModelProfile, top_k: int = 1,
             realizable_fn: Optional[Callable] = None
             ) -> List[PlanCandidate]:
        """Rank feasible candidates by estimated step time.
        ``realizable_fn`` additionally prunes configs the caller's
        executor cannot run (e.g. pp plans whose block family doesn't
        split) — the single home of the realizability contract, shared
        by the Engine's analytic path and plan_measured."""
        priced = [self.price(c, prof) for c in self.candidates()]
        feas = [c for c in priced if c.feasible]
        if not feas:
            detail = "; ".join(
                f"dp{c.dp}/fsdp{c.fsdp}/mp{c.mp}: {c.reason}"
                for c in priced[:6])
            raise ValueError(
                f"no feasible parallel config for {self.n} devices "
                f"({detail}) — add devices or shrink the model/batch")
        if realizable_fn is not None:
            feas = [c for c in feas if realizable_fn(c)]
            if not feas:
                raise ValueError(
                    "no realizable parallel config: every feasible "
                    "candidate needs shardings the caller's executor "
                    "can't deliver (pp with fsdp/mp, or pp not dividing "
                    "the block family) — raise HBM, shrink the model, "
                    "or provide a mesh explicitly")
        feas.sort(key=lambda c: c.est_step_time)
        return feas[:top_k]

    def plan_measured(self, prof: ModelProfile, trial_fn: Callable,
                      top_k: int = 3,
                      realizable_fn: Optional[Callable] = None
                      ) -> PlanCandidate:
        """Time the analytic top-k with ``trial_fn(config_dict) ->
        items/s`` (build_trial_runner's contract); failures (OOM et al)
        are recorded and skipped like the reference's failed trials.
        ``realizable_fn`` prunes candidates the caller's executor cannot
        run BEFORE they occupy trial slots (otherwise 3 unrealizable pp
        plans would exhaust the trials while a realizable pp=1 plan sits
        just below the cut)."""
        cands = self.plan(prof, top_k=top_k, realizable_fn=realizable_fn)
        best = None
        for cand in cands:
            cfg = {"dp_degree": cand.dp, "fsdp_degree": cand.fsdp,
                   "mp_degree": cand.mp}
            if cand.pp > 1:
                cfg["pp_degree"] = cand.pp
                cfg["pp_schedule"] = cand.schedule
            try:
                cand.measured_items_per_s = float(trial_fn(cfg))
            except Exception as e:  # noqa: BLE001 — a failed trial is data
                cand.feasible = False
                cand.reason = f"trial failed: {type(e).__name__}: {e}"
                continue
            if best is None or cand.measured_items_per_s > \
                    best.measured_items_per_s:
                best = cand
        if best is None:
            raise RuntimeError("every trialed config failed")
        return best
