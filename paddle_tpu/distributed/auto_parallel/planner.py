"""Static auto-parallel planner v1: cost model + mesh/strategy search.

ref: python/paddle/distributed/auto_parallel/static/engine.py:100 (the
Engine's completion -> partition -> reshard pipeline is GSPMD here), and
static/cost/ + static/cluster.py — the reference prices each candidate
distributed program with per-op FLOPs/bytes models over a cluster
description, prunes infeasible ones, and picks the cheapest. This
planner does the TPU-native equivalent:

1. enumerate mesh factorizations of n_devices over (dp, fsdp, mp) and —
   when ``max_pp`` allows — a pipeline axis pp (the reference prices
   pipeline candidates through its schedule passes,
   ref: passes/pipeline_scheduler_pass/ + static/cost/);
2. price each with a roofline model — MXU time from model FLOPs,
   ICI time per axis from the collective bytes its sharding implies
   (dp: grad allreduce; fsdp: param allgather fwd+bwd + grad
   reduce-scatter; mp: per-layer activation allreduces; pp: boundary
   p2p bytes plus a bubble factor REPLAYED from the repo's own
   1F1B / ZB-H1 schedule simulators — the cheaper schedule wins and is
   recorded on the candidate);
3. prune configs whose per-chip memory (params + grads + optimizer
   state + activation checkpoints, with pipeline in-flight accounting)
   exceeds the HBM budget — the compile-free OOM verdict (the
   reference's prune-by-memory, auto_tuner/prune.py);
4. (optional) hand the top-k survivors to the auto_tuner trial runner,
   which compiles and TIMES each candidate (distributed/auto_tuner/
   runner.py) — measurement beats modeling for the final pick.

The cluster description (chip FLOP/s, ICI GB/s, HBM bytes) defaults to
v5e and is overridable — the analog of static/cluster.py.
"""
from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple

__all__ = ["Cluster", "ModelProfile", "PlanCandidate", "Planner",
           "profile_model", "detect_cluster"]


@dataclass
class Cluster:
    """ref: auto_parallel/static/cluster.py — the device description the
    cost model prices against. Defaults: one TPU v5e pod slice."""
    chip_flops: float = 197e12          # bf16 peak per chip
    ici_bandwidth: float = 45e9         # bytes/s per link direction
    hbm_bytes: float = 16e9
    mfu_ceiling: float = 0.6            # realistic matmul efficiency
    ici_latency: float = 5e-6           # per-collective launch latency
    mp_min_width: int = 512             # hidden/mp below this starves
    # the MXU (128-wide systolic tiles + pipelining need fat matmuls);
    # compute efficiency scales ~ linearly with shard width under it


# Known accelerator table (peak bf16 FLOP/s, HBM bytes, ICI GB/s per
# link direction); device_kind substring -> spec. The reference loads
# its cluster description from a JSON topology file or auto-detects
# (ref: auto_parallel/static/cluster.py); here jax.devices() is the
# source of truth and this table fills in what PJRT doesn't report.
_CHIP_TABLE = [
    ("v5 lite", (394e12 / 2, 16e9, 45e9)),   # v5e (197 bf16 via 394/2)
    ("v5e", (197e12, 16e9, 45e9)),
    ("v5p", (459e12, 95e9, 100e9)),
    ("v6", (918e12, 32e9, 90e9)),
    ("v4", (275e12, 32e9, 50e9)),
    ("v3", (123e12, 32e9, 70e9)),
]


def detect_cluster(probe: bool = False) -> Cluster:
    """Build a Cluster from the live runtime instead of a hand-filled
    dataclass (ref: static/cluster.py auto-detection): device_kind maps
    through the chip table, HBM comes from PJRT memory_stats when the
    platform reports it, and ``probe=True`` additionally MEASURES chip
    FLOP/s (one timed bf16 matmul) and per-collective latency (a timed
    psum on multi-device runtimes) — measurement beats tables on
    unknown hardware, and the offline fallback is the defaults."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    devs = jax.devices()
    kind = getattr(devs[0], "device_kind", "").lower()
    flops, hbm, ici = next(
        (spec for sub, spec in _CHIP_TABLE if sub in kind),
        (None, None, None))
    c = Cluster()
    if flops is not None:
        c.chip_flops, c.hbm_bytes, c.ici_bandwidth = flops, hbm, ici
    try:
        stats = devs[0].memory_stats()
        if stats and stats.get("bytes_limit"):
            c.hbm_bytes = float(stats["bytes_limit"])
    except Exception:
        pass
    if probe:
        # matmul peak probe: a 2048^3 bf16 dot (~17 GFLOP) timed after
        # warm-up; peak ~= measured / typical large-matmul efficiency
        n = 2048
        x = jnp.ones((n, n), jnp.bfloat16)
        f = jax.jit(lambda a, b: a @ b)
        jax.block_until_ready(f(x, x))
        t0 = time.perf_counter()
        for _ in range(4):
            y = f(x, x)
        jax.block_until_ready(y)
        dt = (time.perf_counter() - t0) / 4
        measured = 2 * n ** 3 / dt
        if flops is None:  # unknown chip (e.g. CPU): trust the probe
            c.chip_flops = measured / max(c.mfu_ceiling, 1e-6)
        if len(devs) > 1:
            from jax.sharding import Mesh, PartitionSpec as P
            mesh = Mesh(np.array(devs), ("x",))
            from .._mesh_axes import shard_map
            g = jax.jit(shard_map(
                lambda a: jax.lax.psum(a, "x"), mesh=mesh,
                in_specs=P(), out_specs=P()))
            z = jnp.ones((8,), jnp.float32)
            jax.block_until_ready(g(z))
            t0 = time.perf_counter()
            for _ in range(8):
                w = g(z)
            jax.block_until_ready(w)
            c.ici_latency = max((time.perf_counter() - t0) / 8, 1e-7)
    return c


@dataclass
class ModelProfile:
    """What the cost model needs to know about one training step."""
    param_bytes: int                    # total parameter bytes
    flops_per_step: float               # fwd+bwd+update FLOPs
    batch_tokens: int = 1
    hidden: int = 1                     # activation width (mp comm unit)
    layer_count: int = 1                # mp comm multiplier
    act_dtype_bytes: int = 2
    bytes_per_param_state: float = 10.0  # grad + opt state per param byte
    # (bf16 grads 1x + f32 moments 8 bytes/2-byte-param => ~10x is AdamW
    # with fp32 state; SGD-momentum would be ~4)
    # -- context parallelism (ring attention) --
    # tokens per SAMPLE: dp/fsdp split samples, cp splits WITHIN one —
    # the axis that matters when one sequence is the whole batch
    seq_len: int = 1
    # -- expert parallelism (MoE) --
    # bytes of expert FFN params (shardable over ep on top of fsdp)
    moe_expert_param_bytes: int = 0
    moe_layer_count: int = 0            # alltoall pairs per step

    @property
    def activation_bytes(self) -> float:
        """Standard transformer footprint ~12 tensors of
        [tokens, hidden] live per layer."""
        return (12.0 * self.layer_count * self.batch_tokens *
                self.hidden * self.act_dtype_bytes)


def profile_model(model, batch_tokens: int,
                  layer_count: Optional[int] = None) -> ModelProfile:
    """Build a ModelProfile from a live Layer: params from the module
    tree, FLOPs from the 6·N·tokens transformer estimate (the standard
    fwd+bwd accounting; ref static_op_benchmark.json's role is pricing
    sanity, not exactness), activations ~ 12·tokens·hidden guess."""
    import numpy as np
    n_params = 0
    p_bytes = 0
    widths: List[int] = []
    for p in model.parameters():
        size = int(np.prod(p.shape)) if len(p.shape) else 1
        n_params += size
        p_bytes += size * p._data.dtype.itemsize
        if len(p.shape) >= 2:
            widths.append(int(p.shape[-1]))
    hidden = int(np.median(widths)) if widths else 1
    layers = layer_count
    if layers is None:
        # count distinct numbered blocks in param names as the proxy
        import re
        idx = {m.group(1) for n, _ in model.named_parameters()
               for m in [re.search(r"(?:^|\.)(\d+)\.", n)] if m}
        layers = max(len(idx), 1)
    return ModelProfile(
        param_bytes=p_bytes,
        flops_per_step=6.0 * n_params * batch_tokens,
        batch_tokens=batch_tokens,
        hidden=hidden,
        layer_count=layers,
    )


@dataclass
class PlanCandidate:
    dp: int
    fsdp: int
    mp: int
    pp: int = 1
    cp: int = 1                   # ring-attention context parallel
    ep: int = 1                   # MoE expert parallel
    schedule: str = ""            # "1f1b" | "zb_h1" when pp > 1
    bubble_fraction: float = 0.0
    est_step_time: float = 0.0
    est_mem_bytes: float = 0.0
    feasible: bool = True
    reason: str = ""
    measured_items_per_s: Optional[float] = None

    @property
    def mesh_shape(self) -> Tuple[int, int, int]:
        return (self.dp, self.fsdp, self.mp)

    @property
    def full_shape(self) -> Tuple[int, int, int, int]:
        return (self.dp, self.fsdp, self.mp, self.pp)

    @property
    def six_axis_shape(self):
        return (self.dp, self.fsdp, self.mp, self.pp, self.cp, self.ep)


def _ring_factor(n: int) -> float:
    return (n - 1) / n if n > 1 else 0.0


@functools.lru_cache(maxsize=None)
def _bubble_fractions(pp: int, micro: int) -> Tuple[float, float]:
    """(1F1B, ZB-H1) bubble fractions for a pp-stage pipeline with
    ``micro`` micro-batches, replayed through the repo's own schedule
    simulator (fleet/pipeline_zero_bubble.py) — the same event/dependency
    model the real schedules execute, not a closed-form guess."""
    from ..fleet.pipeline_zero_bubble import (
        one_f_one_b_schedule, simulate_schedule, zb_h1_schedule)

    busy = 3 * micro  # per-stage work slots: micro * (t_f + t_b + t_w)

    def frac(idle_by_stage):
        worst = max(idle_by_stage.values())
        return worst / (worst + busy)

    f1b = frac(simulate_schedule(
        {s: one_f_one_b_schedule(pp, s, micro) for s in range(pp)},
        fused_bw=True))
    zb = frac(simulate_schedule(
        {s: zb_h1_schedule(pp, s, micro) for s in range(pp)}))
    return f1b, zb


class Planner:
    """Search over (dp, fsdp, mp) factorizations of n_devices.

    ``plan()`` = analytic rank (+ memory prune); ``plan_measured()``
    additionally times the top-k with the auto_tuner trial runner and
    returns the measured winner — the reference's two-phase
    cost-model-then-trials flow (auto_tuner/tuner.py)."""

    def __init__(self, n_devices: int, cluster: Optional[Cluster] = None,
                 max_mp: Optional[int] = None, max_pp: int = 1,
                 micro_batches: Optional[int] = None,
                 schedules=None, max_cp: int = 1, max_ep: int = 1):
        self.n = n_devices
        self.cluster = cluster or Cluster()
        self.max_mp = max_mp or n_devices
        # cp/ep axes open only when the caller can realize them (ring
        # attention in the model / a MoE layer with expert sharding) —
        # the repo's above-parity features the planner can now price
        self.max_cp = max(int(max_cp), 1)
        self.max_ep = max(int(max_ep), 1)
        # pp candidates are enumerated only up to max_pp: the caller must
        # be able to REALIZE a pipeline plan (Engine gates this on its
        # pipeline executor's segmentation contract)
        self.max_pp = max(int(max_pp), 1)
        self.micro_batches = micro_batches  # default: 2*pp per candidate
        # which schedules the CALLER can execute: pp candidates are
        # priced with the best bubble among these and record the pick.
        # Default = the fleet's executable split-B/W schedules; the
        # Engine's compiled-GPipe executor passes ("gpipe",) so the plan
        # is priced with the fill-drain bubble it will actually get.
        self.schedules = tuple(schedules or ("1f1b", "zb_h1"))

    def candidates(self) -> List[PlanCandidate]:
        out = []
        n = self.n
        for pp in range(1, min(self.max_pp, n) + 1):
            if n % pp:
                continue
            n1 = n // pp
            for cp in range(1, min(self.max_cp, n1) + 1):
                if n1 % cp:
                    continue
                n2 = n1 // cp
                for ep in range(1, min(self.max_ep, n2) + 1):
                    if n2 % ep:
                        continue
                    nn = n2 // ep
                    for dp in range(1, nn + 1):
                        if nn % dp:
                            continue
                        rem = nn // dp
                        for fsdp in range(1, rem + 1):
                            if rem % fsdp:
                                continue
                            mp = rem // fsdp
                            if mp > self.max_mp:
                                continue
                            out.append(PlanCandidate(
                                dp=dp, fsdp=fsdp, mp=mp, pp=pp, cp=cp,
                                ep=ep))
        return out

    def _pick_schedule(self, pp: int, micro: int):
        """Best executable schedule for (pp, micro): replay 1F1B/ZB-H1
        through the repo's own simulator (the executable schedules in
        fleet/pipeline_zero_bubble.py); GPipe fill-drain closed form
        is (pp-1) idle slots around micro working slots per stage."""
        f1b, zb = _bubble_fractions(pp, micro)
        gp = (pp - 1) / (micro + pp - 1)
        options = {"1f1b": f1b, "zb_h1": zb, "gpipe": gp}
        return min(((s, options[s]) for s in self.schedules
                    if s in options), key=lambda kv: kv[1])

    def price(self, cand: PlanCandidate, prof: ModelProfile
              ) -> PlanCandidate:
        c = self.cluster
        micro = self.micro_batches or max(2 * cand.pp, 1)
        n_shard = cand.fsdp * cand.mp * cand.pp
        # the data axes can never split finer than the data: dp/fsdp
        # split SAMPLES, cp splits one sample's sequence — this is the
        # physics that makes cp the only way to scale a single long
        # sequence (ring attention, SURVEY §5 long-context)
        batch_samples = max(prof.batch_tokens // max(prof.seq_len, 1), 1)
        if cand.dp * cand.fsdp > batch_samples:
            cand.feasible = False
            cand.reason = (f"dp*fsdp={cand.dp * cand.fsdp} exceeds "
                           f"{batch_samples} batch sample(s)")
            return cand
        if cand.cp > 1 and prof.seq_len // cand.cp < 128:
            cand.feasible = False
            cand.reason = (f"cp={cand.cp} shards seq {prof.seq_len} "
                           f"below one flash tile (128)")
            return cand
        if cand.ep > 1 and (not prof.moe_layer_count
                            or not prof.moe_expert_param_bytes):
            # ep on a dense model would be a free (uncosted) axis that
            # shards nothing — reject rather than mis-rank
            cand.feasible = False
            cand.reason = "ep>1 but the model has no MoE experts"
            return cand
        # -- memory: params+grads+opt sharded by fsdp*mp, and by pp too
        # (each stage owns only its layers). Activations: per-layer
        # rematerialization keeps ONE layer's working set live, but the
        # remat CHECKPOINTS (one [tokens, hidden] boundary per layer,
        # batch split over dp*fsdp) are stored — pipeline stages store
        # them only for their own layers and in-flight micro-batches,
        # which is the memory lever pp has that fsdp doesn't: fsdp can
        # never shard a batch it can't split, pp shards the LAYERS.
        dense_bytes = prof.param_bytes - prof.moe_expert_param_bytes
        state_scale = 1 + prof.bytes_per_param_state
        # expert params additionally shard over ep — THE memory lever
        # of expert parallelism (the reference shards expert FFNs over
        # the ep group, moe_layer.py; dense params don't see ep)
        state_bytes = (dense_bytes * state_scale
                       + prof.moe_expert_param_bytes * state_scale
                       / cand.ep)
        act_live = prof.activation_bytes / max(prof.layer_count, 1)
        ckpt_all = (prof.layer_count * prof.batch_tokens * prof.hidden *
                    prof.act_dtype_bytes)
        ckpt = ckpt_all / (cand.dp * cand.fsdp * cand.cp)
        live = act_live / self.n
        if cand.pp > 1:
            # Pick the schedule FIRST (bubble replay needs only pp and
            # micro) so memory is priced with the schedule that will
            # actually run: 1F1B/ZB cap live checkpoints at the stage
            # depth, but GPipe's fill-drain holds every micro-batch's
            # stage checkpoints until backward starts — pricing a
            # gpipe-executed plan with min(pp, micro) under-counts ~2x
            # and the HBM prune admits plans the executor OOMs on.
            cand.schedule, cand.bubble_fraction = self._pick_schedule(
                cand.pp, micro)
            if cand.schedule == "gpipe":
                in_flight = micro
            else:
                in_flight = min(cand.pp, micro)
            ckpt = ckpt * in_flight / (micro * cand.pp)
            # the pipeline computes ONE micro-batch at a time per stage,
            # so the live working set shrinks with the micro count
            live = live / micro
        mem = state_bytes / n_shard + live + ckpt
        cand.est_mem_bytes = mem
        if mem > c.hbm_bytes:
            cand.feasible = False
            cand.reason = (f"est {mem/1e9:.1f}GB > HBM "
                           f"{c.hbm_bytes/1e9:.1f}GB")
        # -- compute: data/model-parallel FLOPs, degraded when mp
        # shards the hidden dim below the MXU-efficient width (the
        # known physics that makes tiny-model mp lose to dp even though
        # its comm bytes look small)
        width = max(prof.hidden / cand.mp, 1.0)
        mp_eff = min(1.0, width / c.mp_min_width)
        t_compute = prof.flops_per_step / self.n / \
            (c.chip_flops * c.mfu_ceiling * mp_eff)
        # -- pipeline bubble: schedule + fraction were picked in the
        # memory pass above (so memory matches the executed schedule)
        if cand.pp > 1:
            t_compute = t_compute / max(1.0 - cand.bubble_fraction, 1e-3)
        # -- communication per step (ring costs over ICI):
        bw = c.ici_bandwidth
        shard_param_bytes = prof.param_bytes / n_shard
        t_dp = 2 * shard_param_bytes * _ring_factor(cand.dp) / bw
        t_fsdp = 3 * (prof.param_bytes / (cand.mp * cand.pp)) * \
            _ring_factor(cand.fsdp) / bw
        # Megatron mp: two activation allreduces fwd + two bwd per layer
        # over this shard's [tokens, hidden] tensor (tokens split by
        # every data-splitting axis: dp, fsdp AND cp)
        mp_bytes = (4 * prof.layer_count *
                    (prof.batch_tokens / (cand.dp * cand.fsdp * cand.cp))
                    * prof.hidden * prof.act_dtype_bytes)
        t_mp = mp_bytes * _ring_factor(cand.mp) / bw
        # cp ring attention: per layer, (cp-1) ring hops rotate this
        # shard's K/V blocks fwd and again (with grads) bwd — 3 passes
        # of 2*[tokens_local, hidden] over ICI (ring_attention.py's
        # ppermute schedule)
        t_cp = 0.0
        if cand.cp > 1:
            tokens_local = prof.batch_tokens / (cand.dp * cand.fsdp *
                                                cand.cp)
            hop = 2 * tokens_local * prof.hidden * prof.act_dtype_bytes
            t_cp = 3 * prof.layer_count * (cand.cp - 1) * hop / bw
        # ep alltoall: dispatch + combine move this shard's tokens to
        # their experts and back, fwd and bwd (the reference's
        # global_scatter/global_gather pair per MoE layer); the DENSE
        # params see the ep group as plain data parallelism, so their
        # grads pay an extra allreduce over ep
        t_ep = 0.0
        if cand.ep > 1 and prof.moe_layer_count:
            tokens_local = prof.batch_tokens / (cand.dp * cand.fsdp *
                                                cand.cp)
            a2a = (tokens_local * prof.hidden * prof.act_dtype_bytes *
                   (cand.ep - 1) / cand.ep)
            t_ep = (3 * 2 * prof.moe_layer_count * a2a) / bw
            t_ep += 2 * (dense_bytes / n_shard) * \
                _ring_factor(cand.ep) / bw
        # pp boundary p2p: one [tokens_micro, hidden] activation fwd and
        # one grad bwd per stage boundary per micro-batch
        t_pp = 0.0
        if cand.pp > 1:
            tokens_micro = prof.batch_tokens / (cand.dp * cand.fsdp *
                                                cand.cp * micro)
            hop_bytes = tokens_micro * prof.hidden * prof.act_dtype_bytes
            t_pp = 2 * (cand.pp - 1) * micro * hop_bytes / bw
        # per-COLLECTIVE launch latency (ring transfers pipeline, so
        # the launch cost is ~independent of ring length): dp's grad
        # allreduce is one fused pair; fsdp gathers/scatters and mp
        # allreduces fire per layer — at toy scale this fixed cost is
        # why pure dp measures fastest
        lat = c.ici_latency
        t_lat = ((2 * lat if cand.dp > 1 else 0.0) +
                 (3 * prof.layer_count * lat if cand.fsdp > 1 else 0.0) +
                 (4 * prof.layer_count * lat if cand.mp > 1 else 0.0) +
                 (3 * prof.layer_count * (cand.cp - 1) * lat
                  if cand.cp > 1 else 0.0) +
                 (6 * prof.moe_layer_count * lat if cand.ep > 1
                  else 0.0) +
                 (2 * (cand.pp - 1) * micro * lat if cand.pp > 1
                  else 0.0))
        cand.est_step_time = (t_compute + t_dp + t_fsdp + t_mp + t_cp +
                              t_ep + t_pp + t_lat)
        return cand

    def plan(self, prof: ModelProfile, top_k: int = 1,
             realizable_fn: Optional[Callable] = None
             ) -> List[PlanCandidate]:
        """Rank feasible candidates by estimated step time.
        ``realizable_fn`` additionally prunes configs the caller's
        executor cannot run (e.g. pp plans whose block family doesn't
        split) — the single home of the realizability contract, shared
        by the Engine's analytic path and plan_measured."""
        priced = [self.price(c, prof) for c in self.candidates()]
        feas = [c for c in priced if c.feasible]
        if not feas:
            detail = "; ".join(
                f"dp{c.dp}/fsdp{c.fsdp}/mp{c.mp}: {c.reason}"
                for c in priced[:6])
            raise ValueError(
                f"no feasible parallel config for {self.n} devices "
                f"({detail}) — add devices or shrink the model/batch")
        if realizable_fn is not None:
            feas = [c for c in feas if realizable_fn(c)]
            if not feas:
                raise ValueError(
                    "no realizable parallel config: every feasible "
                    "candidate needs shardings the caller's executor "
                    "can't deliver (pp with fsdp/mp, or pp not dividing "
                    "the block family) — raise HBM, shrink the model, "
                    "or provide a mesh explicitly")
        feas.sort(key=lambda c: c.est_step_time)
        return feas[:top_k]

    def plan_measured(self, prof: ModelProfile, trial_fn: Callable,
                      top_k: int = 3,
                      realizable_fn: Optional[Callable] = None
                      ) -> PlanCandidate:
        """Time the analytic top-k with ``trial_fn(config_dict) ->
        items/s`` (build_trial_runner's contract); failures (OOM et al)
        are recorded and skipped like the reference's failed trials.
        ``realizable_fn`` prunes candidates the caller's executor cannot
        run BEFORE they occupy trial slots (otherwise 3 unrealizable pp
        plans would exhaust the trials while a realizable pp=1 plan sits
        just below the cut)."""
        cands = self.plan(prof, top_k=top_k, realizable_fn=realizable_fn)
        best = None
        for cand in cands:
            cfg = {"dp_degree": cand.dp, "fsdp_degree": cand.fsdp,
                   "mp_degree": cand.mp}
            if cand.pp > 1:
                cfg["pp_degree"] = cand.pp
                cfg["pp_schedule"] = cand.schedule
            try:
                cand.measured_items_per_s = float(trial_fn(cfg))
            except Exception as e:  # noqa: BLE001 — a failed trial is data
                cand.feasible = False
                cand.reason = f"trial failed: {type(e).__name__}: {e}"
                continue
            if best is None or cand.measured_items_per_s > \
                    best.measured_items_per_s:
                best = cand
        if best is None:
            raise RuntimeError("every trialed config failed")
        return best
