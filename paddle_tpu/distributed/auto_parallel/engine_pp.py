"""Pipeline-plan realization for the auto-parallel Engine.

The reference's static engine doesn't just PLAN pipeline schedules — it
executes them (ref: auto_parallel/static/engine.py:100 +
passes/pipeline_scheduler_pass/). This module is that executor for the
TPU build: when the planner picks a pp > 1 candidate, the Engine hands
the model here, the repeated-block family becomes the pipeline body
(the reference's PipelineLayer SEGMENTATION role,
fleet/meta_parallel/pp_layers.py), and one jitted train step runs
pre-layers -> compiled GPipe over a ("dp", "pp") mesh
(parallel.spmd_pipeline) -> post-layers -> loss -> grads -> optimizer
update, all inside a single XLA program.

Supported model shape (v1, the same contract the reference's
PipelineLayer imposes): a Sequential whose children contain ONE
contiguous run of >= 2 structurally-identical single-input blocks
(transformer layers, MLP blocks); children before/after the run become
replicated pre/post stages. Blocks must be buffer-free (BN running
stats would need cross-microbatch merging).
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor

__all__ = ["detect_pipeline_split", "PipelineTrainStep",
           "build_pipeline_model"]


def build_pipeline_model(descs):
    """Instantiate a fleet LayerDesc/SharedLayerDesc list into the
    Sequential the compiled pipeline path consumes (ref: PipelineLayer's
    build loop, pp_layers.py:257) — SharedLayerDescs with the same key
    share ONE layer instance, so its parameters are the same Tensor
    objects at every use site and PipelineTrainStep's tied-weight
    detection wires the gradient merge."""
    from ...nn.container import Sequential
    from ...nn.layer import Layer
    from ..fleet.pp_layers import LayerDesc, SharedLayerDesc

    class _SharedUse(Layer):
        """One use-site of a shared layer (optionally through its
        forward_func, e.g. embedding-as-lm-head)."""

        def __init__(self, inner, fwd=None):
            super().__init__()
            self.inner = inner
            self._fwd = fwd

        def forward(self, x):
            if self._fwd is not None:
                return self._fwd(self.inner, x)
            return self.inner(x)

    class _FnLayer(Layer):
        """Plain-callable pipeline item (pp_layers.py:130 accepts
        functions, e.g. a reshape between stages)."""

        def __init__(self, fn):
            super().__init__()
            self._fn = fn

        def forward(self, x):
            return self._fn(x)

    shared = {}
    layers = []
    for d in descs:
        if isinstance(d, SharedLayerDesc):
            if d.layer_name not in shared:
                shared[d.layer_name] = d.build_layer()
            layers.append(_SharedUse(shared[d.layer_name],
                                     d.forward_func))
        elif isinstance(d, LayerDesc):
            layers.append(d.build_layer())
        elif isinstance(d, Layer):
            layers.append(d)
        elif callable(d):
            layers.append(_FnLayer(d))
        else:
            raise TypeError(f"bad pipeline item {d!r}")
    return Sequential(*layers)


def _block_signature(layer):
    """Stacking identity: class + ordered (param name, shape, dtype)."""
    return (type(layer).__name__,
            tuple((n, tuple(p.shape), str(p.dtype))
                  for n, p in layer.named_parameters()))


def detect_pipeline_split(model):
    """(pre_layers, family, post_layers) or None when the model has no
    realizable pipeline body. Family = the longest contiguous run of
    STRUCTURALLY-identical children (same class AND same param
    names/shapes/dtypes — same-class blocks with different widths can't
    stack) with >= 2 members inside a Sequential model."""
    children = [l for _, l in model.named_children()]
    if len(children) < 2 or not hasattr(model, "__getitem__"):
        return None
    best = None  # (length, start, end)
    i = 0
    while i < len(children):
        j = i
        sig = _block_signature(children[i])
        while j < len(children) and \
                _block_signature(children[j]) == sig:
            j += 1
        if j - i >= 2 and (best is None or j - i > best[0]):
            best = (j - i, i, j)
        i = max(j, i + 1)
    if best is None:
        return None
    _, s, e = best
    fam = children[s:e]
    if any(len(dict(b.named_buffers())) for b in fam):
        return None  # buffer-carrying blocks (BN) can't pipeline (v1)
    return children[:s], fam, children[e:]


class PipelineTrainStep:
    """One jitted train step realizing a (dp x pp) pipeline plan.

    loss_fn(out_tensor, *label_tensors) -> scalar Tensor. The loss must
    be a mean over the batch for micro-batch averaging to equal the
    full-batch gradient (GPipe's contract; asserted numerically by
    tests against a flat oracle).
    """

    def __init__(self, model, loss_fn: Callable, optimizer, pp: int,
                 n_devices: Optional[int] = None, micro_batches=None,
                 remat="dots", devices=None):
        from jax.sharding import Mesh

        from ...jit.api import functionalize
        from ...parallel import stack_layer_params

        split = detect_pipeline_split(model)
        if split is None:
            raise ValueError(
                "pipeline plan needs a Sequential model with a "
                "contiguous run of >= 2 identical buffer-free blocks "
                "(the PipelineLayer segmentation contract)")
        pre, fam, post = split
        if len(fam) % pp:
            raise ValueError(
                f"{len(fam)} pipeline blocks not divisible by pp={pp}")
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.pp = pp
        self.micro = micro_batches or 2 * pp
        pool = list(devices) if devices is not None else jax.devices()
        n = n_devices or len(pool)
        if n % pp:
            raise ValueError(f"{n} devices not divisible by pp={pp}")
        if n > len(pool):
            raise ValueError(f"need {n} devices, have {len(pool)}")
        self.mesh = Mesh(
            np.array(pool[:n]).reshape(n // pp, pp), ("dp", "pp"))

        applies = [functionalize(b) for b in fam]
        self._stage_apply = applies[0][0]
        stacked = stack_layer_params([a[1] for a in applies])
        params = {"blocks": stacked}
        # source Tensor maps so updates WRITE BACK into the live model
        # (evaluate()/save() after fit must see trained weights — the
        # DistTrainStep contract)
        self._block_tensors = [dict(b.named_parameters()) for b in fam]
        self._pre_tensors = self._post_tensors = None
        self._pre_apply = self._post_apply = None
        if pre:
            from ...nn.container import Sequential
            seq = Sequential(*pre)
            a, p0, b0 = functionalize(seq)
            if b0:
                raise ValueError("pre-stage buffers unsupported (v1)")
            self._pre_apply, params["pre"] = a, p0
            self._pre_tensors = dict(seq.named_parameters())
        if post:
            from ...nn.container import Sequential
            seq = Sequential(*post)
            a, p0, b0 = functionalize(seq)
            if b0:
                raise ValueError("post-stage buffers unsupported (v1)")
            self._post_apply, params["post"] = a, p0
            self._post_tensors = dict(seq.named_parameters())
        # -- tied weights (SharedLayerDesc semantics, ref:
        # fleet/meta_parallel/parallel_layers/pp_layers.py:92): the SAME
        # Parameter object reachable from both the pre and post stages
        # (tied embedding / lm head) moves to ONE canonical "shared"
        # entry; both stages read it from there inside the step, so
        # autodiff SUMS the two use-sites' gradients — the in-program
        # equivalent of the reference's shared-param grad allreduce
        # across owning stages — and the optimizer updates one copy.
        self._tied = {"pre": {}, "post": {}}
        self._shared_tensors = {}
        by_id = {}
        for sec, tens in (("pre", self._pre_tensors or {}),
                          ("post", self._post_tensors or {})):
            for k, t in tens.items():
                by_id.setdefault(id(t), (t, []))[1].append((sec, k))
        # a Parameter shared with a pipeline BLOCK cannot be tied this
        # way (stack_layer_params copies it into the stacked family, so
        # the copies would silently diverge) — reject loudly
        block_ids = {id(t) for tens in self._block_tensors
                     for t in tens.values()}
        for tid, (t, locs) in by_id.items():
            if len(locs) >= 1 and tid in block_ids:
                raise ValueError(
                    f"parameter {locs[0][1]!r} is shared between a "
                    f"pipeline block and the {locs[0][0]} stage; tying "
                    f"into the stacked block family is unsupported — "
                    f"tie only across the pre/post stages")
        shared = {}
        for t, locs in by_id.values():
            if len(locs) < 2:
                continue
            # section + key makes the canonical name unique (two
            # DIFFERENT ties could share a positional key like
            # '0.weight' across sections)
            sname = ("tied_" + locs[0][0] + "_"
                     + locs[0][1].replace(".", "_"))
            sec0, key0 = locs[0]
            shared[sname] = params[sec0][key0]
            self._shared_tensors[sname] = t
            for sec, key in locs:
                del params[sec][key]
                self._tied[sec][key] = sname
        if shared:
            params["shared"] = shared
        self._params = params
        self._opt_state = None
        self._jitted = None
        self._remat = remat

    def _init_opt_state(self):
        return jax.tree.map(
            lambda leaf: self.optimizer._init_state(Tensor(leaf)),
            self._params)

    def _build(self):
        from ...parallel import spmd_pipeline

        opt = self.optimizer
        loss_fn = self.loss_fn
        pre_apply, post_apply = self._pre_apply, self._post_apply
        stage_apply = self._stage_apply
        mesh, micro, remat = self.mesh, self.micro, self._remat

        def stage_fn(p, x):
            out, _ = stage_apply(p, {}, x)
            return out._data if isinstance(out, Tensor) else out

        tied = self._tied

        def with_tied(ps, sec):
            """Section params + its tied entries materialized from the
            canonical shared copies."""
            base = ps.get(sec, {})
            if not tied[sec]:
                return base
            return {**base, **{k: ps["shared"][s]
                               for k, s in tied[sec].items()}}

        def step_fn(params, opt_state, lr, batch, labels):
            def loss_of(ps):
                x = batch
                if pre_apply is not None:
                    out, _ = pre_apply(with_tied(ps, "pre"), {}, x)
                    x = out._data if isinstance(out, Tensor) else out
                b = x.shape[0]
                if b % micro:
                    raise ValueError(
                        f"batch {b} not divisible by {micro} "
                        f"micro-batches")
                mb = x.reshape(micro, b // micro, *x.shape[1:])
                y = spmd_pipeline(stage_fn, ps["blocks"], mb, mesh,
                                  "pp", ("dp",), remat=remat)
                y = y.reshape(b, *y.shape[2:])
                if post_apply is not None:
                    out, _ = post_apply(with_tied(ps, "post"), {}, y)
                    y = out._data if isinstance(out, Tensor) else out
                lt = loss_fn(Tensor(y),
                             *[Tensor(l) for l in labels])
                return lt._data.astype(jnp.float32)

            loss, grads = jax.value_and_grad(loss_of)(params)
            # opt_state holds a SLOT DICT at each param-leaf position;
            # flatten params and lift the state tree only down to the
            # param leaves so each slot dict rides along intact
            flat_p, tdef = jax.tree.flatten(params)
            flat_g = jax.tree.leaves(grads)
            flat_s = tdef.flatten_up_to(opt_state)
            out = [opt._update(p, g, s, lr)
                   for p, g, s in zip(flat_p, flat_g, flat_s)]
            new_params = jax.tree.unflatten(tdef, [o[0] for o in out])
            new_state = jax.tree.unflatten(tdef, [o[1] for o in out])
            return loss, new_params, new_state

        self._jitted = jax.jit(step_fn, donate_argnums=(0, 1))

    def estimate_peak_bytes(self, batch, *labels) -> int:
        """Global-shape peak of the step via the static jaxpr-liveness
        estimator (compile-free; same model the Engine's memory-aware
        recompute uses) — the auto-tuner's pre-execution OOM gate for
        pipeline trials."""
        from .mem_estimator import estimate_peak_bytes
        if self._jitted is None:
            self._build()
        if self._opt_state is None:
            self._opt_state = self._init_opt_state()

        def sds(a):
            a = np.asarray(a) if not hasattr(a, "dtype") else a
            return jax.ShapeDtypeStruct(np.shape(a), a.dtype)

        raw = [b._data if isinstance(b, Tensor) else np.asarray(b)
               for b in (batch, *labels)]
        traced = self._jitted.trace(
            jax.tree.map(sds, self._params),
            jax.tree.map(sds, self._opt_state),
            jax.ShapeDtypeStruct((), jnp.float32),
            sds(raw[0]), tuple(sds(r) for r in raw[1:]))
        return int(estimate_peak_bytes(traced.jaxpr))

    def _write_back(self):
        """Push the step's param pytree into the live model's Tensors."""
        for i, tens in enumerate(self._block_tensors):
            for k, t in tens.items():
                t._data = self._params["blocks"][k][i]
        if self._pre_tensors:
            for k, t in self._pre_tensors.items():
                if k not in self._tied["pre"]:
                    t._data = self._params["pre"][k]
        if self._post_tensors:
            for k, t in self._post_tensors.items():
                if k not in self._tied["post"]:
                    t._data = self._params["post"][k]
        for sname, t in self._shared_tensors.items():
            t._data = self._params["shared"][sname]

    def state_dict(self):
        """Flat name -> Tensor dict, the same contract DistTrainStep
        gives the sharded-checkpoint machinery (param keys
        'section.name', optimizer slots 'section.name#slot'; stacked
        block params save as single [L, ...] tensors)."""
        if self._opt_state is None:
            self._opt_state = self._init_opt_state()
        out = {}
        for section, tree in self._params.items():
            for k, v in tree.items():
                out[f"{section}.{k}"] = Tensor(v)
            for k, slots in self._opt_state[section].items():
                for sname, sv in slots.items():
                    out[f"{section}.{k}#{sname}"] = Tensor(sv)
        return out

    def set_state_dict(self, sd):
        if self._opt_state is None:
            self._opt_state = self._init_opt_state()
        for key, t in sd.items():
            val = t._data if isinstance(t, Tensor) else jnp.asarray(t)
            name, slot = (key.rsplit("#", 1) + [None])[:2] \
                if "#" in key else (key, None)
            section, pname = name.split(".", 1)
            if section not in self._params or \
                    pname not in self._params[section]:
                raise ValueError(
                    f"checkpoint key {key!r} does not match the "
                    f"pipeline step's parameters")
            if slot is None:
                self._params[section][pname] = val
            else:
                self._opt_state[section][pname][slot] = val
        self._write_back()

    def __call__(self, batch, *labels):
        if self._jitted is None:
            self._build()
        if self._opt_state is None:
            self._opt_state = self._init_opt_state()
        raw = [b._data if isinstance(b, Tensor) else jnp.asarray(b)
               for b in (batch, *labels)]
        lr = jnp.float32(float(self.optimizer.get_lr()))
        loss, self._params, self._opt_state = self._jitted(
            self._params, self._opt_state, lr, raw[0], tuple(raw[1:]))
        self.optimizer._global_step += 1
        self._write_back()
        return Tensor(loss)
