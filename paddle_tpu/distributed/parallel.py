"""Parallel environment bootstrap + DataParallel.

ref: python/paddle/distributed/parallel.py:978 (init_parallel_env),
:396-419 (DataParallel over EagerReducer bucketed allreduce,
ref: paddle/fluid/distributed/collective/reducer.cc). TPU-native:
bootstrap is jax.distributed.initialize (PJRT coordination service plays
the TCPStore role, ref: phi/core/distributed/store/tcp_store.h:121);
DataParallel's gradient sync is an allreduce over the dp group after
backward — on a single controller the preferred path is instead batch
sharding via shard_tensor/pjit, which needs no wrapper at all.
"""
from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np

from ..core.tensor import Tensor
from ..nn.layer import Layer
from .collective import (Group, ReduceOp, _ensure_default_group, all_reduce,
                         _global_rank, _world_size)

__all__ = [
    "init_parallel_env", "get_rank", "get_world_size", "is_initialized",
    "ParallelEnv", "DataParallel",
]

_initialized = False


def init_parallel_env() -> Group:
    """ref: parallel.py:978. Reads PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM /
    PADDLE_MASTER (set by paddle_tpu.distributed.launch) and brings up the
    JAX distributed runtime; single-process when unset."""
    global _initialized
    if _initialized:
        return _ensure_default_group()
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    nranks = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    master = os.environ.get("PADDLE_MASTER",
                            os.environ.get("MASTER_ADDR", ""))
    # probe WITHOUT touching the backend: jax.process_count() would
    # initialize XLA right here, making the initialize() below a
    # guaranteed too-late failure (silent store-transport fallback)
    if nranks > 1 and not jax.distributed.is_initialized():
        port = os.environ.get("MASTER_PORT", "")
        addr = master if ":" in master or not port else f"{master}:{port}"
        try:
            # CPU backend: cross-process collectives need a real CPU
            # collectives implementation (gloo) — the analog of the
            # reference picking ProcessGroupGloo for CPU places
            # (ref: parallel.py:978 _new_process_group_impl backend map)
            if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
                try:
                    jax.config.update(
                        "jax_cpu_collectives_implementation", "gloo")
                except Exception:
                    pass  # older jaxlib: option absent
            jax.distributed.initialize(
                coordinator_address=addr, num_processes=nranks,
                process_id=rank)
        except RuntimeError as e:
            if "must be called before" not in str(e):
                raise  # genuine bootstrap failure (bad address etc.)
            # XLA backend already up (e.g. the import touched jax.devices,
            # or the CPU test harness): eager collectives fall back to the
            # TCPStore channel transport — ranks come from the launcher env.
    _initialized = True
    return _ensure_default_group()


def is_initialized() -> bool:
    return _initialized


def get_rank(group: Optional[Group] = None) -> int:
    if group is not None:
        return group.rank
    return _global_rank()


def get_world_size(group: Optional[Group] = None) -> int:
    if group is not None:
        return group.nranks
    return _world_size()


class ParallelEnv:
    """ref: parallel.py ParallelEnv (env introspection object)."""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def dev_id(self):
        return int(os.environ.get("FLAGS_selected_tpus", "0"))

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "")

    @property
    def trainer_endpoints(self):
        return os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")


class DataParallel(Layer):
    """ref: parallel.py:396 DataParallel. Gradient allreduce over the dp
    group after backward with size-bucketed FUSION (ref: EagerReducer,
    fluid/distributed/collective/reducer.cc Eager_AssignGroupBySize +
    FusedAllReduceSchedule): grads are packed into ~comm_buffer_size-MB
    flat buffers so the eager path issues one collective per bucket —
    over the store transport that's one round-trip per bucket instead of
    one per parameter; in compiled steps XLA's collective combiner plays
    this role."""

    def __init__(self, layers: Layer, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group: Optional[Group] = None):
        super().__init__()
        self._layers = layers
        self._group = group
        self.comm_buffer_size = comm_buffer_size
        self.last_comm_buffer_size = last_comm_buffer_size
        self.find_unused_parameters = find_unused_parameters
        init_parallel_env()

    def _grad_buckets(self):
        """Group parameters by accumulated byte size (ref: reducer.h:41
        Eager_AssignGroupBySize with group limits [last_comm_buffer_size,
        comm_buffer_size] — the first bucket stays small so its fused
        allreduce launches early). Buckets cover EVERY trainable param in
        a deterministic order — a rank whose control flow skipped some
        param contributes zeros rather than shifting the flat layout
        (rank-divergent layouts would sum unrelated slices together)."""
        first_limit = max(int(self.last_comm_buffer_size), 1) * 1024 * 1024
        limit = max(int(self.comm_buffer_size), 1) * 1024 * 1024
        buckets = []
        cur, cur_bytes, cur_dtype = [], 0, None
        for p in self._layers.parameters():
            if p.stop_gradient:
                continue
            nbytes = p._data.nbytes
            cap = first_limit if not buckets else limit
            if cur and (cur_bytes + nbytes > cap or
                        p._data.dtype != cur_dtype):
                buckets.append(cur)
                cur, cur_bytes = [], 0
            cur.append(p)
            cur_bytes += nbytes
            cur_dtype = p._data.dtype
        if cur:
            buckets.append(cur)
        return buckets

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def apply_collective_grads(self):
        """ref: hybrid_parallel_util.py fused_allreduce_gradients +
        reducer.cc FusedAllReduceSchedule — one flat AVG allreduce per
        size bucket, then unpack back into each param's grad. Params with
        no local grad contribute zeros (keeps the flat layout identical
        on every rank) and do not get a grad written back."""
        import jax.numpy as jnp

        n = get_world_size(self._group)
        if n <= 1:
            return
        for bucket in self._grad_buckets():
            # every rank joins every bucket's collective, even with no
            # local grads (zeros) — skipping would desequence the store
            # transport / deadlock the ring on ranks that do have grads
            # the flat layout is bucketed by PARAM dtype (deterministic
            # across ranks even when some rank has no grad); a grad whose
            # dtype differs (e.g. fp32 grads on bf16 params) is packed in
            # the param dtype and restored to its own dtype after — never
            # let jnp.concatenate promote the whole buffer
            if len(bucket) == 1:
                p = bucket[0]
                if p.grad is None:
                    all_reduce(Tensor(jnp.zeros_like(p._data)),
                               ReduceOp.AVG, self._group)
                elif p.grad._data.dtype == p._data.dtype:
                    all_reduce(p.grad, ReduceOp.AVG, self._group)
                else:
                    gdt = p.grad._data.dtype
                    t = Tensor(p.grad._data.astype(p._data.dtype))
                    all_reduce(t, ReduceOp.AVG, self._group)
                    p.grad._data = t._data.astype(gdt)
                continue
            flat = jnp.concatenate([
                (p.grad._data.astype(p._data.dtype)
                 if p.grad is not None
                 else jnp.zeros_like(p._data)).reshape(-1)
                for p in bucket])
            fused = Tensor(flat)
            all_reduce(fused, ReduceOp.AVG, self._group)
            off = 0
            for p in bucket:
                size = p._data.size
                if p.grad is not None:
                    p.grad._data = fused._data[off:off + size].astype(
                        p.grad._data.dtype).reshape(p.grad._data.shape)
                off += size

    def scale_loss(self, loss):
        return loss

    def __getattr__(self, name):
        try:
            return super().__getattr__(name)
        except AttributeError:
            return getattr(self._layers, name)
