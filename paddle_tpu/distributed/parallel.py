"""Parallel environment bootstrap + DataParallel.

ref: python/paddle/distributed/parallel.py:978 (init_parallel_env),
:396-419 (DataParallel over EagerReducer bucketed allreduce,
ref: paddle/fluid/distributed/collective/reducer.cc). TPU-native:
bootstrap is jax.distributed.initialize (PJRT coordination service plays
the TCPStore role, ref: phi/core/distributed/store/tcp_store.h:121);
DataParallel's gradient sync is an allreduce over the dp group after
backward — on a single controller the preferred path is instead batch
sharding via shard_tensor/pjit, which needs no wrapper at all.
"""
from __future__ import annotations

import os
from typing import Optional

import jax

from ..core.tensor import Tensor
from ..nn.layer import Layer
from .collective import (Group, ReduceOp, _ensure_default_group, all_reduce,
                         _global_rank, _world_size)

__all__ = [
    "init_parallel_env", "get_rank", "get_world_size", "is_initialized",
    "ParallelEnv", "DataParallel",
]

_initialized = False


def init_parallel_env() -> Group:
    """ref: parallel.py:978. Reads PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM /
    PADDLE_MASTER (set by paddle_tpu.distributed.launch) and brings up the
    JAX distributed runtime; single-process when unset."""
    global _initialized
    if _initialized:
        return _ensure_default_group()
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    nranks = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    master = os.environ.get("PADDLE_MASTER",
                            os.environ.get("MASTER_ADDR", ""))
    if nranks > 1 and jax.process_count() == 1:
        port = os.environ.get("MASTER_PORT", "")
        addr = master if ":" in master or not port else f"{master}:{port}"
        try:
            jax.distributed.initialize(
                coordinator_address=addr, num_processes=nranks,
                process_id=rank)
        except RuntimeError as e:
            if "must be called before" not in str(e):
                raise  # genuine bootstrap failure (bad address etc.)
            # XLA backend already up (e.g. the import touched jax.devices,
            # or the CPU test harness): eager collectives fall back to the
            # TCPStore channel transport — ranks come from the launcher env.
    _initialized = True
    return _ensure_default_group()


def is_initialized() -> bool:
    return _initialized


def get_rank(group: Optional[Group] = None) -> int:
    if group is not None:
        return group.rank
    return _global_rank()


def get_world_size(group: Optional[Group] = None) -> int:
    if group is not None:
        return group.nranks
    return _world_size()


class ParallelEnv:
    """ref: parallel.py ParallelEnv (env introspection object)."""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def dev_id(self):
        return int(os.environ.get("FLAGS_selected_tpus", "0"))

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "")

    @property
    def trainer_endpoints(self):
        return os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")


class DataParallel(Layer):
    """ref: parallel.py:396 DataParallel. Gradient allreduce over the dp
    group after backward; bucketing (EagerReducer, reducer.cc) is left to
    XLA's collective combiner when the step is jitted — eager path does a
    straight per-param allreduce on apply_collective_grads."""

    def __init__(self, layers: Layer, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group: Optional[Group] = None):
        super().__init__()
        self._layers = layers
        self._group = group
        self.find_unused_parameters = find_unused_parameters
        init_parallel_env()

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def apply_collective_grads(self):
        """ref: hybrid_parallel_util.py fused_allreduce_gradients."""
        n = get_world_size(self._group)
        if n <= 1:
            return
        for p in self._layers.parameters():
            if p.grad is not None:
                all_reduce(p.grad, ReduceOp.SUM, self._group)
                p.grad._data = p.grad._data / n

    def scale_loss(self, loss):
        return loss

    def __getattr__(self, name):
        try:
            return super().__getattr__(name)
        except AttributeError:
            return getattr(self._layers, name)
