"""TCPStore: the rendezvous KV store for multi-host bootstrap.

ref: paddle/phi/core/distributed/store/tcp_store.h:121 (TCPStore with
set/get/add/wait, rank-0 hosts the server) — here backed by the C++
implementation in paddle_tpu/_native/native.cpp. The multi-host mesh
bootstrap (PJRT distributed init) uses this for address exchange the same
way the reference's ProcessGroup creation broadcasts NCCL unique ids
through its store (ref: process_group_nccl.cc CreateNCCLEnvCache).

Client ops retry transient transport failures with exponential backoff
under a per-op deadline (a preempted/restarting coordinator must not
take every worker down with one reset connection); a deliberate server
shutdown (the native call returning None) still aborts immediately.
Fault-injection sites ``store.<op>`` sit inside the retry loop so tests
can prove the retry path without a flaky network.
"""
from __future__ import annotations

import time
from typing import Optional

from .._native import lib as _lib
from ..observability import metrics as _om
from ..utils import backoff as _backoff
from ..utils import fault_injection as _fi

__all__ = ["TCPStore"]

_M_retries = _om.counter(
    "store.op_retries_total",
    "Transient TCPStore transport failures absorbed by the retry loop")
_M_failures = _om.counter(
    "store.op_failures_total",
    "TCPStore ops that exhausted their retry budget/deadline")

# transient transport errors worth retrying (BrokenPipeError is already
# a ConnectionError). Deliberately NOT all of OSError: a structurally
# broken client (EBADF after shutdown, ENOSPC) should fail fast, not
# burn the backoff budget. The abort-path ConnectionError (native None
# return) is raised OUTSIDE the retry loop on purpose.
_RETRYABLE = (ConnectionError, TimeoutError)


class TCPStore:
    """ref-parity API: TCPStore(host, port, is_master, world_size, timeout).

    set/get/add/wait; `wait` blocks until the key exists (server-side
    condition variable, no polling).

    max_retries/backoff/op_deadline govern the transient-failure retry
    of every client op: attempt, sleep backoff*2^n (capped at
    backoff_max), re-attempt, until success, max_retries exhausted, or
    op_deadline seconds have passed — whichever comes first, with the
    last transport error chained into the final ConnectionError."""

    def __init__(self, host: str, port: int, is_master: bool = False,
                 world_size: int = 1, timeout: float = 30.0,
                 max_retries: int = 4, backoff: float = 0.05,
                 backoff_max: float = 2.0, op_deadline: float = 15.0):
        if _lib is None:
            raise RuntimeError(
                "paddle_tpu native runtime unavailable (g++ build failed)")
        self.host = host
        self.port = port
        self.is_master = is_master
        self.world_size = world_size
        self.max_retries = int(max_retries)
        self.backoff = float(backoff)
        self.backoff_max = float(backoff_max)
        self.op_deadline = float(op_deadline)
        self.op_retries = 0   # total transient failures absorbed
        self._server = None
        self._barrier_gen = 0
        if is_master:
            self._server = _lib.store_server_start(port)
        self._client = _lib.store_client_connect(host, port, timeout)

    # -- retry core --------------------------------------------------------
    def _call(self, op: str, fn):
        """Run one client op with bounded retry + exponential backoff +
        deadline. Retries only exceptions raised BY the transport (or
        the ``store.<op>`` injection site); the caller interprets the
        return value (None = deliberate server-side abort, not retried).
        Retries reuse the SAME connection (no reconnect): a response
        lost to a broken connection keeps failing on retry rather than
        re-applying, so a non-idempotent `add` cannot double-count
        today. If reconnect-per-op is ever added, barrier() arrival
        must first become idempotent (per-participant keys), or one
        lost add response could release a barrier early.
        """
        # the deadline bounds the RECOVERY window, so it starts at the
        # first failure — a get/wait/take legitimately blocked for
        # minutes before the coordinator restarted must still get its
        # full retry budget
        deadline = None
        attempt = 0
        while True:
            try:
                _fi.fire(f"store.{op}")
                return fn()
            except _RETRYABLE as e:
                attempt += 1
                if deadline is None:
                    deadline = time.monotonic() + self.op_deadline
                remaining = deadline - time.monotonic()
                if attempt > self.max_retries or remaining <= 0:
                    why = ("retry budget exhausted "
                           f"({self.max_retries} retries)"
                           if attempt > self.max_retries else
                           f"op deadline exceeded ({self.op_deadline}s)")
                    _M_failures.inc(op=op)
                    raise ConnectionError(
                        f"TCPStore {op} to {self.host}:{self.port} failed "
                        f"after {attempt} attempt(s): {why}; last error: "
                        f"{type(e).__name__}: {e}") from e
                self.op_retries += 1
                _M_retries.inc(op=op)
                # full jitter spreads a worker herd retrying the same
                # coordinator restart; the remaining-deadline cap stays
                # OUTSIDE the jitter so the op deadline is still honored
                sleep = min(
                    _backoff.full_jitter(
                        min(self.backoff * (2 ** (attempt - 1)),
                            self.backoff_max)),
                    max(remaining, 0.0))
                if sleep > 0:
                    time.sleep(sleep)

    # -- ops ---------------------------------------------------------------
    def set(self, key: str, value) -> None:
        if isinstance(value, str):
            value = value.encode()
        value = bytes(value)
        self._call("set", lambda: _lib.store_set(self._client, key, value))

    def get(self, key: str) -> bytes:
        """Blocks until the key is set (reference wait-then-get contract)."""
        v = self._call("get",
                       lambda: _lib.store_get(self._client, key, True))
        if v is None:
            raise ConnectionError(
                f"TCPStore wait for {key!r} aborted (server shut down)")
        return v

    def get_nowait(self, key: str) -> Optional[bytes]:
        """None means the key does not exist; b'' is a real empty value."""
        return self._call(
            "get_nowait",
            lambda: _lib.store_get(self._client, key, False))

    def add(self, key: str, amount: int = 1) -> int:
        amount = int(amount)
        return self._call("add",
                          lambda: _lib.store_add(self._client, key, amount))

    def take(self, key: str) -> bytes:
        """Blocking get that atomically deletes the key — the single-consumer
        channel primitive backing eager p2p (send/recv) transport."""
        v = self._call("take", lambda: _lib.store_take(self._client, key))
        if v is None:
            raise ConnectionError(
                f"TCPStore take of {key!r} aborted (server shut down)")
        return v

    def delete(self, key: str) -> None:
        self._call("delete", lambda: _lib.store_delete(self._client, key))

    def wait(self, keys) -> None:
        if isinstance(keys, str):
            keys = [keys]
        for k in keys:
            v = self._call("wait",
                           lambda k=k: _lib.store_get(self._client, k,
                                                      True))
            if v is None:
                raise ConnectionError(
                    f"TCPStore wait for {k!r} aborted (server shut down)")

    def barrier(self, name: str = "barrier") -> None:
        """All world_size participants arrive, then proceed. Keys carry a
        per-call generation so the barrier is reusable (each participant's
        Nth call synchronizes with every peer's Nth call)."""
        gen = self._barrier_gen
        self._barrier_gen += 1
        n = self.add(f"__{name}_{gen}_cnt", 1)
        if n >= self.world_size:
            self.set(f"__{name}_{gen}_done", b"1")
        self.wait(f"__{name}_{gen}_done")

    def shutdown(self):
        if self._server is not None:
            _lib.store_server_stop(self._server)
            self._server = None

    def __del__(self):
        try:
            self.shutdown()
        except Exception:
            pass
