"""TCPStore: the rendezvous KV store for multi-host bootstrap.

ref: paddle/phi/core/distributed/store/tcp_store.h:121 (TCPStore with
set/get/add/wait, rank-0 hosts the server) — here backed by the C++
implementation in paddle_tpu/_native/native.cpp. The multi-host mesh
bootstrap (PJRT distributed init) uses this for address exchange the same
way the reference's ProcessGroup creation broadcasts NCCL unique ids
through its store (ref: process_group_nccl.cc CreateNCCLEnvCache).
"""
from __future__ import annotations

from typing import Optional

from .._native import lib as _lib

__all__ = ["TCPStore"]


class TCPStore:
    """ref-parity API: TCPStore(host, port, is_master, world_size, timeout).

    set/get/add/wait; `wait` blocks until the key exists (server-side
    condition variable, no polling)."""

    def __init__(self, host: str, port: int, is_master: bool = False,
                 world_size: int = 1, timeout: float = 30.0):
        if _lib is None:
            raise RuntimeError(
                "paddle_tpu native runtime unavailable (g++ build failed)")
        self.host = host
        self.port = port
        self.is_master = is_master
        self.world_size = world_size
        self._server = None
        self._barrier_gen = 0
        if is_master:
            self._server = _lib.store_server_start(port)
        self._client = _lib.store_client_connect(host, port, timeout)

    def set(self, key: str, value) -> None:
        if isinstance(value, str):
            value = value.encode()
        _lib.store_set(self._client, key, bytes(value))

    def get(self, key: str) -> bytes:
        """Blocks until the key is set (reference wait-then-get contract)."""
        v = _lib.store_get(self._client, key, True)
        if v is None:
            raise ConnectionError(
                f"TCPStore wait for {key!r} aborted (server shut down)")
        return v

    def get_nowait(self, key: str) -> Optional[bytes]:
        """None means the key does not exist; b'' is a real empty value."""
        return _lib.store_get(self._client, key, False)

    def add(self, key: str, amount: int = 1) -> int:
        return _lib.store_add(self._client, key, int(amount))

    def take(self, key: str) -> bytes:
        """Blocking get that atomically deletes the key — the single-consumer
        channel primitive backing eager p2p (send/recv) transport."""
        v = _lib.store_take(self._client, key)
        if v is None:
            raise ConnectionError(
                f"TCPStore take of {key!r} aborted (server shut down)")
        return v

    def delete(self, key: str) -> None:
        _lib.store_delete(self._client, key)

    def wait(self, keys) -> None:
        if isinstance(keys, str):
            keys = [keys]
        for k in keys:
            _lib.store_get(self._client, k, True)

    def barrier(self, name: str = "barrier") -> None:
        """All world_size participants arrive, then proceed. Keys carry a
        per-call generation so the barrier is reusable (each participant's
        Nth call synchronizes with every peer's Nth call)."""
        gen = self._barrier_gen
        self._barrier_gen += 1
        n = self.add(f"__{name}_{gen}_cnt", 1)
        if n >= self.world_size:
            self.set(f"__{name}_{gen}_done", b"1")
        self.wait(f"__{name}_{gen}_done")

    def shutdown(self):
        if self._server is not None:
            _lib.store_server_stop(self._server)
            self._server = None

    def __del__(self):
        try:
            self.shutdown()
        except Exception:
            pass
