"""Sharded whole-training-step compiler.

The TPU-native replacement for the reference's hybrid-parallel training
machinery (ref: fleet/meta_parallel/* + auto_parallel/static/engine.py:100):
parameters carry NamedShardings (attached by shard_llama / shard_tensor),
and ONE jax.jit of loss-fwd + backward + optimizer-update compiles the
whole dp x fsdp x tp program — XLA GSPMD inserts the ICI collectives the
reference issues manually through ProcessGroupNCCL (all-gather for ZeRO-3
param shards, reduce-scatter of grads, allreduce over dp). Optimizer state
inherits each parameter's sharding, which *is* sharding stage-1/2/3
depending on the placement rules used.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import random as random_mod
from ..core.tensor import Tensor
from ..jit.api import _Swap, functionalize

__all__ = ["DistTrainStep"]


class DistTrainStep:
    """Compiled train step over (possibly sharded) params.

    loss_fn(outputs, *labels) -> scalar Tensor. Batch arrays should be
    device_put with their data sharding (Shard(0) on the dp axis) before the
    call — or pass `data_sharding` to have the step do it.
    """

    def __init__(self, model, loss_fn: Callable, optimizer,
                 data_sharding=None, donate: bool = True,
                 accumulate_steps: int = 1):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.data_sharding = data_sharding
        self._swap = _Swap(model)
        self._params = self._swap.params
        self._opt_state = None
        self._jitted = None
        self._donate = donate
        # device-resident RNG (root key + counter) and lr cache: a
        # per-step key upload / lr DevicePut each cost a host->device
        # transfer (measured ~3 ms/step over the test tunnel)
        self._rng = None
        self._rng_epoch = None
        self._lr_host = None
        self._lr_dev = None
        # gradient merge (ref: passes/auto_parallel_gradient_merge.py):
        # the global batch is split into accumulate_steps micro-batches,
        # grads averaged inside ONE compiled step via lax.scan, then a
        # single optimizer update — the whole merge stays on-device
        self.accumulate_steps = max(int(accumulate_steps), 1)

    def _init_opt_state(self):
        """Optimizer state co-sharded with its parameter — the ZeRO contract
        (ref: dygraph_sharding_optimizer.py partitions state by param
        ownership; here ownership = the param's own placement)."""
        state = {}
        for k, p in self._params.items():
            if p.stop_gradient:
                continue
            s = self.optimizer._init_state(p)
            arr = p._data
            if hasattr(arr, "sharding"):
                s = {
                    name: jax.device_put(v, arr.sharding)
                    if getattr(v, "shape", None) == arr.shape else v
                    for name, v in s.items()
                }
            state[k] = s
        return state

    def _build(self):
        model, loss_fn, opt = self.model, self.loss_fn, self.optimizer
        swap = self._swap
        trainable = {k for k, p in self._params.items()
                     if not p.stop_gradient}

        acc = self.accumulate_steps

        def step_fn(params, buffers, opt_state, lr, rng, batch, labels):
            root, count = rng
            key = jax.random.fold_in(root, count)
            train_p = {k: v for k, v in params.items() if k in trainable}
            frozen_p = {k: v for k, v in params.items()
                        if k not in trainable}

            def loss_of(tp, bufs, mb, lbls, k_):
                full = {**tp, **frozen_p}
                from ..core.autograd import no_grad
                with no_grad(), random_mod.key_stream(k_):
                    out, new_buffers = swap.run(
                        full, bufs, model.__call__,
                        *[Tensor(b) for b in mb])
                    loss_t = loss_fn(out, *[Tensor(x) for x in lbls])
                return loss_t._data.astype(jnp.float32), new_buffers

            if acc <= 1:
                (loss, new_buffers), grads = jax.value_and_grad(
                    loss_of, has_aux=True)(train_p, buffers, batch, labels,
                                           key)
            else:
                # split dim0 into [acc, -1] micro-batches and scan,
                # averaging grads (gradient merge, fully on-device)
                for arr in (*batch, *labels):
                    if arr.shape[0] % acc:
                        raise ValueError(
                            f"gradient merge: batch dim {arr.shape[0]} "
                            f"is not divisible by accumulate_steps="
                            f"{acc}; drop or pad the tail batch")
                micro_b = tuple(
                    b.reshape((acc, b.shape[0] // acc) + b.shape[1:])
                    for b in batch)
                micro_l = tuple(
                    x.reshape((acc, x.shape[0] // acc) + x.shape[1:])
                    for x in labels)
                keys = jax.random.split(key, acc)

                def scan_body(carry, xs):
                    loss_sum, gsum, bufs = carry
                    mb, lbls, k_ = xs
                    (l, nb), g = jax.value_and_grad(
                        loss_of, has_aux=True)(train_p, bufs, mb, lbls, k_)
                    gsum = jax.tree.map(
                        lambda a, b_: a + b_.astype(jnp.float32), gsum, g)
                    return (loss_sum + l, gsum, nb), None

                # fp32 accumulators: merging k bf16 micro-grads in bf16
                # would lose the low bits the merge exists to keep
                g0 = jax.tree.map(
                    lambda p: jnp.zeros(p.shape, jnp.float32), train_p)
                (loss_sum, grads, new_buffers), _ = jax.lax.scan(
                    scan_body, (jnp.float32(0.0), g0, buffers),
                    (micro_b, micro_l, keys))
                loss = loss_sum / acc
                grads = jax.tree.map(lambda g: g / acc, grads)
            new_params = dict(params)
            new_opt = dict(opt_state)
            for k in trainable:
                g_k = opt._apply_regularizer(params[k], grads[k])
                new_p, new_s = opt._update(params[k], g_k,
                                           opt_state[k], lr)
                new_params[k] = new_p
                new_opt[k] = new_s
            return (loss, new_params, new_buffers, new_opt,
                    (root, count + jnp.uint32(1)))

        # buffers (argnum 1) donated as well — without aliasing, the
        # per-step buffer updates (BN stats etc.) force device copies
        donate = (0, 1, 2, 4) if self._donate else ()
        self._jitted = jax.jit(step_fn, donate_argnums=donate)

    # -- checkpoint ---------------------------------------------------------
    def state_dict(self) -> Dict[str, Tensor]:
        """Optimizer-state slots as named Tensors for
        dist.save_state_dict (ref: the sharded-optimizer ckpt merge
        utilities in fleet; slot naming param.slot)."""
        if self._opt_state is None:
            self._opt_state = self._init_opt_state()
        out = {}
        for k, slots in self._opt_state.items():
            for name, v in slots.items():
                out[f"{k}#{name}"] = Tensor(v)
        return out

    def set_state_dict(self, sd: Dict) -> None:
        if self._opt_state is None:
            self._opt_state = self._init_opt_state()
        unmatched = []
        covered = set()
        for key, t in sd.items():
            if "#" not in key:
                unmatched.append(key)
                continue
            pname, slot = key.rsplit("#", 1)
            if pname not in self._opt_state:
                unmatched.append(key)
                continue
            covered.add((pname, slot))
            arr = t._data if isinstance(t, Tensor) else jnp.asarray(t)
            param_arr = self._params[pname]._data
            sharding = getattr(param_arr, "sharding", None)
            from jax.sharding import NamedSharding, PartitionSpec
            if isinstance(sharding, NamedSharding):
                if arr.shape != param_arr.shape:
                    # scalar slots (beta pows) replicate over the mesh
                    sharding = NamedSharding(sharding.mesh, PartitionSpec())
                if getattr(arr, "sharding", None) == sharding:
                    pass  # already placed (the dist-checkpoint load
                    # path fills slots with the param's own sharding);
                    # re-putting a multi-controller global array would
                    # be an unsupported cross-host transfer
                elif (isinstance(arr, jax.Array)
                      and not arr.is_fully_addressable):
                    raise ValueError(
                        f"optimizer slot {key!r} arrives as a "
                        f"multi-process array with sharding "
                        f"{arr.sharding} but the parameter needs "
                        f"{sharding}; reshard it via dist checkpoint "
                        f"load (host-side assembly) instead")
                else:
                    # a COMMITTED device array can't be device_put
                    # across processes (pinned src placement); hop
                    # through host — every process holds the full
                    # value, so the put only writes local shards
                    if isinstance(arr, jax.Array):
                        arr = np.asarray(arr)
                    arr = jax.device_put(arr, sharding)
            self._opt_state[pname][slot] = arr
        missing = [f"{p}#{s}" for p, slots in self._opt_state.items()
                   for s in slots if (p, s) not in covered]
        if unmatched or missing:
            raise ValueError(
                "optimizer checkpoint does not match the current model "
                "(resuming would silently reset state): "
                f"unmatched keys {unmatched[:5]}, "
                f"missing slots {missing[:5]}")

    def _abstract_opt_state(self):
        """Shape-only optimizer state (no device allocation): each
        slot's shapes/dtypes via eval_shape over the optimizer's own
        init fn — the trace-only probes must not materialize a second
        copy of the AdamW moments in exactly the memory-constrained
        configurations they diagnose."""
        out = {}
        for k, p in self._params.items():
            if p.stop_gradient:
                continue
            out[k] = jax.eval_shape(
                lambda d, _p=p: self.optimizer._init_state(
                    Tensor(d, stop_gradient=_p.stop_gradient)), p._data)
        return out

    def _probe_args(self, *batch_and_labels, num_labels: int = 1,
                    abstract: bool = False):
        """Shared arg prep for the no-run diagnostics (compile_stats /
        trace_jaxpr): current params/buffers/opt-state plus a FIXED
        probe rng key — a diagnostic must not advance the global RNG
        stream (seed-fixed training after a stats query stays
        identical). ``abstract=True`` substitutes ShapeDtypeStructs
        everywhere (trace-only callers: zero device allocation; note
        shardings are NOT carried, so compile-fidelity callers must use
        the concrete form)."""
        if self._jitted is None:
            self._build()

        def sds(a):
            return jax.ShapeDtypeStruct(a.shape, a.dtype)

        if abstract:
            # shape metadata only — np.asarray on host data reads shape/
            # dtype without any device transfer, honoring the
            # zero-device-allocation contract
            raw = [b._data if isinstance(b, Tensor) else b
                   for b in batch_and_labels]
            raw = [sds(r) if isinstance(r, jax.Array)
                   else sds(np.asarray(r)) for r in raw]
        else:
            raw = [b._data if isinstance(b, Tensor)
                   else b if isinstance(b, jax.Array)
                   else jnp.asarray(np.asarray(b))
                   for b in batch_and_labels]
            if self.data_sharding is not None:
                raw = [jax.device_put(r, self.data_sharding)
                       for r in raw]
        batch = tuple(raw[:len(raw) - num_labels])
        labels = tuple(raw[len(raw) - num_labels:]) if num_labels else ()
        if abstract:
            params = {k: sds(t._data) for k, t in self._params.items()}
            buffers = {k: sds(t._data)
                       for k, t in self._swap.buffers.items()}
            opt_state = (jax.tree.map(sds, self._opt_state)
                         if self._opt_state is not None
                         else self._abstract_opt_state())
            probe_rng = (jax.eval_shape(lambda: jax.random.key(0)),
                         jax.ShapeDtypeStruct((), jnp.uint32))
            lr = jax.ShapeDtypeStruct((), jnp.float32)
            return (params, buffers, opt_state, lr, probe_rng, batch,
                    labels)
        if self._opt_state is None:
            self._opt_state = self._init_opt_state()
        params = {k: t._data for k, t in self._params.items()}
        buffers = {k: t._data for k, t in self._swap.buffers.items()}
        probe_rng = (jax.random.key(0), jnp.uint32(0))
        return (params, buffers, self._opt_state, jnp.float32(0.0),
                probe_rng, batch, labels)

    def compile_stats(self, *batch_and_labels, num_labels: int = 1,
                      return_compiled: bool = False):
        """Compile the step for these batch shapes WITHOUT running it and
        return XLA's memory analysis (argument/output/temp bytes). The
        auto-tuner's memory model prunes configs on this before paying
        for a trial run (ref: auto_tuner/prune.py's OOM-signature
        pruning, done here ahead of time from the compiled program).
        With return_compiled=True also returns the AOT executable so the
        caller can time steps without a second compile."""
        args = self._probe_args(*batch_and_labels, num_labels=num_labels)
        compiled = self._jitted.lower(*args).compile()
        mem = compiled.memory_analysis()
        if return_compiled:
            return mem, compiled, (args[0], args[1], args[5], args[6])
        return mem

    def trace_jaxpr(self, *batch_and_labels, num_labels: int = 1,
                    abstract: bool = False):
        """Trace (no compile) the step and return its ClosedJaxpr — the
        input to the static peak-memory estimator
        (auto_parallel.mem_estimator.estimate_peak_bytes).
        ``abstract=True`` traces from ShapeDtypeStructs: no device
        allocation at all (probe-safe in memory-tight configs)."""
        args = self._probe_args(*batch_and_labels, num_labels=num_labels,
                                abstract=abstract)
        return self._jitted.trace(*args).jaxpr

    def __call__(self, *batch_and_labels, num_labels: int = 1):
        if self._jitted is None:
            self._build()
        if self._opt_state is None:
            self._opt_state = self._init_opt_state()
        # device arrays pass through untouched — np.asarray on a jax.Array
        # would round-trip the whole batch through the host every step
        raw = [b._data if isinstance(b, Tensor)
               else b if isinstance(b, jax.Array)
               else jnp.asarray(np.asarray(b)) for b in batch_and_labels]
        if self.data_sharding is not None:
            raw = [jax.device_put(r, self.data_sharding) for r in raw]
        if len(raw) <= num_labels:
            raise ValueError(
                f"need at least {num_labels + 1} arrays (inputs + "
                f"{num_labels} labels), got {len(raw)}")
        batch = tuple(raw[:len(raw) - num_labels])
        labels = tuple(raw[len(raw) - num_labels:]) if num_labels else ()
        params = {k: t._data for k, t in self._params.items()}
        buffers = {k: t._data for k, t in self._swap.buffers.items()}
        if self._rng is None or \
                self._rng_epoch != random_mod.seed_epoch():
            # ONE draw from the global stream seeds this step's
            # device-side stream: distinct step objects stay on distinct
            # streams, the stream follows paddle.seed, and a re-seed
            # mid-run (epoch bump) re-derives it
            self._rng = (random_mod.next_key(), jnp.uint32(0))
            self._rng_epoch = random_mod.seed_epoch()
        lr_now = float(self.optimizer.get_lr())
        if self._lr_host != lr_now:
            self._lr_dev = jnp.float32(lr_now)
            self._lr_host = lr_now
        loss, new_params, new_buffers, new_opt, self._rng = self._jitted(
            params, buffers, self._opt_state, self._lr_dev, self._rng,
            batch, labels)
        for k, t in self._params.items():
            t._data = new_params[k]
        for k, t in self._swap.buffers.items():
            t._data = new_buffers[k]
        self._opt_state = new_opt
        self.optimizer._global_step += 1
        return Tensor(loss)
