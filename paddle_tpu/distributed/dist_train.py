"""Sharded whole-training-step compiler — on the SOT capture engine.

The TPU-native replacement for the reference's hybrid-parallel training
machinery (ref: fleet/meta_parallel/* + auto_parallel/static/engine.py:100):
parameters carry NamedShardings (attached by shard_llama / shard_tensor),
and ONE captured executable of loss-fwd + backward + optimizer-update
compiles the whole dp x fsdp x tp program — XLA GSPMD inserts the ICI
collectives the reference issues manually through ProcessGroupNCCL
(all-gather for ZeRO-3 param shards, reduce-scatter of grads, allreduce
over dp). Optimizer state inherits each parameter's sharding, which *is*
sharding stage-1/2/3 depending on the placement rules used.

Since Fusion III's distributed round this class is a thin wrapper over
``jit.sot.CapturedStep`` in non-strict mode — the same signature
guards, LRU program cache, retrace/fallback counters and flight events
the single-chip ``jit.TrainStep`` rides (its bespoke ``jax.jit``
closure is gone) — plus two distributed specializations:

* **Gradient merge** (ref: passes/auto_parallel_gradient_merge.py):
  ``accumulate_steps`` micro-batches scanned inside the ONE captured
  program, grads accumulated in fp32.
* **Bucketed compute–collective overlap** (the T3 paper's fine-grained
  tracking-and-triggering): instead of gradient synchronization
  running as a serial epilogue after the full backward, grads group
  into ``FLAGS_dist_grad_bucket_bytes`` buckets in REVERSE-backward
  order and each bucket's all-reduce/reduce-scatter is emitted as its
  own first-class node in the captured DAG
  (``collective.bucketed_grad_sync``) — bucket k depends only on its
  own grads, so XLA's async collectives launch it while earlier
  layers are still differentiating. Per-bucket payload rides the
  flight recorder's collective events each step.
"""
from __future__ import annotations

import time as _time
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.flags import flag_value
from ..core.tensor import Tensor
from ..jit.sot import CapturedStep

__all__ = ["DistTrainStep"]


class _DistCapturedStep(CapturedStep):
    """CapturedStep specialized for the sharded whole-step program:
    batch arrays device_put with the data sharding, freshly created
    optimizer slots co-sharded with their parameter (the ZeRO
    contract), gradient merge via an in-program scan, and bucketed
    gradient synchronization between backward and the optimizer tail."""

    def __init__(self, model, loss_fn, optimizer, data_sharding=None,
                 donate: bool = True, accumulate_steps: int = 1):
        super().__init__(model, loss_fn, optimizer, cast_loss_f32=True,
                         donate=donate, strict=False,
                         name="dist_train_step",
                         build_kind="dist_train_step")
        self.data_sharding = data_sharding
        self.accumulate_steps = max(int(accumulate_steps), 1)
        # bucket plans keyed by (bucket_bytes, trainable keys) — the
        # only inputs the plan depends on (grad shapes ARE the param
        # shapes). Keyed, not last-trace: a cached program replayed
        # after a flag round-trip must report ITS plan, not the most
        # recently traced one
        self._bucket_plans: Dict[tuple, List[Dict]] = {}

    # -- signature ---------------------------------------------------------
    def _signature(self, kind, arrays, n_ins, tkeys, scaler_statics=None):
        sig = super()._signature(kind, arrays, n_ins, tkeys,
                                 scaler_statics)
        if sig is None:
            return None
        # the bucket target shapes the traced program (bucket count +
        # barrier chain): a flag flip must retrace, not replay a stale
        # program — it joins the guards like every other trace input
        return sig + (("bucket_bytes",
                       int(flag_value("dist_grad_bucket_bytes") or 0)),)

    # -- batch plumbing ----------------------------------------------------
    def _arrays(self, values):
        out = super()._arrays(values)
        if out is not None and self.data_sharding is not None:
            out = [jax.device_put(r, self.data_sharding) for r in out]
        return out

    # -- optimizer state ---------------------------------------------------
    def _opt_state_for(self, p):
        """Slot state co-sharded with its parameter — the ZeRO contract
        (ref: dygraph_sharding_optimizer.py partitions state by param
        ownership; here ownership = the param's own placement).
        Scalar slots (beta pows) keep their shape and replicate."""
        opt = self.optimizer
        st = opt._states.get(id(p))
        if st is not None:
            return st
        st = opt._state_for(p)
        arr = p._data
        if hasattr(arr, "sharding"):
            st = {
                name: jax.device_put(v, arr.sharding)
                if getattr(v, "shape", None) == arr.shape else v
                for name, v in st.items()
            }
            opt._states[id(p)] = st
        return st

    # -- gradient merge ----------------------------------------------------
    def _value_and_grads(self, loss_of, train_p, buffers, batch, labels,
                         key):
        acc = self.accumulate_steps
        if acc <= 1:
            return super()._value_and_grads(loss_of, train_p, buffers,
                                            batch, labels, key)
        # split dim0 into [acc, -1] micro-batches and scan, averaging
        # grads (gradient merge, fully on-device)
        for arr in (*batch, *labels):
            if arr.shape[0] % acc:
                raise ValueError(
                    f"gradient merge: batch dim {arr.shape[0]} "
                    f"is not divisible by accumulate_steps="
                    f"{acc}; drop or pad the tail batch")
        micro_b = tuple(
            b.reshape((acc, b.shape[0] // acc) + b.shape[1:])
            for b in batch)
        micro_l = tuple(
            x.reshape((acc, x.shape[0] // acc) + x.shape[1:])
            for x in labels)
        keys = jax.random.split(key, acc)

        def scan_body(carry, xs):
            loss_sum, gsum, bufs = carry
            mb, lbls, k_ = xs
            (_, (l, nb)), g = jax.value_and_grad(
                loss_of, has_aux=True)(train_p, bufs, mb, lbls, k_)
            gsum = jax.tree.map(
                lambda a, b_: a + b_.astype(jnp.float32), gsum, g)
            return (loss_sum + l.astype(jnp.float32), gsum, nb), None

        # fp32 accumulators: merging k bf16 micro-grads in bf16 would
        # lose the low bits the merge exists to keep
        g0 = jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), train_p)
        (loss_sum, grads, new_buffers), _ = jax.lax.scan(
            scan_body, (jnp.float32(0.0), g0, buffers),
            (micro_b, micro_l, keys))
        loss = loss_sum / acc
        grads = jax.tree.map(lambda g: g / acc, grads)
        return loss, grads, new_buffers

    # -- bucketed compute–collective overlap -------------------------------
    def current_bucket_plan(self) -> List[Dict]:
        """The plan of the program the CURRENT flag/trainable-set
        combination selects (empty before its first trace or with
        bucketing disabled)."""
        target = int(flag_value("dist_grad_bucket_bytes") or 0)
        return self._bucket_plans.get(
            (target, tuple(self._tkeys())), [])

    def _sync_grads(self, grads, tkeys):
        from jax.sharding import NamedSharding
        from . import collective as coll

        target = int(flag_value("dist_grad_bucket_bytes") or 0)
        plan_key = (target, tuple(tkeys))
        if target <= 0 or not grads:
            self._bucket_plans[plan_key] = []
            return grads
        # REVERSE-backward order: _Swap.params preserves registration
        # (forward) order, so its reverse approximates grad-retirement
        # order — the last layers' grads are ready first
        order = [k for k in reversed(list(self._swap.params))
                 if k in grads]
        sizes = []
        for k in order:
            g = grads[k]
            sizes.append((k, int(np.prod(g.shape))
                          * np.dtype(g.dtype).itemsize))
        buckets = coll.bucket_assignment(sizes, target)
        shardings = {}
        for k in order:
            sh = getattr(self._swap.params[k]._data, "sharding", None)
            if isinstance(sh, NamedSharding):
                shardings[k] = sh
        synced, plan = coll.bucketed_grad_sync(grads, buckets, shardings)
        self._bucket_plans[plan_key] = plan
        return synced

    # -- per-step telemetry ------------------------------------------------
    def step(self, inputs, labels=(), scaler=None):
        from ..observability import flight as _flight
        if not _flight.enabled():
            return super().step(inputs, labels, scaler)
        from . import collective as coll
        t0 = _time.perf_counter()
        loss = super().step(inputs, labels, scaler)
        if loss is not None:
            coll.journal_grad_buckets(
                self.current_bucket_plan(),
                dur_us=(_time.perf_counter() - t0) * 1e6)
        return loss


class DistTrainStep:
    """Compiled train step over (possibly sharded) params.

    loss_fn(outputs, *labels) -> scalar Tensor. Batch arrays should be
    device_put with their data sharding (Shard(0) on the dp axis) before the
    call — or pass `data_sharding` to have the step do it.
    """

    def __init__(self, model, loss_fn: Callable, optimizer,
                 data_sharding=None, donate: bool = True,
                 accumulate_steps: int = 1):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.data_sharding = data_sharding
        self._step = _DistCapturedStep(
            model, loss_fn, optimizer, data_sharding=data_sharding,
            donate=donate, accumulate_steps=accumulate_steps)
        self._swap = self._step._swap
        self._params = self._swap.params

    @property
    def accumulate_steps(self) -> int:
        return self._step.accumulate_steps

    @property
    def stats(self):
        """CapturedStep counters: compiles / cache_hits /
        captured_steps — the shared capture telemetry plane."""
        return self._step.stats

    def bucket_plan(self) -> List[Dict]:
        """The gradient-bucket plan the current
        FLAGS_dist_grad_bucket_bytes/trainable-set combination selects
        (empty before its first trace or with bucketing disabled):
        [{"bucket", "grads", "bytes", "keys"}] in reverse-backward
        issue order."""
        return list(self._step.current_bucket_plan())

    @staticmethod
    def _split(batch_and_labels, num_labels: int):
        if len(batch_and_labels) <= num_labels:
            raise ValueError(
                f"need at least {num_labels + 1} arrays (inputs + "
                f"{num_labels} labels), got {len(batch_and_labels)}")
        n = len(batch_and_labels) - num_labels
        ins = list(batch_and_labels[:n])
        lbls = list(batch_and_labels[n:]) if num_labels else []
        return ins, lbls

    def __call__(self, *batch_and_labels, num_labels: int = 1):
        ins, lbls = self._split(batch_and_labels, num_labels)
        return self._step.step(ins, lbls)

    # -- checkpoint ---------------------------------------------------------
    def _tstates(self):
        """{param_name: slot dict} for every trainable param, creating
        (co-sharded) slots on demand — slot storage is the SHARED
        ``optimizer._states`` plane, so ``optimizer.state_dict()``
        round-trips cover captured distributed training too."""
        out = {}
        for k, p in self._params.items():
            if p.stop_gradient:
                continue
            out[k] = self._step._opt_state_for(p)
        return out

    def state_dict(self) -> Dict[str, Tensor]:
        """Optimizer-state slots as named Tensors for
        dist.save_state_dict (ref: the sharded-optimizer ckpt merge
        utilities in fleet; slot naming param.slot). Leaves are
        snapshot-copied: the live slot buffers are DONATED by the next
        captured step."""
        out = {}
        for k, slots in self._tstates().items():
            for name, v in slots.items():
                out[f"{k}#{name}"] = Tensor(jnp.copy(v))
        return out

    def set_state_dict(self, sd: Dict) -> None:
        states = self._tstates()
        unmatched = []
        covered = set()
        for key, t in sd.items():
            if "#" not in key:
                unmatched.append(key)
                continue
            pname, slot = key.rsplit("#", 1)
            if pname not in states:
                unmatched.append(key)
                continue
            covered.add((pname, slot))
            arr = t._data if isinstance(t, Tensor) else jnp.asarray(t)
            param_arr = self._params[pname]._data
            sharding = getattr(param_arr, "sharding", None)
            from jax.sharding import NamedSharding, PartitionSpec
            if isinstance(sharding, NamedSharding):
                if arr.shape != param_arr.shape:
                    # scalar slots (beta pows) replicate over the mesh
                    sharding = NamedSharding(sharding.mesh, PartitionSpec())
                if getattr(arr, "sharding", None) == sharding:
                    pass  # already placed (the dist-checkpoint load
                    # path fills slots with the param's own sharding);
                    # re-putting a multi-controller global array would
                    # be an unsupported cross-host transfer
                elif (isinstance(arr, jax.Array)
                      and not arr.is_fully_addressable):
                    raise ValueError(
                        f"optimizer slot {key!r} arrives as a "
                        f"multi-process array with sharding "
                        f"{arr.sharding} but the parameter needs "
                        f"{sharding}; reshard it via dist checkpoint "
                        f"load (host-side assembly) instead")
                else:
                    # a COMMITTED device array can't be device_put
                    # across processes (pinned src placement); hop
                    # through host — every process holds the full
                    # value, so the put only writes local shards
                    if isinstance(arr, jax.Array):
                        arr = np.asarray(arr)
                    arr = jax.device_put(arr, sharding)
            states[pname][slot] = arr
        missing = [f"{p}#{s}" for p, slots in states.items()
                   for s in slots if (p, s) not in covered]
        if unmatched or missing:
            raise ValueError(
                "optimizer checkpoint does not match the current model "
                "(resuming would silently reset state): "
                f"unmatched keys {unmatched[:5]}, "
                f"missing slots {missing[:5]}")

    # -- no-run diagnostics --------------------------------------------------
    def _abstract_opt_state(self):
        """Shape-only optimizer state (no device allocation): each
        slot's shapes/dtypes via eval_shape over the optimizer's own
        init fn — the trace-only probes must not materialize a second
        copy of the AdamW moments in exactly the memory-constrained
        configurations they diagnose. Ordered by the captured
        program's tkeys."""
        out = []
        for k in self._step._tkeys():
            p = self._params[k]
            out.append(jax.eval_shape(
                lambda d, _p=p: self.optimizer._init_state(
                    Tensor(d, stop_gradient=_p.stop_gradient)), p._data))
        return out

    def _probe_args(self, *batch_and_labels, num_labels: int = 1,
                    abstract: bool = False):
        """Shared arg prep for the no-run diagnostics (compile_stats /
        trace_jaxpr): current params/buffers/opt-state plus a FIXED
        probe rng key — a diagnostic must not advance the global RNG
        stream (seed-fixed training after a stats query stays
        identical). ``abstract=True`` substitutes ShapeDtypeStructs
        everywhere (trace-only callers: zero device allocation; note
        shardings are NOT carried, so compile-fidelity callers must use
        the concrete form)."""
        step = self._step

        def sds(a):
            return jax.ShapeDtypeStruct(a.shape, a.dtype)

        if abstract:
            # shape metadata only — np.asarray on host data reads shape/
            # dtype without any device transfer, honoring the
            # zero-device-allocation contract
            raw = [b._data if isinstance(b, Tensor) else b
                   for b in batch_and_labels]
            raw = [sds(r) if isinstance(r, jax.Array)
                   else sds(np.asarray(r)) for r in raw]
            params = {k: sds(t._data) for k, t in self._params.items()}
            buffers = {k: sds(t._data)
                       for k, t in self._swap.buffers.items()}
            states = self._abstract_opt_state()
            probe_rng = (jax.eval_shape(lambda: jax.random.key(0)),
                         jax.ShapeDtypeStruct((), jnp.uint32))
            lr = jax.ShapeDtypeStruct((), jnp.float32)
            return (params, buffers, states, lr, probe_rng, tuple(raw))
        ins, lbls = self._split(batch_and_labels, num_labels)
        raw = step._arrays(ins + lbls)
        params = {k: t._data for k, t in self._params.items()}
        buffers = {k: t._data for k, t in self._swap.buffers.items()}
        states = [dict(step._opt_state_for(self._params[k]))
                  for k in step._tkeys()]
        probe_rng = (jax.random.key(0), jnp.uint32(0))
        return (params, buffers, states, jnp.float32(0.0), probe_rng,
                tuple(raw))

    def compile_stats(self, *batch_and_labels, num_labels: int = 1,
                      return_compiled: bool = False):
        """Compile the step for these batch shapes WITHOUT running it and
        return XLA's memory analysis (argument/output/temp bytes). The
        auto-tuner's memory model prunes configs on this before paying
        for a trial run (ref: auto_tuner/prune.py's OOM-signature
        pruning, done here ahead of time from the compiled program).
        With return_compiled=True also returns the AOT executable so the
        caller can time steps without a second compile — call it as
        ``compiled(params, buffers, states, lr, rng, *arrays)``."""
        n_ins = len(batch_and_labels) - num_labels
        jitted = self._step._build("train", n_ins)
        args = self._probe_args(*batch_and_labels, num_labels=num_labels)
        params, buffers, states, lr, rng, raw = args
        compiled = jitted.lower(params, buffers, states, lr, rng,
                                *raw).compile()
        mem = compiled.memory_analysis()
        if return_compiled:
            return mem, compiled, (params, buffers, states, raw)
        return mem

    def trace_jaxpr(self, *batch_and_labels, num_labels: int = 1,
                    abstract: bool = False):
        """Trace (no compile) the step and return its ClosedJaxpr — the
        input to the static peak-memory estimator
        (auto_parallel.mem_estimator.estimate_peak_bytes).
        ``abstract=True`` traces from ShapeDtypeStructs: no device
        allocation at all (probe-safe in memory-tight configs)."""
        n_ins = len(batch_and_labels) - num_labels
        jitted = self._step._build("train", n_ins)
        args = self._probe_args(*batch_and_labels, num_labels=num_labels,
                                abstract=abstract)
        params, buffers, states, lr, rng, raw = args
        return jitted.trace(params, buffers, states, lr, rng,
                            *raw).jaxpr

    def _resync(self, params, buffers, states) -> None:
        """Rebind model/optimizer state after a caller drove the AOT
        executable directly (the auto-tuner trial loop): donation
        consumed the original buffers, so the threaded-through values
        become the live ones."""
        for k, t in self._params.items():
            t._data = params[k]
        for k, t in self._swap.buffers.items():
            t._data = buffers[k]
        opt = self.optimizer
        for k, ns in zip(self._step._tkeys(), states):
            opt._states[id(self._params[k])] = ns
