"""Generation serving: fixed-slot continuous batching over a compiled
single-token decode step.

The reference's inference engine is a production deliverable whose LLM
path runs fused multi-transformer decode kernels behind the predictor
(ref: paddle/fluid/inference/api/analysis_predictor.h +
phi/kernels/fusion/gpu/fused_multi_transformer_op.cu). The TPU-native
equivalent keeps everything STATIC-SHAPED so XLA compiles exactly two
program families:

- ``prefill[bucket]``: whole-prompt forward (prompt padded to a pow-2
  bucket) writing K/V into one slot's region of the fixed cache;
- ``decode``: ONE step advancing ALL slots together — q of shape
  [slots, 1] against [slots, max_seq] caches with per-slot position
  masks. Iteration-level (continuous) batching falls out: requests
  join/leave at step boundaries, the compiled program never changes.

KV caches live as per-layer arrays [slots, max_seq, KVH, D] (a
stacked [L, ...] form measured ~11 ms/step of slice/stack copies),
donated through the decode step so the update is in-place in HBM.
``int8=True`` runs every projection as a REAL s8 x s8 -> s32 MXU matmul
(dynamic per-tensor activation quant, per-channel weight scales — the
same math as quantization.Int8Linear) with bf16 caches/activations.

Decode is memory-bound (every step streams the full weight set), so the
bench grades tokens/s against the weight-streaming roofline:
slots / (weight_bytes / HBM_BW).
"""
from __future__ import annotations

import itertools
import queue as _queue
import threading
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .observability import flight as _flight
from .observability import metrics as _om
from .utils import fault_injection as _fi

__all__ = ["LlamaDecodeEngine", "GenerationServer"]

# process registry instruments (one set across all servers; the
# per-instance stats() dict stays the legacy view)
_M = _om.scope("serving")
_M_admitted = _M.counter("admitted_total", "Requests admitted into slots")
_M_rejected = _M.counter("rejected_total",
                         "Submissions rejected (server shutting down)")
_M_expired = _M.counter("deadline_expired_total",
                        "Requests failed by their deadline")
_M_failed = _M.counter("failed_total",
                       "Requests completed with an error")
_M_steps = _M.counter("steps_total", "Decode steps run by server loops")
_M_tokens = _M.counter("tokens_total", "Tokens delivered to requests")
_M_req_s = _M.histogram("request_seconds",
                        "Submit-to-completion wall time per request")
_M_token_s = _M.histogram(
    "token_seconds",
    "Per-token latency: request wall time / tokens produced")
_G_queue = _M.gauge("queue_depth",
                    "Requests waiting in the submission queue")
_G_inflight = _M.gauge("in_flight", "Requests currently holding a slot")
# queue-vs-decode latency split (the admission/load-shedding evidence:
# queue_seconds growing while decode_seconds holds means shed load)
_M_queue_s = _M.histogram(
    "queue_seconds", "Submit-to-admission wall time per request")
_M_decode_s = _M.histogram(
    "decode_seconds",
    "Admission-to-completion wall time per request (prefill + decode)")

# process-unique request trace ids: every lifecycle event of a request
# carries one, so a flight dump (or GenerationServer.trace) replays a
# single request's submit -> queued -> admitted -> decode -> terminal
# trail even across servers
_REQ_SEQ = itertools.count(1)


def _quantize_w(w_t):
    """Per-output-channel symmetric int8 of a TRANSPOSED [out, in]
    weight (ref: quantize.py PTQ convert)."""
    w_t = np.asarray(w_t, np.float32)
    step = np.maximum(np.abs(w_t).max(axis=1), 1e-8) / 127.0
    q = np.clip(np.round(w_t / step[:, None]), -127, 127).astype(np.int8)
    return jnp.asarray(q), jnp.asarray(step.astype(np.float32))


class LlamaDecodeEngine:
    """Compiled decode engine for a LlamaForCausalLM.

    Host-side state per slot: position, remaining budget, output ids.
    Device-side: params (frozen), K/V caches (donated each step).
    """

    def __init__(self, model, max_slots: int = 4, max_seq: int = 256,
                 int8: bool = False, eos_id: Optional[int] = None):
        cfg = model.config
        self.cfg = cfg
        self.max_slots = int(max_slots)
        self.max_seq = int(max_seq)
        self.eos_id = eos_id
        self.int8 = bool(int8)
        self.head_dim = cfg.hidden_size // cfg.num_attention_heads
        self.n_rep = cfg.num_attention_heads // cfg.num_key_value_heads

        sd = {k: v._data for k, v in model.named_parameters()}
        dt = jnp.bfloat16 if str(cfg.dtype) == "bfloat16" else jnp.float32
        self.dtype = dt

        def get(name):
            return jnp.asarray(sd[name], dt)

        p: Dict[str, object] = {"emb": get("llama.embed_tokens.weight"),
                                "norm": get("llama.norm.weight")}
        # projections stored transposed ([out, in]) — see _mm
        if cfg.tie_word_embeddings:
            p["head"] = p["emb"]          # [V, H] is already the
        else:                             # transposed head
            p["head"] = get("lm_head.weight").T
        layers = []
        for i in range(cfg.num_hidden_layers):
            pre = f"llama.layers.{i}."
            lp = {"in_ln": get(pre + "input_layernorm.weight"),
                  "post_ln": get(pre + "post_attention_layernorm.weight")}
            for nm in ("q_proj", "k_proj", "v_proj", "o_proj"):
                lp[nm] = get(pre + "self_attn." + nm + ".weight").T
            for nm in ("gate_proj", "up_proj", "down_proj"):
                lp[nm] = get(pre + "mlp." + nm + ".weight").T
            if int8:
                for nm in ("q_proj", "k_proj", "v_proj", "o_proj",
                           "gate_proj", "up_proj", "down_proj"):
                    lp[nm] = _quantize_w(lp[nm])
            layers.append(lp)
        p["layers"] = layers
        if int8:
            p["head"] = _quantize_w(p["head"])
        self.params = p

        S, L = self.max_slots, cfg.num_hidden_layers
        kvh = cfg.num_key_value_heads
        # per-LAYER cache arrays (not one stacked [L, ...] array): the
        # stacked form costs a slice per layer + a stack per step that
        # XLA materializes as whole-cache copies (~11 ms/step measured
        # at 6 layers x 8 slots x 1024); per-layer donated leaves
        # update in place
        self.k_cache = [jnp.zeros((S, self.max_seq, kvh, self.head_dim),
                                  dt) for _ in range(L)]
        self.v_cache = [jnp.zeros_like(self.k_cache[0])
                        for _ in range(L)]

        # host slot state
        self.pos = np.zeros(S, np.int32)          # next cache index
        self.active = np.zeros(S, bool)
        self.last_ids = np.zeros((S, 1), np.int32)

        # caches are donated: each decode step updates them in place in
        # HBM instead of allocating a second [L,S,max_seq,...] copy.
        # The jitted step is registered as a CAPTURED step program
        # (jit.sot.capture_jit): its clean capture plan is checked in
        # (tests/test_capture_plan.py), so every call counts into
        # sot.captured_steps_total and the first compile lands in the
        # flight journal — identical execution to a bare jax.jit
        from .jit.sot import capture_jit as _capture_jit
        self._capture_jit = _capture_jit
        self._decode = _capture_jit(self._decode_impl,
                                    donate_argnums=(1, 2),
                                    name="serving.decode")
        self._decode_collect = None
        self._prefills: Dict[int, object] = {}

    # -- math ---------------------------------------------------------------
    # Weights are stored TRANSPOSED ([out, in]) and contracted against
    # their LAST dim: with the natural [in, out] orientation XLA's
    # chosen executable layout disagreed with the call-input layout and
    # re-transposed ~1 GB of weights EVERY step (~3.6 ms/step measured)
    # — a per-call copy no warm-up can amortize because jit inputs
    # cannot be layout-pinned across calls.
    def _mm(self, h, w):
        """h @ w (w stored transposed); int8 path = dynamic per-tensor
        act quant + s8*s8->s32 with per-channel scale epilogue
        (quantize._int8_linear_impl math, calibration-free because
        decode activations are visible)."""
        if isinstance(w, tuple):
            w_q, w_step = w
            step = jnp.maximum(jnp.max(jnp.abs(h.astype(jnp.float32))),
                               1e-8) / 127.0
            qh = jnp.clip(jnp.round(h.astype(jnp.float32) / step),
                          -127, 127).astype(jnp.int8)
            acc = jax.lax.dot_general(
                qh, w_q, (((qh.ndim - 1,), (1,)), ((), ())),
                preferred_element_type=jnp.int32)
            return (acc.astype(jnp.float32) * (w_step * step)).astype(
                h.dtype)
        return jax.lax.dot_general(
            h, w, (((h.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32).astype(h.dtype)

    def _rms(self, h, w):
        h32 = h.astype(jnp.float32)
        var = jnp.mean(jnp.square(h32), axis=-1, keepdims=True)
        return (h32 * jax.lax.rsqrt(var + self.cfg.rms_norm_eps)).astype(
            h.dtype) * w

    def _rope(self, x, positions):
        """x [S, T, Hd, D] rotated at per-slot absolute positions
        (positions [S, T])."""
        d2 = self.head_dim // 2
        inv = 1.0 / (self.cfg.rope_theta ** (
            jnp.arange(0, d2, dtype=jnp.float32) / d2))
        freqs = positions.astype(jnp.float32)[..., None] * inv  # [S,T,d2]
        cos = jnp.cos(freqs)[:, :, None, :]
        sin = jnp.sin(freqs)[:, :, None, :]
        x1, x2 = x[..., :d2], x[..., d2:]
        return jnp.concatenate(
            [x1 * cos - x2 * sin, x2 * cos + x1 * sin],
            axis=-1).astype(x.dtype)

    def _attend(self, q, k_all, v_all, col_mask):
        """q [S,T,H,D] vs caches [S,max_seq,KVH,D]; col_mask
        [S,T,max_seq] True where attendable. Dots run in the cache
        dtype with f32 accumulation (preferred_element_type) so the
        bf16 cache is never materialized as f32 — that conversion cost
        a full extra cache pass per step."""
        if self.n_rep > 1:
            # grouped contraction against the UNEXPANDED caches: a
            # jnp.repeat would stream n_rep x the cache bytes per step,
            # defeating exactly the KV saving GQA exists for
            S, T, H, D = q.shape
            q5 = q.reshape(S, T, -1, self.n_rep, D)
            scores = jnp.einsum("stkrd,smkd->skrtm", q5, k_all,
                                preferred_element_type=jnp.float32)
            scores = scores / np.sqrt(self.head_dim)
            scores = jnp.where(col_mask[:, None, None, :, :], scores,
                               -1e30)
            w = jax.nn.softmax(scores, axis=-1)
            out = jnp.einsum("skrtm,smkd->stkrd", w.astype(v_all.dtype),
                             v_all, preferred_element_type=jnp.float32)
            return out.reshape(S, T, H, D).astype(q.dtype)
        scores = jnp.einsum("sthd,smhd->shtm", q, k_all,
                            preferred_element_type=jnp.float32)
        scores = scores / np.sqrt(self.head_dim)
        scores = jnp.where(col_mask[:, None, :, :], scores, -1e30)
        w = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("shtm,smhd->sthd", w.astype(v_all.dtype),
                         v_all, preferred_element_type=jnp.float32)
        return out.astype(q.dtype)

    def _block(self, lp, h, kc_l, vc_l, positions, col_mask, write_cols):
        """One decoder layer over [S, T, H] with fixed-cache K/V
        writes at write_cols [S, T]."""
        S, T, H = h.shape
        kvh = self.cfg.num_key_value_heads
        res = h
        x = self._rms(h, lp["in_ln"])
        q = self._mm(x, lp["q_proj"]).reshape(
            S, T, self.cfg.num_attention_heads, self.head_dim)
        k = self._mm(x, lp["k_proj"]).reshape(S, T, kvh, self.head_dim)
        v = self._mm(x, lp["v_proj"]).reshape(S, T, kvh, self.head_dim)
        q = self._rope(q, positions)
        k = self._rope(k, positions)
        sl = jnp.arange(S)[:, None].repeat(T, 1)      # [S, T] slot ids
        kc_l = kc_l.at[sl, write_cols].set(k)
        vc_l = vc_l.at[sl, write_cols].set(v)
        att = self._attend(q, kc_l, vc_l, col_mask)
        h = res + self._mm(att.reshape(S, T, H), lp["o_proj"])
        res = h
        x = self._rms(h, lp["post_ln"])
        ff = self._mm(jax.nn.silu(
            self._mm(x, lp["gate_proj"]).astype(jnp.float32)).astype(
                x.dtype) * self._mm(x, lp["up_proj"]),
            lp["down_proj"])
        return res + ff, kc_l, vc_l

    def _forward(self, params, k_cache, v_cache, ids, positions,
                 col_mask):
        """Shared prefill/decode body: ids [S, T] -> logits [S, T, V];
        caches are per-layer lists (donated leaves, in-place)."""
        h = jnp.take(params["emb"], ids, axis=0).astype(self.dtype)
        new_k, new_v = [], []
        for li, lp in enumerate(params["layers"]):
            h, kc_l, vc_l = self._block(
                lp, h, k_cache[li], v_cache[li], positions, col_mask,
                positions)
            new_k.append(kc_l)
            new_v.append(vc_l)
        h = self._rms(h, params["norm"])
        logits = self._mm(h, params["head"])
        # barrier: without it XLA fuses the [H, V] head matmul into the
        # consumer argmax as a VPU reduce-loop fusion (measured 2.8 ms
        # vs ~0.3 ms for the same contraction on the MXU)
        logits = jax.lax.optimization_barrier(logits)
        return (logits, new_k, new_v)

    def _decode_impl(self, params, k_cache, v_cache, last_ids, pos):
        """One token for every slot: ids [S,1], pos [S] = cache index
        to write (== tokens so far)."""
        positions = pos[:, None]                        # [S, 1]
        cols = jnp.arange(self.max_seq)[None, None, :]  # [1,1,max_seq]
        col_mask = cols <= positions[:, :, None]
        logits, k_cache, v_cache = self._forward(
            params, k_cache, v_cache, last_ids, positions, col_mask)
        nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
        return nxt, k_cache, v_cache

    def _prefill_impl(self, params, k_cache, v_cache, ids, slot,
                      true_len):
        """Prompt forward for ONE slot: ids [1, B] (bucket-padded),
        writes cache rows [0, B), returns argmax at the last real
        token. Runs the whole-cache forward with the other slots
        masked off (their K/V rows are untouched: write_cols for
        inactive slots point at their own rows but values are zero —
        instead we narrow to the one slot by slicing)."""
        B = ids.shape[1]
        positions = jnp.arange(B)[None, :]              # [1, B]
        cols = jnp.arange(self.max_seq)[None, None, :]
        causal = cols <= positions[:, :, None]
        valid = cols < jnp.minimum(true_len, B)
        col_mask = jnp.logical_and(causal, valid)
        kc = [jax.lax.dynamic_slice_in_dim(c, slot, 1, axis=0)
              for c in k_cache]
        vc = [jax.lax.dynamic_slice_in_dim(c, slot, 1, axis=0)
              for c in v_cache]
        logits, kc, vc = self._forward(params, kc, vc, ids, positions,
                                       col_mask)
        k_cache = [jax.lax.dynamic_update_slice_in_dim(c, u, slot, axis=0)
                   for c, u in zip(k_cache, kc)]
        v_cache = [jax.lax.dynamic_update_slice_in_dim(c, u, slot, axis=0)
                   for c, u in zip(v_cache, vc)]
        first = jnp.argmax(logits[0, true_len - 1, :]).astype(jnp.int32)
        return first, k_cache, v_cache

    # -- host orchestration -------------------------------------------------
    def _bucket(self, n: int) -> int:
        b = 8
        while b < n:
            b *= 2
        return min(b, self.max_seq)

    def prefill(self, slot: int, prompt_ids: np.ndarray) -> int:
        """Load a prompt into ``slot``; returns the first generated
        token (greedy)."""
        prompt_ids = np.asarray(prompt_ids, np.int32).reshape(-1)
        n = int(prompt_ids.shape[0])
        if not 0 < n <= self.max_seq - 1:
            raise ValueError(
                f"prompt length {n} not in [1, {self.max_seq - 1}]")
        b = self._bucket(n)
        if b not in self._prefills:
            self._prefills[b] = jax.jit(self._prefill_impl,
                                        donate_argnums=(1, 2))
        padded = np.zeros((1, b), np.int32)
        padded[0, :n] = prompt_ids
        first, self.k_cache, self.v_cache = self._prefills[b](
            self.params, self.k_cache, self.v_cache, jnp.asarray(padded),
            jnp.int32(slot), jnp.int32(n))
        first = int(first)
        self.pos[slot] = n
        self.active[slot] = True
        self.last_ids[slot, 0] = first
        return first

    def step(self) -> np.ndarray:
        """One decode iteration for ALL slots; returns next token per
        slot (garbage for inactive slots — callers consult .active)."""
        nxt, self.k_cache, self.v_cache = self._decode(
            self.params, self.k_cache, self.v_cache,
            jnp.asarray(self.last_ids), jnp.asarray(self.pos))
        nxt = np.asarray(nxt)
        for s in range(self.max_slots):
            if self.active[s]:
                self.pos[s] += 1
                self.last_ids[s, 0] = nxt[s]
        return nxt

    def _decode_collect_impl(self, params, k_cache, v_cache, last_ids,
                             pos, buf, i):
        """Decode step + on-device token collection (buf [S, n] donated;
        column i written in-place)."""
        nxt, k_cache, v_cache = self._decode_impl(
            params, k_cache, v_cache, last_ids, pos)
        buf = jax.lax.dynamic_update_slice(buf, nxt[:, None],
                                           (jnp.int32(0), i))
        return nxt, k_cache, v_cache, buf

    def decode_steps(self, n: int) -> np.ndarray:
        """``n`` chained decode iterations with DEVICE-resident token
        feedback — dispatches pipeline asynchronously and ONE host
        fetch closes the window. Every slot must be active; returns
        [S, n] generated tokens.

        Measured alternatives at 8 slots x 1024 ctx on v5e, all SLOWER
        than this per-step form (989 tok/s): lax.scan-fused loop 319
        (cache carries copy inside the while body), 8x unrolled chunks
        672 (intermediate cache generations copy), AOT layout-AUTO
        executables 331 (per-call relayout + AOT dispatch overhead),
        [S,KVH,M,D] / flattened-3D cache layouts 957 / 638. The
        residual above the weights+cache roofline is two boundary
        layout conversions of the caches per step that XLA emits
        regardless of shape arrangement."""
        if not self.active.all():
            raise ValueError(
                "decode_steps advances EVERY slot; use step() when some "
                "slots are free (the continuous-batching server path)")
        if int(self.pos.max()) + n > self.max_seq - 1:
            raise ValueError(
                f"decode_steps({n}) would write past the {self.max_seq}"
                f"-token cache (max pos {int(self.pos.max())}); out-of-"
                f"bounds K/V writes are silently dropped by XLA and the "
                f"position mask would then attend unwritten rows")
        if self._decode_collect is None:
            self._decode_collect = self._capture_jit(
                self._decode_collect_impl, donate_argnums=(1, 2, 5),
                name="serving.decode_window")
        ids = jnp.asarray(self.last_ids)
        pos = jnp.asarray(self.pos)
        # tokens accumulate in ONE donated device buffer: holding a
        # per-step list of output arrays measured 2x slower (every live
        # buffer adds tunnel-handle bookkeeping to later dispatches)
        buf = jnp.zeros((self.max_slots, n), jnp.int32)
        for i in range(n):
            nxt, self.k_cache, self.v_cache, buf = self._decode_collect(
                self.params, self.k_cache, self.v_cache, ids, pos, buf,
                jnp.int32(i))
            ids = nxt[:, None]
            pos = pos + 1
        toks = np.asarray(buf)                      # the one fetch
        self.pos += n
        self.last_ids = toks[:, -1:].astype(np.int32).copy()
        return toks

    def release(self, slot: int) -> None:
        self.active[slot] = False
        self.pos[slot] = 0

    def generate(self, prompt_ids, max_new_tokens: int = 32,
                 slot: int = 0) -> List[int]:
        """Single-request convenience path (tests / warm-up)."""
        out = [self.prefill(slot, prompt_ids)]
        for _ in range(max_new_tokens - 1):
            if self.eos_id is not None and out[-1] == self.eos_id:
                break
            if self.pos[slot] >= self.max_seq - 1:
                break
            out.append(int(self.step()[slot]))
        self.release(slot)
        return out

    def export_decode(self):
        """AOT-serialize the decode step via jax.export — the StableHLO
        artifact a serving process can run without this class (ref: the
        reference predictor's save/load of an analyzed program)."""
        avals = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
            (self.params, self.k_cache, self.v_cache,
             jnp.asarray(self.last_ids), jnp.asarray(self.pos)))
        exported = jax.export.export(jax.jit(self._decode_impl))(*avals)
        return exported.serialize()


class GenerationServer:
    """Iteration-level continuous batching around a LlamaDecodeEngine:
    requests are admitted into free slots at step boundaries, every
    step advances all active requests together, finished requests free
    their slot for the next admission — no request waits for another
    to finish (ref role: the multi-stream request loop of the
    reference's serving predictor).

    Robustness contract: ``submit(..., deadline=s)`` bounds a request's
    wall time — expiry (checked at step boundaries, queued or active)
    fails THAT request with TimeoutError, keeping whatever tokens it
    already produced in ``req["out"]``. ``shutdown()`` drains: new
    submissions are rejected immediately, in-flight and already-queued
    requests run to completion, then the loop exits — no completed
    token is ever dropped by a shutdown."""

    _STOP = object()  # queue sentinel: wake the loop for shutdown

    def __init__(self, engine: LlamaDecodeEngine):
        self.engine = engine
        self._q: "_queue.Queue" = _queue.Queue()
        self._slots: Dict[int, dict] = {}
        self.steps_run = 0
        self.admitted = 0
        self.rejected = 0           # submissions after shutdown
        self.deadline_expired = 0   # requests failed by their deadline
        self._stopping = threading.Event()
        self._drained = threading.Event()
        # orders submit's stopping-check+enqueue against shutdown's
        # stopping.set(): a request that passed the check is enqueued
        # BEFORE stopping becomes visible, so the drain loop (which
        # only exits on stopping AND empty queue) cannot strand it
        from .analysis.locks import make_lock
        self._submit_lock = make_lock("serving.submit")
        self._metrics_server = None
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def metrics_endpoint(self, port: int = 0, host: str = "127.0.0.1"):
        """Serve the process metrics registry over HTTP: ``GET /metrics``
        (Prometheus text exposition) + ``/metrics.json`` (the nested
        snapshot). Idempotent per server; the endpoint is closed by
        ``shutdown()``. Returns the handle (``.url``, ``.port``,
        ``.close()``)."""
        if self._metrics_server is None:
            from .observability.http import start_metrics_server
            self._metrics_server = start_metrics_server(port=port,
                                                        host=host)
        return self._metrics_server

    def submit(self, prompt_ids, max_new_tokens: int = 32,
               deadline: Optional[float] = None) -> dict:
        """Enqueue a request. ``deadline`` (seconds from now) bounds its
        total wall time; None = unbounded. The returned dict carries
        ``trace_id`` — the key of this request's flight-recorder
        lifecycle trail (see :meth:`trace`)."""
        trace_id = f"req-{next(_REQ_SEQ)}"
        _flight.record("serving", "submit", trace_id=trace_id,
                       max_new=int(max_new_tokens))
        if self._stopping.is_set():
            self.rejected += 1
            _M_rejected.inc()
            _flight.record("serving", "rejected", trace_id=trace_id,
                           reason="shutting_down")
            raise RuntimeError(
                "GenerationServer is shutting down; new submissions are "
                "rejected (in-flight requests are draining)")
        if int(max_new_tokens) < 1:
            _flight.record("serving", "rejected", trace_id=trace_id,
                           reason="invalid_max_new")
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens} "
                f"(prefill always produces the first token)")
        if deadline is not None and deadline <= 0:
            _flight.record("serving", "rejected", trace_id=trace_id,
                           reason="invalid_deadline")
            raise ValueError(f"deadline must be > 0, got {deadline}")
        req = {"prompt": np.asarray(prompt_ids, np.int32).reshape(-1),
               "max_new": int(max_new_tokens), "out": [],
               "done": threading.Event(), "error": None,
               "trace_id": trace_id,
               "t0": time.monotonic(),
               "expires": (time.monotonic() + deadline
                           if deadline is not None else None)}
        with self._submit_lock:
            if self._stopping.is_set():
                self.rejected += 1
                _M_rejected.inc()
                _flight.record("serving", "rejected", trace_id=trace_id,
                               reason="shutting_down")
                raise RuntimeError(
                    "GenerationServer is shutting down; new submissions "
                    "are rejected (in-flight requests are draining)")
            self._q.put(req)
        _flight.record("serving", "queued", trace_id=trace_id,
                       prompt_len=int(req["prompt"].shape[0]))
        return req

    def generate(self, prompt_ids, max_new_tokens: int = 32,
                 timeout: float = 300.0,
                 deadline: Optional[float] = None) -> List[int]:
        req = self.submit(prompt_ids, max_new_tokens, deadline=deadline)
        if not req["done"].wait(timeout):
            raise TimeoutError("generation timed out")
        if req["error"] is not None:
            raise req["error"]
        return list(req["out"])

    def _expired(self, req) -> bool:
        return (req["expires"] is not None
                and time.monotonic() > req["expires"])

    def _fail(self, req, error) -> None:
        req["error"] = error
        req["done"].set()
        _M_failed.inc()
        _flight.record(
            "serving",
            "expired" if isinstance(error, TimeoutError) else "failed",
            trace_id=req.get("trace_id"), error=type(error).__name__,
            tokens=len(req["out"]))
        self._observe_done(req)

    @staticmethod
    def _observe_done(req) -> None:
        """Request-completion telemetry: tokens delivered (partial counts
        too — a deadline-failed request keeps its tokens) + wall time +
        per-token latency, plus the queue/decode latency split."""
        tokens = len(req["out"])
        if tokens:
            _M_tokens.inc(tokens)
        now = time.monotonic()
        dt = now - req["t0"]
        _M_req_s.observe(dt)
        _M_token_s.observe(dt / max(tokens, 1))
        t_admit = req.get("t_admit")
        if t_admit is not None:
            _M_decode_s.observe(now - t_admit)
        else:
            # never admitted (deadline expired / cancelled while
            # queued): its whole life WAS queue time. Without this the
            # histogram only sees survivors — under the very overload
            # the metric exists to expose, the starved majority would
            # be censored and queue_seconds would stay low
            _M_queue_s.observe(dt)

    def _admit_one(self, req, slot) -> None:
        eng = self.engine
        if req is self._STOP or req["done"].is_set():
            return  # sentinel, or already failed while queued
        if self._expired(req):
            self.deadline_expired += 1
            _M_expired.inc()
            self._fail(req, TimeoutError(
                "request deadline expired while queued"))
            return
        # stamp admission BEFORE prefill: queue_seconds is the pure
        # submit->admission wait and decode_seconds covers prefill +
        # decode (slow prefill must not masquerade as queueing — the
        # load-shedding signal would point at admission when the real
        # cost is the model)
        req["t_admit"] = time.monotonic()
        _M_queue_s.observe(req["t_admit"] - req["t0"])
        try:
            first = eng.prefill(slot, req["prompt"])
        except Exception as e:  # noqa: BLE001 — surfaced per request
            self._fail(req, e)
            return
        req["out"].append(first)
        self._slots[slot] = req
        self.admitted += 1
        _M_admitted.inc()
        _flight.record("serving", "admitted",
                       trace_id=req.get("trace_id"), slot=slot)
        self._finish_if_done(slot, req)

    def _free_slots(self):
        eng = self.engine
        return [s for s in range(eng.max_slots) if not eng.active[s]]

    def _admit(self):
        free = self._free_slots()
        while free:
            try:
                req = self._q.get_nowait()
            except _queue.Empty:
                return
            if req is self._STOP or req["done"].is_set():
                continue  # sentinel, or failed while queued (deadline)
            self._admit_one(req, free[0])
            if req["done"].is_set() and req["error"] is not None:
                continue  # rejected before prefill: the slot is still free
            free.pop(0)

    def _finish_if_done(self, slot, req):
        eng = self.engine
        done = (len(req["out"]) >= req["max_new"]
                or (eng.eos_id is not None
                    and req["out"][-1] == eng.eos_id)
                or eng.pos[slot] >= eng.max_seq - 1)
        if done:
            eng.release(slot)
            del self._slots[slot]
            req["done"].set()
            _flight.record("serving", "finished",
                           trace_id=req.get("trace_id"),
                           tokens=len(req["out"]))
            self._observe_done(req)
        return done

    def _expire_active(self):
        """Step-boundary deadline sweep: an expired active request is
        failed with TimeoutError and its slot freed; the tokens it
        already produced stay in ``req['out']``."""
        for slot in list(self._slots):
            req = self._slots[slot]
            if self._expired(req):
                self.deadline_expired += 1
                _M_expired.inc()
                self.engine.release(slot)
                del self._slots[slot]
                self._fail(req, TimeoutError(
                    f"request deadline expired after "
                    f"{len(req['out'])} token(s)"))

    def _expire_queued(self):
        """Fail expired requests still WAITING in the queue — even when
        every slot is busy, a starved request's caller is unblocked at
        the next step boundary, not when a slot eventually frees. The
        failed entry stays enqueued; _admit() discards it on dequeue."""
        with self._q.mutex:
            waiting = list(self._q.queue)
        for req in waiting:
            if req is not self._STOP and not req["done"].is_set() \
                    and self._expired(req):
                self.deadline_expired += 1
                _M_expired.inc()
                self._fail(req, TimeoutError(
                    "request deadline expired while queued"))

    def _loop(self):
        while True:
            try:
                self._admit()
                if not self._slots:
                    if self._stopping.is_set() and self._q.empty():
                        break  # drained: nothing active, nothing queued
                    # idle: block for the next request and admit it
                    # DIRECTLY — a get-then-requeue would let requests
                    # submitted in the window jump ahead of it (FIFO)
                    self._set_gauges()  # idle: a scrape must read 0
                    req = self._q.get()
                    if req is self._STOP:
                        continue
                    self._admit_one(req, self._free_slots()[0])
                    continue
                # fault-injection site: a kill-point armed here
                # simulates a crash mid-decode — the loop thread dies
                # (KillPoint is a BaseException) and the flight
                # recorder's threading.excepthook dump carries every
                # in-flight request's lifecycle trail
                _fi.fire("serving.decode")
                nxt = self.engine.step()
                self.steps_run += 1
                _M_steps.inc()
                for slot in list(self._slots):
                    req = self._slots[slot]
                    req["out"].append(int(nxt[slot]))
                    _flight.record("serving", "decode",
                                   trace_id=req.get("trace_id"),
                                   step=self.steps_run,
                                   tokens=len(req["out"]))
                    self._finish_if_done(slot, req)
                self._expire_active()
                self._expire_queued()
                # gauges AFTER the completion/expiry sweep: a scrape
                # between steps must not report finished requests as
                # in-flight
                self._set_gauges()
            except Exception as e:  # noqa: BLE001 — fail loudly, stay up
                _flight.record("serving", "loop_error",
                               error=type(e).__name__)
                for slot, req in list(self._slots.items()):
                    self._fail(req, e)
                    self.engine.release(slot)
                self._slots.clear()
                self._set_gauges()
        self._set_gauges()
        self._drained.set()

    def _set_gauges(self) -> None:
        _G_queue.set(self._q.qsize())
        _G_inflight.set(len(self._slots))

    def shutdown(self, drain: bool = True,
                 timeout: Optional[float] = 300.0) -> bool:
        """Stop the server. ``drain=True`` (default) lets in-flight and
        already-queued requests finish while new submissions are
        rejected; ``drain=False`` additionally cancels everything still
        waiting in the queue (active requests still finish — a decode
        step cannot be abandoned mid-flight without corrupting slots).
        Returns True once the loop has fully drained."""
        with self._submit_lock:
            self._stopping.set()
        if not drain:
            # cancel queued work; requests already in slots complete
            while True:
                try:
                    req = self._q.get_nowait()
                except _queue.Empty:
                    break
                if req is not self._STOP:
                    self._fail(req, RuntimeError(
                        "request cancelled: server shut down before "
                        "admission"))
        self._q.put(self._STOP)  # wake an idle loop
        # Event.wait(None) blocks until drained — timeout=None means
        # "wait as long as it takes", never "skip the wait"
        drained = self._drained.wait(timeout)
        if self._metrics_server is not None:
            try:
                self._metrics_server.close()
            finally:
                self._metrics_server = None
        return drained

    @staticmethod
    def trace(request_id) -> List[dict]:
        """The flight-recorder lifecycle trail of ONE request — submit,
        queued, admitted, per-step decode, finished/expired/failed —
        live from the in-process ring (a crash dump carries the same
        events). ``request_id`` is the ``trace_id`` string or the req
        dict :meth:`submit` returned."""
        tid = (request_id.get("trace_id")
               if isinstance(request_id, dict) else request_id)
        return _flight.events(trace_id=tid)

    def stats(self) -> Dict[str, int]:
        with self._q.mutex:  # don't count _STOP sentinels as work
            queued = sum(1 for r in self._q.queue
                         if r is not self._STOP
                         and not r["done"].is_set())
        return {"steps_run": self.steps_run, "admitted": self.admitted,
                "rejected": self.rejected,
                "deadline_expired": self.deadline_expired,
                "in_flight": len(self._slots), "queued": queued,
                "draining": int(self._stopping.is_set()),
                "drained": int(self._drained.is_set())}
