"""Generation serving: fixed-slot continuous batching over a compiled
single-token decode step.

The reference's inference engine is a production deliverable whose LLM
path runs fused multi-transformer decode kernels behind the predictor
(ref: paddle/fluid/inference/api/analysis_predictor.h +
phi/kernels/fusion/gpu/fused_multi_transformer_op.cu). The TPU-native
equivalent keeps everything STATIC-SHAPED so XLA compiles exactly two
program families:

- ``prefill[bucket]``: prompt forward (padded to a pow-2 bucket)
  writing K/V into the slot's cache;
- ``decode``: ONE step advancing ALL slots together — q of shape
  [slots, 1] against the per-slot K/V history with per-slot position
  masks. Iteration-level (continuous) batching falls out: requests
  join/leave at step boundaries, the compiled program never changes.

Two cache layouts ship:

- **Dense** (:class:`LlamaDecodeEngine`): per-layer arrays
  [slots, max_seq, KVH, D] (a stacked [L, ...] form measured
  ~11 ms/step of slice/stack copies), donated through the decode step
  so the update is in-place in HBM. Simple, but HBM scales with
  *capacity* (slots x max_seq) whether slots are full or idle.
- **Paged** (:class:`PagedLlamaDecodeEngine`, the production/server
  default): a shared per-layer block pool [num_blocks, block_size,
  KVH, D] plus per-slot block tables (``serving_cache.PagedKVCache``),
  so HBM scales with *active tokens*; prompts prefill in CHUNKS
  through their own bucketed executable interleaved with decode steps
  (a long prompt never stalls the in-flight batch), and the decode
  attention is a tiled streaming walk of each slot's block list
  (``serving_cache.paged_attention``) that never materializes a dense
  [S, max_seq] view. Optional bf16/int8 block storage
  (``kv_quant=``) reuses the quantize.py absmax math.

``int8=True`` runs every projection as a REAL s8 x s8 -> s32 MXU matmul
(dynamic per-tensor activation quant, per-channel weight scales — the
same math as quantization.Int8Linear) with bf16 caches/activations.

Every engine's attention routes through the ONE
``serving_cache.paged_attention`` seam (the dense cache is viewed as
an identity-mapped block pool), behind which
``FLAGS_paged_attention_kernel`` selects the Pallas block-table TPU
kernel or the pure-jnp tile walk (the CPU/tier-1 numerics oracle).
The paged engine additionally supports **speculative decoding**
(``attach_draft``): a cheap draft — typically ``make_draft``'s
truncated-layer weight-sharing view — proposes
``FLAGS_serving_spec_tokens`` tokens per step, the target verifies
the whole window in one batched call, accepted prefixes commit and
rejected suffixes roll their block writes back through the admission
reservation (``PagedKVCache.truncate``); greedy output stays
BIT-equal to the non-speculative stream.

Decode is memory-bound (every step streams the full weight set), so the
bench grades tokens/s against the weight-streaming roofline:
slots / (weight_bytes / HBM_BW) — with the cache-traffic term sized
O(slots x max_seq) for the dense engine and O(active tokens) for the
paged one (``llama_decode_paged_tokens_per_sec``).
"""
from __future__ import annotations

import itertools
import queue as _queue
import threading
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .observability import flight as _flight
from .observability import metrics as _om
from .utils import fault_injection as _fi

__all__ = ["LlamaDecodeEngine", "PagedLlamaDecodeEngine",
           "GenerationServer"]

# process registry instruments (one set across all servers; the
# per-instance stats() dict stays the legacy view)
_M = _om.scope("serving")
_M_admitted = _M.counter("admitted_total", "Requests admitted into slots")
_M_rejected = _M.counter("rejected_total",
                         "Submissions rejected (server shutting down)")
_M_expired = _M.counter("deadline_expired_total",
                        "Requests failed by their deadline")
_M_failed = _M.counter("failed_total",
                       "Requests completed with an error")
_M_steps = _M.counter("steps_total", "Decode steps run by server loops")
_M_tokens = _M.counter("tokens_total", "Tokens delivered to requests")
_M_req_s = _M.histogram("request_seconds",
                        "Submit-to-completion wall time per request")
_M_token_s = _M.histogram(
    "token_seconds",
    "Per-token latency: request wall time / tokens produced")
_G_queue = _M.gauge("queue_depth",
                    "Requests waiting in the submission queue")
_G_inflight = _M.gauge("in_flight", "Requests currently holding a slot")
# queue-vs-decode latency split (the admission/load-shedding evidence:
# queue_seconds growing while decode_seconds holds means shed load)
_M_queue_s = _M.histogram(
    "queue_seconds", "Submit-to-admission wall time per request")
_M_decode_s = _M.histogram(
    "decode_seconds",
    "Admission-to-completion wall time per request (prefill + decode)")
# speculative decoding (per-step counted so acceptance rate is
# readable off the registry: accepted/proposed)
_M_spec_steps = _M.counter(
    "spec_steps_total", "Speculative decode steps (draft propose + "
    "one batched verify) run by engines")
_M_spec_proposed = _M.counter(
    "spec_proposed_total", "Draft tokens proposed to the target")
_M_spec_accepted = _M.counter(
    "spec_accepted_total",
    "Draft tokens the target verified and committed")
_M_spec_rolled = _M.counter(
    "spec_rolled_back_total",
    "KV blocks rolled back from rejected draft suffixes (re-credited "
    "to the slot's admission reservation)")
_M_shed = _M.counter(
    "shed_total",
    "Submissions rejected by the load-shedding policy (block pool "
    "exhausted AND the deferred-waiting list over "
    "FLAGS_serving_shed_queue, or the adaptive policy at its shed "
    "level)")
_M_deadline_rej = _M.counter(
    "admission_deadline_rejected_total",
    "Submissions rejected at submit time because the request's "
    "deadline cannot be met at the observed decode rate (adaptive "
    "admission; the request never burns KV blocks)")
# zero-downtime weight hot-swap (GenerationServer.swap_weights):
# applied between decode steps on the loop thread, in-flight requests
# keep their KV blocks and continue on the new weights
_M_swaps = _M.counter(
    "weight_swaps_total",
    "Weight hot-swaps applied by server loops (between decode steps; "
    "no request dropped, no recompile)")
_M_swap_rejected = _M.counter(
    "weight_swaps_rejected_total",
    "Weight hot-swaps rejected (shape/dtype/name mismatch against "
    "the live tree) — the old weights stay installed")
_M_swap_s = _M.histogram(
    "swap_seconds",
    "Wall seconds a weight hot-swap held the decode loop at its step "
    "boundary (weight prep + validation + install)")
# which implementation the paged_attention seam runs (decided once per
# engine at program-build time; the compiled steps bake the path in)
_M_pa_kernel = _M.counter(
    "paged_attention_kernel_steps_total",
    "Engine steps whose attention ran the Pallas block-table kernel")
_M_pa_fallback = _M.counter(
    "paged_attention_fallback_steps_total",
    "Engine steps whose attention ran the pure-jnp tile walk (the "
    "CPU/oracle fallback)")
# content-addressed prefix sharing (PagedKVCache radix tree):
# hits/reuse counted at TARGET admission only — an attached draft
# mirrors every admission, so counting both engines would double
# every hit (draft engines run with _prefix_metrics = False)
_M_prefix_hits = _M.counter(
    "prefix_hits_total",
    "Paged admissions whose prompt matched a cached prefix in the "
    "radix tree: matched blocks aliased with refcount bumps, their "
    "prefill skipped")
_M_prefix_reused = _M.counter(
    "prefix_tokens_reused_total",
    "Prompt tokens served from shared prefix blocks instead of "
    "being re-prefilled (the prefill work the radix cache saved)")

# process-unique request trace ids: every lifecycle event of a request
# carries one, so a flight dump (or GenerationServer.trace) replays a
# single request's submit -> queued -> admitted -> decode -> terminal
# trail even across servers
_REQ_SEQ = itertools.count(1)

# 0-d int32 aval for pre-warm lowers: matches the jnp.int32(...) args
# the live host orchestration passes, without compiling anything
_I32 = jax.ShapeDtypeStruct((), np.int32)


def _quantize_w(w_t):
    """Per-output-channel symmetric int8 of a TRANSPOSED [out, in]
    weight (ref: quantize.py PTQ convert)."""
    w_t = np.asarray(w_t, np.float32)
    step = np.maximum(np.abs(w_t).max(axis=1), 1e-8) / 127.0
    q = np.clip(np.round(w_t / step[:, None]), -127, 127).astype(np.int8)
    return jnp.asarray(q), jnp.asarray(step.astype(np.float32))


class LlamaDecodeEngine:
    """Compiled decode engine for a LlamaForCausalLM.

    Host-side state per slot: position, remaining budget, output ids.
    Device-side: params (frozen), K/V caches (donated each step).
    """

    def __init__(self, model, max_slots: int = 4, max_seq: int = 256,
                 int8: bool = False, eos_id: Optional[int] = None,
                 num_layers: Optional[int] = None,
                 share_params: Optional[Dict[str, object]] = None):
        cfg = model.config
        self.cfg = cfg
        self.max_slots = int(max_slots)
        self.max_seq = int(max_seq)
        self.eos_id = eos_id
        self.int8 = bool(int8)
        # num_layers < cfg.num_hidden_layers builds the TRUNCATED-LAYER
        # view (first N decoder layers + the full norm/head): the cheap
        # draft model of speculative decoding shares every retained
        # weight with its target at zero extra HBM (see make_draft)
        self.n_layers = int(num_layers or cfg.num_hidden_layers)
        if not 1 <= self.n_layers <= cfg.num_hidden_layers:
            raise ValueError(
                f"num_layers must be in [1, {cfg.num_hidden_layers}], "
                f"got {num_layers}")
        self.head_dim = cfg.hidden_size // cfg.num_attention_heads
        self.n_rep = cfg.num_attention_heads // cfg.num_key_value_heads

        dt = jnp.bfloat16 if str(cfg.dtype) == "bfloat16" else jnp.float32
        self.dtype = dt

        if share_params is not None:
            # truncated-layer VIEW of another engine's params (the
            # make_draft path): re-bind the caller's device arrays —
            # never re-upload/re-transpose/re-quantize a second weight
            # set, which would transiently double weight HBM exactly
            # where speculative decoding wants headroom least
            p: Dict[str, object] = dict(share_params)
            p["layers"] = list(share_params["layers"])[:self.n_layers]
        else:
            p = self._build_params(
                {k: v._data for k, v in model.named_parameters()})
        self.params = p

        S = self.max_slots
        # host slot state
        self.pos = np.zeros(S, np.int32)          # next cache index
        self.active = np.zeros(S, bool)
        self.last_ids = np.zeros((S, 1), np.int32)

        from . import serving_cache as _sc
        self._sc = _sc
        # every engine's attention rides the ONE paged_attention seam
        # (the dense cache is viewed as an identity-mapped block pool);
        # the implementation behind it — Pallas kernel vs jnp walk —
        # is decided here ONCE so the per-step path counters report
        # what the compiled programs actually baked in
        self._pa_kernel = _sc.use_kernel_default()
        self._attend_tile = next(
            ts for ts in (128, 64, 32, 16, 8, 4, 2, 1)
            if self.max_seq % ts == 0)
        self._draft: Optional["PagedLlamaDecodeEngine"] = None
        self._spec_k = 0
        # adaptive-admission brownout knobs, applied by the server at
        # step boundaries: _spec_suppressed drops speculative windows
        # to plain steps, _chunk_cap bounds the prefill chunk length
        # (both are step-boundary decisions — no compiled program
        # changes shape mid-stream)
        self._spec_suppressed = False
        self._chunk_cap: Optional[int] = None
        from .jit.sot import capture_jit as _capture_jit
        self._capture_jit = _capture_jit
        self._init_cache()

    def _build_params(self, sd) -> Dict[str, object]:
        """Device param pytree from a name -> array/Tensor state dict:
        the same prep ``__init__`` does — dtype cast, TRANSPOSED
        projections, optional int8 quantization, layer truncation — so
        a swapped-in tree is layout-identical to a boot-time one and
        the compiled step programs are reused as-is."""
        cfg, dt = self.cfg, self.dtype

        def get(name):
            try:
                v = sd[name]
            except KeyError:
                raise ValueError(
                    f"weight state dict is missing {name!r} — not a "
                    f"checkpoint of this model") from None
            if hasattr(v, "_data"):
                v = v._data
            return jnp.asarray(v, dt)

        p: Dict[str, object] = {"emb": get("llama.embed_tokens.weight"),
                                "norm": get("llama.norm.weight")}
        # projections stored transposed ([out, in]) — see _mm
        if cfg.tie_word_embeddings:
            p["head"] = p["emb"]      # [V, H] is already the
        else:                         # transposed head
            p["head"] = get("lm_head.weight").T
        layers = []
        for i in range(self.n_layers):
            pre = f"llama.layers.{i}."
            lp = {"in_ln": get(pre + "input_layernorm.weight"),
                  "post_ln": get(pre
                                 + "post_attention_layernorm"
                                   ".weight")}
            for nm in ("q_proj", "k_proj", "v_proj", "o_proj"):
                lp[nm] = get(pre + "self_attn." + nm + ".weight").T
            for nm in ("gate_proj", "up_proj", "down_proj"):
                lp[nm] = get(pre + "mlp." + nm + ".weight").T
            if self.int8:
                for nm in ("q_proj", "k_proj", "v_proj", "o_proj",
                           "gate_proj", "up_proj", "down_proj"):
                    lp[nm] = _quantize_w(lp[nm])
            layers.append(lp)
        p["layers"] = layers
        if self.int8:
            p["head"] = _quantize_w(p["head"])
        return p

    @staticmethod
    def _leaf_specs(p) -> Dict[str, object]:
        """leaf name -> (shape, dtype) spec of a param pytree (int8
        (codes, scales) tuples spec both halves)."""
        def spec(v):
            if isinstance(v, tuple):
                return tuple(spec(x) for x in v)
            return (tuple(v.shape), str(v.dtype))

        out: Dict[str, object] = {}
        for k, v in p.items():
            if k == "layers":
                for i, lp in enumerate(v):
                    for nm, lv in lp.items():
                        out[f"layers.{i}.{nm}"] = spec(lv)
            else:
                out[k] = spec(v)
        return out

    def prepare_swap(self, state_dict):
        """Build the device param tree for a weight swap WITHOUT
        installing it — the expensive half (host->device upload,
        per-layer transposes, optional KV quantization) that a
        caller can run off the decode loop's thread; pass the result
        to ``swap_weights(prepared=...)`` for the cheap validate +
        pointer install at a step boundary."""
        return self._build_params(dict(state_dict))

    def swap_weights(self, state_dict=None, *, prepared=None) -> None:
        """Replace this engine's weights IN PLACE between decode
        steps: ``state_dict`` (model parameter names -> tensors, e.g.
        a ``CheckpointManager.restore`` payload) is prepped exactly
        like boot-time weights (or arrives pre-built via
        ``prepared=``, see :meth:`prepare_swap`), validated
        leaf-for-leaf against the live tree — same shapes/dtypes ⇒
        the compiled decode/prefill/spec programs are reused with
        ZERO recompiles — and only then installed. Any mismatch
        raises with the old weights intact.
        Slot state and KV blocks are untouched, so in-flight requests
        continue on the new weights with their history preserved. An
        attached weight-sharing draft (``make_draft`` view) is
        re-pointed at the new arrays in the same swap; an independent
        draft keeps its own weights (swap it separately) — the accept
        rule keeps the committed stream correct either way."""
        new_p = prepared if prepared is not None \
            else self._build_params(dict(state_dict))
        old_spec, new_spec = (self._leaf_specs(self.params),
                              self._leaf_specs(new_p))
        if old_spec != new_spec:
            bad = [k for k in sorted(set(old_spec) | set(new_spec))
                   if old_spec.get(k) != new_spec.get(k)]
            raise ValueError(
                f"weight swap rejected: {len(bad)} leaf(s) with "
                f"incompatible shape/dtype (first: {bad[:4]}) — a "
                f"zero-recompile swap requires the checkpoint to match "
                f"the serving model's geometry exactly")
        old = self.params
        self.params = new_p
        draft = self._draft
        if draft is not None and draft.params.get("emb") is \
                old.get("emb"):
            view: Dict[str, object] = dict(new_p)
            view["layers"] = list(new_p["layers"])[:draft.n_layers]
            draft.params = view

    def _warm_geo(self) -> Dict[str, object]:
        """The serving geometry recorded beside every warm-bundle
        program entry — what ``_bundle_stale`` checks a bundle's
        entries against at pre-warm time, so a bundle written by a
        differently-configured replica degrades to cold compile
        (counted ``warmup.failures_total{reason=stale}``) instead of
        silently replaying programs the persistent cache has no
        artifacts for."""
        return {"layout": "dense", "slots": self.max_slots,
                "max_seq": self.max_seq}

    def _bundle_stale(self, meta, keys=None) -> List[str]:
        """Geometry keys on which a warm-bundle entry disagrees with
        this live engine (empty = fresh). ``keys`` restricts the
        check to the geometry a given program's SHAPE actually
        depends on — a replica differing only in an irrelevant knob
        (e.g. the prefill chunk, for a decode program) must not
        discard valid warmth. Keys absent from ``meta``
        (pre-freshness bundles) are not checked — the replay then
        simply rebuilds over live shapes as before."""
        geo = self._warm_geo()
        if keys is not None:
            geo = {k: geo[k] for k in keys if k in geo}
        return sorted(k for k, v in geo.items()
                      if k in meta and meta[k] != v)

    def reset_state(self) -> None:
        """Discard ALL slot and cache state — the crash-recovery seam:
        after a decode-loop crash the donated cache buffers may be
        mid-donation (deleted), so fresh zero pools replace them and
        the host bookkeeping (pos/active/last_ids) resets. The
        compiled step programs are KEPT — they are pure functions of
        their arguments, so recovery costs zero recompiles."""
        self.pos[:] = 0
        self.active[:] = False
        self.last_ids[:] = 0
        self._alloc_cache()

    def _prewarm_entry(self, entry):
        """AOT-rebuild one recorded serving program (a warm-bundle
        entry) over this engine's live geometry via
        ``lower().compile()`` — with the persistent executable cache
        enabled this is a disk read, not a fresh XLA compile. Returns
        False for entries this engine cannot replay (unknown program,
        spec programs without a draft attached) and the string
        ``"stale"`` for entries whose recorded geometry disagrees with
        the live config (replaying those would compile FRESH programs
        at boot while claiming warmth)."""
        meta = entry.get("meta") or {}
        if meta.get("program") != "decode":
            return False
        if self._bundle_stale(meta):
            return "stale"
        S = self.max_slots
        # helper args are NumPy-backed (device_put, not a compiled
        # fill program): pre-warm must never compile anything the
        # bundle's writer didn't
        self._decode._jitted.lower(
            self.params, self.k_cache, self.v_cache,
            jnp.asarray(np.zeros((S, 1), np.int32)),
            jnp.asarray(np.zeros(S, np.int32))).compile()
        _flight.record("warmup", "serving_program", program="decode")
        return True

    def _alloc_cache(self) -> None:
        """(Re)allocate the dense per-layer cache arrays — fresh zeros
        at boot AND at crash recovery (``reset_state``)."""
        cfg = self.cfg
        S, L = self.max_slots, self.n_layers
        kvh = cfg.num_key_value_heads
        # per-LAYER cache arrays (not one stacked [L, ...] array): the
        # stacked form costs a slice per layer + a stack per step that
        # XLA materializes as whole-cache copies (~11 ms/step measured
        # at 6 layers x 8 slots x 1024); per-layer donated leaves
        # update in place
        self.k_cache = [jnp.zeros((S, self.max_seq, kvh, self.head_dim),
                                  self.dtype) for _ in range(L)]
        self.v_cache = [jnp.zeros_like(self.k_cache[0])
                        for _ in range(L)]

    def _init_cache(self) -> None:
        """Build the DENSE cache layout + its compiled step programs
        (PagedLlamaDecodeEngine overrides with the block pool)."""
        self._alloc_cache()
        # caches are donated: each decode step updates them in place in
        # HBM instead of allocating a second [L,S,max_seq,...] copy.
        # The jitted step is registered as a CAPTURED step program
        # (jit.sot.capture_jit): its clean capture plan is checked in
        # (tests/test_capture_plan.py), so every call counts into
        # sot.captured_steps_total and the first compile lands in the
        # flight journal — identical execution to a bare jax.jit
        self._decode = self._capture_jit(self._decode_impl,
                                         donate_argnums=(1, 2),
                                         name="serving.decode",
                                         warm={"program": "decode",
                                               **self._warm_geo()})
        self._decode_collect = None
        self._prefills: Dict[int, object] = {}

    # -- math ---------------------------------------------------------------
    # Weights are stored TRANSPOSED ([out, in]) and contracted against
    # their LAST dim: with the natural [in, out] orientation XLA's
    # chosen executable layout disagreed with the call-input layout and
    # re-transposed ~1 GB of weights EVERY step (~3.6 ms/step measured)
    # — a per-call copy no warm-up can amortize because jit inputs
    # cannot be layout-pinned across calls.
    def _mm(self, h, w):
        """h @ w (w stored transposed); int8 path = dynamic per-tensor
        act quant + s8*s8->s32 with per-channel scale epilogue
        (quantize._int8_linear_impl math, calibration-free because
        decode activations are visible)."""
        if isinstance(w, tuple):
            w_q, w_step = w
            step = jnp.maximum(jnp.max(jnp.abs(h.astype(jnp.float32))),
                               1e-8) / 127.0
            qh = jnp.clip(jnp.round(h.astype(jnp.float32) / step),
                          -127, 127).astype(jnp.int8)
            acc = jax.lax.dot_general(
                qh, w_q, (((qh.ndim - 1,), (1,)), ((), ())),
                preferred_element_type=jnp.int32)
            return (acc.astype(jnp.float32) * (w_step * step)).astype(
                h.dtype)
        return jax.lax.dot_general(
            h, w, (((h.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32).astype(h.dtype)

    def _rms(self, h, w):
        h32 = h.astype(jnp.float32)
        var = jnp.mean(jnp.square(h32), axis=-1, keepdims=True)
        return (h32 * jax.lax.rsqrt(var + self.cfg.rms_norm_eps)).astype(
            h.dtype) * w

    def _rope(self, x, positions):
        """x [S, T, Hd, D] rotated at per-slot absolute positions
        (positions [S, T])."""
        d2 = self.head_dim // 2
        inv = 1.0 / (self.cfg.rope_theta ** (
            jnp.arange(0, d2, dtype=jnp.float32) / d2))
        freqs = positions.astype(jnp.float32)[..., None] * inv  # [S,T,d2]
        cos = jnp.cos(freqs)[:, :, None, :]
        sin = jnp.sin(freqs)[:, :, None, :]
        x1, x2 = x[..., :d2], x[..., d2:]
        return jnp.concatenate(
            [x1 * cos - x2 * sin, x2 * cos + x1 * sin],
            axis=-1).astype(x.dtype)

    def _attend(self, q, k_all, v_all, positions):
        """q [S,T,H,D] vs caches [S,max_seq,KVH,D]; row (s,t) may
        attend every column c <= positions[s,t]. Routed through the
        ONE ``serving_cache.paged_attention`` seam by viewing the
        dense per-slot rows as an identity-mapped block pool (a free
        leading-dim reshape), so no engine — dense or paged — ever
        materializes a ``[*, max_seq]`` score row (the two historical
        ``jax.nn.softmax(scores)`` sites lived here), GQA stays a
        grouped contraction against the UNEXPANDED caches, and the
        Pallas kernel accelerates the dense engine too. The walk still
        streams every max_seq column (all tiles): the dense cache IS
        capacity-sized — O(active tokens) streaming is precisely what
        the paged engine's block tables buy."""
        S, M = k_all.shape[0], k_all.shape[1]
        ts = self._attend_tile
        nb = M // ts
        k_pool = k_all.reshape((S * nb, ts) + k_all.shape[2:])
        v_pool = v_all.reshape((S * nb, ts) + v_all.shape[2:])
        tables = jnp.arange(S * nb, dtype=jnp.int32).reshape(S, nb)
        # use_kernel pinned to the __init__-time decision so the
        # compiled programs bake exactly what _count_pa_path reports
        # (a flag flip after construction changes neither)
        return self._sc.paged_attention(
            q, k_pool, v_pool, tables, positions, block_size=ts,
            n_rep=self.n_rep, use_kernel=self._pa_kernel)

    def _block(self, lp, h, kc_l, vc_l, positions, write_cols):
        """One decoder layer over [S, T, H] with fixed-cache K/V
        writes at write_cols [S, T]."""
        S, T, H = h.shape
        kvh = self.cfg.num_key_value_heads
        res = h
        x = self._rms(h, lp["in_ln"])
        q = self._mm(x, lp["q_proj"]).reshape(
            S, T, self.cfg.num_attention_heads, self.head_dim)
        k = self._mm(x, lp["k_proj"]).reshape(S, T, kvh, self.head_dim)
        v = self._mm(x, lp["v_proj"]).reshape(S, T, kvh, self.head_dim)
        q = self._rope(q, positions)
        k = self._rope(k, positions)
        sl = jnp.arange(S)[:, None].repeat(T, 1)      # [S, T] slot ids
        kc_l = kc_l.at[sl, write_cols].set(k)
        vc_l = vc_l.at[sl, write_cols].set(v)
        att = self._attend(q, kc_l, vc_l, positions)
        h = res + self._mm(att.reshape(S, T, H), lp["o_proj"])
        res = h
        x = self._rms(h, lp["post_ln"])
        ff = self._mm(jax.nn.silu(
            self._mm(x, lp["gate_proj"]).astype(jnp.float32)).astype(
                x.dtype) * self._mm(x, lp["up_proj"]),
            lp["down_proj"])
        return res + ff, kc_l, vc_l

    def _forward(self, params, k_cache, v_cache, ids, positions):
        """Shared prefill/decode body: ids [S, T] -> logits [S, T, V];
        caches are per-layer lists (donated leaves, in-place)."""
        h = jnp.take(params["emb"], ids, axis=0).astype(self.dtype)
        new_k, new_v = [], []
        for li, lp in enumerate(params["layers"]):
            h, kc_l, vc_l = self._block(
                lp, h, k_cache[li], v_cache[li], positions, positions)
            new_k.append(kc_l)
            new_v.append(vc_l)
        h = self._rms(h, params["norm"])
        logits = self._mm(h, params["head"])
        # barrier: without it XLA fuses the [H, V] head matmul into the
        # consumer argmax as a VPU reduce-loop fusion (measured 2.8 ms
        # vs ~0.3 ms for the same contraction on the MXU)
        logits = jax.lax.optimization_barrier(logits)
        return (logits, new_k, new_v)

    def _decode_impl(self, params, k_cache, v_cache, last_ids, pos):
        """One token for every slot: ids [S,1], pos [S] = cache index
        to write (== tokens so far)."""
        positions = pos[:, None]                        # [S, 1]
        logits, k_cache, v_cache = self._forward(
            params, k_cache, v_cache, last_ids, positions)
        nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
        return nxt, k_cache, v_cache

    def _prefill_impl(self, params, k_cache, v_cache, ids, slot,
                      true_len):
        """Prompt forward for ONE slot: ids [1, B] (bucket-padded),
        writes cache rows [0, B), returns argmax at the last real
        token, narrowed to the one slot by slicing. Rows past
        true_len are bucket padding: their outputs are never read and
        their cache rows are overwritten by later decode writes
        before any position mask can attend them, so the causal
        positions mask alone is sufficient."""
        B = ids.shape[1]
        positions = jnp.arange(B)[None, :]              # [1, B]
        kc = [jax.lax.dynamic_slice_in_dim(c, slot, 1, axis=0)
              for c in k_cache]
        vc = [jax.lax.dynamic_slice_in_dim(c, slot, 1, axis=0)
              for c in v_cache]
        logits, kc, vc = self._forward(params, kc, vc, ids, positions)
        k_cache = [jax.lax.dynamic_update_slice_in_dim(c, u, slot, axis=0)
                   for c, u in zip(k_cache, kc)]
        v_cache = [jax.lax.dynamic_update_slice_in_dim(c, u, slot, axis=0)
                   for c, u in zip(v_cache, vc)]
        first = jnp.argmax(logits[0, true_len - 1, :]).astype(jnp.int32)
        return first, k_cache, v_cache

    # -- host orchestration -------------------------------------------------
    def _bucket(self, n: int) -> int:
        b = 8
        while b < n:
            b *= 2
        return min(b, self.max_seq)

    def _count_pa_path(self, n: int = 1) -> None:
        """Per-step accounting of which implementation the
        paged_attention seam ran — Pallas kernel vs jnp walk, decided
        once at program-build time (``_pa_kernel``), so the counters
        report what the compiled steps actually baked in."""
        (_M_pa_kernel if self._pa_kernel else _M_pa_fallback).inc(n)

    def prefill(self, slot: int, prompt_ids: np.ndarray) -> int:
        """Load a prompt into ``slot``; returns the first generated
        token (greedy)."""
        prompt_ids = np.asarray(prompt_ids, np.int32).reshape(-1)
        n = int(prompt_ids.shape[0])
        if not 0 < n <= self.max_seq - 1:
            raise ValueError(
                f"prompt length {n} not in [1, {self.max_seq - 1}]")
        b = self._bucket(n)
        if b not in self._prefills:
            self._prefills[b] = jax.jit(self._prefill_impl,
                                        donate_argnums=(1, 2))
        padded = np.zeros((1, b), np.int32)
        padded[0, :n] = prompt_ids
        first, self.k_cache, self.v_cache = self._prefills[b](
            self.params, self.k_cache, self.v_cache, jnp.asarray(padded),
            jnp.int32(slot), jnp.int32(n))
        first = int(first)
        self.pos[slot] = n
        self.active[slot] = True
        self.last_ids[slot, 0] = first
        return first

    def step(self) -> np.ndarray:
        """One decode iteration for ALL slots; returns next token per
        slot (garbage for inactive slots — callers consult .active)."""
        nxt, self.k_cache, self.v_cache = self._decode(
            self.params, self.k_cache, self.v_cache,
            jnp.asarray(self.last_ids), jnp.asarray(self.pos))
        self._count_pa_path()
        nxt = np.asarray(nxt)
        for s in range(self.max_slots):
            if self.active[s]:
                self.pos[s] += 1
                self.last_ids[s, 0] = nxt[s]
        return nxt

    def _decode_collect_impl(self, params, k_cache, v_cache, last_ids,
                             pos, buf, i):
        """Decode step + on-device token collection (buf [S, n] donated;
        column i written in-place)."""
        nxt, k_cache, v_cache = self._decode_impl(
            params, k_cache, v_cache, last_ids, pos)
        buf = jax.lax.dynamic_update_slice(buf, nxt[:, None],
                                           (jnp.int32(0), i))
        return nxt, k_cache, v_cache, buf

    def decode_steps(self, n: int) -> np.ndarray:
        """``n`` chained decode iterations with DEVICE-resident token
        feedback — dispatches pipeline asynchronously and ONE host
        fetch closes the window. Every slot must be active; returns
        [S, n] generated tokens.

        Measured alternatives at 8 slots x 1024 ctx on v5e, all SLOWER
        than this per-step form (989 tok/s): lax.scan-fused loop 319
        (cache carries copy inside the while body), 8x unrolled chunks
        672 (intermediate cache generations copy), AOT layout-AUTO
        executables 331 (per-call relayout + AOT dispatch overhead),
        [S,KVH,M,D] / flattened-3D cache layouts 957 / 638. The
        residual above the weights+cache roofline is two boundary
        layout conversions of the caches per step that XLA emits
        regardless of shape arrangement."""
        if not self.active.all():
            raise ValueError(
                "decode_steps advances EVERY slot; use step() when some "
                "slots are free (the continuous-batching server path)")
        if int(self.pos.max()) + n > self.max_seq - 1:
            raise ValueError(
                f"decode_steps({n}) would write past the {self.max_seq}"
                f"-token cache (max pos {int(self.pos.max())}); out-of-"
                f"bounds K/V writes are silently dropped by XLA and the "
                f"position mask would then attend unwritten rows")
        if self._decode_collect is None:
            self._decode_collect = self._capture_jit(
                self._decode_collect_impl, donate_argnums=(1, 2, 5),
                name="serving.decode_window")
        ids = jnp.asarray(self.last_ids)
        pos = jnp.asarray(self.pos)
        # tokens accumulate in ONE donated device buffer: holding a
        # per-step list of output arrays measured 2x slower (every live
        # buffer adds tunnel-handle bookkeeping to later dispatches)
        buf = jnp.zeros((self.max_slots, n), jnp.int32)
        for i in range(n):
            nxt, self.k_cache, self.v_cache, buf = self._decode_collect(
                self.params, self.k_cache, self.v_cache, ids, pos, buf,
                jnp.int32(i))
            ids = nxt[:, None]
            pos = pos + 1
        self._count_pa_path(n)
        toks = np.asarray(buf)                      # the one fetch
        self.pos += n
        self.last_ids = toks[:, -1:].astype(np.int32).copy()
        return toks

    def release(self, slot: int, evicted: bool = False) -> None:
        """Free ``slot`` for the next admission. ``evicted`` marks a
        reclaim (deadline expiry / failure) — meaningful on the paged
        engine, where it feeds ``serving.block_evictions_total``;
        the dense engine's rows are slot-owned either way."""
        self.active[slot] = False
        self.pos[slot] = 0

    def generate(self, prompt_ids, max_new_tokens: int = 32,
                 slot: int = 0) -> List[int]:
        """Single-request convenience path (tests / warm-up): prefill
        into ``slot``'s cache region — dense [max_seq] rows here,
        freshly allocated pool blocks on the paged engine — then greedy
        single-token steps until eos/budget/capacity."""
        out = [self.prefill(slot, prompt_ids)]
        for _ in range(max_new_tokens - 1):
            if self.eos_id is not None and out[-1] == self.eos_id:
                break
            if self.pos[slot] >= self.max_seq - 1:
                break
            out.append(int(self.step()[slot]))
        self.release(slot)
        return out

    def export_decode(self):
        """AOT-serialize the decode step via jax.export — the StableHLO
        artifact a serving process can run without this class (ref: the
        reference predictor's save/load of an analyzed program). The
        exported signature matches the live engine's cache layout:
        dense per-layer [slots, max_seq, KVH, D] arrays here; the paged
        engine exports its block-pool signature (pools + block tables +
        active mask) instead."""
        avals = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
            (self.params, self.k_cache, self.v_cache,
             jnp.asarray(self.last_ids), jnp.asarray(self.pos)))
        exported = jax.export.export(jax.jit(self._decode_impl))(*avals)
        return exported.serialize()


class PagedLlamaDecodeEngine(LlamaDecodeEngine):
    """Paged-KV decode engine: the dense engine's math (weights,
    projections, rope, int8 matmuls) over a **block-pool cache**.

    Layout: one shared pool per layer ``[num_blocks, block_size, KVH,
    D]`` (``serving_cache.PagedKVCache``) addressed through per-slot
    block tables, so KV HBM scales with ACTIVE tokens instead of
    slots x max_seq. Admission reserves a request's worst-case block
    count (prompt + generation budget), prompt blocks are mapped
    immediately, and decode extends one block at a time at step
    boundaries — extension can therefore never fail mid-stream.

    Prefill is CHUNKED: ``begin_request`` allocates, then
    ``prefill_chunk`` runs at most ``FLAGS_serving_prefill_chunk``
    prompt tokens through a bucketed executable per call, writing K/V
    straight into the slot's blocks; the GenerationServer loop
    interleaves one chunk with each decode step so a long prompt
    stalls the in-flight batch by at most one chunk forward.

    The decode step (``_decode_impl``, registered through
    ``capture_jit`` with the pool pytree donated) walks each slot's
    block list with the tiled streaming attention
    (``serving_cache.paged_attention``) — no dense ``[S, max_seq]``
    score or cache view is ever materialized.

    ``kv_quant``: None stores blocks in the model dtype, "bfloat16"
    halves f32 pools, "int8" stores absmax codes + per-(token, head)
    scales (quantize.py math) dequantized per gathered tile.
    """

    paged = True
    # process-registry prefix metrics are target-engine only; an
    # attached draft mirrors every admission (attach_draft flips this)
    _prefix_metrics = True

    def __init__(self, model, max_slots: int = 4, max_seq: int = 256,
                 int8: bool = False, eos_id: Optional[int] = None,
                 block_size: Optional[int] = None,
                 num_blocks: Optional[int] = None,
                 kv_quant: Optional[str] = None,
                 prefill_chunk: Optional[int] = None,
                 num_layers: Optional[int] = None,
                 share_params: Optional[Dict[str, object]] = None):
        from .core.flags import flag_value
        self.block_size = int(block_size or
                              flag_value("serving_block_size"))
        mbs = -(-int(max_seq) // self.block_size)
        auto = int(max_slots) * mbs  # dense capacity parity
        self.num_blocks = int(num_blocks or
                              flag_value("serving_num_blocks") or auto)
        if kv_quant not in (None, "bfloat16", "int8"):
            raise ValueError(
                f"kv_quant must be None, 'bfloat16' or 'int8', got "
                f"{kv_quant!r}")
        self.kv_quant = kv_quant
        self.prefill_chunk_len = int(
            prefill_chunk or flag_value("serving_prefill_chunk"))
        super().__init__(model, max_slots=max_slots, max_seq=max_seq,
                         int8=int8, eos_id=eos_id,
                         num_layers=num_layers,
                         share_params=share_params)

    def _alloc_pools(self) -> Dict[str, list]:
        """Fresh zeroed block pools (per-layer K/V + optional int8
        scales) — built at boot and again at crash recovery
        (``reset_state``), where the donated pool pytree may be
        mid-donation."""
        kvh = self.cfg.num_key_value_heads
        pool_dt = {"int8": jnp.int8,
                   "bfloat16": jnp.bfloat16}.get(self.kv_quant,
                                                 self.dtype)
        NB, bs, L = self.num_blocks, self.block_size, self.n_layers
        kv = {"k": [jnp.zeros((NB, bs, kvh, self.head_dim), pool_dt)
                    for _ in range(L)],
              "v": [jnp.zeros((NB, bs, kvh, self.head_dim), pool_dt)
                    for _ in range(L)]}
        if self.kv_quant == "int8":
            kv["ksc"] = [jnp.zeros((NB, bs, kvh), jnp.float32)
                         for _ in range(L)]
            kv["vsc"] = [jnp.zeros((NB, bs, kvh), jnp.float32)
                         for _ in range(L)]
        return kv

    def _init_cache(self) -> None:
        from . import serving_cache as _sc
        self._sc = _sc
        self._kv = _sc.PagedKVCache(
            max_slots=self.max_slots, max_seq=self.max_seq,
            block_size=self.block_size, num_blocks=self.num_blocks)
        self.kvs = self._alloc_pools()
        # the pool pytree is donated each step/chunk: K/V writes land
        # in place in HBM, and capture_jit keeps the paged step inside
        # captured-step accounting exactly like the dense one
        self._decode = self._capture_jit(self._decode_impl,
                                         donate_argnums=(1,),
                                         name="serving.paged_decode",
                                         warm={"program": "decode",
                                               **self._warm_geo()})
        self._decode_collect = None
        self._prefills: Dict[int, object] = {}
        self._prefill_state: Dict[int, dict] = {}
        # prefix-sharing state: the boundary copy-on-write program is
        # built lazily (first block-aligned hit), per-request hit
        # accounting feeds the server's req["prefix_hit_tokens"]
        self._cow = None
        self.prefix_hit_tokens: Dict[int, int] = {}

    def _warm_geo(self) -> Dict[str, object]:
        return {"layout": "paged", "slots": self.max_slots,
                "max_seq": self.max_seq, "block_size": self.block_size,
                "num_blocks": self.num_blocks,
                "chunk": self.prefill_chunk_len}

    def reset_state(self) -> None:
        """Crash-recovery reset over the block pool: every owned slot
        is released as a counted EVICTION (its request is being
        re-admitted or quarantined by the supervisor), staged prefills
        are dropped, and the donated pool pytree is rebuilt as fresh
        zeros. Compiled programs are kept — zero recompiles. An
        attached draft resets in the same call (mirrored slots)."""
        for s in range(self.max_slots):
            self._kv.release(s, evicted=True)
        # the pool pytree is about to be rebuilt as ZEROS: every
        # cached radix node's block content dies with it, so the tree
        # must empty in the same breath (releasing all slots above
        # drove every refcount to 0 — reset cannot throw here)
        self._kv.reset_prefix_cache()
        self.prefix_hit_tokens.clear()
        self._prefill_state.clear()
        self.pos[:] = 0
        self.active[:] = False
        self.last_ids[:] = 0
        self.kvs = self._alloc_pools()
        if self._draft is not None:
            self._draft.reset_state()

    # -- device side --------------------------------------------------------
    def _write_kv(self, kvl, k, v, positions, tables, wmask):
        """Scatter rope'd K/V rows [S, T, KVH, D] into their (physical
        block, offset) cells; rows with ``wmask`` False or an unmapped
        table entry are dropped (OOB index), so prefill padding and
        inactive slots never touch a real block."""
        S, T = positions.shape
        bidx = jnp.minimum(positions // self.block_size,
                           self._kv.max_blocks_per_slot - 1)
        phys = jnp.take_along_axis(tables, bidx, axis=1)
        ok = jnp.logical_and(wmask, phys >= 0)
        phys = jnp.where(ok, phys, self.num_blocks).reshape(-1)
        off = (positions % self.block_size).reshape(-1)
        kf = k.reshape((S * T,) + k.shape[2:])
        vf = v.reshape((S * T,) + v.shape[2:])
        out = dict(kvl)
        if self.kv_quant == "int8":
            kq, ks = self._sc.absmax_quantize(kf)
            vq, vs = self._sc.absmax_quantize(vf)
            out["k"] = self._sc.write_kv_tokens(kvl["k"], phys, off, kq)
            out["v"] = self._sc.write_kv_tokens(kvl["v"], phys, off, vq)
            out["ksc"] = self._sc.write_kv_tokens(kvl["ksc"], phys,
                                                  off, ks)
            out["vsc"] = self._sc.write_kv_tokens(kvl["vsc"], phys,
                                                  off, vs)
        else:
            out["k"] = self._sc.write_kv_tokens(kvl["k"], phys, off, kf)
            out["v"] = self._sc.write_kv_tokens(kvl["v"], phys, off, vf)
        return out

    def _cow_impl(self, params, kvs, src, dst):
        """Boundary copy-on-write: clone physical block ``src`` into
        ``dst`` across every pool leaf (per-layer K/V + int8 scales).
        One captured executable with the pool pytree donated — the
        copy lands in place in HBM like every other pool write."""
        del params
        return {name: [self._sc.copy_block(p, src, dst)
                       for p in pools]
                for name, pools in kvs.items()}

    def _block_paged(self, lp, h, kvl, positions, tables, n_tiles,
                     wmask):
        """One decoder layer over [S, T, H] with block-pool K/V writes
        and the tiled streaming attention."""
        S, T, H = h.shape
        kvh = self.cfg.num_key_value_heads
        res = h
        x = self._rms(h, lp["in_ln"])
        q = self._mm(x, lp["q_proj"]).reshape(
            S, T, self.cfg.num_attention_heads, self.head_dim)
        k = self._mm(x, lp["k_proj"]).reshape(S, T, kvh, self.head_dim)
        v = self._mm(x, lp["v_proj"]).reshape(S, T, kvh, self.head_dim)
        q = self._rope(q, positions)
        k = self._rope(k, positions)
        kvl = self._write_kv(kvl, k, v, positions, tables, wmask)
        att = self._sc.paged_attention(
            q, kvl["k"], kvl["v"], tables, positions,
            block_size=self.block_size, n_rep=self.n_rep,
            n_tiles=n_tiles, k_scale=kvl.get("ksc"),
            v_scale=kvl.get("vsc"), use_kernel=self._pa_kernel)
        h = res + self._mm(att.reshape(S, T, H), lp["o_proj"])
        res = h
        x = self._rms(h, lp["post_ln"])
        ff = self._mm(jax.nn.silu(
            self._mm(x, lp["gate_proj"]).astype(jnp.float32)).astype(
                x.dtype) * self._mm(x, lp["up_proj"]),
            lp["down_proj"])
        return res + ff, kvl

    def _forward_paged(self, params, kv, ids, positions, tables,
                       n_tiles, wmask):
        """Shared chunked-prefill/decode body: ids [S, T] -> logits
        [S, T, V]; the pool pytree is donated, writes land in place."""
        h = jnp.take(params["emb"], ids, axis=0).astype(self.dtype)
        out_kv = {key: [] for key in kv}
        for li, lp in enumerate(params["layers"]):
            kvl = {key: kv[key][li] for key in kv}
            h, kvl = self._block_paged(lp, h, kvl, positions, tables,
                                       n_tiles, wmask)
            for key in out_kv:
                out_kv[key].append(kvl[key])
        h = self._rms(h, params["norm"])
        logits = self._mm(h, params["head"])
        # same MXU-vs-fused-argmax barrier as the dense engine
        logits = jax.lax.optimization_barrier(logits)
        return logits, out_kv

    def _decode_impl(self, params, kv, last_ids, pos, tables, act):
        """One token for every slot: ids [S,1], pos [S] = write
        position, tables [S, max_blocks] block tables, act [S] bool
        (inactive slots neither write nor advance). The block walk is
        bounded by the LONGEST active history, so short batches pay
        only their own tiles."""
        positions = pos[:, None]                        # [S, 1]
        n_tiles = jnp.max(pos) // self.block_size + 1
        logits, kv = self._forward_paged(params, kv, last_ids,
                                         positions, tables, n_tiles,
                                         act[:, None])
        nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
        return nxt, kv

    def _prefill_impl(self, params, kv, ids, table_row, start, nvalid,
                      true_len):
        """ONE prompt chunk for ONE slot: ids [1, B] (bucket-padded)
        holds prompt tokens [start, start+nvalid); rows write into the
        slot's blocks and attend to every earlier position (previous
        chunks' blocks + causal within the chunk). Returns the greedy
        token at the prompt's LAST position — meaningful only on the
        final chunk (the host ignores it before that)."""
        B = ids.shape[1]
        offs = jnp.arange(B)
        positions = (start + offs)[None, :]             # [1, B]
        wmask = (offs < nvalid)[None, :]
        tables = table_row[None, :]
        n_tiles = (start + nvalid - 1) // self.block_size + 1
        logits, kv = self._forward_paged(params, kv, ids, positions,
                                         tables, n_tiles, wmask)
        last = jnp.clip(true_len - 1 - start, 0, B - 1)
        tok = jnp.argmax(logits[0, last, :]).astype(jnp.int32)
        return tok, kv

    def _decode_collect_impl(self, params, kv, last_ids, pos, buf, i,
                             tables, act):
        """Decode step + on-device token collection (buf [S, n]
        donated; column i written in-place)."""
        nxt, kv = self._decode_impl(params, kv, last_ids, pos, tables,
                                    act)
        buf = jax.lax.dynamic_update_slice(buf, nxt[:, None],
                                           (jnp.int32(0), i))
        return nxt, kv, buf

    def _propose_impl(self, params, kv, last_ids, pos, tables, act):
        """DRAFT side of a speculative step: ``_spec_propose_k``
        sequential greedy decode steps chained device-side inside ONE
        captured executable (token feedback never touches the host),
        writing the draft's own block pool at positions
        [pos, pos + k). Returns (draft tokens [S, k], kv)."""
        ids, p = last_ids, pos
        toks = []
        for _ in range(self._spec_propose_k):
            nxt, kv = self._decode_impl(params, kv, ids, p, tables,
                                        act)
            toks.append(nxt)
            ids = nxt[:, None]
            p = p + 1
        return jnp.stack(toks, axis=1), kv

    def _spec_verify_impl(self, params, kv, last_ids, draft_tok, pos,
                          tables, act):
        """TARGET side: score the whole speculation window in ONE
        batched paged-attention call — ids [S, k+1] = [last_id,
        d1..dk] at positions [pos, pos+k] (the same multi-position
        executable family chunked prefill runs), writing the target's
        K/V for every window position. Greedy targets t [S, k+1]
        (t[:, i] conditions on the prefix through d_i) and the
        device-computed accepted-prefix length n_acc [S] =
        |leading i with d_{i+1} == t_i| come back together; the host
        commits min(n_acc + 1, k) tokens and rolls the rest back, so
        the greedy stream is BIT-equal to non-speculative decode."""
        k = draft_tok.shape[1]
        ids = jnp.concatenate([last_ids, draft_tok], axis=1)
        positions = pos[:, None] + jnp.arange(k + 1)[None, :]
        n_tiles = (jnp.max(pos) + k) // self.block_size + 1
        wmask = jnp.broadcast_to(act[:, None], positions.shape)
        logits, kv = self._forward_paged(params, kv, ids, positions,
                                         tables, n_tiles, wmask)
        t = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        match = (draft_tok == t[:, :k]).astype(jnp.int32)
        n_acc = jnp.sum(jnp.cumprod(match, axis=1), axis=1)
        return t, n_acc, kv

    # -- host orchestration -------------------------------------------------
    def make_draft(self, model,
                   num_layers: Optional[int] = None
                   ) -> "PagedLlamaDecodeEngine":
        """Build the cheap draft engine for speculative decoding as a
        TRUNCATED-LAYER view of this target: same geometry (slots,
        max_seq, block pool sizing, quantization), first
        ``num_layers`` decoder layers (default
        ``FLAGS_serving_spec_draft_layers``, 0 = half the target's,
        min 1) — and the retained weights are re-bound to the
        TARGET'S device arrays, so the draft costs only its own KV
        pool, never a second weight set."""
        from .core.flags import flag_value
        n = int(num_layers or flag_value("serving_spec_draft_layers")
                or max(1, self.n_layers // 2))
        if not 1 <= n <= self.n_layers:
            raise ValueError(
                f"draft num_layers must be in [1, {self.n_layers}] — "
                f"the TARGET's depth, not the model's — got {n} (a "
                f"draft at least as deep as its target makes "
                f"speculation strictly slower than plain stepping)")
        return PagedLlamaDecodeEngine(
            model, max_slots=self.max_slots, max_seq=self.max_seq,
            int8=self.int8, eos_id=self.eos_id,
            block_size=self.block_size, num_blocks=self.num_blocks,
            kv_quant=self.kv_quant,
            prefill_chunk=self.prefill_chunk_len, num_layers=n,
            share_params=self.params)

    def attach_draft(self, draft: "PagedLlamaDecodeEngine",
                     spec_tokens: Optional[int] = None
                     ) -> "PagedLlamaDecodeEngine":
        """Enable speculative decoding: ``draft`` (a make_draft view
        or ANY second paged engine over the same geometry) proposes
        ``spec_tokens`` (default ``FLAGS_serving_spec_tokens``) tokens
        per step; this target verifies the window in one batched
        call. Admission reserves ``spec_tokens`` extra budget per
        request so window pre-extension can never out-draw the
        reservation; rejected suffixes roll their blocks back
        (``PagedKVCache.truncate``). Returns self (chainable)."""
        from .core.flags import flag_value
        k = int(spec_tokens or flag_value("serving_spec_tokens"))
        if k < 1:
            raise ValueError(f"spec_tokens must be >= 1, got {k}")
        if (draft.max_slots != self.max_slots
                or draft.max_seq != self.max_seq
                or draft.block_size != self.block_size):
            raise ValueError(
                "draft engine geometry (max_slots/max_seq/block_size) "
                "must match the target's — the two advance in "
                "lockstep over mirrored slot state")
        if self.active.any() or self._prefill_state \
                or self._kv.occupied_slots():
            raise ValueError(
                "attach_draft requires an IDLE engine: requests "
                "admitted before attachment were reserved without the "
                "spec_k margin and have no mirrored draft slot, so "
                "the next step would exhaust mid-decode — exactly "
                "what admission reservations exist to prevent. Drain "
                "or release every slot first")
        self._draft = draft
        # every admission mirrors into the draft's pool/tree: counting
        # its prefix hits in the process registry would double every
        # hit (the draft keeps its own per-instance stats() view)
        draft._prefix_metrics = False
        self._spec_k = k
        draft._spec_propose_k = k
        self._spec_propose = draft._capture_jit(
            draft._propose_impl, donate_argnums=(1,),
            name="serving.spec_draft",
            warm={"program": "spec_draft", "k": k,
                  "draft_layers": draft.n_layers,
                  **self._warm_geo()})
        self._spec_verify = self._capture_jit(
            self._spec_verify_impl, donate_argnums=(1,),
            name="serving.spec_verify",
            warm={"program": "spec_verify", "k": k,
                  **self._warm_geo()})
        return self

    def _device_cow(self, slot: int, src: int, dst: int) -> None:
        """Run the boundary copy-on-write on device: block ``src`` ->
        ``dst`` in every pool leaf, remapped by the allocator before
        this call. Dispatched synchronously with admission/step
        bookkeeping, so program order guarantees the clone reads the
        shared content before any later pool write can touch it."""
        if self._cow is None:
            self._cow = self._capture_jit(
                self._cow_impl, donate_argnums=(1,),
                name="serving.prefix_cow",
                warm={"program": "prefix_cow", **self._warm_geo()})
        self.kvs = self._cow(self.params, self.kvs, jnp.int32(src),
                             jnp.int32(dst))
        if self._prefix_metrics:
            _flight.record("serving", "prefix_cow", slot=slot,
                           src=src, dst=dst)

    def _apply_cow(self, slot: int) -> None:
        """Consume the admission-recorded boundary COW (block-aligned
        full-prefix hit: the last matched block is cloned so the
        re-prefilled final prompt token writes privately)."""
        mv = self._kv.take_cow(slot)
        if mv is not None:
            self._device_cow(slot, *mv)

    def _shared_write_guard(self, slot: int) -> None:
        """Decode/spec writes land at ``pos >= len(prompt)``, past
        every shared block by construction (``commit_prefix`` only
        caches full PROMPT blocks) — but a write that DID land inside
        the shared prefix would corrupt every sharer's stream, so the
        boundary is guarded, not trusted: ``cow_for_write`` detaches
        the block (and raises loudly on a mid-prefix write) before
        the table ships to the device."""
        mv = self._kv.cow_for_write(slot, int(self.pos[slot]))
        if mv is not None:
            self._device_cow(slot, *mv)

    def begin_request(self, slot: int, prompt_ids,
                      max_new_tokens: int) -> bool:
        """Admit a request into ``slot``: map blocks for the prompt
        and reserve its worst-case generation budget (+ the
        speculation window when a draft is attached — verify writes
        up to ``spec_k`` positions past the committed stream before
        rollback). Returns False when the pool cannot cover it right
        now (caller should keep the request queued — exhaustion
        queues, never crashes); raises ValueError for a request the
        pool could NEVER hold. With a draft attached, the draft's
        pool admits the same request in lockstep."""
        prompt_ids = np.asarray(prompt_ids, np.int32).reshape(-1)
        n = int(prompt_ids.shape[0])
        if not 0 < n <= self.max_seq - 1:
            raise ValueError(
                f"prompt length {n} not in [1, {self.max_seq - 1}]")
        budget = max(int(max_new_tokens), 1) + self._spec_k
        total = min(n + budget, self.max_seq)
        if not self._kv.admit(slot, n, total, token_ids=prompt_ids):
            return False
        if self._draft is not None:
            # both pools or neither: a draft that cannot cover the
            # mirror (defer OR a custom draft pool that could never
            # hold it) must not strand the target's blocks
            try:
                ok = self._draft.begin_request(slot, prompt_ids,
                                               budget)
            except Exception:
                self._kv.release(slot)
                raise
            if not ok:
                self._kv.release(slot)
                return False
        # prefix hit: matched tokens are already resident in aliased
        # blocks — prefill starts at the first unmatched token (a
        # block-aligned FULL match re-prefills only the last prompt
        # token, into its COW'd boundary clone, to seed the first
        # generated token)
        skip = self._kv.matched_tokens(slot)
        self._apply_cow(slot)
        self.prefix_hit_tokens[slot] = skip
        if skip and self._prefix_metrics:
            _M_prefix_hits.inc()
            _M_prefix_reused.inc(skip)
            _flight.record("serving", "prefix_hit", slot=slot,
                           tokens=skip, prompt=n)
        self._prefill_state[slot] = {"ids": prompt_ids, "next": skip}
        self.pos[slot] = 0
        self.active[slot] = False
        return True

    def prefill_chunk(self, slot: int) -> Optional[int]:
        """Run the next prompt chunk for ``slot``. Returns None while
        prefill is incomplete; on the final chunk, activates the slot
        and returns the first generated token (greedy)."""
        st = self._prefill_state[slot]
        ids, start = st["ids"], st["next"]
        n = int(ids.shape[0])
        # _chunk_cap is the adaptive-admission brownout knob: under
        # pressure the policy bounds each chunk (floor 8 = the
        # smallest bucket) so prefill draws smaller slices of the
        # step budget; None = the configured chunk length
        limit = self.prefill_chunk_len if self._chunk_cap is None \
            else max(8, min(self.prefill_chunk_len, self._chunk_cap))
        c = min(limit, n - start)
        b = min(self._bucket(c), self.prefill_chunk_len)
        if b not in self._prefills:
            self._prefills[b] = self._capture_jit(
                self._prefill_impl, donate_argnums=(1,),
                name="serving.paged_prefill",
                warm={"program": "prefill", "bucket": b,
                      **self._warm_geo()})
        padded = np.zeros((1, b), np.int32)
        padded[0, :c] = ids[start:start + c]
        row = jnp.asarray(self._kv.block_tables[slot])
        tok, self.kvs = self._prefills[b](
            self.params, self.kvs, jnp.asarray(padded), row,
            jnp.int32(start), jnp.int32(c), jnp.int32(n))
        st["next"] = start + c
        # publish every fully-written prompt block into the radix
        # tree as soon as its last token lands: a concurrent
        # admission can hit a prefix whose OWNER is still prefilling
        # its tail (content-identical blocks dedupe against existing
        # nodes, remapping the table to the cached copy)
        self._kv.commit_prefix(slot, ids, st["next"])
        if st["next"] < n:
            # draft prefill rides the same interleave budget: one
            # draft chunk per target chunk (same chunk length — a
            # make_draft view — finishes in lockstep; an arbitrary
            # second engine catches up on the final chunk below)
            if self._draft is not None \
                    and slot in self._draft._prefill_state:
                self._draft.prefill_chunk(slot)
            return None
        first = int(tok)
        del self._prefill_state[slot]
        self.pos[slot] = n
        self.active[slot] = True
        self.last_ids[slot, 0] = first
        if self._draft is not None:
            while slot in self._draft._prefill_state:
                self._draft.prefill_chunk(slot)
            # the draft's stream mirrors the TARGET's: its own
            # prefill token is discarded, the target's first token
            # seeds both engines' next step
            self._draft.last_ids[slot, 0] = first
        return first

    def prefill(self, slot: int, prompt_ids,
                budget: Optional[int] = None) -> int:
        """One-shot prefill (dense-API compat: tests / direct use):
        admits with ``budget`` generation tokens reserved (default:
        the worst case, max_seq - len(prompt)) and runs every chunk
        back to back. The server path uses begin_request +
        prefill_chunk instead to interleave with decode."""
        prompt_ids = np.asarray(prompt_ids, np.int32).reshape(-1)
        n = int(prompt_ids.shape[0])
        if budget is None:
            budget = self.max_seq - n
        if not self.begin_request(slot, prompt_ids, budget):
            raise RuntimeError(
                f"KV block pool exhausted admitting slot {slot} "
                f"({self._kv.stats()}); release a slot or raise "
                f"FLAGS_serving_num_blocks")
        while True:
            first = self.prefill_chunk(slot)
            if first is not None:
                return first

    def _extend_tables(self) -> None:
        """Step-boundary block extension: map the block covering each
        active slot's next write position (drawn from its admission
        reservation, so this cannot fail)."""
        for s in range(self.max_slots):
            if self.active[s]:
                self._shared_write_guard(s)
                self._kv.ensure_token(s, int(self.pos[s]))

    def step(self) -> np.ndarray:
        """One decode iteration for ALL active slots; returns next
        token per slot (garbage for inactive slots — callers consult
        .active). With a draft attached, the draft runs a mirrored
        (cheap, truncated-layer) step on the SAME inputs so its KV
        cache stays complete — a plain-step iteration (capacity
        fallback, direct use) must not punch holes in the draft's
        history, or every later speculation window would propose from
        garbage and acceptance would silently collapse."""
        self._extend_tables()
        draft = self._draft
        act = jnp.asarray(self.active)
        ids = jnp.asarray(self.last_ids)
        pos = jnp.asarray(self.pos)
        if draft is not None:
            for s in range(self.max_slots):
                if self.active[s]:
                    draft._shared_write_guard(s)
                    draft._kv.ensure_token(s, int(self.pos[s]))
            _, draft.kvs = draft._decode(
                draft.params, draft.kvs, ids, pos,
                jnp.asarray(draft._kv.block_tables), act)
        nxt, self.kvs = self._decode(
            self.params, self.kvs, ids, pos,
            jnp.asarray(self._kv.block_tables), act)
        self._count_pa_path()
        nxt = np.asarray(nxt)
        for s in range(self.max_slots):
            if self.active[s]:
                self.pos[s] += 1
                self.last_ids[s, 0] = nxt[s]
                if draft is not None:
                    draft.pos[s] = self.pos[s]
                    draft.last_ids[s, 0] = nxt[s]
        return nxt

    def spec_ready(self) -> bool:
        """True when the next iteration can run speculatively: a
        draft is attached, at least one slot is active, and every
        active slot has room for the whole verify window (a slot
        within ``spec_k`` tokens of capacity drops the batch to plain
        single-token steps for that iteration — correctness never
        depends on the window fitting). A brownout
        (``_spec_suppressed``, set by the adaptive admission policy at
        a step boundary) also drops to plain steps: under block
        pressure the +spec_k window pre-extension is exactly the
        block draw to shed first."""
        if self._draft is None or self._spec_suppressed:
            return False
        act = [s for s in range(self.max_slots) if self.active[s]]
        if not act:
            return False
        k = self._spec_k
        return all(int(self.pos[s]) + k + 1 <= self.max_seq - 1
                   for s in act)

    def spec_step(self):
        """One SPECULATIVE decode iteration for all active slots: the
        draft proposes ``spec_k`` tokens (one captured executable,
        device-chained), the target verifies the whole window in one
        batched paged-attention call (a second captured executable),
        and ONE host fetch closes the window — the same fetch budget
        as a single plain step, for up to ``spec_k`` committed tokens.

        Greedy acceptance: with d1..dk the draft's proposals and
        t0..tk the target's greedy tokens per window position, the
        committed prefix is t[:m], m = min(|leading d_{i+1}==t_i|+1,
        k) — every committed token conditions on a committed prefix,
        so the stream is BIT-equal to non-speculative decode. The
        first rejection truncates ``pos`` and rolls the rejected
        suffix's block writes back through
        ``PagedKVCache.truncate`` (re-crediting the admission
        reservation); a fully-accepted window commits k tokens and
        leaves both engines exactly one pending write behind, the
        plain-step invariant.

        Returns ``(tokens [S, k+1], counts [S])``: row s's first
        ``counts[s]`` tokens are the committed stream continuation
        (garbage for inactive slots — callers consult ``.active``)."""
        k = self._spec_k
        draft = self._draft
        for s in range(self.max_slots):
            if self.active[s]:
                # window pre-extension, drawn from the +spec_k
                # admission margin: target writes [pos, pos+k],
                # draft writes [pos, pos+k-1]. Both engines COW-guard
                # the shared prefix first — a spec write must never
                # land in an aliased block (rollback would then rip
                # tokens out of every sharer's stream)
                self._shared_write_guard(s)
                draft._shared_write_guard(s)
                self._kv.reserve_through(s, int(self.pos[s]) + k)
                draft._kv.reserve_through(s, int(self.pos[s]) + k - 1)
        last = jnp.asarray(self.last_ids)
        pos = jnp.asarray(self.pos)
        act = jnp.asarray(self.active)
        draft_tok, draft.kvs = self._spec_propose(
            draft.params, draft.kvs, last, pos,
            jnp.asarray(draft._kv.block_tables), act)
        t, n_acc, self.kvs = self._spec_verify(
            self.params, self.kvs, last, draft_tok, pos,
            jnp.asarray(self._kv.block_tables), act)
        self._count_pa_path()
        toks = np.asarray(t)
        acc = np.asarray(n_acc)
        counts = np.minimum(acc + 1, k).astype(np.int32)
        proposed = accepted = rolled = 0
        for s in range(self.max_slots):
            if not self.active[s]:
                continue
            m = int(counts[s])
            self.pos[s] += m
            self.last_ids[s, 0] = toks[s, m - 1]
            draft.pos[s] = self.pos[s]
            draft.last_ids[s, 0] = toks[s, m - 1]
            rolled += self._kv.truncate(s, int(self.pos[s]))
            rolled += draft._kv.truncate(s, int(self.pos[s]))
            proposed += k
            accepted += int(acc[s])
        _M_spec_steps.inc()
        if proposed:
            _M_spec_proposed.inc(proposed)
        if accepted:
            _M_spec_accepted.inc(accepted)
        if rolled:
            _M_spec_rolled.inc(rolled)
        _flight.record("serving", "spec_step", proposed=proposed,
                       accepted=accepted, rolled_back=rolled)
        return toks, counts

    def decode_steps(self, n: int) -> np.ndarray:
        """``n`` chained decode iterations with DEVICE-resident token
        feedback (one host fetch closes the window) — the dense
        engine's contract over the block pool. Blocks for the whole
        window are mapped up front so the device-side table stays
        valid without host round-trips."""
        if not self.active.all():
            raise ValueError(
                "decode_steps advances EVERY slot; use step() when "
                "some slots are free (the continuous-batching server "
                "path)")
        if int(self.pos.max()) + n > self.max_seq - 1:
            raise ValueError(
                f"decode_steps({n}) would write past the "
                f"{self.max_seq}-token capacity (max pos "
                f"{int(self.pos.max())})")
        for s in range(self.max_slots):
            self._shared_write_guard(s)
            self._kv.reserve_through(s, int(self.pos[s]) + n - 1)
        if self._decode_collect is None:
            self._decode_collect = self._capture_jit(
                self._decode_collect_impl, donate_argnums=(1, 4),
                name="serving.paged_decode_window")
        ids = jnp.asarray(self.last_ids)
        pos = jnp.asarray(self.pos)
        tables = jnp.asarray(self._kv.block_tables)
        act = jnp.asarray(self.active)
        buf = jnp.zeros((self.max_slots, n), jnp.int32)
        for i in range(n):
            nxt, self.kvs, buf = self._decode_collect(
                self.params, self.kvs, ids, pos, buf, jnp.int32(i),
                tables, act)
            ids = nxt[:, None]
            pos = pos + 1
        self._count_pa_path(n)
        toks = np.asarray(buf)                      # the one fetch
        self.pos += n
        self.last_ids = toks[:, -1:].astype(np.int32).copy()
        return toks

    def generate(self, prompt_ids, max_new_tokens: int = 32,
                 slot: int = 0) -> List[int]:
        """Single-request convenience path over the block pool: the
        admission reservation is sized to ``max_new_tokens`` so a
        short request holds only its own blocks."""
        out = [self.prefill(slot, prompt_ids, budget=max_new_tokens)]
        for _ in range(max_new_tokens - 1):
            if self.eos_id is not None and out[-1] == self.eos_id:
                break
            if self.pos[slot] >= self.max_seq - 1:
                break
            out.append(int(self.step()[slot]))
        self.release(slot)
        return out

    def release(self, slot: int, evicted: bool = False) -> None:
        """Free the slot AND return its blocks + reservation to the
        pool; ``evicted=True`` (expiry/failure/cancellation) counts
        them into ``serving.block_evictions_total``. An attached
        draft releases its mirrored slot in the same call."""
        self.active[slot] = False
        self.pos[slot] = 0
        self._prefill_state.pop(slot, None)
        self.prefix_hit_tokens.pop(slot, None)
        self._kv.release(slot, evicted=evicted)
        if self._draft is not None:
            self._draft.release(slot, evicted=evicted)

    def _prewarm_entry(self, entry):
        """Paged warm-bundle replay: decode, prefill (per recorded
        bucket) and — with a draft attached — the speculative
        propose/verify pair, each rebuilt AOT over the live block-pool
        geometry (``lower().compile()`` = a persistent-cache disk
        read). Spec entries without a draft return False (skipped, not
        failed): the bundle writer's topology simply doesn't apply.
        Entries recorded against a DIFFERENT serving geometry
        (slots/blocks/chunk/spec_k — ``_bundle_stale``) return
        ``"stale"``: replaying them would compile fresh programs at
        boot while the counters claim warmth, so the caller counts
        ``warmup.failures_total{reason=stale}`` and boots cold
        instead."""
        meta = entry.get("meta") or {}
        prog = meta.get("program")
        if prog in ("spec_draft", "spec_verify") and self._draft is None:
            return False
        if prog in ("decode", "prefill", "spec_draft", "spec_verify"):
            # every paged program's shape depends on the POOL geometry;
            # the prefill chunk is NOT part of any program shape — it
            # only bounds which buckets are reachable, so a prefill
            # entry is stale exactly when its recorded bucket exceeds
            # the live chunk, and decode/spec entries ignore it
            stale = self._bundle_stale(
                meta, ("layout", "slots", "max_seq", "block_size",
                       "num_blocks"))
            if prog == "prefill" and isinstance(meta.get("bucket"),
                                                int) \
                    and meta["bucket"] > self.prefill_chunk_len:
                stale.append("bucket")
            if prog in ("spec_draft", "spec_verify") \
                    and "k" in meta and meta["k"] != self._spec_k:
                stale.append("k")
            if stale:
                _flight.record("warmup", "stale_entry",
                               program=str(prog),
                               mismatches=",".join(stale))
                return "stale"
        S = self.max_slots
        # NumPy-backed helper args (device_put, no compiled fill
        # programs): pre-warm must never compile anything the bundle's
        # writer didn't
        ids = jnp.asarray(np.zeros((S, 1), np.int32))
        pos = jnp.asarray(np.zeros(S, np.int32))
        tables = jnp.asarray(self._kv.block_tables)
        act = jnp.asarray(np.zeros(S, bool))
        if prog == "decode":
            self._decode._jitted.lower(
                self.params, self.kvs, ids, pos, tables, act).compile()
        elif prog == "prefill":
            b = int(meta.get("bucket", 0) or
                    min(self._bucket(1), self.prefill_chunk_len))
            if b not in self._prefills:
                # same warm meta as the prefill_chunk registration:
                # a bundle RE-exported by this prewarmed replica must
                # carry the geometry too, or its entries would bypass
                # the freshness check downstream
                self._prefills[b] = self._capture_jit(
                    self._prefill_impl, donate_argnums=(1,),
                    name="serving.paged_prefill",
                    warm={"program": "prefill", "bucket": b,
                          **self._warm_geo()})
            self._prefills[b]._jitted.lower(
                self.params, self.kvs,
                jnp.asarray(np.zeros((1, b), np.int32)),
                jnp.asarray(self._kv.block_tables[0]),
                _I32, _I32, _I32).compile()
        elif prog == "spec_draft":
            draft = self._draft
            if draft is None:
                return False
            self._spec_propose._jitted.lower(
                draft.params, draft.kvs, ids, pos,
                jnp.asarray(draft._kv.block_tables), act).compile()
        elif prog == "spec_verify":
            if self._draft is None:
                return False
            self._spec_verify._jitted.lower(
                self.params, self.kvs, ids,
                jnp.asarray(np.zeros((S, self._spec_k), np.int32)),
                pos, tables, act).compile()
        else:
            return False
        _flight.record("warmup", "serving_program", program=str(prog))
        return True

    def export_decode(self):
        """AOT-serialize the PAGED decode step via jax.export: the
        signature carries the block pools, per-slot block tables and
        the active mask, so a serving process can run the streaming
        decode step without this class."""
        avals = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
            (self.params, self.kvs, jnp.asarray(self.last_ids),
             jnp.asarray(self.pos), jnp.asarray(self._kv.block_tables),
             jnp.asarray(self.active)))
        exported = jax.export.export(jax.jit(self._decode_impl))(*avals)
        return exported.serialize()


class GenerationServer:
    """Iteration-level continuous batching around a LlamaDecodeEngine:
    requests are admitted into free slots at step boundaries, every
    step advances all active requests together, finished requests free
    their slot for the next admission — no request waits for another
    to finish (ref role: the multi-stream request loop of the
    reference's serving predictor).

    With a :class:`PagedLlamaDecodeEngine` the loop additionally
    splits prefill from decode: admission allocates + reserves KV
    blocks (pool exhaustion defers the request — it WAITS for blocks,
    it never crashes the loop), and each iteration advances at most
    ONE prompt chunk before the decode step, so a long prompt admitted
    mid-stream costs already-decoding requests one chunk forward per
    step instead of the whole prompt.

    Robustness contract: ``submit(..., deadline=s)`` bounds a request's
    wall time — expiry (checked at step boundaries; queued, waiting
    for blocks, prefilling or active) fails THAT request with
    TimeoutError, keeping whatever tokens it already produced in
    ``req["out"]`` (and returning its KV blocks as counted evictions).
    ``shutdown()`` drains: new submissions are rejected immediately,
    in-flight and already-queued requests run to completion, then the
    loop exits — no completed token is ever dropped by a shutdown.

    Self-healing plane (``serving_supervisor``): admission routes
    through a policy object (``policy=`` /
    ``FLAGS_serving_admission_policy``) consulted at submit and fed
    evidence at step boundaries, and the loop exports the supervision
    seams — a heartbeat (``_beat``/``_idle``), an epoch fence (a
    restarted loop's zombie predecessor exits without touching
    state), and a BaseException boundary that journals the crash and
    refreshes the gauges before the thread dies — so
    ``serving_supervisor.supervise(server)`` can restart a crashed or
    stalled loop and resume its in-flight streams bit-equal from
    their committed tokens."""

    _STOP = object()  # queue sentinel: wake the loop for shutdown

    def __init__(self, engine: LlamaDecodeEngine, policy=None):
        self.engine = engine
        self._paged = bool(getattr(engine, "paged", False))
        self._q: "_queue.Queue" = _queue.Queue()
        self._slots: Dict[int, dict] = {}
        # paged engines split admission from activation: a slot in
        # _prefilling holds blocks and runs one prompt chunk per loop
        # iteration; _waiting holds admitted-order requests deferred
        # because the block pool couldn't cover their reservation yet
        # (the supervisor also re-admits recovered requests through
        # its head, so they precede anything newer)
        self._prefilling: Dict[int, dict] = {}
        self._waiting: List[dict] = []
        self._cancel_waiting = False  # set by shutdown(drain=False)
        self.steps_run = 0
        self.admitted = 0
        self.rejected = 0           # submissions after shutdown/shed
        self.shed = 0               # rejections by load-shedding alone
        self.deadline_rejected = 0  # unmeetable-deadline rejections
        self.deadline_expired = 0   # requests failed by their deadline
        self.weight_swaps = 0       # hot-swaps applied by this loop
        self.tokens_delivered = 0   # committed tokens (policy evidence)
        self.loop_restarts = 0      # supervisor restarts of this loop
        self.recovered = 0          # requests resumed after a crash
        self.quarantined = 0        # poison requests failed, not retried
        # admission policy: a ServingSupervisor-plane object consulted
        # at submit time (admit_verdict) and fed evidence at step
        # boundaries (on_step). Default (None) follows
        # FLAGS_serving_admission_policy — "static" keeps the
        # FLAGS_serving_shed_queue behavior as the fallback policy
        if policy is None:
            from .serving_supervisor import default_policy
            policy = default_policy()
        self.policy = policy
        self._stopping = threading.Event()
        self._drained = threading.Event()
        # orders submit's stopping-check+enqueue against shutdown's
        # stopping.set(): a request that passed the check is enqueued
        # BEFORE stopping becomes visible, so the drain loop (which
        # only exits on stopping AND empty queue) cannot strand it
        from .analysis.locks import make_lock
        self._submit_lock = make_lock("serving.submit")
        # pending weight hot-swap: (state_dict, done Event, result
        # slot), set under the submit lock, applied by the LOOP thread
        # at its next step boundary (never mid-decode)
        self._swap_req = None
        self._metrics_server = None
        # supervision plane: _epoch fences zombie loop threads (a
        # stalled thread that wakes after a supervisor restart sees a
        # newer epoch and exits without touching state), _beat is the
        # loop heartbeat the stall watchdog reads, _idle marks the
        # loop parked on the empty queue (not a stall)
        self._epoch = 0
        self._beat = time.monotonic()
        self._idle = False
        self._start_loop()

    def _start_loop(self) -> None:
        """Start (or, from the supervisor, RESTART) the decode-loop
        thread. The crashed/crash-error markers reset so the
        supervisor can tell this incarnation's death from the last
        one's, and the heartbeat restarts NOW — a restarted loop must
        not inherit the dead one's stale beat, or the stall watchdog
        would re-fire before the new thread's first iteration."""
        self._crashed = False
        self._crash_error: Optional[BaseException] = None
        self._beat = time.monotonic()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="serving-loop")
        self._thread.start()

    def _fenced(self) -> bool:
        """True on a ZOMBIE loop thread: one whose stamped epoch (set
        at its loop entry) no longer matches the server's. Mutation
        paths the loop calls into (_admit_one/_admit_paged/
        _run_prefill) check this before touching request dicts or the
        slot tables, so a stalled thread that wakes mid-recovery
        cannot double-commit tokens or register stale slots beside
        the replacement loop. Non-loop threads (tests driving admit
        helpers directly) carry no stamp and are never fenced."""
        my = getattr(threading.current_thread(),
                     "_serving_loop_epoch", None)
        return my is not None and my != self._epoch

    def _run(self) -> None:
        """Decode-loop thread body: the loop, plus the BaseException
        boundary the satellite audit asked for — a KillPoint (or any
        other escape ``except Exception`` must not swallow) still
        kills this thread, but first the crash is journaled and the
        gauges refreshed so ``queue_depth``/``in_flight`` read the
        TRUE post-crash state (requests still holding slots/blocks)
        instead of whatever the last completed step boundary wrote.
        The re-raise keeps ``threading.excepthook`` crash forensics
        (automatic flight dump) intact."""
        try:
            self._loop()
        except BaseException as e:
            self._crashed = True
            self._crash_error = e
            _flight.record("serving", "loop_crashed",
                           error=type(e).__name__,
                           in_flight=len(self._slots)
                           + len(self._prefilling))
            self._set_gauges()
            raise

    def _apply_brownout(self, spec_off: bool,
                        chunk_cap: Optional[int]) -> None:
        """Install the adaptive policy's brownout knobs on the engine
        (step-boundary-safe: both only steer which ALREADY-COMPILED
        program the next iteration picks)."""
        eng = self.engine
        eng._spec_suppressed = bool(spec_off)
        eng._chunk_cap = chunk_cap

    def metrics_endpoint(self, port: int = 0, host: str = "127.0.0.1"):
        """Serve the process metrics registry over HTTP: ``GET /metrics``
        (Prometheus text exposition) + ``/metrics.json`` (the nested
        snapshot) + ``/healthz`` (readiness: decode loop alive,
        supervisor not given up, admission pressure — the same snapshot
        the fleet router's probe reads). Idempotent per server; the
        endpoint is closed by ``shutdown()``. Returns the handle
        (``.url``, ``.port``, ``.close()``)."""
        if self._metrics_server is None:
            from .observability.http import start_metrics_server
            from .serving_fleet import health_snapshot
            self._metrics_server = start_metrics_server(
                port=port, host=host,
                health_cb=lambda: health_snapshot(self))
        return self._metrics_server

    def submit(self, prompt_ids, max_new_tokens: int = 32,
               deadline: Optional[float] = None) -> dict:
        """Enqueue a request. ``deadline`` (seconds from now) bounds its
        total wall time; None = unbounded. The returned dict carries
        ``trace_id`` — the key of this request's flight-recorder
        lifecycle trail (see :meth:`trace`)."""
        trace_id = f"req-{next(_REQ_SEQ)}"
        _flight.record("serving", "submit", trace_id=trace_id,
                       max_new=int(max_new_tokens))
        if self._stopping.is_set():
            self.rejected += 1
            _M_rejected.inc()
            _flight.record("serving", "rejected", trace_id=trace_id,
                           reason="shutting_down")
            raise RuntimeError(
                "GenerationServer is shutting down; new submissions are "
                "rejected (in-flight requests are draining)")
        if int(max_new_tokens) < 1:
            _flight.record("serving", "rejected", trace_id=trace_id,
                           reason="invalid_max_new")
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens} "
                f"(prefill always produces the first token)")
        if deadline is not None and deadline <= 0:
            _flight.record("serving", "rejected", trace_id=trace_id,
                           reason="invalid_deadline")
            raise ValueError(f"deadline must be > 0, got {deadline}")
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        # the admission policy decides here, on submit's thread, from
        # evidence the loop refreshed at its last step boundary:
        # "shed" (hard overload) or "deadline" (the request could not
        # finish in time at the observed rate — rejecting NOW spares
        # its blocks AND the caller's wait)
        verdict = self.policy.admit_verdict(
            self, int(prompt.shape[0]), int(max_new_tokens), deadline)
        if verdict is not None:
            self.rejected += 1
            _M_rejected.inc()
            if verdict == "deadline":
                self.deadline_rejected += 1
                _M_deadline_rej.inc()
            else:
                self.shed += 1
                _M_shed.inc()
            _flight.record("serving", "rejected", trace_id=trace_id,
                           reason=verdict,
                           policy=self.policy.name,
                           waiting=len(self._waiting))
            raise RuntimeError(
                f"request rejected by the {self.policy.name} admission "
                f"policy (reason={verdict}): "
                + ("its deadline cannot be met at the observed decode "
                   "rate — retry with a larger deadline or fewer "
                   "tokens" if verdict == "deadline" else
                   "the replica is overloaded (KV blocks exhausted "
                   "with a deferred backlog) — retry later or raise "
                   "FLAGS_serving_num_blocks"))
        req = {"prompt": prompt,
               "max_new": int(max_new_tokens), "out": [],
               "done": threading.Event(), "error": None,
               "trace_id": trace_id,
               "t0": time.monotonic(),
               "expires": (time.monotonic() + deadline
                           if deadline is not None else None)}
        with self._submit_lock:
            if self._stopping.is_set():
                self.rejected += 1
                _M_rejected.inc()
                _flight.record("serving", "rejected", trace_id=trace_id,
                               reason="shutting_down")
                raise RuntimeError(
                    "GenerationServer is shutting down; new submissions "
                    "are rejected (in-flight requests are draining)")
            self._q.put(req)
        _flight.record("serving", "queued", trace_id=trace_id,
                       prompt_len=int(req["prompt"].shape[0]))
        return req

    def generate(self, prompt_ids, max_new_tokens: int = 32,
                 timeout: float = 300.0,
                 deadline: Optional[float] = None) -> List[int]:
        req = self.submit(prompt_ids, max_new_tokens, deadline=deadline)
        if not req["done"].wait(timeout):
            raise TimeoutError("generation timed out")
        if req["error"] is not None:
            raise req["error"]
        return list(req["out"])

    @staticmethod
    def _swap_state(source) -> dict:
        """Normalize a swap source into a model state dict ON THE
        CALLER'S THREAD (disk reads and CRC verification never stall
        the decode loop): a ``CheckpointManager`` restores its newest
        good checkpoint, a path loads through the verifying
        ``framework.checkpoint`` reader, a dict passes through —
        with the conventional 'model'/'state_dict' sub-tree peeled
        off by ``extract_state_dict``."""
        from .framework.checkpoint import (CheckpointManager,
                                           extract_state_dict,
                                           load_checkpoint)
        if isinstance(source, CheckpointManager):
            got = source.restore()
            if got is None:
                raise ValueError(
                    f"no loadable checkpoint under {source.root!r} to "
                    f"swap from")
            source = got[1]
        elif isinstance(source, str):
            source = load_checkpoint(source)
        return extract_state_dict(source)

    def swap_weights(self, checkpoint_or_state=None,
                     timeout: Optional[float] = 300.0, *,
                     prepared=None) -> dict:
        """Zero-downtime weight hot-swap: install new weights into the
        running engine BETWEEN decode steps, without dropping or
        corrupting any in-flight request — their KV blocks and partial
        streams are untouched and the next decode step runs on the new
        weights (an attached weight-sharing draft rolls in the same
        swap).

        ``checkpoint_or_state``: a model state dict, a checkpoint path
        (verified by the ``framework.checkpoint`` reader), or a
        ``CheckpointManager`` (its newest good checkpoint). Weight
        prep (disk I/O + the full host->device build,
        :meth:`~LlamaDecodeEngine.prepare_swap`) happens on THIS
        thread; the loop thread only validates + pointer-installs at
        its next step boundary. Same shapes/dtypes ⇒ zero recompiles;
        any mismatch raises here with the old weights intact (counted
        in ``serving.weight_swaps_rejected_total``). Returns swap
        stats (``seconds``, ``in_flight`` at the boundary, ...). A
        timeout clears the request if the loop has not yet claimed
        it, so a later swap can be submitted.

        ``prepared=`` bypasses the prep: a device tree already in the
        engine's layout (``prepare_swap``'s output — or a RETAINED
        pre-swap ``engine.params``, which is how the canary rollout
        rolls a bad checkpoint back without re-reading disk)."""
        if prepared is not None:
            prepped = prepared
        else:
            sd = self._swap_state(checkpoint_or_state)
            try:
                prepped = self.engine.prepare_swap(sd)
            except Exception:
                _M_swap_rejected.inc()
                _flight.record("serving", "swap_end", ok=False,
                               error="prepare")
                raise
        done = threading.Event()
        slot: dict = {}
        with self._submit_lock:
            if self._stopping.is_set():
                raise RuntimeError(
                    "GenerationServer is shutting down; weights cannot "
                    "be swapped into a draining loop")
            if self._swap_req is not None:
                raise RuntimeError(
                    "a weight swap is already pending; wait for it "
                    "before submitting another")
            self._swap_req = (prepped, done, slot)
        self._q.put(self._STOP)  # wake an idle loop (sentinel no-op)
        if not done.wait(timeout):
            with self._submit_lock:
                cancelled = (self._swap_req is not None
                             and self._swap_req[1] is done)
                if cancelled:
                    self._swap_req = None
            raise TimeoutError(
                f"weight swap not applied within {timeout}s — "
                + ("cancelled before the loop claimed it"
                   if cancelled else
                   "the loop claimed it mid-apply; it may still land"))
        if "error" in slot:
            raise slot["error"]
        return slot["result"]

    def _shed(self) -> bool:
        """The STATIC load-shedding rule (ROADMAP 1c) — now the
        fallback policy behind ``serving_supervisor.StaticShedPolicy``
        (the default) and the adaptive policy's floor: shed
        when admission is block-starved (``serving.blocks_free`` at
        zero AND a request is already deferred on blocks — the
        signal that queue_seconds is about to climb) and the waiting
        backlog (deferred + queued, the ``queue_depth`` gauge's own
        sum — hold-the-line fairness keeps the deferred list itself
        at one) exceeds ``FLAGS_serving_shed_queue``. 0 (default)
        disables the policy — exhaustion defers unboundedly as
        before."""
        from .core.flags import flag_value
        bound = int(flag_value("serving_shed_queue"))
        if not self._paged or bound <= 0:
            return False
        return (self._waiting != []
                and self._q.qsize() + len(self._waiting) > bound
                and self.engine._kv.available_blocks() <= 0)

    def _expired(self, req) -> bool:
        return (req["expires"] is not None
                and time.monotonic() > req["expires"])

    def _fail(self, req, error) -> None:
        req["error"] = error
        req["done"].set()
        _M_failed.inc()
        _flight.record(
            "serving",
            "expired" if isinstance(error, TimeoutError) else "failed",
            trace_id=req.get("trace_id"), error=type(error).__name__,
            tokens=len(req["out"]))
        self._observe_done(req)

    @staticmethod
    def _observe_done(req) -> None:
        """Request-completion telemetry: tokens delivered (partial counts
        too — a deadline-failed request keeps its tokens) + wall time +
        per-token latency, plus the queue/decode latency split."""
        tokens = len(req["out"])
        if tokens:
            _M_tokens.inc(tokens)
        now = time.monotonic()
        dt = now - req["t0"]
        _M_req_s.observe(dt)
        _M_token_s.observe(dt / max(tokens, 1))
        t_admit = req.get("t_admit")
        if t_admit is not None:
            _M_decode_s.observe(now - t_admit)
        else:
            # never admitted (deadline expired / cancelled while
            # queued): its whole life WAS queue time. Without this the
            # histogram only sees survivors — under the very overload
            # the metric exists to expose, the starved majority would
            # be censored and queue_seconds would stay low
            _M_queue_s.observe(dt)

    def _admit_one(self, req, slot) -> None:
        eng = self.engine
        if req is self._STOP or req["done"].is_set():
            return  # sentinel, or already failed while queued
        if self._expired(req):
            self.deadline_expired += 1
            _M_expired.inc()
            self._fail(req, TimeoutError(
                "request deadline expired while queued"))
            return
        # stamp admission BEFORE prefill: queue_seconds is the pure
        # submit->admission wait and decode_seconds covers prefill +
        # decode (slow prefill must not masquerade as queueing — the
        # load-shedding signal would point at admission when the real
        # cost is the model). t_queue0 rebases the origin for
        # crash-recovered requests: their pre-crash DECODE time is
        # not admission starvation
        req["t_admit"] = time.monotonic()
        _M_queue_s.observe(req["t_admit"] - req.get("t_queue0",
                                                    req["t0"]))
        try:
            first = eng.prefill(slot, req["prompt"])
        except Exception as e:  # noqa: BLE001 — surfaced per request
            if self._fenced():
                return  # zombie: the request was already re-admitted
            self._fail(req, e)
            return
        if self._fenced():
            return  # zombie woke from a wedged prefill: the new loop
            # owns this request — committing here would duplicate its
            # stream and register a stale slot
        req["out"].append(first)
        self._slots[slot] = req
        self.admitted += 1
        _M_admitted.inc()
        _flight.record("serving", "admitted",
                       trace_id=req.get("trace_id"), slot=slot)
        self._finish_if_done(slot, req)

    def _release_slot(self, slot, evicted: bool = False) -> None:
        """Free an engine slot on a failure/expiry path. Only paged
        engines take the eviction marker (it feeds
        serving.block_evictions_total); duck-typed dense engines keep
        the bare release(slot) contract."""
        if self._paged:
            self.engine.release(slot, evicted=evicted)
        else:
            self.engine.release(slot)

    def _free_slots(self):
        eng = self.engine
        return [s for s in range(eng.max_slots)
                if not eng.active[s] and s not in self._prefilling]

    def _admit_paged(self, req, slot) -> str:
        """Paged admission: allocate + reserve blocks and start the
        chunked prefill. Returns 'admitted', 'defer' (pool cannot
        cover the reservation yet — exhaustion queues, never
        crashes) or 'dropped' (sentinel/expired/failed)."""
        eng = self.engine
        if req is self._STOP or req["done"].is_set() \
                or self._fenced():
            return "dropped"
        if self._expired(req):
            self.deadline_expired += 1
            _M_expired.inc()
            self._fail(req, TimeoutError(
                "request deadline expired while queued"))
            return "dropped"
        try:
            # budget = REMAINING tokens: a crash-recovered request
            # re-admits with prompt + committed tokens as its prompt,
            # so reserving the full max_new again would over-draw the
            # pool for work already delivered (fresh requests have
            # empty out — identical behavior)
            ok = eng.begin_request(
                slot, req["prompt"],
                max(req["max_new"] - len(req["out"]), 1))
        except Exception as e:  # noqa: BLE001 — surfaced per request
            self._fail(req, e)
            return "dropped"
        if not ok:
            return "defer"
        req["t_admit"] = time.monotonic()
        # t_queue0 = recovery rebase (see _admit_one)
        _M_queue_s.observe(req["t_admit"] - req.get("t_queue0",
                                                    req["t0"]))
        # per-request prefix accounting: tokens this admission served
        # from shared radix blocks (0 = cold prompt), readable off the
        # finished request next to its tokens/latency (getattr:
        # duck-typed fake engines keep the bare paged contract)
        req["prefix_hit_tokens"] = getattr(
            eng, "prefix_hit_tokens", {}).get(slot, 0)
        self._prefilling[slot] = req
        self.admitted += 1
        _M_admitted.inc()
        _flight.record("serving", "admitted",
                       trace_id=req.get("trace_id"), slot=slot,
                       prefix_hit=req["prefix_hit_tokens"])
        return "admitted"

    def _admit(self):
        if not self._paged:
            free = self._free_slots()
            # supervisor-recovered requests land in _waiting (dense
            # engines never defer on blocks, so this list is otherwise
            # empty): admit them ahead of the queue, oldest first
            while free and self._waiting:
                req = self._waiting.pop(0)
                if req["done"].is_set():
                    continue
                self._admit_one(req, free[0])
                if req["done"].is_set() and req["error"] is not None:
                    continue  # rejected before prefill: slot still free
                free.pop(0)
            while free:
                try:
                    req = self._q.get_nowait()
                except _queue.Empty:
                    return
                if req is self._STOP or req["done"].is_set():
                    continue  # sentinel, or failed while queued
                self._admit_one(req, free[0])
                if req["done"].is_set() and req["error"] is not None:
                    continue  # rejected before prefill: slot still free
                free.pop(0)
            return
        if self._cancel_waiting:
            # shutdown(drain=False) signalled: cancel block-deferred
            # requests HERE, on the loop thread — failing them from
            # the shutdown thread would race this function's
            # done-check-then-admit sequence (a request could be
            # cancelled and admitted simultaneously)
            for req in self._waiting:
                if not req["done"].is_set():
                    self._fail(req, RuntimeError(
                        "request cancelled: server shut down before "
                        "admission"))
            self._waiting = []
        free = self._free_slots()
        # block-deferred requests retry first, and HOLD THE LINE: while
        # any of them still cannot be covered, nothing newer is pulled
        # from the queue — otherwise a stream of small later requests
        # would keep re-consuming every freed block and starve a large
        # deferred request forever (fairness over utilization; the
        # backlog accrues queue_seconds and deadlines as usual)
        still: List[dict] = []
        for req in self._waiting:
            if req["done"].is_set():
                continue  # cancelled/expired while deferred
            if not free:
                still.append(req)
                continue
            verdict = self._admit_paged(req, free[0])
            if verdict == "admitted":
                free.pop(0)
            elif verdict == "defer":
                still.append(req)
        self._waiting = still
        while free and not self._waiting:
            try:
                req = self._q.get_nowait()
            except _queue.Empty:
                return
            verdict = self._admit_paged(req, free[0])
            if verdict == "admitted":
                free.pop(0)
            elif verdict == "defer":
                self._waiting.append(req)

    def _run_prefill(self):
        """Advance ONE prompt chunk of the OLDEST-admitted prefilling
        slot (dict insertion order — slot-index order would let a
        newer request admitted into a lower slot starve an older
        in-progress prefill) — the prefill/decode interleave: each
        loop iteration costs at most one chunk forward on top of the
        decode step, so already-admitted slots keep streaming."""
        for slot in list(self._prefilling):
            req = self._prefilling[slot]
            try:
                first = self.engine.prefill_chunk(slot)
            except Exception as e:  # noqa: BLE001 — per-request
                if self._fenced():
                    return  # zombie: recovery owns the request now
                del self._prefilling[slot]
                self._release_slot(slot, evicted=True)
                self._fail(req, e)
                return
            if self._fenced():
                return  # zombie woke from a wedged chunk: commit
                # nothing — the new loop re-admitted this request
            if first is not None:
                del self._prefilling[slot]
                req["out"].append(first)
                self._slots[slot] = req
                _flight.record("serving", "prefilled",
                               trace_id=req.get("trace_id"), slot=slot,
                               prompt_len=int(req["prompt"].shape[0]))
                self._finish_if_done(slot, req)
            return

    def _finish_if_done(self, slot, req):
        eng = self.engine
        done = (len(req["out"]) >= req["max_new"]
                or (eng.eos_id is not None
                    and req["out"][-1] == eng.eos_id)
                or eng.pos[slot] >= eng.max_seq - 1)
        if done:
            eng.release(slot)
            del self._slots[slot]
            req["done"].set()
            _flight.record("serving", "finished",
                           trace_id=req.get("trace_id"),
                           tokens=len(req["out"]))
            self._observe_done(req)
        return done

    def _expire_active(self):
        """Step-boundary deadline sweep over active, prefilling and
        block-waiting requests: an expired request is failed with
        TimeoutError and its slot/blocks freed (paged blocks count as
        EVICTIONS — serving.block_evictions_total); tokens already
        produced stay in ``req['out']``."""
        for slot in list(self._slots):
            req = self._slots[slot]
            if self._expired(req):
                self.deadline_expired += 1
                _M_expired.inc()
                self._release_slot(slot, evicted=True)
                del self._slots[slot]
                self._fail(req, TimeoutError(
                    f"request deadline expired after "
                    f"{len(req['out'])} token(s)"))
        for slot in list(self._prefilling):
            req = self._prefilling[slot]
            if self._expired(req):
                self.deadline_expired += 1
                _M_expired.inc()
                self._release_slot(slot, evicted=True)
                del self._prefilling[slot]
                self._fail(req, TimeoutError(
                    "request deadline expired during prefill"))
        still = []
        for req in self._waiting:
            if not req["done"].is_set() and self._expired(req):
                self.deadline_expired += 1
                _M_expired.inc()
                self._fail(req, TimeoutError(
                    "request deadline expired waiting for KV blocks"))
            elif not req["done"].is_set():
                still.append(req)
        self._waiting = still

    def _expire_queued(self):
        """Fail expired requests still WAITING in the queue — even when
        every slot is busy, a starved request's caller is unblocked at
        the next step boundary, not when a slot eventually frees. The
        failed entry stays enqueued; _admit() discards it on dequeue."""
        with self._q.mutex:
            waiting = list(self._q.queue)
        for req in waiting:
            if req is not self._STOP and not req["done"].is_set() \
                    and self._expired(req):
                self.deadline_expired += 1
                _M_expired.inc()
                self._fail(req, TimeoutError(
                    "request deadline expired while queued"))

    def _apply_pending_swap(self) -> None:
        """Apply a pending weight hot-swap HERE, on the loop thread,
        at a step boundary: the previous decode step has fully
        committed its tokens and no new step has dispatched, so no
        in-flight request drops or corrupts a token — its KV blocks
        and slot state are untouched and the next step simply runs on
        the new weights. A rejected swap (engine validation) leaves
        the old weights installed and the loop running."""
        if self._swap_req is None:
            return
        with self._submit_lock:  # claim races a caller-side timeout
            req = self._swap_req
            self._swap_req = None
        if req is None:
            return
        prepped, done, slot = req
        t0 = time.perf_counter()
        _flight.record("serving", "swap_begin",
                       in_flight=len(self._slots),
                       prefilling=len(self._prefilling))
        try:
            self.engine.swap_weights(prepared=prepped)
        except Exception as e:  # noqa: BLE001 — surfaced to the caller
            _M_swap_rejected.inc()
            _flight.record("serving", "swap_end", ok=False,
                           error=type(e).__name__)
            slot["error"] = e
            done.set()
            return
        dt = time.perf_counter() - t0
        self.weight_swaps += 1
        _M_swaps.inc()
        _M_swap_s.observe(dt)
        _flight.record("serving", "swap_end", ok=True,
                       seconds=round(dt, 4))
        slot["result"] = {"seconds": dt,
                          "in_flight": len(self._slots),
                          "prefilling": len(self._prefilling),
                          "steps_run": self.steps_run}
        done.set()

    def _loop(self):
        # the epoch captured here fences THIS incarnation: after a
        # supervisor restart (crash or stall), a zombie of the old
        # loop that wakes up sees a newer epoch and exits without
        # touching slots, engine state, or the queue (the thread
        # stamp lets the admit/prefill helpers check the same fence
        # from inside a call the zombie was wedged in)
        my_epoch = self._epoch
        threading.current_thread()._serving_loop_epoch = my_epoch
        while True:
            if self._epoch != my_epoch:
                return  # fenced: a supervisor replaced this loop
            self._beat = time.monotonic()  # stall-watchdog heartbeat
            try:
                self._apply_pending_swap()
                self._admit()
                if self._paged and self._prefilling:
                    self._run_prefill()
                if not self._slots:
                    if self._prefilling or self._waiting:
                        # prompts still chunking / requests waiting on
                        # blocks: keep cycling (no decode batch yet)
                        self._expire_active()
                        self._expire_queued()
                        self._set_gauges()
                        self.policy.on_step(self)
                        continue
                    if self._stopping.is_set() and self._q.empty():
                        break  # drained: nothing active, nothing queued
                    # idle: block for the next request and admit it
                    # DIRECTLY — a get-then-requeue would let requests
                    # submitted in the window jump ahead of it (FIFO)
                    self._set_gauges()  # idle: a scrape must read 0
                    self._idle = True   # parked, not stalled
                    try:
                        req = self._q.get()
                    finally:
                        self._idle = False
                    if self._epoch != my_epoch:
                        # fenced while parked: the request belongs to
                        # the NEW loop — hand it back and exit
                        if req is not self._STOP:
                            self._q.put(req)
                        return
                    if req is self._STOP:
                        continue
                    if self._paged:
                        verdict = self._admit_paged(
                            req, self._free_slots()[0])
                        if verdict == "defer":
                            self._waiting.append(req)
                        continue
                    self._admit_one(req, self._free_slots()[0])
                    continue
                # fault-injection site: a kill-point armed here
                # simulates a crash mid-decode — the loop thread dies
                # (KillPoint is a BaseException) and the flight
                # recorder's threading.excepthook dump carries every
                # in-flight request's lifecycle trail
                _fi.fire("serving.decode")
                eng = self.engine
                if self._paged and eng.spec_ready():
                    # speculative iteration: up to spec_k committed
                    # tokens per slot for one step's host fetch; the
                    # greedy stream is bit-equal to plain stepping,
                    # so requests cut off mid-window (eos / budget)
                    # see exactly the tokens they would have anyway
                    toks, counts = eng.spec_step()
                else:
                    # plain stepping is the counts == 1 case of the
                    # same commit loop
                    toks = eng.step()[:, None]
                    counts = np.ones(eng.max_slots, np.int32)
                if self._epoch != my_epoch:
                    return  # fenced mid-step (stall restart): the new
                    # loop owns the slots — do not commit or fail
                self.steps_run += 1
                _M_steps.inc()
                for slot in list(self._slots):
                    req = self._slots[slot]
                    before = len(req["out"])
                    for j in range(int(counts[slot])):
                        tok = int(toks[slot, j])
                        req["out"].append(tok)
                        if len(req["out"]) >= req["max_new"]:
                            break
                        if eng.eos_id is not None \
                                and tok == eng.eos_id:
                            break
                    self.tokens_delivered += len(req["out"]) - before
                    _flight.record("serving", "decode",
                                   trace_id=req.get("trace_id"),
                                   step=self.steps_run,
                                   tokens=len(req["out"]))
                    self._finish_if_done(slot, req)
                self._expire_active()
                self._expire_queued()
                # gauges AFTER the completion/expiry sweep: a scrape
                # between steps must not report finished requests as
                # in-flight
                self._set_gauges()
                # step boundary: feed the admission policy its
                # evidence (EWMAs of blocks/backlog/throughput) and
                # let it move brownout/shed levels
                self.policy.on_step(self)
            except Exception as e:  # noqa: BLE001 — fail loudly, stay up
                if self._epoch != my_epoch:
                    return  # fenced: the slots hold RE-ADMITTED
                    # requests now — failing them here would double
                    # their terminal events
                _flight.record("serving", "loop_error",
                               error=type(e).__name__)
                for slot, req in list(self._slots.items()):
                    self._fail(req, e)
                    self._release_slot(slot, evicted=True)
                self._slots.clear()
                for slot, req in list(self._prefilling.items()):
                    self._fail(req, e)
                    self._release_slot(slot, evicted=True)
                self._prefilling.clear()
                self._set_gauges()
        self._set_gauges()
        # a swap still pending at loop exit can never apply: unblock
        # its caller with the reason instead of letting it time out
        req = self._swap_req
        if req is not None:
            self._swap_req = None
            req[2]["error"] = RuntimeError(
                "server shut down before the weight swap applied")
            req[1].set()
        self._drained.set()

    def _set_gauges(self) -> None:
        # block-deferred requests are still queued work: a scrape must
        # see them (queue_seconds keeps accruing for them too)
        _G_queue.set(self._q.qsize() + len(self._waiting))
        _G_inflight.set(len(self._slots) + len(self._prefilling))

    def shutdown(self, drain: bool = True,
                 timeout: Optional[float] = 300.0) -> bool:
        """Stop the server. ``drain=True`` (default) lets in-flight and
        already-queued requests finish while new submissions are
        rejected; ``drain=False`` additionally cancels everything still
        waiting in the queue (active requests still finish — a decode
        step cannot be abandoned mid-flight without corrupting slots).
        Returns True once the loop has fully drained."""
        with self._submit_lock:
            self._stopping.set()
        if not drain:
            # cancel queued work; requests already in slots complete.
            # Queue pops are atomic (whoever pops a request owns
            # failing it), but the _waiting list belongs to the loop
            # thread — signal it to cancel those at its next admission
            # pass instead of racing its done-check-then-admit sequence
            self._cancel_waiting = True
            while True:
                try:
                    req = self._q.get_nowait()
                except _queue.Empty:
                    break
                if req is not self._STOP:
                    self._fail(req, RuntimeError(
                        "request cancelled: server shut down before "
                        "admission"))
        self._q.put(self._STOP)  # wake an idle loop
        # Event.wait(None) blocks until drained — timeout=None means
        # "wait as long as it takes", never "skip the wait"
        drained = self._drained.wait(timeout)
        if self._metrics_server is not None:
            try:
                self._metrics_server.close()
            finally:
                self._metrics_server = None
        return drained

    @staticmethod
    def trace(request_id) -> List[dict]:
        """The flight-recorder lifecycle trail of ONE request — submit,
        queued, admitted, per-step decode, finished/expired/failed —
        live from the in-process ring (a crash dump carries the same
        events). ``request_id`` is the ``trace_id`` string or the req
        dict :meth:`submit` returned."""
        tid = (request_id.get("trace_id")
               if isinstance(request_id, dict) else request_id)
        return _flight.events(trace_id=tid)

    def stats(self) -> Dict[str, int]:
        with self._q.mutex:  # don't count _STOP sentinels as work
            queued = sum(1 for r in self._q.queue
                         if r is not self._STOP
                         and not r["done"].is_set())
        out = {"steps_run": self.steps_run, "admitted": self.admitted,
               "rejected": self.rejected, "shed": self.shed,
               "deadline_rejected": self.deadline_rejected,
               "deadline_expired": self.deadline_expired,
               "weight_swaps": self.weight_swaps,
               "tokens_delivered": self.tokens_delivered,
               "loop_restarts": self.loop_restarts,
               "recovered": self.recovered,
               "quarantined": self.quarantined,
               "crashed": int(self._crashed),
               "in_flight": len(self._slots), "queued": queued,
               "prefilling": len(self._prefilling),
               "waiting_for_blocks": len(self._waiting),
               "draining": int(self._stopping.is_set()),
               "drained": int(self._drained.is_set())}
        if self._paged:
            out["kv_pool"] = self.engine._kv.stats()
        return out
