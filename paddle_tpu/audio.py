"""paddle.audio equivalent: spectrogram/mel/MFCC features.

ref: python/paddle/audio/ — functional (hz_to_mel/mel_to_hz/
compute_fbank_matrix/create_dct, functional/functional.py) and features
(Spectrogram/MelSpectrogram/LogMelSpectrogram/MFCC, features/layers.py).
Built on paddle_tpu.signal.stft so features compile into the same XLA
program as the model consuming them.
"""
from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from .core.autograd import apply_op
from .core.tensor import Tensor
from .nn.layer import Layer
from . import signal as _signal

__all__ = [
    "hz_to_mel", "mel_to_hz", "compute_fbank_matrix", "create_dct",
    "Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC",
]


def hz_to_mel(freq, htk: bool = False):
    """ref: audio/functional/functional.py hz_to_mel (slaney default)."""
    f = np.asarray(freq, np.float64)
    if htk:
        out = 2595.0 * np.log10(1.0 + f / 700.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        out = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        out = np.where(f >= min_log_hz,
                       min_log_mel + np.log(np.maximum(f, 1e-10)
                                            / min_log_hz) / logstep, out)
    return out if out.shape else float(out)


def mel_to_hz(mel, htk: bool = False):
    m = np.asarray(mel, np.float64)
    if htk:
        out = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        out = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        out = np.where(m >= min_log_mel,
                       min_log_hz * np.exp(logstep * (m - min_log_mel)),
                       out)
    return out if out.shape else float(out)


def compute_fbank_matrix(sr: int, n_fft: int, n_mels: int = 64,
                         f_min: float = 0.0, f_max=None, htk: bool = False,
                         norm: str = "slaney"):
    """[n_mels, n_fft//2+1] triangular mel filter bank (ref: functional.py
    compute_fbank_matrix)."""
    f_max = f_max or sr / 2.0
    fft_freqs = np.linspace(0, sr / 2.0, n_fft // 2 + 1)
    mel_pts = np.linspace(hz_to_mel(f_min, htk), hz_to_mel(f_max, htk),
                          n_mels + 2)
    hz_pts = np.asarray([mel_to_hz(m, htk) for m in mel_pts])
    fb = np.zeros((n_mels, n_fft // 2 + 1), np.float32)
    for i in range(n_mels):
        lo, ctr, hi = hz_pts[i], hz_pts[i + 1], hz_pts[i + 2]
        up = (fft_freqs - lo) / max(ctr - lo, 1e-10)
        down = (hi - fft_freqs) / max(hi - ctr, 1e-10)
        fb[i] = np.maximum(0.0, np.minimum(up, down))
    if norm == "slaney":
        enorm = 2.0 / (hz_pts[2:] - hz_pts[:-2])
        fb *= enorm[:, None]
    return Tensor(jnp.asarray(fb))


def create_dct(n_mfcc: int, n_mels: int, norm: str = "ortho"):
    """[n_mels, n_mfcc] DCT-II matrix (ref: functional.py create_dct)."""
    n = np.arange(n_mels)
    k = np.arange(n_mfcc)[:, None]
    dct = np.cos(math.pi / n_mels * (n + 0.5) * k).astype(np.float32)
    if norm == "ortho":
        dct[0] *= 1.0 / math.sqrt(2.0)
        dct *= math.sqrt(2.0 / n_mels)
    else:
        dct *= 2.0
    return Tensor(jnp.asarray(dct.T))


class Spectrogram(Layer):
    """ref: audio/features/layers.py Spectrogram — |STFT|^power."""

    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True,
                 pad_mode="reflect"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        if window == "hann":
            w = jnp.asarray(np.hanning(self.win_length).astype(np.float32))
        elif window == "hamming":
            w = jnp.asarray(np.hamming(self.win_length).astype(np.float32))
        else:
            w = jnp.ones((self.win_length,), jnp.float32)
        self.window = Tensor(w)

    def forward(self, x):
        spec = _signal.stft(x, self.n_fft, self.hop_length,
                            self.win_length, self.window,
                            center=self.center, pad_mode=self.pad_mode)
        return apply_op(
            lambda s: jnp.abs(s) ** self.power, spec, op_name="spec_power")


class MelSpectrogram(Layer):
    """ref: features/layers.py MelSpectrogram."""

    def __init__(self, sr=16000, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, n_mels=64,
                 f_min=0.0, f_max=None):
        super().__init__()
        self.spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                       window, power)
        self.fbank = compute_fbank_matrix(sr, n_fft, n_mels, f_min, f_max)

    def forward(self, x):
        spec = self.spectrogram(x)   # [..., freq, time]
        return apply_op(lambda s, fb: jnp.einsum("...ft,mf->...mt", s, fb),
                        spec, self.fbank, op_name="mel_fbank")


class LogMelSpectrogram(Layer):
    def __init__(self, sr=16000, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, n_mels=64,
                 f_min=0.0, f_max=None, ref_value=1.0, amin=1e-10,
                 top_db=None):
        super().__init__()
        self.mel = MelSpectrogram(sr, n_fft, hop_length, win_length,
                                  window, power, n_mels, f_min, f_max)
        self.amin = amin
        self.ref_value = ref_value
        self.top_db = top_db

    def forward(self, x):
        m = self.mel(x)

        def f(s):
            log_spec = 10.0 * jnp.log10(jnp.maximum(s, self.amin)
                                        / self.ref_value)
            if self.top_db is not None:
                log_spec = jnp.maximum(log_spec,
                                       log_spec.max() - self.top_db)
            return log_spec

        return apply_op(f, m, op_name="log_mel")


class MFCC(Layer):
    """ref: features/layers.py MFCC = DCT(log-mel)."""

    def __init__(self, sr=16000, n_mfcc=40, n_fft=512, n_mels=64, **kw):
        super().__init__()
        self.log_mel = LogMelSpectrogram(sr=sr, n_fft=n_fft, n_mels=n_mels,
                                         **kw)
        self.dct = create_dct(n_mfcc, n_mels)

    def forward(self, x):
        lm = self.log_mel(x)         # [..., n_mels, time]
        return apply_op(lambda s, d: jnp.einsum("...mt,mk->...kt", s, d),
                        lm, self.dct, op_name="mfcc_dct")
