"""paddle_tpu: a TPU-native deep-learning framework with the capabilities of
PaddlePaddle (the reference at /root/reference), built on JAX/XLA/Pallas.

Top-level namespace mirrors `paddle.*` (ref: python/paddle/__init__.py):
tensor creation/math/manipulation/linalg ops, nn, optimizer, io, amp,
distributed, jit, vision.
"""
from __future__ import annotations

__version__ = "0.1.0"

import warnings as _warnings

import jax as _jax

# fp32 matmuls accumulate in full precision by default (the reference's cuBLAS
# fp32 GEMMs do); bf16 inputs still ride the MXU at full rate. Perf-sensitive
# code paths opt into lower precision per-call via jax.default_matmul_precision.
_jax.config.update("jax_default_matmul_precision", "float32")

# TPU/XLA runs with 32-bit index types by default (jax x64 disabled); the
# paddle-style API nominally uses int64 indices, which JAX silently narrows.
_warnings.filterwarnings(
    "ignore", message="Explicitly requested dtype int64")
_warnings.filterwarnings(
    "ignore", message="Explicitly requested dtype float64")

# dtypes
from .core.dtype import (  # noqa: F401
    bool_ as bool8, uint8, int8, int16, int32, int64, float16, bfloat16,
    float32, float64, complex64, complex128,
    get_default_dtype, set_default_dtype,
)
from .core import dtype as dtype_module  # noqa: F401
from .core.dtype import bool_  # noqa: F401

# core tensor + autograd
from .core.tensor import Tensor, Parameter, to_tensor  # noqa: F401
from .core.autograd import no_grad, enable_grad, is_grad_enabled, grad  # noqa: F401
from .core.flags import get_flags, set_flags  # noqa: F401
from .core.random import seed, get_rng_state, set_rng_state  # noqa: F401
from .core.device import (  # noqa: F401
    set_device, get_device, device_count, is_compiled_with_cuda,
    is_compiled_with_tpu, CPUPlace, TPUPlace, Place,
)

# functional ops (also patches Tensor methods)
from .ops import *  # noqa: F401,F403
from .ops import cast, increment  # noqa: F401

from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import io  # noqa: F401
from . import amp  # noqa: F401
from . import autograd  # noqa: F401
from . import jit  # noqa: F401
from . import distributed  # noqa: F401
from . import vision  # noqa: F401
from . import metric  # noqa: F401
from . import linalg  # noqa: F401
from . import fft  # noqa: F401
from . import signal  # noqa: F401
from . import distribution  # noqa: F401
from . import sparse  # noqa: F401
from . import quantization  # noqa: F401
from . import regularizer  # noqa: F401
from . import incubate  # noqa: F401
from . import models  # noqa: F401
from . import audio  # noqa: F401
from . import text  # noqa: F401
from . import utils  # noqa: F401
from . import inference  # noqa: F401
# NOTE: paddle_tpu.profiler is intentionally NOT imported here — it pulls
# in the native extension, whose first import compiles C++; users import
# it explicitly (matching `import paddle.profiler` usage).
from .framework.io import save, load  # noqa: F401
from .hapi.model import Model, flops, summary  # noqa: F401
from . import callbacks  # noqa: F401

from . import static  # noqa: F401
from . import geometric  # noqa: F401


def disable_static(place=None):
    """Back to eager (the default). ref: paddle.disable_static."""
    from .static.program import _set_static_mode
    _set_static_mode(False)


def enable_static():
    """Record subsequent ops into static.default_main_program(); run them
    with static.Executor. ref: paddle.enable_static (SURVEY layer 14)."""
    from .static.program import _set_static_mode
    _set_static_mode(True)


def in_dynamic_mode():
    from .static.program import _static_mode
    return not _static_mode()
