"""paddle_tpu: a TPU-native deep-learning framework with the capabilities of
PaddlePaddle (the reference at /root/reference), built on JAX/XLA/Pallas.

Top-level namespace mirrors `paddle.*` (ref: python/paddle/__init__.py):
tensor creation/math/manipulation/linalg ops, nn, optimizer, io, amp,
distributed, jit, vision.
"""
from __future__ import annotations

__version__ = "0.1.0"

import warnings as _warnings

import jax as _jax

# fp32 matmuls accumulate in full precision by default (the reference's cuBLAS
# fp32 GEMMs do); bf16 inputs still ride the MXU at full rate. Perf-sensitive
# code paths opt into lower precision per-call via jax.default_matmul_precision.
_jax.config.update("jax_default_matmul_precision", "float32")

# TPU/XLA runs with 32-bit index types by default (jax x64 disabled); the
# paddle-style API nominally uses int64 indices, which JAX silently narrows.
_warnings.filterwarnings(
    "ignore", message="Explicitly requested dtype int64")
_warnings.filterwarnings(
    "ignore", message="Explicitly requested dtype float64")

# dtypes
from .core.dtype import (  # noqa: F401
    bool_ as bool8, uint8, int8, int16, int32, int64, float16, bfloat16,
    float32, float64, complex64, complex128,
    get_default_dtype, set_default_dtype,
)
from .core import dtype as dtype_module  # noqa: F401
from .core.dtype import bool_  # noqa: F401

# core tensor + autograd
from .core import fusion  # noqa: F401  (paddle.fusion.stats() surface)
from .core.tensor import Tensor, Parameter, to_tensor  # noqa: F401
from .core.autograd import no_grad, enable_grad, is_grad_enabled, grad  # noqa: F401
from .core.flags import get_flags, set_flags  # noqa: F401
from .core.random import seed, get_rng_state, set_rng_state  # noqa: F401
from .core.device import (  # noqa: F401
    set_device, get_device, device_count, is_compiled_with_cuda,
    is_compiled_with_tpu, CPUPlace, TPUPlace, Place,
)

# functional ops (also patches Tensor methods)
from .ops import *  # noqa: F401,F403
from .ops import cast, increment  # noqa: F401

from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import io  # noqa: F401
from . import amp  # noqa: F401
from . import autograd  # noqa: F401
from . import jit  # noqa: F401
from . import distributed  # noqa: F401
from . import vision  # noqa: F401
from . import metric  # noqa: F401
from . import linalg  # noqa: F401
from . import fft  # noqa: F401
from . import signal  # noqa: F401
from . import distribution  # noqa: F401
from . import sparse  # noqa: F401
from . import quantization  # noqa: F401
from . import regularizer  # noqa: F401
from . import incubate  # noqa: F401
from . import models  # noqa: F401
from . import audio  # noqa: F401
from . import text  # noqa: F401
from . import utils  # noqa: F401
from . import observability  # noqa: F401  (unified telemetry runtime)
from . import inference  # noqa: F401
# NOTE: paddle_tpu.profiler is intentionally NOT imported here — it pulls
# in the native extension, whose first import compiles C++; users import
# it explicitly (matching `import paddle.profiler` usage).
from .framework.io import save, load  # noqa: F401
from .hapi.model import Model, flops, summary  # noqa: F401
from . import callbacks  # noqa: F401

from .ops import inplace as _inplace_ops  # noqa: F401  (installs op_ variants)
from . import static  # noqa: F401
from . import geometric  # noqa: F401
from . import device as device  # noqa: F401
from . import hub  # noqa: F401
from . import onnx  # noqa: F401

# hot start: a boot with FLAGS_executable_cache_dir in the environment
# gets the persistent executable cache configured BEFORE any compile
# (model-init jnp programs included) — a no-op string compare when the
# flag is empty (the compile seams re-check on runtime set_flags)
jit.warmup.ensure_executable_cache()


def disable_static(place=None):
    """Back to eager (the default). ref: paddle.disable_static."""
    from .static.program import _set_static_mode
    _set_static_mode(False)


def enable_static():
    """Record subsequent ops into static.default_main_program(); run them
    with static.Executor. ref: paddle.enable_static (SURVEY layer 14)."""
    from .static.program import _set_static_mode
    _set_static_mode(True)


def in_dynamic_mode():
    from .static.program import _static_mode
    return not _static_mode()


# ---------------------------------------------------------------------------
# misc top-level parity (ref: python/paddle/__init__.py __all__ tail)
# ---------------------------------------------------------------------------
def iinfo(dtype):
    """ref: paddle.iinfo — integer type info."""
    from .core.dtype import convert_dtype as _cd
    return np.iinfo(np.dtype(str(jnp.dtype(_cd(dtype)))))


def finfo(dtype):
    """ref: paddle.finfo — float type info."""
    from .core.dtype import convert_dtype as _cd
    return jnp.finfo(jnp.dtype(_cd(dtype)))


dtype = jnp.dtype

from .distributed.parallel import DataParallel  # noqa: F401,E402


class CUDAPlace(Place):  # noqa: F405  (accepted alias; executes on TPU)
    def __init__(self, device_id=0):
        super().__init__("gpu", device_id)


class CUDAPinnedPlace(Place):  # noqa: F405
    def __init__(self):
        super().__init__("gpu_pinned", 0)


class LazyGuard:
    """ref: paddle.LazyGuard — deferred parameter init. Parameters here
    are cheap jax arrays, so the guard is a no-op context."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """ref: paddle.create_parameter."""
    from .nn import initializer as I
    init = default_initializer or (I.Constant(0.0) if is_bias
                                   else I.XavierNormal())
    from .core.dtype import convert_dtype as _cd
    data = init(tuple(shape), _cd(dtype))
    p = Parameter(data)
    if name:
        p.name = name
    return p


def rank(x):
    """ref: paddle.rank — number of dimensions as a 0-D tensor."""
    return to_tensor(np.asarray((x._data if isinstance(x, Tensor)
                                 else np.asarray(x)).ndim))  # noqa: F405


def shape(x):
    """ref: paddle.shape — runtime shape as an int tensor."""
    return to_tensor(np.asarray(  # noqa: F405
        (x._data if isinstance(x, Tensor) else np.asarray(x)).shape,
        np.int64))


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """ref: paddle.set_printoptions — applies to numpy reprs."""
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)


def set_grad_enabled(mode):
    """ref: paddle.set_grad_enabled (context manager)."""
    from .core.autograd import _GradModeGuard
    return _GradModeGuard(True if mode else False)


def is_compiled_with_cinn():
    return False  # the compiler here is XLA


def is_compiled_with_rocm():
    return False


def is_compiled_with_xpu():
    return False


def disable_signal_handler():
    return None


def check_shape(x):
    return None  # shapes are static under tracing; nothing to defer


def batch(reader, batch_size, drop_last=False):
    """ref: paddle.batch (legacy reader decorator)."""
    def batched():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return batched


from .nn import ParamAttr  # noqa: F401,E402

float8_e4m3fn = jnp.float8_e4m3fn
float8_e5m2 = jnp.float8_e5m2


def get_cuda_rng_state():
    """Alias of get_rng_state (accepted for reference compat; the device
    stream is the framework generator)."""
    return get_rng_state()  # noqa: F405


def set_cuda_rng_state(state):
    return set_rng_state(state)  # noqa: F405


def binomial(count, prob, name=None):
    """ref: paddle.binomial — draws with per-element counts/probs."""
    from .core import random as _rnd
    import jax as _jax
    key = _rnd.next_key()
    from .core.autograd import apply_op as _apply
    return _apply(lambda n, q: _jax.random.binomial(
        key, n, q).astype(jnp.int64), count, prob, op_name="binomial")


def _toplevel_inplace(name):
    def f(x, *args, **kwargs):
        return getattr(x, name)(*args, **kwargs)
    f.__name__ = name
    return f


# tensor-method inplace forms also exposed at module level
normal_ = _toplevel_inplace("normal_")
log_normal_ = _toplevel_inplace("log_normal_")
bernoulli_ = _toplevel_inplace("bernoulli_")
cauchy_ = _toplevel_inplace("cauchy_")
geometric_ = _toplevel_inplace("geometric_")
divide_ = _toplevel_inplace("divide_")


def addmm_(input, x, y, beta=1.0, alpha=1.0, name=None):
    out = addmm(input, x, y, beta=beta, alpha=alpha)  # noqa: F405
    input._data = out._data
    return input


def where_(condition, x, y, name=None):
    """ref: tensor/search.py:828 where_ — the result lands in x."""
    out = where(condition, x, y)  # noqa: F405
    x._data = out._data
    return x


def tolist(x):
    return x.tolist()


# paddle.bool dtype alias — assigned last so the module body above keeps
# the builtin
bool = bool_  # noqa: F405,A001
