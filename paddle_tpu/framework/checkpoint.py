"""Crash-safe persistence: atomic checkpoint writes, per-tensor CRC32
manifests, retention + corruption fallback, async snapshot-then-persist.

``paddle.save`` historically wrote one pickle straight to its final
path — a preemption mid-save (the exact failure mode
``distributed/elastic.py`` exists to survive) truncated the only copy
and the elastic restart had nothing valid to resume from. This module
makes durability a subsystem:

- **Atomic saves** (:func:`atomic_save`): serialize to ``path.tmp.<pid>``,
  flush + fsync, then ``os.replace`` onto the final name — readers see
  either the old complete file or the new complete file, never a
  partial. The record embeds a format version and a manifest mapping
  each tensor's tree path to the CRC32 of its bytes, so silent
  corruption (not just truncation) is detectable at load.
- **Legacy compat**: files written by the pre-manifest ``paddle.save``
  (a bare pickle of the packed tree) still load; the loader sniffs the
  version marker and falls back to the v1 decode.
- **:class:`CheckpointManager`**: ``save(obj, step)`` with ``keep_n``
  retention and ``latest()`` that verifies manifests and silently walks
  back past truncated/corrupt checkpoints to the newest good one.
- **Async mode**: ``save`` snapshots device arrays to host (the only
  step the training loop must wait for), then a background thread
  serializes, fsyncs and renames — following T3's overlap theme the
  durability cost leaves the step's critical path. The next ``save`` /
  ``wait`` / ``close`` barriers on (and re-raises from) the in-flight
  persist.

Fault-injection sites (``paddle_tpu.utils.fault_injection``):
``checkpoint.snapshot``, ``checkpoint.write``, ``checkpoint.rename`` —
the tests kill, truncate and error each one and assert recovery.
"""
from __future__ import annotations

import os
import pickle
import re
import threading
import time as _time
import zlib
from typing import Any, Dict, List, Optional, Tuple

from ..core.flags import define_flag, flag_value
from ..observability import flight as _flight
from ..observability import metrics as _om
from ..utils import fault_injection as _fi
from .io import _TensorPayload, _pack, _unpack

__all__ = ["atomic_save", "load_checkpoint", "verify_checkpoint",
           "extract_state_dict", "CheckpointManager",
           "CheckpointCorruptError", "FORMAT_VERSION"]

FORMAT_KEY = "__paddle_tpu_ckpt__"
FORMAT_VERSION = 2

define_flag("checkpoint_fsync", True,
            "fsync checkpoint temp files (and their directory) before "
            "the atomic rename. Durability contract against power loss; "
            "disable only in tests/benchmarks on throwaway dirs")


class CheckpointCorruptError(RuntimeError):
    """A checkpoint failed CRC/structure verification at load."""


# process-wide durability telemetry (aggregates across every manager and
# bare paddle.save; per-instance CheckpointManager.stats() stays the
# legacy per-directory view)
_M = _om.scope("checkpoint")
_M_saves = _M.counter("saves_total", "Durable checkpoint persists")
_M_bytes = _M.counter("bytes_written_total", "Serialized checkpoint bytes")
_M_save_s = _M.histogram(
    "save_seconds", "Wall seconds per durable persist "
    "(serialize + write + fsync + rename)")
_M_loads = _M.counter("loads_total", "Checkpoints loaded successfully")
_M_corrupt = _M.counter(
    "corrupt_skipped_total",
    "Damaged checkpoints skipped by latest()/restore() fallback")
_M_async = _M.counter("async_saves_total", "Async save submissions")
_M_retired = _M.counter("retired_total", "Checkpoints pruned by retention")


# -- manifest -------------------------------------------------------------

def _build_manifest(packed) -> Dict[str, Dict[str, Any]]:
    """Tree-path -> {crc32, nbytes, shape, dtype} for every tensor
    payload in the packed tree."""
    entries: Dict[str, Dict[str, Any]] = {}

    def walk(obj, path):
        if isinstance(obj, _TensorPayload):
            entries[path] = {
                "crc32": zlib.crc32(obj.bytes) & 0xFFFFFFFF,
                "nbytes": len(obj.bytes),
                "shape": list(obj.shape),
                "dtype": obj.dtype_str,
            }
        elif isinstance(obj, dict):
            for k, v in obj.items():
                walk(v, f"{path}/{k}")
        elif isinstance(obj, (list, tuple)):
            for i, v in enumerate(obj):
                walk(v, f"{path}[{i}]")

    walk(packed, "")
    return entries


def _verify_manifest(manifest, packed) -> List[str]:
    """Recompute CRCs against the manifest; returns mismatch reasons."""
    actual = _build_manifest(packed)
    bad = []
    for path, want in manifest.items():
        got = actual.get(path)
        if got is None:
            bad.append(f"{path or '/'}: tensor missing from payload")
        elif (got["crc32"] != want["crc32"]
              or got["nbytes"] != want["nbytes"]):
            bad.append(
                f"{path or '/'}: crc32 {got['crc32']:#010x} != manifest "
                f"{want['crc32']:#010x} ({got['nbytes']} bytes)")
    extra = set(actual) - set(manifest)
    if extra:
        bad.append(f"{len(extra)} tensor(s) not in manifest")
    return bad


# -- save / load ----------------------------------------------------------

def _fsync_dir(dirname: str) -> None:
    try:
        fd = os.open(dirname or ".", os.O_RDONLY)
    except OSError:
        return  # fs without directory fds (or vanished dir)
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_save(obj, path: str, protocol: int = 4) -> int:
    """Snapshot ``obj`` and atomically persist it at ``path``; returns
    bytes written. Any failure (including a kill mid-write) leaves the
    previous contents of ``path`` untouched."""
    _fi.fire("checkpoint.snapshot")
    packed = _pack(obj)
    return _persist_packed(packed, path, protocol)


def _persist_packed(packed, path: str, protocol: int = 4) -> int:
    """The durable half of a save (async mode runs this off-thread):
    serialize the already-host-resident tree, write-fsync-rename."""
    t0 = _time.perf_counter()
    record = {FORMAT_KEY: FORMAT_VERSION,
              "manifest": _build_manifest(packed),
              "payload": packed}
    blob = pickle.dumps(record, protocol=protocol)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            _fi.write_bytes("checkpoint.write", f, blob)
            f.flush()
            if flag_value("checkpoint_fsync"):
                os.fsync(f.fileno())
        _fi.fire("checkpoint.rename")
        os.replace(tmp, path)
    except Exception:
        # a REAL error is reported after best-effort cleanup; a
        # KillPoint (BaseException) skips this and leaves the partial
        # tmp file behind, exactly like a preemption would
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    if flag_value("checkpoint_fsync"):
        _fsync_dir(d)
    _M_saves.inc()
    _M_bytes.inc(len(blob))
    dt = _time.perf_counter() - t0
    _M_save_s.observe(dt)
    _flight.record("checkpoint", "save", path=os.path.basename(path),
                   bytes=len(blob), dur_ms=round(dt * 1e3, 1))
    return len(blob)


def _read_record(path: str):
    """-> (version, manifest, packed_payload). Legacy bare-pickle files
    report version 1 with an empty manifest."""
    with open(path, "rb") as f:
        record = pickle.load(f)
    if isinstance(record, dict) and FORMAT_KEY in record \
            and "payload" in record:
        version = record[FORMAT_KEY]
        if not isinstance(version, int) or version > FORMAT_VERSION:
            raise CheckpointCorruptError(
                f"{path}: checkpoint format version {version!r} is newer "
                f"than this build understands (<= {FORMAT_VERSION})")
        return version, record.get("manifest", {}), record["payload"]
    return 1, {}, record


def load_checkpoint(path: str, return_numpy: bool = False,
                    verify: bool = True):
    """Load a checkpoint written by this module OR a legacy
    ``paddle.save`` pickle. v2 files are CRC-verified before a single
    tensor is handed back; a mismatch raises
    :class:`CheckpointCorruptError` instead of returning garbage."""
    version, manifest, packed = _read_record(path)
    if verify and version >= 2:
        bad = _verify_manifest(manifest, packed)
        if bad:
            raise CheckpointCorruptError(
                f"{path}: {len(bad)} corrupt tensor(s): "
                + "; ".join(bad[:4]))
    _M_loads.inc()
    _flight.record("checkpoint", "restore",
                   path=os.path.basename(path), version=version)
    return _unpack(packed, return_numpy=return_numpy)


def extract_state_dict(obj) -> Dict[str, Any]:
    """The model state dict inside a checkpoint payload: a sub-tree
    under the conventional ``model`` / ``state_dict`` / ``params``
    keys when the payload is a composite (model + optimizer + step
    bookkeeping, the trainer convention), else the payload itself
    when it already is a flat name -> tensor mapping. The serving
    weight hot-swap (``GenerationServer.swap_weights``) normalizes
    every checkpoint shape through this one seam."""
    if isinstance(obj, dict):
        for key in ("model", "state_dict", "params"):
            sub = obj.get(key)
            if isinstance(sub, dict) and sub and \
                    all(isinstance(k, str) for k in sub) and \
                    all(hasattr(v, "shape") or hasattr(v, "_data")
                        for v in sub.values()):
                return sub
        if obj and all(isinstance(k, str) for k in obj) and \
                all(hasattr(v, "shape") or hasattr(v, "_data")
                    for v in obj.values()):
            return obj
    raise ValueError(
        "cannot find a model state dict in the checkpoint payload — "
        "expected a flat {name: tensor} mapping or one nested under a "
        "'model'/'state_dict'/'params' key")


def verify_checkpoint(path: str) -> Tuple[bool, str]:
    """Full integrity check without materializing tensors on device:
    (True, "") for a loadable checkpoint, else (False, reason). Never
    raises for on-disk damage — truncation, unpicklable bytes and CRC
    mismatches all come back as reasons."""
    try:
        version, manifest, packed = _read_record(path)
    except CheckpointCorruptError as e:
        return False, str(e)
    except Exception as e:  # noqa: BLE001 — any decode failure = damage
        return False, f"unreadable ({type(e).__name__}: {e})"
    if version >= 2:
        bad = _verify_manifest(manifest, packed)
        if bad:
            return False, "; ".join(bad)
    return True, ""


# -- manager --------------------------------------------------------------

class CheckpointManager:
    """Step-indexed checkpoints under one directory with retention,
    corruption fallback and optional async persistence.

    ``save(obj, step)`` writes ``<root>/<prefix>-<step>.pdckpt``
    atomically and prunes to the newest ``keep_n``. ``latest()`` walks
    steps newest-first, verifying each manifest, and silently falls
    back past truncated/corrupt files to the newest good one — the
    elastic-restart contract: whatever a preemption did to the last
    save, resume finds a consistent state.
    """

    _SUFFIX = ".pdckpt"

    def __init__(self, root: str, keep_n: int = 3,
                 async_save: bool = False, prefix: str = "ckpt"):
        if keep_n < 1:
            raise ValueError(f"keep_n must be >= 1, got {keep_n}")
        self.root = str(root)
        self.keep_n = int(keep_n)
        self.async_save = bool(async_save)
        self.prefix = prefix
        os.makedirs(self.root, exist_ok=True)
        from ..analysis.locks import make_lock
        self._lock = make_lock("checkpoint.manager")
        self._pending: Optional[threading.Thread] = None
        self._pending_error: Optional[BaseException] = None
        self._stats = {"saves": 0, "async_saves": 0, "bytes_written": 0,
                       "corrupt_skipped": 0, "retired": 0}
        steps = self.steps()
        self._next_step = (steps[-1] + 1) if steps else 0

    # -- paths ----------------------------------------------------------
    def _path(self, step: int) -> str:
        return os.path.join(self.root,
                            f"{self.prefix}-{step:08d}{self._SUFFIX}")

    def steps(self) -> List[int]:
        """Steps with a (possibly damaged) checkpoint file, ascending.
        In-flight ``.tmp.*`` files are never counted."""
        pat = re.compile(
            rf"^{re.escape(self.prefix)}-(\d+){re.escape(self._SUFFIX)}$")
        out = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        for name in names:
            m = pat.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    # -- save ------------------------------------------------------------
    def save(self, obj, step: Optional[int] = None) -> str:
        """Checkpoint ``obj``; returns the final path. Sync mode blocks
        until the file is durable; async mode returns once the host
        snapshot exists and persists on the background thread (the
        previous in-flight persist is barriered first, and its failure
        re-raised here)."""
        self.wait()
        if step is None:
            step = self._next_step
        step = int(step)
        self._next_step = max(self._next_step, step + 1)
        path = self._path(step)
        _fi.fire("checkpoint.snapshot")
        packed = _pack(obj)  # device -> host; the only sync cost
        if not self.async_save:
            self._persist(packed, path)
            return path

        def run():
            try:
                self._persist(packed, path)
            except BaseException as e:  # noqa: BLE001 — incl. KillPoint
                with self._lock:
                    self._pending_error = e

        t = threading.Thread(target=run, daemon=True,
                             name=f"ckpt-persist-{step}")
        # start BEFORE publishing: joining an unstarted thread raises,
        # and a concurrent reader may _join_pending() the moment the
        # slot is visible
        t.start()
        with self._lock:
            self._pending = t
            self._stats["async_saves"] += 1
        _M_async.inc()
        return path

    def _persist(self, packed, path: str) -> None:
        n = _persist_packed(packed, path)
        with self._lock:
            self._stats["saves"] += 1
            self._stats["bytes_written"] += n
        self._retire()

    def _retire(self) -> None:
        for step in self.steps()[:-self.keep_n]:
            try:
                os.remove(self._path(step))
                with self._lock:
                    self._stats["retired"] += 1
                _M_retired.inc()
            except OSError:
                pass  # already gone / transient: retry next save

    # -- async barrier ---------------------------------------------------
    def _join_pending(self) -> None:
        """Join the in-flight persist thread (if any) and clear the
        slot ONLY if it still holds that same thread — a reader joining
        concurrently with a trainer's save() must never null out a
        freshly started persist."""
        t = self._pending
        if t is not None:
            t.join()
            with self._lock:
                if self._pending is t:
                    self._pending = None

    def wait(self) -> None:
        """Barrier on the in-flight async persist; re-raises its
        failure (KillPoint included) exactly once."""
        self._join_pending()
        with self._lock:
            err, self._pending_error = self._pending_error, None
        if err is not None:
            raise err

    def _drain_quietly(self) -> None:
        """Read-side barrier: the reader wants the newest durable state,
        not the background writer's exception — that stays parked for
        the next save()/wait()/close()."""
        self._join_pending()

    # -- restore ---------------------------------------------------------
    def latest(self) -> Optional[str]:
        """Path of the newest checkpoint whose manifest verifies, or
        None. Damaged files are skipped silently (counted in
        ``stats()['corrupt_skipped']``) — fallback IS the recovery
        path, not an error."""
        self._drain_quietly()
        for step in reversed(self.steps()):
            path = self._path(step)
            ok, _reason = verify_checkpoint(path)
            if ok:
                return path
            with self._lock:
                self._stats["corrupt_skipped"] += 1
            _M_corrupt.inc()
            _flight.record("checkpoint", "corrupt_fallback",
                           path=os.path.basename(path), where="latest")
        return None

    def _step_of(self, path: str) -> int:
        return int(os.path.basename(path)[len(self.prefix) + 1:
                                          -len(self._SUFFIX)])

    def latest_step(self) -> Optional[int]:
        path = self.latest()
        return None if path is None else self._step_of(path)

    def restore(self, return_numpy: bool = False):
        """(step, obj) from the newest good checkpoint, or None when no
        loadable checkpoint exists. One read+verify pass per candidate
        (latest()-then-load would decode and CRC the winner twice)."""
        self._drain_quietly()
        for step in reversed(self.steps()):
            try:
                obj = load_checkpoint(self._path(step),
                                      return_numpy=return_numpy)
            except Exception:  # noqa: BLE001 — damaged: fall back
                with self._lock:
                    self._stats["corrupt_skipped"] += 1
                _M_corrupt.inc()
                _flight.record(
                    "checkpoint", "corrupt_fallback",
                    path=os.path.basename(self._path(step)),
                    where="restore")
                continue
            return step, obj
        return None

    # -- lifecycle / observability ---------------------------------------
    def close(self) -> None:
        self.wait()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def stats(self) -> Dict[str, int]:
        with self._lock:
            out = dict(self._stats)
        t = self._pending
        out["async_queue_depth"] = int(t is not None and t.is_alive())
        return out
