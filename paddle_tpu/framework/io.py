"""paddle.save / paddle.load: single-process checkpointing.

ref: python/paddle/framework/io.py. Tensors are serialized as numpy arrays
with dtype preserved (bfloat16 via ml_dtypes view trick); nested dicts/lists
(state_dicts, optimizer states) round-trip transparently.

Durability lives in ``framework/checkpoint.py``: ``save`` writes
atomically (tmp + fsync + rename) with a per-tensor CRC32 manifest, and
``load`` verifies the manifest before handing tensors back. Files
written by the pre-manifest bare-pickle format still load — the
``_TensorPayload`` class must stay importable from THIS module path,
which is what legacy pickles reference.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor


class _TensorPayload:
    __slots__ = ("bytes", "shape", "dtype_str")

    def __init__(self, arr: np.ndarray):
        self.shape = arr.shape
        self.dtype_str = str(arr.dtype)
        if arr.dtype.kind == "V" or "bfloat16" in self.dtype_str:
            self.bytes = arr.view(np.uint16).tobytes()
            self.dtype_str = "bfloat16"
        else:
            self.bytes = arr.tobytes()

    def restore(self) -> np.ndarray:
        if self.dtype_str == "bfloat16":
            import ml_dtypes
            return np.frombuffer(self.bytes, np.uint16).view(
                ml_dtypes.bfloat16).reshape(self.shape)
        return np.frombuffer(
            self.bytes, np.dtype(self.dtype_str)).reshape(self.shape)


def _pack(obj):
    if isinstance(obj, Tensor):
        return _TensorPayload(np.asarray(obj._data))
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_pack(v) for v in obj)
    return obj


def _unpack(obj, return_numpy=False):
    if isinstance(obj, _TensorPayload):
        arr = obj.restore()
        return arr if return_numpy else Tensor(jnp.asarray(arr))
    if isinstance(obj, dict):
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_unpack(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    from .checkpoint import atomic_save  # lazy: avoids an import cycle
    atomic_save(obj, path, protocol=protocol)


def load(path, return_numpy=False, **configs):
    from .checkpoint import load_checkpoint
    return load_checkpoint(path, return_numpy=return_numpy)
