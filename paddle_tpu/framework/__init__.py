from .io import save, load  # noqa: F401
from .checkpoint import (  # noqa: F401
    CheckpointCorruptError, CheckpointManager, atomic_save,
    load_checkpoint, verify_checkpoint)
