from .io import save, load  # noqa: F401
