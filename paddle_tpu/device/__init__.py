"""paddle.device equivalent (ref: python/paddle/device/__init__.py).

TPU build notes: PJRT owns devices; streams/events are XLA's async
dispatch, so Stream/Event/synchronize are thin wrappers over the
dispatch queue (the reference's CUDA stream objects have no TPU
analog — XLA schedules).
"""
from __future__ import annotations

import contextlib

import jax

from ..core.device import (  # noqa: F401
    CPUPlace, Place, TPUPlace, device_count, get_device,
    is_compiled_with_cuda, is_compiled_with_tpu, set_device)

__all__ = [
    "get_cudnn_version", "set_device", "get_device", "XPUPlace",
    "IPUPlace", "is_compiled_with_xpu", "is_compiled_with_ipu",
    "is_compiled_with_cinn", "is_compiled_with_cuda",
    "is_compiled_with_rocm", "is_compiled_with_distribute",
    "is_compiled_with_custom_device", "get_all_device_type",
    "get_all_custom_device_type", "get_available_device",
    "get_available_custom_device", "Stream", "Event", "current_stream",
    "set_stream", "stream_guard", "synchronize",
    "memory_stats", "memory_allocated", "max_memory_allocated",
    "memory_reserved", "max_memory_reserved",
    "reset_max_memory_allocated", "reset_peak_memory_stats",
    "empty_cache", "program_memory_analysis",
]


def get_cudnn_version():
    """None on non-CUDA builds (ref: device/__init__.py)."""
    return None


def XPUPlace(dev_id: int = 0):
    raise RuntimeError("this build has no XPU backend (TPU-native)")


def IPUPlace():
    raise RuntimeError("this build has no IPU backend (TPU-native)")


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_ipu() -> bool:
    return False


def is_compiled_with_cinn() -> bool:
    # XLA plays CINN's role and is always present
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_distribute() -> bool:
    return True


def is_compiled_with_custom_device(device_type: str) -> bool:
    """TPU is this build's 'custom device' in reference terms."""
    return device_type in ("tpu", "axon")


def _platforms():
    plats = []
    for d in jax.devices():
        p = "tpu" if d.platform in ("tpu", "axon") else d.platform
        if p not in plats:
            plats.append(p)
    return plats


def get_all_device_type():
    return ["cpu"] + [p for p in _platforms() if p != "cpu"]


def get_all_custom_device_type():
    return [p for p in _platforms() if p not in ("cpu", "gpu")]


def get_available_device():
    out = []
    for i, d in enumerate(jax.devices()):
        p = "tpu" if d.platform in ("tpu", "axon") else d.platform
        out.append(f"{p}:{i}")
    return out or ["cpu"]


def get_available_custom_device():
    return [d for d in get_available_device()
            if d.split(":")[0] not in ("cpu", "gpu")]


class Stream:
    """Execution stream handle (ref: device/__init__.py Stream). XLA
    owns scheduling on TPU; the object carries identity + sync only."""

    def __init__(self, device=None, priority=2):
        self.device = device or get_device()
        self.priority = priority

    def synchronize(self):
        synchronize(self.device)

    def wait_event(self, event):
        event.synchronize()

    def wait_stream(self, stream):
        stream.synchronize()

    def record_event(self, event=None):
        event = event or Event()
        event.record(self)
        return event


class Event:
    """Cross-stream sync point (ref: device/__init__.py Event)."""

    def __init__(self, device=None, enable_timing=False, blocking=False,
                 interprocess=False):
        self.device = device or get_device()
        self._recorded_on = None

    def record(self, stream=None):
        self._recorded_on = stream

    def query(self) -> bool:
        return True  # XLA dispatch: enqueued work completes in order

    def synchronize(self):
        synchronize(self.device)


_current_streams: dict = {}


def current_stream(device=None):
    key = device or get_device()
    if key not in _current_streams:
        _current_streams[key] = Stream(key)
    return _current_streams[key]


def set_stream(stream):
    prev = current_stream(stream.device)
    _current_streams[stream.device] = stream
    return prev


@contextlib.contextmanager
def stream_guard(stream):
    prev = set_stream(stream)
    try:
        yield
    finally:
        set_stream(prev)


def synchronize(device=None):
    """Block until enqueued device work completes (ref: device
    synchronize): realized by fetching a tiny value through the same
    queue — the only ordered barrier XLA exposes."""
    import jax.numpy as jnp
    jax.block_until_ready(jnp.zeros(()))


# ---------------------------------------------------------------------------
# live device-memory observability
# (ref: python/paddle/device/cuda/__init__.py:233 max_memory_allocated over
#  paddle/phi/core/memory/stats.h current/peak counters; here the counters
#  come from PJRT memory_stats when the platform reports them, else from
#  the framework's op-boundary tracker in core/memory.py backed by the
#  native MemStats registry)
# ---------------------------------------------------------------------------

def _resolve_device(device=None):
    devs = jax.devices()
    if device is None:
        return devs[0]
    if isinstance(device, int):
        return devs[device]
    if hasattr(device, "platform"):  # already a jax device
        return device
    spec = str(device)
    if ":" in spec:
        return devs[int(spec.split(":")[1])]
    return devs[0]


def memory_stats(device=None):
    """Full stat dict for one device: allocated/reserved current+peak,
    plus the raw PJRT dict under ``"pjrt"`` when the backend has one."""
    from ..core import memory as _memory
    return _memory.stats_for(_resolve_device(device))


def memory_allocated(device=None) -> int:
    """Bytes of live device buffers right now (exact: PJRT counters or a
    live-array scan). ref: device/cuda/__init__.py memory_allocated."""
    return memory_stats(device)["allocated.current"]


def max_memory_allocated(device=None) -> int:
    """High-water mark of allocated bytes since start / last reset.
    ref: device/cuda/__init__.py:233."""
    return memory_stats(device)["allocated.peak"]


def memory_reserved(device=None) -> int:
    """Bytes reserved from the platform allocator (== allocated where
    PJRT doesn't report a separate reservation pool)."""
    return memory_stats(device)["reserved.current"]


def max_memory_reserved(device=None) -> int:
    return memory_stats(device)["reserved.peak"]


def reset_max_memory_allocated(device=None) -> None:
    """Peak watermark := current (reference ResetPeakValue semantics)."""
    from ..core import memory as _memory
    d = _resolve_device(device)
    _memory.reconcile(d)
    _memory.reset_peak(d)


def reset_peak_memory_stats(device=None) -> None:
    reset_max_memory_allocated(device)


def empty_cache() -> None:
    """Release cached host-side objects (PJRT owns device memory; the
    analog of the reference's allocator-cache flush is dropping dead
    Python references + XLA's compilation caches stay warm)."""
    import gc
    gc.collect()


def program_memory_analysis(compiled_or_fn, *example_args):
    """Per-device memory breakdown of a compiled XLA program: dict with
    argument/output/temp/alias/generated-code bytes and a ``peak_hbm``
    estimate (args + outputs + temps - aliased). jit-internal temps are
    invisible to the live counters — this is the API that sees them.

    Accepts a ``jax.stages.Compiled``, a jitted fn + example args (will
    lower+compile), or any object with ``memory_analysis()``.
    """
    obj = compiled_or_fn
    if example_args:
        obj = jax.jit(obj) if not hasattr(obj, "lower") else obj
        obj = obj.lower(*example_args).compile()
    ma = obj.memory_analysis()
    out = {
        "argument_bytes": int(ma.argument_size_in_bytes),
        "output_bytes": int(ma.output_size_in_bytes),
        "temp_bytes": int(ma.temp_size_in_bytes),
        "alias_bytes": int(ma.alias_size_in_bytes),
        "generated_code_bytes": int(ma.generated_code_size_in_bytes),
    }
    out["peak_hbm"] = (out["argument_bytes"] + out["output_bytes"]
                       + out["temp_bytes"] - out["alias_bytes"])
    return out
