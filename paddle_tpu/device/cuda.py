"""Compat shim for ``paddle.device.cuda`` memory APIs
(ref: python/paddle/device/cuda/__init__.py).

This build has no CUDA backend; the reference raises on such builds.
For drop-in friendliness the memory observability functions forward to
the device-agnostic implementations in ``paddle_tpu.device`` (they
report the default accelerator — the TPU), while device-management
functions keep the reference's raise-on-non-CUDA contract.
"""
from __future__ import annotations

from . import (  # noqa: F401
    empty_cache, max_memory_allocated, max_memory_reserved,
    memory_allocated, memory_reserved, memory_stats,
    reset_max_memory_allocated, reset_peak_memory_stats, synchronize)

__all__ = [
    "Stream", "Event", "current_stream", "synchronize", "device_count",
    "empty_cache", "max_memory_allocated", "max_memory_reserved",
    "memory_allocated", "memory_reserved", "reset_max_memory_allocated",
    "reset_peak_memory_stats", "stream_guard", "get_device_properties",
    "get_device_name", "get_device_capability",
]


def device_count() -> int:
    return 0  # no CUDA devices in this build


def get_device_properties(device=None):
    raise ValueError(
        "paddle_tpu is not compiled with CUDA; use paddle_tpu.device "
        "for the TPU device APIs")


def get_device_name(device=None):
    raise ValueError(
        "paddle_tpu is not compiled with CUDA; use paddle_tpu.device "
        "for the TPU device APIs")


def get_device_capability(device=None):
    raise ValueError(
        "paddle_tpu is not compiled with CUDA; use paddle_tpu.device "
        "for the TPU device APIs")


from . import Stream, Event, current_stream, stream_guard  # noqa: F401,E402
