"""paddle.callbacks namespace (ref: python/paddle/callbacks.py re-exports
the hapi callbacks)."""
from .hapi.callbacks import (  # noqa: F401
    Callback, EarlyStopping, History, LRScheduler, MetricsLogger,
    ModelCheckpoint, ProgBarLogger, VisualDL,
)
