"""paddle.io equivalent. ref: python/paddle/io/__init__.py"""
from .dataset import (  # noqa: F401
    Dataset, IterableDataset, TensorDataset, ComposeDataset, ChainDataset,
    ConcatDataset, Subset, random_split,
)
from .sampler import (  # noqa: F401
    Sampler, SequenceSampler, RandomSampler, SubsetRandomSampler,
    WeightedRandomSampler, BatchSampler, DistributedBatchSampler,
)
from .dataloader import DataLoader, default_collate_fn  # noqa: F401
from .worker import WorkerInfo, get_worker_info  # noqa: F401
