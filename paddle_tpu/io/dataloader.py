"""DataLoader: host input pipeline with background prefetch.

ref: python/paddle/io/dataloader/dataloader_iter.py (single/multi-process
iterators) + worker.py shared-memory loop. TPU-native shape: the device is
fed from the host, so the pipeline is (a) index batches from a sampler,
(b) a thread pool mapping dataset.__getitem__ + collate, (c) a bounded
prefetch queue overlapping host work with device steps (the analog of the
reference's pin-memory + worker processes; threads suffice because the work
is numpy/IO which releases the GIL).
"""
from __future__ import annotations

import queue
import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..core.tensor import Tensor
from .sampler import BatchSampler


def default_collate_fn(batch):
    """Stack samples into batched numpy arrays (structure-preserving).
    ref: python/paddle/io/dataloader/collate.py"""
    sample = batch[0]
    if isinstance(sample, Tensor):
        return Tensor(np.stack([np.asarray(s._data) for s in batch]))
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, float, np.number)):
        return np.asarray(batch)
    if isinstance(sample, (str, bytes)):
        return list(batch)
    if isinstance(sample, dict):
        return {k: default_collate_fn([s[k] for s in batch])
                for k in sample}
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return type(sample)(default_collate_fn(list(t)) for t in transposed)
    return batch


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(prefetch_factor, 2)
        from .dataset import IterableDataset
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)

    def __len__(self):
        if self._iterable:
            raise TypeError("IterableDataset has no fixed length")
        return len(self.batch_sampler)

    def _iter_iterable(self):
        batch = []
        for sample in self.dataset:
            batch.append(sample)
            if len(batch) == self.batch_size:
                yield self.collate_fn(batch)
                batch = []
        if batch and not self.drop_last:
            yield self.collate_fn(batch)

    def __iter__(self):
        if self._iterable:
            yield from self._iter_iterable()
            return
        if self.num_workers == 0:
            for idx_batch in self.batch_sampler:
                samples = [self.dataset[i] for i in idx_batch]
                yield self.collate_fn(samples)
            return
        yield from self._iter_prefetch()

    def _iter_prefetch(self):
        """Thread-pool fetch + bounded queue prefetch."""
        q: queue.Queue = queue.Queue(
            maxsize=self.num_workers * self.prefetch_factor)
        sentinel = object()

        def producer():
            # submit lazily: at most queue-capacity + workers batches in
            # flight, so a slow consumer can't accumulate the whole epoch
            try:
                with ThreadPoolExecutor(self.num_workers) as pool:
                    def fetch(idx_batch):
                        samples = [self.dataset[i] for i in idx_batch]
                        return self.collate_fn(samples)
                    pending = []
                    it = iter(self.batch_sampler)
                    for idx_batch in it:
                        pending.append(pool.submit(fetch, idx_batch))
                        if len(pending) >= self.num_workers:
                            q.put(pending.pop(0).result())
                    for fut in pending:
                        q.put(fut.result())
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is sentinel:
                break
            yield item
        t.join()
