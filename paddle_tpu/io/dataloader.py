"""DataLoader: host input pipeline with worker processes.

ref: python/paddle/io/dataloader/dataloader_iter.py (single/multi-process
iterators) + worker.py shared-memory loop. num_workers>0 forks worker
PROCESSES (io/worker.py) that run dataset.__getitem__ + collate off the
main process and off the GIL, shipping big arrays back through /dev/shm
(the reference's mmap_allocator transport). A legacy in-process thread
pool remains behind FLAGS_dataloader_use_threads for fork-hostile
setups.
"""
from __future__ import annotations

import os
import queue
import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..core.tensor import Tensor
from .sampler import BatchSampler


def default_collate_fn(batch):
    """Stack samples into batched numpy arrays (structure-preserving).
    ref: python/paddle/io/dataloader/collate.py"""
    sample = batch[0]
    if isinstance(sample, Tensor):
        return Tensor(np.stack([np.asarray(s._data) for s in batch]))
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, float, np.number)):
        return np.asarray(batch)
    if isinstance(sample, (str, bytes)):
        return list(batch)
    if isinstance(sample, dict):
        return {k: default_collate_fn([s[k] for s in batch])
                for k in sample}
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return type(sample)(default_collate_fn(list(t)) for t in transposed)
    return batch


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(prefetch_factor, 2)
        self.use_shared_memory = use_shared_memory
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self.persistent_workers = persistent_workers
        if persistent_workers and num_workers > 0:
            import warnings
            warnings.warn(
                "persistent_workers is not implemented: workers are "
                "forked per epoch (fork is cheap on Linux; worker state "
                "does not persist across epochs)", stacklevel=2)
        # num_workers>0 => worker PROCESSES (the reference contract);
        # transforms must be fork-safe numpy/IO — don't return device
        # Tensors from dataset.__getitem__ under workers. The env flag
        # forces the legacy in-process thread pool.
        self._use_processes = (num_workers > 0 and hasattr(os, "fork")
                               and not os.environ.get(
                                   "FLAGS_dataloader_use_threads"))
        from .dataset import IterableDataset
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)

    def __len__(self):
        if self._iterable:
            raise TypeError("IterableDataset has no fixed length")
        return len(self.batch_sampler)

    def _iter_iterable(self):
        batch = []
        for sample in self.dataset:
            batch.append(sample)
            if len(batch) == self.batch_size:
                yield self.collate_fn(batch)
                batch = []
        if batch and not self.drop_last:
            yield self.collate_fn(batch)

    def __iter__(self):
        if self.num_workers > 0 and self._use_processes:
            yield from self._iter_multiprocess()
            return
        if self._iterable:
            yield from self._iter_iterable()
            return
        if self.num_workers == 0:
            for idx_batch in self.batch_sampler:
                samples = [self.dataset[i] for i in idx_batch]
                yield self.collate_fn(samples)
            return
        yield from self._iter_prefetch()

    def _iter_multiprocess(self):
        """Worker PROCESSES + shared-memory transport (ref:
        dataloader_iter.py _DataLoaderIterMultiProcess :370 + worker.py
        _worker_loop :281 + mmap_allocator.cc). At most
        prefetch_factor * num_workers index batches are in flight (a
        consumed result refills the worker that produced it); results are
        re-ordered to sampler order. Workers are forked so transforms run
        off the main process and off the GIL — fork of a JAX-threaded
        parent is the same documented tradeoff the reference/torch take
        on Linux; set FLAGS_dataloader_use_threads=1 if a fork ever
        misbehaves in your setup. Worker death (even SIGKILL, which
        sends no 'end') is detected by a liveness poll instead of
        hanging."""
        import multiprocessing as mp
        import queue as queue_mod

        from .worker import _decode, _release_shm, _worker_loop

        ctx = mp.get_context("fork" if hasattr(os, "fork") else "spawn")
        result_q = ctx.Queue()
        base_seed = int(np.random.randint(0, 2 ** 31 - 1))
        workers, index_qs = [], []
        iterable = self._iterable
        # per-run /dev/shm directory: segments are unlinked as decoded,
        # and the whole dir is removed at teardown so early exit or a
        # worker killed mid-handoff cannot leak tmpfs (RAM) files
        from .worker import _shm_ok
        shm_dir = None
        if self.use_shared_memory and _shm_ok():
            import tempfile
            shm_dir = tempfile.mkdtemp(dir="/dev/shm", prefix="ptpu_dl_")
        timeout = self.timeout if self.timeout and self.timeout > 0 \
            else None
        poll = min(timeout, 5.0) if timeout else 5.0

        ended = set()  # worker ids that posted their 'end' sentinel

        def get_result():
            """Queue get with liveness detection and a descriptive
            timeout error instead of a bare queue.Empty. A worker that
            posted 'end' is allowed to be gone; one that vanished without
            it (SIGKILL/OOM) means lost batches."""
            waited = 0.0
            while True:
                try:
                    return result_q.get(timeout=poll)
                except queue_mod.Empty:
                    waited += poll
                    dead = [w.name for i, w in enumerate(workers)
                            if not w.is_alive() and i not in ended]
                    if dead:
                        raise RuntimeError(
                            f"DataLoader worker(s) {dead} died without "
                            f"reporting (killed? OOM?) — batches are "
                            f"lost") from None
                    if timeout and waited >= timeout:
                        raise RuntimeError(
                            f"DataLoader timed out after {timeout}s "
                            f"waiting for a worker batch") from None

        try:
            for wid in range(self.num_workers):
                # map-style: index batches; iterable: flow-control tokens
                iq = ctx.Queue()
                index_qs.append(iq)
                w = ctx.Process(
                    target=_worker_loop,
                    args=(self.dataset, self.collate_fn, iq, result_q,
                          wid, self.num_workers, base_seed,
                          self.worker_init_fn, shm_dir,
                          iterable, self.batch_size
                          if iterable else 0, self.drop_last
                          if iterable else False),
                    daemon=True)
                w.start()
                workers.append(w)

            if iterable:
                # arrival order; each worker streams its own shard,
                # bounded to prefetch_factor tokens in flight per worker
                for iq in index_qs:
                    for _ in range(self.prefetch_factor):
                        iq.put(True)
                live = self.num_workers
                while live:
                    msg = get_result()
                    if msg[0] == "end":
                        ended.add(msg[1])
                        live -= 1
                    elif msg[0] == "error":
                        raise RuntimeError(
                            f"DataLoader worker {msg[1]} failed:\n"
                            f"{msg[2]}")
                    else:
                        _, wid, payload = msg
                        index_qs[wid].put(True)  # return the token
                        yield _decode(payload)
                return

            # map-style: bounded dispatch — initial round-robin window,
            # then refill the worker that returned a result (adaptively
            # balances slow workers); re-order results to sampler order
            sampler_it = enumerate(iter(self.batch_sampler))
            window = self.prefetch_factor * self.num_workers
            n_sent = 0
            exhausted = False
            owner = {}  # batch idx -> worker id

            def send_next(wid):
                nonlocal n_sent, exhausted
                if exhausted:
                    return False
                try:
                    bidx, idx_batch = next(sampler_it)
                except StopIteration:
                    exhausted = True
                    for iq in index_qs:
                        iq.put(None)
                    return False
                index_qs[wid].put((bidx, list(idx_batch)))
                owner[bidx] = wid
                n_sent += 1
                return True

            for i in range(window):
                if not send_next(i % self.num_workers):
                    break
            buf, next_idx, received = {}, 0, 0
            live = self.num_workers
            while not exhausted or next_idx < n_sent:
                if next_idx in buf:
                    yield buf.pop(next_idx)
                    next_idx += 1
                    continue
                if received >= n_sent and exhausted:
                    break  # nothing further can arrive
                msg = get_result()
                if msg[0] == "end":
                    ended.add(msg[1])
                    live -= 1
                    if live == 0 and (not exhausted or
                                      received < n_sent):
                        raise RuntimeError(
                            "DataLoader workers exited before producing "
                            "all batches")
                    continue
                if msg[0] == "error":
                    raise RuntimeError(
                        f"DataLoader worker {msg[1]} failed:\n{msg[2]}")
                bidx, data = msg
                received += 1
                buf[bidx] = _decode(data)
                send_next(owner.pop(bidx))
        finally:
            for w in workers:
                if w.is_alive():
                    w.terminate()
            for w in workers:
                w.join(timeout=5)
            # drain undecoded results (unlinks their segments), then
            # remove the per-run dir — catches even segments whose queue
            # message never landed (worker killed mid-put)
            while True:
                try:
                    msg = result_q.get_nowait()
                except Exception:
                    break
                if msg and msg[0] not in ("end", "error"):
                    _release_shm(msg[-1])
            if shm_dir is not None:
                import shutil
                shutil.rmtree(shm_dir, ignore_errors=True)

    def _iter_prefetch(self):
        """Thread-pool fetch + bounded queue prefetch."""
        q: queue.Queue = queue.Queue(
            maxsize=self.num_workers * self.prefetch_factor)
        sentinel = object()

        def producer():
            # submit lazily: at most queue-capacity + workers batches in
            # flight, so a slow consumer can't accumulate the whole epoch
            try:
                with ThreadPoolExecutor(self.num_workers) as pool:
                    def fetch(idx_batch):
                        samples = [self.dataset[i] for i in idx_batch]
                        return self.collate_fn(samples)
                    pending = []
                    it = iter(self.batch_sampler)
                    for idx_batch in it:
                        pending.append(pool.submit(fetch, idx_batch))
                        if len(pending) >= self.num_workers:
                            q.put(pending.pop(0).result())
                    for fut in pending:
                        q.put(fut.result())
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is sentinel:
                break
            yield item
        t.join()
