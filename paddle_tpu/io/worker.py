"""DataLoader worker processes + shared-memory transport.

ref: python/paddle/io/dataloader/worker.py (_worker_loop :281,
WorkerInfo/get_worker_info) and paddle/phi/core/memory/allocation/
mmap_allocator.cc (shared-memory sample transport). TPU-native shape:
workers are forked CPU processes running dataset.__getitem__ + collate
(pure numpy/IO — JAX/device state stays in the parent); big arrays
travel through /dev/shm memmap files instead of the queue pipe, sidestepping
both pickling-through-pipe copies and the multiprocessing.shared_memory
resource-tracker cross-process warts. The parent reads then unlinks each
file, so segment lifetime is one batch.
"""
from __future__ import annotations

import os
import random
import tempfile

import numpy as np

__all__ = ["WorkerInfo", "get_worker_info"]

_SHM_DIR = "/dev/shm"
_SHM_MIN_BYTES = 16 * 1024  # below this, pipe pickling is cheaper


class WorkerInfo:
    """ref: io/dataloader/worker.py WorkerInfo — read-only description of
    the calling worker (None in the main process)."""

    def __init__(self, id, num_workers, seed, dataset):
        self.id = id
        self.num_workers = num_workers
        self.seed = seed
        self.dataset = dataset

    def __repr__(self):
        return (f"WorkerInfo(id={self.id}, num_workers={self.num_workers},"
                f" seed={self.seed})")


_worker_info: WorkerInfo | None = None


def get_worker_info():
    """ref: paddle.io.get_worker_info — the current worker's info inside
    a DataLoader worker process, None in the main process. IterableDataset
    shards itself with this (id/num_workers)."""
    return _worker_info


def _shm_ok():
    return os.name == "posix" and os.path.isdir(_SHM_DIR)


def _encode(obj, use_shm):
    """Structure-preserving encode for the result queue: big ndarrays ->
    /dev/shm memmap descriptors; Tensors -> tagged ndarrays (workers must
    not touch device state, the parent re-wraps). ``use_shm`` is the
    per-run segment DIRECTORY (or None): the parent rmtree's it at
    iterator teardown, so a worker killed mid-handoff can't leak
    segments."""
    from ..core.tensor import Tensor
    if isinstance(obj, Tensor):
        return ("__tensor__", _encode(np.asarray(obj._data), use_shm))
    if isinstance(obj, np.ndarray):
        if use_shm and obj.nbytes >= _SHM_MIN_BYTES:
            fd, path = tempfile.mkstemp(dir=use_shm, prefix="ptpu_dl_")
            os.close(fd)
            mm = np.memmap(path, dtype=obj.dtype, mode="w+",
                           shape=obj.shape if obj.shape else (1,))
            mm[...] = obj if obj.shape else obj.reshape(1)
            mm.flush()
            del mm
            return ("__shm__", path, str(obj.dtype), obj.shape)
        return obj
    if isinstance(obj, dict):
        return {k: _encode(v, use_shm) for k, v in obj.items()}
    if isinstance(obj, tuple):
        return tuple(_encode(v, use_shm) for v in obj)
    if isinstance(obj, list):
        return ["__list__"] + [_encode(v, use_shm) for v in obj]
    return obj


def _decode(obj):
    from ..core.tensor import Tensor
    tag = obj[0] if (isinstance(obj, tuple) and obj
                     and isinstance(obj[0], str)) else None
    if tag == "__tensor__":
        import jax.numpy as jnp
        return Tensor(jnp.asarray(_decode(obj[1])))
    if tag == "__shm__":
        _, path, dtype, shape = obj
        mm = np.memmap(path, dtype=np.dtype(dtype), mode="r",
                       shape=shape if shape else (1,))
        arr = np.array(mm)  # own the data before the file goes away
        del mm
        try:
            os.unlink(path)
        except OSError:
            pass
        return arr if shape else arr.reshape(())
    if isinstance(obj, dict):
        return {k: _decode(v) for k, v in obj.items()}
    if isinstance(obj, tuple):
        return tuple(_decode(v) for v in obj)
    if isinstance(obj, list) and obj and isinstance(obj[0], str) and \
            obj[0] == "__list__":
        return [_decode(v) for v in obj[1:]]
    return obj


def _release_shm(obj):
    """Unlink every /dev/shm segment referenced by an UNdecoded message
    (early-exit / error cleanup — normally _decode unlinks on read)."""
    tag = obj[0] if (isinstance(obj, tuple) and obj
                     and isinstance(obj[0], str)) else None
    if tag == "__shm__":
        try:
            os.unlink(obj[1])
        except OSError:
            pass
        return
    if tag == "__tensor__":
        _release_shm(obj[1])
        return
    if isinstance(obj, dict):
        for v in obj.values():
            _release_shm(v)
    elif isinstance(obj, (list, tuple)):
        for v in obj:
            _release_shm(v)


def _seed_worker(worker_id, base_seed):
    seed = (base_seed + worker_id) % (2 ** 31)
    np.random.seed(seed)
    random.seed(seed)
    try:
        from ..core import random as random_mod
        random_mod.seed(seed)
    except Exception:
        pass
    return seed


def _worker_loop(dataset, collate_fn, index_queue, result_queue, worker_id,
                 num_workers, base_seed, worker_init_fn, use_shared_memory,
                 iterable, batch_size, drop_last):
    """ref: worker.py _worker_loop — consume index batches, emit collated
    results, exit on the None sentinel. For IterableDataset the worker
    iterates its own (get_worker_info-sharded) stream instead."""
    global _worker_info
    seed = _seed_worker(worker_id, base_seed)
    _worker_info = WorkerInfo(worker_id, num_workers, seed, dataset)
    # use_shared_memory arrives as the per-run /dev/shm directory path
    # (already gated on _shm_ok by the parent) or None
    use_shm = use_shared_memory
    try:
        if worker_init_fn is not None:
            worker_init_fn(worker_id)
        if iterable:
            # flow control: one token (from index_queue) is consumed per
            # emitted batch; the parent returns tokens as it consumes,
            # bounding in-flight batches like the map-style window
            batch = []
            for sample in dataset:
                batch.append(sample)
                if len(batch) == batch_size:
                    index_queue.get()
                    result_queue.put(
                        ("data", worker_id,
                         _encode(collate_fn(batch), use_shm)))
                    batch = []
            if batch and not drop_last:
                index_queue.get()
                result_queue.put(
                    ("data", worker_id,
                     _encode(collate_fn(batch), use_shm)))
        else:
            while True:
                item = index_queue.get()
                if item is None:
                    break
                bidx, idxs = item
                data = collate_fn([dataset[i] for i in idxs])
                result_queue.put((bidx, _encode(data, use_shm)))
    except KeyboardInterrupt:
        pass
    except Exception:  # propagate the traceback, don't hang the parent
        import traceback
        result_queue.put(("error", worker_id, traceback.format_exc()))
    finally:
        result_queue.put(("end", worker_id))
