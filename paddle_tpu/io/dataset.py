"""Dataset abstractions. ref: python/paddle/io/dataloader/dataset.py"""
from __future__ import annotations

import bisect

import numpy as np


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            sample = d[idx]
            if isinstance(sample, (list, tuple)):
                out.extend(sample)
            else:
                out.append(sample)
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            for sample in d:
                yield sample


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumulative_sizes = np.cumsum(
            [len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx = len(self) + idx
        ds_idx = bisect.bisect_right(self.cumulative_sizes, idx)
        prev = 0 if ds_idx == 0 else self.cumulative_sizes[ds_idx - 1]
        return self.datasets[ds_idx][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    from ..core import random as random_mod
    import jax
    n = len(dataset)
    if all(isinstance(l, float) for l in lengths):
        lengths = [int(np.floor(n * l)) for l in lengths]
        lengths[-1] = n - sum(lengths[:-1])
    if sum(lengths) != n:
        raise ValueError("sum of lengths must equal dataset size")
    perm = np.asarray(jax.random.permutation(
        random_mod.next_key(), np.arange(n)))
    out, off = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[off:off + l].tolist()))
        off += l
    return out
