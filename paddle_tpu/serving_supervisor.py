"""Self-healing serving plane: supervised decode loop with crash
recovery, adaptive admission, and canary weight rollout.

The serving PRIMITIVES all exist below this module — the paged KV pool
with admission reservations (``serving_cache``), speculative decode,
zero-downtime ``swap_weights``, warm bundles, flight traces with the
queue/decode latency split. This module is the POLICY layer that keeps
a replica alive under faults, overload, and bad deploys:

- :class:`ServingSupervisor` — watches a ``GenerationServer``'s decode
  loop thread for death (exception — ``KillPoint`` preemptions
  included — OR a heartbeat stall), auto-dumps the flight ring,
  resets the engine (fresh zero pools, ZERO recompiles — the compiled
  step programs are pure), and restarts the loop with bounded
  exponential backoff. In-flight requests are **recovered**: each
  request's committed tokens are durable host state, so recovery
  re-admits it through the normal prefill path with
  ``prompt + committed_tokens`` as the prompt — under greedy decoding
  the resumed stream is BIT-equal to an uninterrupted run. A request
  active at ``quarantine_after`` consecutive crashes is quarantined
  (terminal ``failed``, reason=poison) so one pathological input
  cannot crash-loop the replica.

- :class:`AdaptiveAdmissionPolicy` — replaces the static
  ``FLAGS_serving_shed_queue`` check (kept as
  :class:`StaticShedPolicy`, the default and the adaptive policy's
  floor) with step-boundary EWMAs of the existing evidence:
  ``blocks_free`` draining while the backlog rises raises the
  pressure level ONE step at a time — brownout first (suppress the
  speculative window, then cap the prefill chunk; both are
  step-boundary knobs on already-compiled programs), hard shedding
  only above both — and deadline-aware rejection fails an unmeetable
  request at submit, before it burns blocks. Every decision is
  journaled (``journal()`` + flight ``admission`` events + counters).

- :func:`rollout` — drives ``swap_weights`` across replicas in
  stages: swap the CANARY first, watch ``swap_seconds``, the
  rejection counters, a non-finite-weight scan, and a token-level
  canary probe (a fixed probe prompt decoded pre/post-swap); any
  trip auto-rolls the canary back via the retained pre-swap prepared
  weights (streams restored bit-equal) and HALTS the rollout — the
  rest of the fleet never sees the bad checkpoint.

Capture-plane note: everything here is HOST control flow by design —
recovery bookkeeping, EWMA state and rollout staging advance BETWEEN
the captured serving programs (see ``CAPTURE_ALLOWLIST``).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from .core.flags import flag_value
from .observability import flight as _flight
from .observability import metrics as _om
from .utils import backoff as _backoff

__all__ = ["ServingSupervisor", "supervise", "StaticShedPolicy",
           "AdaptiveAdmissionPolicy", "default_policy", "RolloutPolicy",
           "rollout"]

_M = _om.scope("serving")
_M_restarts = _M.counter(
    "supervisor_restarts_total",
    "Decode-loop restarts by the supervisor (crash or stall), each "
    "after a bounded-exponential backoff")
_M_recovered = _M.counter(
    "supervisor_recovered_total",
    "In-flight requests re-admitted after a decode-loop death with "
    "prompt + committed tokens as the prompt (greedy streams resume "
    "bit-equal)")
_M_quarantined = _M.counter(
    "supervisor_quarantined_total",
    "Requests failed as poison (reason=poison) after being active at "
    "quarantine_after consecutive decode-loop deaths — never "
    "re-admitted, so one pathological input cannot crash-loop the "
    "replica")
_M_stalls = _M.counter(
    "supervisor_stalls_total",
    "Decode-loop stalls detected by the supervisor watchdog (thread "
    "alive, heartbeat stale, work pending) — the stalled thread is "
    "fenced and a fresh loop started")
_M_brownouts = _M.counter(
    "admission_brownouts_total",
    "Adaptive-admission brownout engagements by knob (spec = "
    "speculative window suppressed, prefill = chunk capped) — the "
    "graceful degradations that precede any hard shed")
_M_rollouts = _M.counter(
    "rollouts_total", "Canary weight rollouts started")
_M_rollbacks = _M.counter(
    "rollout_rollbacks_total",
    "Canary replicas auto-rolled back to their retained pre-swap "
    "weights (probe divergence / slow swap beyond policy)")
_M_halts = _M.counter(
    "rollout_halted_total",
    "Rollouts halted before reaching every replica (canary rollback, "
    "non-finite checkpoint weights, or a swap rejection)")
_M_nonfinite = _M.counter(
    "rollout_nonfinite_weights_total",
    "Non-finite values found scanning a rollout checkpoint's prepared "
    "weights — the checkpoint never reaches any replica")


# ---------------------------------------------------------------------------
# admission policies
# ---------------------------------------------------------------------------

class StaticShedPolicy:
    """The pre-supervisor behavior as a policy object: shed exactly
    when ``GenerationServer._shed()`` says so (block-starved AND the
    backlog over ``FLAGS_serving_shed_queue``; 0 disables). No
    brownout, no deadline awareness — the fallback policy."""

    name = "static"

    def on_step(self, server) -> None:  # no step-boundary state
        return None

    def admit_verdict(self, server, prompt_len: int, max_new: int,
                      deadline: Optional[float]) -> Optional[str]:
        return "shed" if server._shed() else None

    def journal(self) -> List[dict]:
        return []


class AdaptiveAdmissionPolicy:
    """Step-boundary adaptive admission over EWMAs of the evidence the
    serving plane already exports.

    ``on_step`` (called by the decode loop at every step boundary)
    folds ``blocks_free``, the backlog (queued + block-deferred) and
    the committed-token throughput into EWMAs and moves a pressure
    LEVEL one step per boundary — so the journal always shows the
    graceful path engage in order, and release the same way:

    ====== =================== =======================================
    level  name                effect
    ====== =================== =======================================
    0      normal              —
    1      brownout_spec       speculative window suppressed (plain
                               steps; the +spec_k block pre-extension
                               is the first draw to shed)
    2      brownout_prefill    prefill chunk capped (long prompts draw
                               smaller slices of each step)
    3      shed                submit() rejects (reason=shed)
    ====== =================== =======================================

    Pressure RISES while the pool is starved (available blocks at or
    below ``starve_frac`` of the pool) with a backlog behind it, and
    FALLS as the evidence clears (hysteresis: release needs the
    backlog EWMA to drain, not one lucky step). ``admit_verdict``
    additionally re-checks on the submit thread so a cleared replica
    whose loop is parked idle releases immediately, applies
    deadline-aware rejection — a request whose deadline cannot be met
    at the observed steps/sec is rejected at submit instead of
    expiring after burning blocks — and keeps the static
    ``FLAGS_serving_shed_queue`` rule as a floor. Every transition
    and rejection decision is journaled (bounded ``journal()``, flight
    ``admission`` events, counters)."""

    name = "adaptive"
    LEVEL_NAMES = ("normal", "brownout_spec", "brownout_prefill",
                   "shed")

    def __init__(self, alpha: float = 0.5,
                 starve_frac: float = 0.125,
                 queue_bound: Optional[int] = None,
                 brownout_chunk: int = 8,
                 deadline_margin: float = 1.25,
                 min_steps: int = 3,
                 rate_window: float = 30.0,
                 journal_cap: int = 256):
        self.alpha = float(alpha)
        self.starve_frac = float(starve_frac)
        # hard-shed backlog bound: explicit, else the static flag,
        # else 1 deferred request
        self.queue_bound = queue_bound
        self.brownout_chunk = int(brownout_chunk)
        self.deadline_margin = float(deadline_margin)
        self.min_steps = int(min_steps)
        self.rate_window = float(rate_window)
        self.level = 0
        self._journal: deque = deque(maxlen=int(journal_cap))
        self._ewma_avail: Optional[float] = None
        self._ewma_backlog = 0.0
        # PER-REQUEST tokens/sec: the deadline estimator's rate.
        # Steps/sec alone under-counts speculative decoding (a spec
        # step commits up to k tokens per request) and would reject
        # meetable requests; delivered tokens normalized by the batch
        # width measure what one request actually experiences
        self._ewma_rps: Optional[float] = None
        self._steps_seen = 0
        # (t, steps, tokens) at the last rate measurement
        self._last: Optional[Tuple[float, int, int]] = None

    # -- evidence -----------------------------------------------------------
    def _bound(self) -> int:
        if self.queue_bound is not None:
            return int(self.queue_bound)
        return int(flag_value("serving_shed_queue")) or 1

    def _mix(self, prev: Optional[float], x: float) -> float:
        if prev is None:
            return float(x)
        return self.alpha * float(x) + (1.0 - self.alpha) * prev

    def on_step(self, server) -> None:
        """Fold the step boundary's evidence into the EWMAs, move the
        pressure level at most ONE step, and install the brownout
        knobs on the engine. Runs on the decode-loop thread."""
        now = time.monotonic()
        paged = getattr(server, "_paged", False)
        total = server.engine._kv.num_blocks if paged else 0
        avail = server.engine._kv.available_blocks() if paged else total
        backlog = server._q.qsize() + len(server._waiting)
        self._ewma_avail = self._mix(self._ewma_avail, avail)
        self._ewma_backlog = self._mix(self._ewma_backlog, backlog)
        if self._last is None:
            self._last = (now, server.steps_run,
                          server.tokens_delivered)
        else:
            dt = now - self._last[0]
            steps = server.steps_run - self._last[1]
            tokens = server.tokens_delivered - self._last[2]
            # rate over REAL decode progress only: the loop also calls
            # on_step from its prefill/waiting cycling branch, and
            # mixing those zero-step intervals in would decay the rate
            # toward 0 and spuriously deadline-reject everything (a
            # truly wedged loop is the stall watchdog's job, not this
            # estimator's). An interval longer than rate_window is an
            # IDLE GAP, not a measurement: the first step after an
            # hour of silence must not average over the hour and
            # crater the rate — skip the sample, restart the window
            if steps > 0 and dt > 1e-6:
                if dt <= self.rate_window and tokens > 0:
                    width = max(len(server._slots)
                                + len(server._prefilling), 1)
                    self._ewma_rps = self._mix(self._ewma_rps,
                                               tokens / dt / width)
                self._last = (now, server.steps_run,
                              server.tokens_delivered)
        self._steps_seen += 1

        starved = (paged and total > 0
                   and self._ewma_avail <= self.starve_frac * total)
        if starved and self._ewma_backlog > self._bound():
            target = 3
        elif starved and self._ewma_backlog >= 1.0:
            target = 2
        elif starved and backlog > 0:
            target = 1
        elif not starved and self._ewma_backlog < 0.5:
            target = 0
        else:
            target = self.level  # hysteresis band: hold
        self._move_level(server, target, avail=avail, backlog=backlog)

    def _move_level(self, server, target: int, **evidence) -> None:
        if target == self.level:
            return
        # one step per boundary: brownout ALWAYS precedes shed on the
        # way up, and shedding releases through brownout on the way
        # down — the journal reads as the staircase it is
        new = self.level + (1 if target > self.level else -1)
        old, self.level = self.level, new
        event = ("engage_" if new > old else "release_") \
            + self.LEVEL_NAMES[max(new, old)]
        self._note(event, level=new, **evidence)
        if new > old and new in (1, 2):
            _M_brownouts.inc(knob="spec" if new == 1 else "prefill")
        server._apply_brownout(
            spec_off=new >= 1,
            chunk_cap=self.brownout_chunk if new >= 2 else None)

    def _note(self, event: str, **attrs) -> None:
        entry = {"t": time.monotonic(), "event": event}
        entry.update(attrs)
        self._journal.append(entry)
        _flight.record("admission", event, **attrs)

    def journal(self) -> List[dict]:
        """The bounded decision journal (oldest → newest): every
        level transition, shed and deadline rejection with the
        evidence it was decided on."""
        return list(self._journal)

    # -- submit-side --------------------------------------------------------
    def _maybe_release(self, server) -> None:
        """Submit-thread release path: an idle loop runs no step
        boundaries, so a cleared replica must not stay wedged at its
        last pressure level. Evidence-clear here drops straight to
        normal (journaled)."""
        if self.level == 0:
            return
        paged = getattr(server, "_paged", False)
        total = server.engine._kv.num_blocks if paged else 0
        avail = server.engine._kv.available_blocks() if paged else 0
        backlog = server._q.qsize() + len(server._waiting)
        if backlog == 0 and (not paged or total == 0
                             or avail > self.starve_frac * total):
            self._ewma_backlog = 0.0
            self._ewma_avail = float(avail)
            old, self.level = self.level, 0
            self._note("release_clear", from_level=old, available=avail)
            server._apply_brownout(spec_off=False, chunk_cap=None)

    def admit_verdict(self, server, prompt_len: int, max_new: int,
                      deadline: Optional[float]) -> Optional[str]:
        self._maybe_release(server)
        if self.level >= 3:
            self._note("shed", backlog=server._q.qsize()
                       + len(server._waiting))
            return "shed"
        if server._shed():  # the static flag stays the policy FLOOR
            self._note("shed_static")
            return "shed"
        if deadline is not None and self._ewma_rps \
                and self._steps_seen >= self.min_steps:
            est = self.deadline_margin * max_new / self._ewma_rps
            if est > deadline:
                self._note("deadline_reject", estimate=round(est, 3),
                           deadline=deadline, max_new=max_new)
                return "deadline"
        return None


def default_policy():
    """The policy ``GenerationServer`` installs when none is passed:
    ``FLAGS_serving_admission_policy`` — 'adaptive' builds
    :class:`AdaptiveAdmissionPolicy` with defaults, anything else the
    static fallback."""
    if str(flag_value("serving_admission_policy")).strip() == "adaptive":
        return AdaptiveAdmissionPolicy()
    return StaticShedPolicy()


# ---------------------------------------------------------------------------
# the supervisor
# ---------------------------------------------------------------------------

class ServingSupervisor:
    """Crash/stall supervisor for one ``GenerationServer``.

    A monitor thread polls the decode-loop thread. On death (the loop
    thread died — ``KillPoint`` and friends re-raise through
    ``GenerationServer._run``'s BaseException boundary) or stall
    (alive, heartbeat older than ``stall_seconds`` while holding
    work), it:

    1. auto-dumps the flight ring (``trigger=supervisor``),
    2. FENCES the old loop (epoch bump — a zombie that wakes later
       exits without touching state),
    3. strikes every request that was active (in a slot or
       prefilling); a request at ``quarantine_after`` strikes is
       quarantined — terminal ``failed`` with reason=poison — the
       rest are queued for recovery with ``prompt + committed
       tokens`` as their prompt (greedy streams resume bit-equal),
    4. resets the engine (fresh zero pools; compiled programs kept —
       zero recompiles) and clears the slot tables,
    5. sleeps the bounded exponential backoff and restarts the loop.

    ``max_restarts`` consecutive deaths (the streak resets after
    ``healthy_seconds`` without one) give up: everything pending is
    failed so no caller hangs, and the monitor exits. All of it is
    counted (``serving.supervisor_*``) and journaled (flight
    ``supervisor`` events)."""

    def __init__(self, server, *, backoff: Optional[float] = None,
                 backoff_cap: float = 2.0, max_restarts: int = 8,
                 stall_seconds: Optional[float] = None,
                 quarantine_after: int = 2, healthy_seconds: float = 5.0,
                 poll: float = 0.01, dump_on_death: bool = True):
        self.server = server
        self.backoff = float(flag_value("serving_supervisor_backoff")
                             if backoff is None else backoff)
        self.backoff_cap = float(backoff_cap)
        self.max_restarts = int(max_restarts)
        self.stall_seconds = float(
            flag_value("serving_supervisor_stall_seconds")
            if stall_seconds is None else stall_seconds)
        self.quarantine_after = max(int(quarantine_after), 1)
        self.healthy_seconds = float(healthy_seconds)
        self.poll = float(poll)
        self.dump_on_death = bool(dump_on_death)
        self.restarts = 0
        self.recovered = 0
        self.quarantined = 0
        self.stalls = 0
        self.gave_up = False
        self._streak = 0
        self._deaths = 0  # death index, for consecutive-strike checks
        self._last_death = 0.0
        self._stop_evt = threading.Event()
        self._thread = threading.Thread(target=self._monitor,
                                        daemon=True,
                                        name="serving-supervisor")
        self._thread.start()
        _flight.record("supervisor", "attached",
                       stall_seconds=self.stall_seconds,
                       max_restarts=self.max_restarts)

    # -- lifecycle ----------------------------------------------------------
    def stop(self, timeout: float = 5.0) -> None:
        """Detach: stop monitoring (the server keeps running
        unsupervised)."""
        self._stop_evt.set()
        self._thread.join(timeout)

    def stats(self) -> Dict[str, int]:
        return {"restarts": self.restarts, "recovered": self.recovered,
                "quarantined": self.quarantined, "stalls": self.stalls,
                "gave_up": int(self.gave_up)}

    # -- monitor ------------------------------------------------------------
    def _monitor(self) -> None:
        srv = self.server
        while not self._stop_evt.wait(self.poll):
            if srv._drained.is_set():
                return  # clean shutdown: nothing left to supervise
            thread = srv._thread
            if not thread.is_alive():
                if srv._stopping.is_set():
                    # died mid-drain: restarting would serve nobody —
                    # unblock shutdown() by failing what's left
                    self._abort_drain()
                    return
                if not self._handle_death("crash",
                                          error=srv._crash_error):
                    return
                continue
            if self.stall_seconds > 0 and not srv._idle \
                    and not srv._stopping.is_set() \
                    and (time.monotonic() - srv._beat
                         > self.stall_seconds) \
                    and self._has_work():
                self.stalls += 1
                _M_stalls.inc()
                if not self._handle_death("stall", error=None):
                    return

    def _has_work(self) -> bool:
        srv = self.server
        return bool(srv._slots or srv._prefilling or srv._waiting
                    or not srv._q.empty())

    # -- death handling -----------------------------------------------------
    def _handle_death(self, kind: str,
                      error: Optional[BaseException]) -> bool:
        """Recover from one decode-loop death. Returns False when the
        supervisor gives up (monitor should exit)."""
        srv = self.server
        now = time.monotonic()
        if now - self._last_death > self.healthy_seconds:
            self._streak = 0  # the last incarnation lived long enough
        self._last_death = now
        self._streak += 1
        self._deaths += 1
        err = type(error).__name__ if error is not None else kind
        _flight.record("supervisor", "loop_death", kind=kind,
                       error=err, streak=self._streak,
                       in_flight=len(srv._slots) + len(srv._prefilling))
        if self.dump_on_death:
            try:
                _flight.dump(trigger="supervisor",
                             note=f"decode loop {kind}: {err}")
            except Exception:  # noqa: BLE001 — forensics best-effort
                pass
        # fence FIRST: a stalled zombie that wakes mid-recovery must
        # see the new epoch before it can commit tokens or fail the
        # requests this recovery is about to re-admit
        srv._epoch += 1
        if self._streak > self.max_restarts:
            self._give_up(kind, err)
            return False
        recovered, poisoned = self._collect_victims()
        try:
            reset = getattr(srv.engine, "reset_state", None)
            if reset is not None:
                reset()
        except Exception as e:  # noqa: BLE001 — recovery must continue
            _flight.record("supervisor", "reset_error",
                           error=type(e).__name__)
        for req in poisoned:
            self.quarantined += 1
            srv.quarantined += 1
            _M_quarantined.inc()
            _flight.record("supervisor", "quarantine",
                           trace_id=req.get("trace_id"),
                           reason="poison", crashes=req["crashes"])
            srv._fail(req, RuntimeError(
                f"request quarantined (reason=poison): it was active "
                f"at {req['crashes']} consecutive decode-loop "
                f"deaths — re-admitting it again would crash-loop "
                f"the replica"))
        now2 = time.monotonic()
        for req in recovered:
            # fold ONLY the not-yet-folded committed tokens into the
            # prompt: a request recovered a second time (quarantine
            # threshold > 2) already carries its first recovery's
            # tokens in the prompt — re-folding them would duplicate
            # the stream and break the bit-equal resume contract
            folded = req.get("folded", 0)
            fresh = np.asarray(req["out"][folded:], np.int32)
            if fresh.size:
                req["prompt"] = np.concatenate([req["prompt"], fresh])
            req["folded"] = len(req["out"])
            req.pop("t_admit", None)
            # rebase the queue-latency origin: queue_seconds is the
            # documented submit->admission wait — pre-crash DECODE
            # time must not masquerade as admission starvation
            req["t_queue0"] = now2
            self.recovered += 1
            srv.recovered += 1
            _M_recovered.inc()
            _flight.record("supervisor", "recover",
                           trace_id=req.get("trace_id"),
                           tokens=len(req["out"]),
                           crashes=req["crashes"])
        # recovered requests head the deferred list IN their original
        # submit order: _admit drains _waiting before the queue (and
        # holds the line), so nothing newer overtakes a resumed stream
        srv._waiting = recovered + srv._waiting
        delay = _backoff.full_jitter(
            min(self.backoff * (2 ** (self._streak - 1)),
                self.backoff_cap))
        if delay > 0:
            time.sleep(delay)
        self.restarts += 1
        srv.loop_restarts += 1
        _M_restarts.inc()
        srv._start_loop()
        _flight.record("supervisor", "restart", kind=kind,
                       backoff=round(delay, 4), streak=self._streak,
                       recovered=len(recovered),
                       quarantined=len(poisoned))
        return True

    def _collect_victims(self) -> Tuple[List[dict], List[dict]]:
        """Strike every request that was ACTIVE at the death (holding
        a slot or prefilling) and split them into (recovered,
        poisoned) by strike count; clears the slot tables. Requests
        merely queued or block-deferred were untouched by the crash
        and stay where they are."""
        srv = self.server
        active = list(srv._slots.values()) \
            + list(srv._prefilling.values())
        srv._slots.clear()
        srv._prefilling.clear()
        recovered: List[dict] = []
        poisoned: List[dict] = []
        for req in sorted(active, key=lambda r: r["t0"]):
            if req["done"].is_set():
                continue
            # strikes count CONSECUTIVE deaths only (the documented
            # quarantine contract): a request that sat out a death —
            # recovered, decoded healthily, and was merely a
            # bystander at a much later unrelated crash — starts its
            # count over instead of inheriting old strikes
            if req.get("strike_death") is not None \
                    and req["strike_death"] != self._deaths - 1:
                req["crashes"] = 0
            req["strike_death"] = self._deaths
            req["crashes"] = req.get("crashes", 0) + 1
            if req["crashes"] >= self.quarantine_after:
                poisoned.append(req)
            else:
                recovered.append(req)
        return recovered, poisoned

    def _give_up(self, kind: str, err: str) -> None:
        """Restart budget exhausted: fail everything pending so no
        caller blocks forever, journal, and stop supervising."""
        srv = self.server
        self.gave_up = True
        reason = RuntimeError(
            f"serving supervisor gave up after {self.max_restarts} "
            f"consecutive decode-loop deaths (last: {kind}/{err})")
        # stop the intake FIRST (under the submit lock, so nothing
        # slips past the check into the queue after the drain below)
        # and mark drained: the loop is dead for good — later
        # submit() calls reject fast and shutdown() returns instead
        # of timing out against a drain that can never happen
        with srv._submit_lock:
            srv._stopping.set()
        recovered, poisoned = self._collect_victims()
        for req in recovered + poisoned + srv._waiting:
            if not req["done"].is_set():
                srv._fail(req, reason)
        srv._waiting = []
        while True:
            try:
                req = srv._q.get_nowait()
            except Exception:  # noqa: BLE001 — Empty only
                break
            if req is not srv._STOP and not req["done"].is_set():
                srv._fail(req, reason)
        srv._set_gauges()
        srv._drained.set()
        _flight.record("supervisor", "give_up", kind=kind, error=err,
                       restarts=self.restarts)

    def _abort_drain(self) -> None:
        """The loop died while shutdown() was draining: fail the
        leftovers and mark the server drained so shutdown's wait
        returns instead of timing out."""
        srv = self.server
        reason = RuntimeError(
            "decode loop died during shutdown drain")
        for table in (srv._slots, srv._prefilling):
            for slot, req in list(table.items()):
                srv._fail(req, reason)
                srv._release_slot(slot, evicted=True)
            table.clear()
        for req in srv._waiting:
            if not req["done"].is_set():
                srv._fail(req, reason)
        srv._waiting = []
        srv._set_gauges()
        _flight.record("supervisor", "abort_drain")
        srv._drained.set()


def supervise(server, **kwargs) -> ServingSupervisor:
    """Attach a :class:`ServingSupervisor` to ``server`` (kwargs
    forwarded to the constructor). Returns the supervisor."""
    return ServingSupervisor(server, **kwargs)


# ---------------------------------------------------------------------------
# canary rollout
# ---------------------------------------------------------------------------

class RolloutPolicy:
    """What :func:`rollout` watches on the canary, and the probe it
    decodes. ``max_divergence`` is the tolerated fraction of probe
    tokens that may change across the swap — 0.0 demands bit-equal
    probes (right for a hotfix re-deploy of identical weights), a
    real fine-tune sets it to taste. ``max_swap_seconds`` (None =
    off) additionally bounds the step-boundary stall a swap may
    cost."""

    def __init__(self, probe_prompt=(1, 2, 3, 4), probe_tokens: int = 8,
                 max_divergence: float = 0.25,
                 require_finite: bool = True,
                 max_swap_seconds: Optional[float] = None,
                 probe_timeout: float = 120.0):
        self.probe_prompt = list(probe_prompt)
        self.probe_tokens = int(probe_tokens)
        self.max_divergence = float(max_divergence)
        self.require_finite = bool(require_finite)
        self.max_swap_seconds = max_swap_seconds
        self.probe_timeout = float(probe_timeout)


def _try_rollback(srv, retained, stage, replica: int) -> bool:
    """Best-effort canary rollback. A rollback swap that itself fails
    (loop dead, concurrent swap, timeout) must not escape rollout()
    with the fleet state unrecorded — it is journaled and reported
    instead. Returns True when the retained weights are back in."""
    try:
        srv.swap_weights(prepared=retained)
        return True
    except Exception as e:  # noqa: BLE001 — journaled, not raised
        stage["rollback_error"] = type(e).__name__
        _flight.record("rollout", "rollback_failed", replica=replica,
                       error=type(e).__name__)
        return False


def _divergence(a: List[int], b: List[int]) -> float:
    """Fraction of probe positions that changed (length differences
    count as divergent positions)."""
    n = max(len(a), len(b))
    if n == 0:
        return 0.0
    same = sum(1 for x, y in zip(a, b) if x == y)
    return 1.0 - same / n


def _count_nonfinite(prepared) -> int:
    """Non-finite values across a prepared weight tree (int8 code
    leaves cast clean; their float scales are what can go NaN). A
    fleet ``RemotePrepared`` handle carries the replica-side scan as
    ``.nonfinite`` — the tree lives in another process, so the count
    rides the handle instead of a tree walk."""
    if hasattr(prepared, "nonfinite"):
        return int(prepared.nonfinite)
    import jax
    import jax.numpy as jnp
    bad = 0
    for leaf in jax.tree_util.tree_leaves(prepared):
        arr = np.asarray(jnp.asarray(leaf, jnp.float32))
        bad += int(arr.size - np.isfinite(arr).sum())
    return bad


def rollout(checkpoint_or_state, servers, policy: Optional[RolloutPolicy]
            = None) -> dict:
    """Staged canary rollout of one checkpoint across ``servers``
    (a list of ``GenerationServer``; the first is the CANARY).

    Per the fleet contract: the checkpoint is loaded/verified once,
    scanned for non-finite weights (trip ⇒ halt before ANY replica
    swaps, counted ``serving.rollout_nonfinite_weights_total``), then
    swapped onto the canary — whose pre-swap prepared weights are
    RETAINED — and probed: the fixed probe prompt is decoded before
    and after the swap, and divergence beyond
    ``policy.max_divergence`` (or a swap slower than
    ``policy.max_swap_seconds``) auto-rolls the canary back to the
    retained weights (streams restored bit-equal, counted
    ``rollout_rollbacks_total``) and HALTS the rollout. A healthy
    canary lets the remaining replicas swap without probing. Every
    stage is journaled as flight ``rollout`` events; the returned
    report carries per-stage verdicts."""
    from .serving import GenerationServer
    policy = policy or RolloutPolicy()
    servers = list(servers)
    if not servers:
        raise ValueError("rollout needs at least one server")
    _M_rollouts.inc()
    report = {"replicas": len(servers), "swapped": 0,
              "rolled_back": 0, "halted": False, "reason": None,
              "stages": []}
    _flight.record("rollout", "begin", replicas=len(servers))
    sd = GenerationServer._swap_state(checkpoint_or_state)
    scanned = False
    for i, srv in enumerate(servers):
        canary = i == 0
        stage = {"replica": i, "canary": canary, "ok": False}
        report["stages"].append(stage)
        try:
            prepared = srv.engine.prepare_swap(sd)
        except Exception as e:  # noqa: BLE001 — a deploy gate verdict
            stage["error"] = type(e).__name__
            report["halted"], report["reason"] = True, "prepare"
            _M_halts.inc()
            _flight.record("rollout", "halted", reason="prepare",
                           replica=i, error=type(e).__name__)
            break
        if policy.require_finite and not scanned:
            scanned = True
            bad = _count_nonfinite(prepared)
            if bad:
                _M_nonfinite.inc(bad)
                report["halted"] = True
                report["reason"] = "nonfinite_weights"
                stage["nonfinite"] = bad
                _flight.record("rollout", "halted",
                               reason="nonfinite_weights", count=bad)
                break
        retained = srv.engine.params  # the rollback tree
        pre = None
        if canary:
            try:
                pre = srv.generate(policy.probe_prompt,
                                   policy.probe_tokens,
                                   timeout=policy.probe_timeout)
            except Exception as e:  # noqa: BLE001 — deploy-gate verdict
                # can't even probe the PRE-swap replica: nothing was
                # swapped, halt without touching any weights
                stage["error"] = type(e).__name__
                report["halted"], report["reason"] = True, \
                    "probe_failed"
                _M_halts.inc()
                _flight.record("rollout", "halted",
                               reason="probe_failed", replica=i,
                               error=type(e).__name__)
                break
            stage["probe_pre"] = pre
        try:
            res = srv.swap_weights(prepared=prepared)
        except Exception as e:  # noqa: BLE001 — rejection verdict
            stage["error"] = type(e).__name__
            report["halted"], report["reason"] = True, "swap_rejected"
            _M_halts.inc()
            _flight.record("rollout", "halted", reason="swap_rejected",
                           replica=i, error=type(e).__name__)
            break
        stage["swap_seconds"] = res["seconds"]
        if canary:
            try:
                post = srv.generate(policy.probe_prompt,
                                    policy.probe_tokens,
                                    timeout=policy.probe_timeout)
            except Exception as e:  # noqa: BLE001 — verdict, not crash
                # the new weights are INSTALLED and unprobeable
                # (timeout / shed under the very overload a bad
                # checkpoint causes): roll back, halt, journal — a
                # raw escape here would strand the canary on the bad
                # weights with no rollback and no report
                _try_rollback(srv, retained, stage, i)
                stage["error"] = type(e).__name__
                report["rolled_back"] += 1
                report["halted"], report["reason"] = True, \
                    "probe_failed"
                _M_rollbacks.inc()
                _M_halts.inc()
                _flight.record("rollout", "rollback", replica=i,
                               reason="probe_failed",
                               error=type(e).__name__)
                break
            div = _divergence(pre, post)
            stage["probe_post"] = post
            stage["divergence"] = div
            slow = (policy.max_swap_seconds is not None
                    and res["seconds"] > policy.max_swap_seconds)
            _flight.record("rollout", "canary_probe", replica=i,
                           divergence=round(div, 4),
                           swap_seconds=round(res["seconds"], 4))
            if div > policy.max_divergence or slow:
                _try_rollback(srv, retained, stage, i)
                report["rolled_back"] += 1
                report["halted"] = True
                report["reason"] = ("slow_swap" if slow
                                    else "probe_divergence")
                _M_rollbacks.inc()
                _M_halts.inc()
                _flight.record("rollout", "rollback", replica=i,
                               reason=report["reason"],
                               divergence=round(div, 4))
                break
        stage["ok"] = True
        report["swapped"] += 1
        _flight.record("rollout", "stage_ok", replica=i,
                       canary=canary)
    _flight.record("rollout", "end", swapped=report["swapped"],
                   halted=report["halted"],
                   reason=str(report["reason"]))
    return report
