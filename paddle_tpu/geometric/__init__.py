"""paddle.geometric equivalent — graph message passing primitives.

ref: python/paddle/geometric/ (segment_sum/mean/max/min in
math/segment.py, send_u_recv / send_ue_recv message passing in
message_passing/send_recv.py, reindex_graph in reindex.py). TPU-native:
jax.ops.segment_* (one-hot scatter-add lowers onto the MXU for large
segment counts; XLA picks the strategy).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.autograd import apply_op
from ..core.tensor import Tensor

__all__ = [
    "segment_sum", "segment_mean", "segment_max", "segment_min",
    "send_u_recv", "send_ue_recv", "reindex_graph",
]


def _num_segments(segment_ids, n):
    if n is not None:
        return int(n)
    ids = segment_ids._data if isinstance(segment_ids, Tensor) else \
        jnp.asarray(segment_ids)
    return int(jax.device_get(ids.max())) + 1 if ids.size else 0


def segment_sum(data, segment_ids, name=None, num_segments=None):
    n = _num_segments(segment_ids, num_segments)
    return apply_op(
        lambda d, i: jax.ops.segment_sum(d, i, num_segments=n),
        data, segment_ids, op_name="segment_sum")


def segment_mean(data, segment_ids, name=None, num_segments=None):
    n = _num_segments(segment_ids, num_segments)

    def f(d, i):
        s = jax.ops.segment_sum(d, i, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones((d.shape[0],), d.dtype), i,
                                  num_segments=n)
        shape = (n,) + (1,) * (d.ndim - 1)
        return s / jnp.maximum(cnt, 1).reshape(shape)
    return apply_op(f, data, segment_ids, op_name="segment_mean")


def segment_max(data, segment_ids, name=None, num_segments=None):
    n = _num_segments(segment_ids, num_segments)

    def f(d, i):
        out = jax.ops.segment_max(d, i, num_segments=n)
        # paddle returns 0 for empty segments (not -inf)
        cnt = jax.ops.segment_sum(jnp.ones((d.shape[0],)), i,
                                  num_segments=n)
        shape = (n,) + (1,) * (d.ndim - 1)
        return jnp.where(cnt.reshape(shape) > 0, out, 0).astype(d.dtype)
    return apply_op(f, data, segment_ids, op_name="segment_max")


def segment_min(data, segment_ids, name=None, num_segments=None):
    n = _num_segments(segment_ids, num_segments)

    def f(d, i):
        out = jax.ops.segment_min(d, i, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones((d.shape[0],)), i,
                                  num_segments=n)
        shape = (n,) + (1,) * (d.ndim - 1)
        return jnp.where(cnt.reshape(shape) > 0, out, 0).astype(d.dtype)
    return apply_op(f, data, segment_ids, op_name="segment_min")


_REDUCERS = {"sum": segment_sum, "mean": segment_mean, "max": segment_max,
             "min": segment_min, "add": segment_sum}


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather x[src] then segment-reduce onto dst
    (ref: message_passing/send_recv.py send_u_recv)."""
    if reduce_op not in _REDUCERS:
        raise ValueError(f"unsupported reduce_op {reduce_op!r}")
    xd = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    n = int(out_size) if out_size is not None else xd.shape[0]
    gathered = apply_op(lambda a, s: jnp.take(a, s, axis=0), x, src_index,
                        op_name="gather_src")
    return _REDUCERS[reduce_op](gathered, dst_index, num_segments=n)


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Combine x[src] with edge features y, then reduce onto dst
    (ref: send_recv.py send_ue_recv)."""
    ops = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
           "div": jnp.divide}
    if message_op not in ops:
        raise ValueError(f"unsupported message_op {message_op!r}")
    if reduce_op not in _REDUCERS:
        raise ValueError(f"unsupported reduce_op {reduce_op!r}")
    xd = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    n = int(out_size) if out_size is not None else xd.shape[0]
    msg = apply_op(
        lambda a, e, s: ops[message_op](jnp.take(a, s, axis=0), e),
        x, y, src_index, op_name="message")
    return _REDUCERS[reduce_op](msg, dst_index, num_segments=n)


def reindex_graph(x, neighbors, count, name=None):
    """Compact global node ids to local ids (ref: reindex.py
    reindex_graph). Host-side (ragged, data-dependent sizes — not a
    compiled op in the reference either)."""
    import numpy as np

    xv = np.asarray(x.numpy() if isinstance(x, Tensor) else x)
    nv = np.asarray(neighbors.numpy() if isinstance(neighbors, Tensor)
                    else neighbors)
    cv = np.asarray(count.numpy() if isinstance(count, Tensor) else count)
    order = {int(v): i for i, v in enumerate(xv)}
    nodes = list(xv)
    for v in nv:
        if int(v) not in order:
            order[int(v)] = len(nodes)
            nodes.append(v)
    reindex_src = np.array([order[int(v)] for v in nv], np.int64)
    reindex_dst = np.repeat(np.arange(len(cv), dtype=np.int64), cv)
    out_nodes = np.asarray(nodes, dtype=xv.dtype)
    return (Tensor(jnp.asarray(reindex_src)),
            Tensor(jnp.asarray(reindex_dst)),
            Tensor(jnp.asarray(out_nodes)))


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Edge features from gathered node pairs: x[src] op y[dst]
    (ref: message_passing/send_recv.py send_uv)."""
    ops = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
           "div": jnp.divide}
    if message_op not in ops:
        raise ValueError(f"unsupported message_op {message_op!r}")
    return apply_op(
        lambda a, b, s, d: ops[message_op](jnp.take(a, s, axis=0),
                                           jnp.take(b, d, axis=0)),
        x, y, src_index, dst_index, op_name="send_uv")


def _np_of(t):
    import numpy as np
    return np.asarray(t.numpy() if isinstance(t, Tensor) else t)


def _host_rng():
    """Host-side RNG seeded from the framework key stream so
    paddle.seed() makes graph sampling reproducible like every other
    random op."""
    import numpy as np

    from ..core import random as random_mod
    seed = int(jax.device_get(
        random_mod.derive_seed(random_mod.next_key())))
    return np.random.default_rng(seed & 0x7FFFFFFF)


def sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                     return_eids=False, perm_buffer=None, name=None):
    """Uniform neighbor sampling over a CSC graph (ref:
    sampling/neighbors.py sample_neighbors). Host-side like
    reindex_graph: output sizes are data-dependent (ragged), which is
    not a compilable TPU shape — graph sampling belongs to the input
    pipeline (the reference's GPU kernel serves the same stage)."""
    import numpy as np

    if return_eids and eids is None:
        raise ValueError("return_eids=True needs eids")
    rowv, colv = _np_of(row).reshape(-1), _np_of(colptr).reshape(-1)
    nodes = _np_of(input_nodes).reshape(-1)
    eidv = _np_of(eids).reshape(-1) if eids is not None else None
    rng = _host_rng()
    out_n, out_c, out_e = [], [], []
    for n in nodes:
        lo, hi = int(colv[n]), int(colv[n + 1])
        deg = hi - lo
        if sample_size < 0 or deg <= sample_size:
            sel = np.arange(lo, hi)
        else:
            sel = lo + rng.choice(deg, size=sample_size, replace=False)
        out_n.append(rowv[sel])
        out_c.append(len(sel))
        if return_eids:
            out_e.append(eidv[sel])
    neigh = np.concatenate(out_n) if out_n else np.empty(0, rowv.dtype)
    cnt = np.asarray(out_c, dtype=rowv.dtype)
    res = (Tensor(jnp.asarray(neigh)), Tensor(jnp.asarray(cnt)))
    if return_eids:
        ev = np.concatenate(out_e) if out_e else np.empty(0, rowv.dtype)
        return res + (Tensor(jnp.asarray(ev)),)
    return res


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None,
                              return_eids=False, name=None):
    """Weight-proportional neighbor sampling without replacement (ref:
    sampling/neighbors.py weighted_sample_neighbors); host-side, see
    sample_neighbors."""
    import numpy as np

    if return_eids and eids is None:
        raise ValueError("return_eids=True needs eids")
    rowv, colv = _np_of(row).reshape(-1), _np_of(colptr).reshape(-1)
    wv = _np_of(edge_weight).reshape(-1).astype(np.float64)
    nodes = _np_of(input_nodes).reshape(-1)
    eidv = _np_of(eids).reshape(-1) if eids is not None else None
    rng = _host_rng()
    out_n, out_c, out_e = [], [], []
    for n in nodes:
        lo, hi = int(colv[n]), int(colv[n + 1])
        deg = hi - lo
        if deg == 0:
            out_c.append(0)
            continue
        if sample_size < 0 or deg <= sample_size:
            sel = np.arange(lo, hi)
        else:
            w = wv[lo:hi]
            p = w / w.sum() if w.sum() > 0 else None
            sel = lo + rng.choice(deg, size=sample_size, replace=False,
                                  p=p)
        out_n.append(rowv[sel])
        out_c.append(len(sel))
        if return_eids:
            out_e.append(eidv[sel])
    neigh = np.concatenate(out_n) if out_n else np.empty(0, rowv.dtype)
    cnt = np.asarray(out_c, dtype=rowv.dtype)
    res = (Tensor(jnp.asarray(neigh)), Tensor(jnp.asarray(cnt)))
    if return_eids:
        ev = np.concatenate(out_e) if out_e else np.empty(0, rowv.dtype)
        return res + (Tensor(jnp.asarray(ev)),)
    return res


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """reindex_graph over multiple edge types sharing one id space
    (ref: reindex.py reindex_heter_graph): ids are renumbered once
    across all graphs; per-graph edges are concatenated."""
    import numpy as np

    xv = _np_of(x).reshape(-1)
    order = {int(v): i for i, v in enumerate(xv)}
    nodes = list(xv)
    srcs, dsts = [], []
    for nb, ct in zip(neighbors, count):
        nv = _np_of(nb).reshape(-1)
        cv = _np_of(ct).reshape(-1)
        for v in nv:
            if int(v) not in order:
                order[int(v)] = len(nodes)
                nodes.append(v)
        srcs.append(np.array([order[int(v)] for v in nv], np.int64))
        dsts.append(np.repeat(np.arange(len(cv), dtype=np.int64), cv))
    reindex_src = np.concatenate(srcs) if srcs else np.empty(0, np.int64)
    reindex_dst = np.concatenate(dsts) if dsts else np.empty(0, np.int64)
    out_nodes = np.asarray(nodes, dtype=xv.dtype)
    return (Tensor(jnp.asarray(reindex_src)),
            Tensor(jnp.asarray(reindex_dst)),
            Tensor(jnp.asarray(out_nodes)))


__all__ += ["send_uv", "sample_neighbors", "weighted_sample_neighbors",
            "reindex_heter_graph"]
