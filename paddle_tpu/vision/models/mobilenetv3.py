"""MobileNetV3 small/large. ref: python/paddle/vision/models/mobilenetv3.py:
463-506 (factory surface); inverted residuals with squeeze-excite and
hardswish per the MobileNetV3 paper."""
from __future__ import annotations

from ... import nn

__all__ = ["MobileNetV3Small", "MobileNetV3Large", "mobilenet_v3_small",
           "mobilenet_v3_large"]


def _make_divisible(v, divisor=8):
    new_v = max(divisor, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class _SqueezeExcite(nn.Layer):
    def __init__(self, ch, squeeze_ch):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(ch, squeeze_ch, 1)
        self.relu = nn.ReLU()
        self.fc2 = nn.Conv2D(squeeze_ch, ch, 1)
        self.hsig = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class _InvertedResidual(nn.Layer):
    def __init__(self, in_ch, exp_ch, out_ch, kernel, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and in_ch == out_ch
        layers = []
        act_layer = nn.Hardswish if act == "hardswish" else nn.ReLU
        if exp_ch != in_ch:
            layers += [nn.Conv2D(in_ch, exp_ch, 1, bias_attr=False),
                       nn.BatchNorm2D(exp_ch), act_layer()]
        layers += [nn.Conv2D(exp_ch, exp_ch, kernel, stride=stride,
                             padding=kernel // 2, groups=exp_ch,
                             bias_attr=False),
                   nn.BatchNorm2D(exp_ch), act_layer()]
        if use_se:
            layers.append(_SqueezeExcite(exp_ch,
                                         _make_divisible(exp_ch // 4)))
        layers += [nn.Conv2D(exp_ch, out_ch, 1, bias_attr=False),
                   nn.BatchNorm2D(out_ch)]
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


# (kernel, expanded, out, use_se, act, stride) per the paper's tables
_SMALL = [
    (3, 16, 16, True, "relu", 2),
    (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1),
    (5, 96, 40, True, "hardswish", 2),
    (5, 240, 40, True, "hardswish", 1),
    (5, 240, 40, True, "hardswish", 1),
    (5, 120, 48, True, "hardswish", 1),
    (5, 144, 48, True, "hardswish", 1),
    (5, 288, 96, True, "hardswish", 2),
    (5, 576, 96, True, "hardswish", 1),
    (5, 576, 96, True, "hardswish", 1),
]
_LARGE = [
    (3, 16, 16, False, "relu", 1),
    (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1),
    (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1),
    (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hardswish", 2),
    (3, 200, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1),
    (3, 480, 112, True, "hardswish", 1),
    (3, 672, 112, True, "hardswish", 1),
    (5, 672, 160, True, "hardswish", 2),
    (5, 960, 160, True, "hardswish", 1),
    (5, 960, 160, True, "hardswish", 1),
]


class MobileNetV3(nn.Layer):
    def __init__(self, config, last_channel, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        in_ch = _make_divisible(16 * scale)
        self.conv_stem = nn.Sequential(
            nn.Conv2D(3, in_ch, 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(in_ch), nn.Hardswish(),
        )
        blocks = []
        for k, exp, out, se, act, s in config:
            exp_ch = _make_divisible(exp * scale)
            out_ch = _make_divisible(out * scale)
            blocks.append(_InvertedResidual(in_ch, exp_ch, out_ch, k, s,
                                            se, act))
            in_ch = out_ch
        self.blocks = nn.Sequential(*blocks)
        last_conv = _make_divisible(6 * in_ch)
        self.conv_last = nn.Sequential(
            nn.Conv2D(in_ch, last_conv, 1, bias_attr=False),
            nn.BatchNorm2D(last_conv), nn.Hardswish(),
        )
        self.num_classes = num_classes
        self.with_pool = with_pool
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(last_conv, last_channel), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(last_channel, num_classes),
            )

    def forward(self, x):
        x = self.conv_last(self.blocks(self.conv_stem(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


class MobileNetV3Small(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_SMALL, _make_divisible(1024 * scale), scale,
                         num_classes, with_pool)


class MobileNetV3Large(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_LARGE, _make_divisible(1280 * scale), scale,
                         num_classes, with_pool)


model_urls = {
    "mobilenet_v3_small_x1.0": (
        "https://paddle-hapi.bj.bcebos.com/models/"
        "mobilenet_v3_small_x1.0.pdparams",
        "34fe0e7c1f8b00b2b056ad6788d0590c"),
    "mobilenet_v3_large_x1.0": (
        "https://paddle-hapi.bj.bcebos.com/models/"
        "mobilenet_v3_large_x1.0.pdparams",
        "118db5792b4e183b925d8e8e334db3df"),
}


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    model = MobileNetV3Small(scale=scale, **kwargs)
    if pretrained:
        from ._utils import load_pretrained
        from ._utils import scale_suffix
        load_pretrained(model,
                        f"mobilenet_v3_small_x{scale_suffix(scale)}",
                        urls=model_urls)
    return model


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    model = MobileNetV3Large(scale=scale, **kwargs)
    if pretrained:
        from ._utils import load_pretrained
        from ._utils import scale_suffix
        load_pretrained(model,
                        f"mobilenet_v3_large_x{scale_suffix(scale)}",
                        urls=model_urls)
    return model
