"""ResNet family.

ref: python/paddle/vision/models/resnet.py (BasicBlock/BottleneckBlock/
ResNet, resnet18..152, wide/resnext variants). Structure matches the
reference so state_dicts correspond; data layout is NCHW like the
reference (XLA lays out for the MXU regardless).
"""
from __future__ import annotations

from ... import nn

__all__ = [
    "ResNet", "resnet18", "resnet34", "resnet50", "resnet101", "resnet152",
    "wide_resnet50_2", "wide_resnet101_2", "resnext50_32x4d",
    "resnext50_64x4d", "resnext101_32x4d", "resnext101_64x4d",
    "resnext152_32x4d", "resnext152_64x4d",
]


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None, groups=1,
                 base_width=64, dilation=1, norm_layer=None,
                 data_format="NCHW"):
        super().__init__()
        norm_layer = norm_layer or nn.BatchNorm2D
        df = data_format
        self.conv1 = nn.Conv2D(inplanes, planes, 3, padding=1, stride=stride,
                               bias_attr=False, data_format=df)
        self.bn1 = norm_layer(planes, data_format=df)
        self.relu = nn.ReLU()
        self.conv2 = nn.Conv2D(planes, planes, 3, padding=1,
                               bias_attr=False, data_format=df)
        self.bn2 = norm_layer(planes, data_format=df)
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None, groups=1,
                 base_width=64, dilation=1, norm_layer=None,
                 data_format="NCHW"):
        super().__init__()
        norm_layer = norm_layer or nn.BatchNorm2D
        df = data_format
        width = int(planes * (base_width / 64.0)) * groups
        self.conv1 = nn.Conv2D(inplanes, width, 1, bias_attr=False,
                               data_format=df)
        self.bn1 = norm_layer(width, data_format=df)
        self.conv2 = nn.Conv2D(width, width, 3, padding=dilation,
                               stride=stride, groups=groups,
                               dilation=dilation, bias_attr=False,
                               data_format=df)
        self.bn2 = norm_layer(width, data_format=df)
        self.conv3 = nn.Conv2D(width, planes * self.expansion, 1,
                               bias_attr=False, data_format=df)
        self.bn3 = norm_layer(planes * self.expansion, data_format=df)
        self.relu = nn.ReLU()
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNet(nn.Layer):
    """ref: vision/models/resnet.py ResNet."""

    def __init__(self, block, depth=50, width=64, num_classes=1000,
                 with_pool=True, groups=1, data_format="NCHW"):
        super().__init__()
        layer_cfg = {18: [2, 2, 2, 2], 34: [3, 4, 6, 3], 50: [3, 4, 6, 3],
                     101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}
        layers = layer_cfg[depth]
        self.groups = groups
        self.base_width = width
        self.num_classes = num_classes
        self.with_pool = with_pool
        self._norm_layer = nn.BatchNorm2D
        self.inplanes = 64
        self.dilation = 1
        # NHWC is the TPU-native layout: channel stays in the 128-lane
        # minor dim, so BN stats reduce over contiguous major dims and
        # XLA fuses the BN/ReLU elementwise into conv epilogues (the
        # NCHW profile showed ~20ms/step of convert/reduce BN kernels)
        self.data_format = data_format

        df = data_format
        self.conv1 = nn.Conv2D(3, self.inplanes, kernel_size=7, stride=2,
                               padding=3, bias_attr=False, data_format=df)
        self.bn1 = self._norm_layer(self.inplanes, data_format=df)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(kernel_size=3, stride=2, padding=1,
                                    data_format=df)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layers[3], stride=2)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1), data_format=df)
        if num_classes > 0:
            self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1):
        norm_layer = self._norm_layer
        df = self.data_format
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2D(self.inplanes, planes * block.expansion, 1,
                          stride=stride, bias_attr=False, data_format=df),
                norm_layer(planes * block.expansion, data_format=df))
        layers = [block(self.inplanes, planes, stride, downsample,
                        self.groups, self.base_width, norm_layer=norm_layer,
                        data_format=df)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes, groups=self.groups,
                                base_width=self.base_width,
                                norm_layer=norm_layer, data_format=df))
        return nn.Sequential(*layers)

    def forward(self, x):
        x = self.relu(self.bn1(self.conv1(x)))
        x = self.maxpool(x)
        x = self.layer1(x)
        x = self.layer2(x)
        x = self.layer3(x)
        x = self.layer4(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = nn.Flatten()(x)
            x = self.fc(x)
        return x


# published weight artifacts (ref: vision/models/resnet.py model_urls —
# same URLs/checksums, so reference-trained weights load directly)
model_urls = {
    "resnet18": (
        "https://paddle-hapi.bj.bcebos.com/models/resnet18.pdparams",
        "cf548f46534aa3560945be4b95cd11c4"),
    "resnet34": (
        "https://paddle-hapi.bj.bcebos.com/models/resnet34.pdparams",
        "8d2275cf8706028345f78ac0e1d31969"),
    "resnet50": (
        "https://paddle-hapi.bj.bcebos.com/models/resnet50.pdparams",
        "ca6f485ee1ab0492d38f323885b0ad80"),
    "resnet101": (
        "https://paddle-hapi.bj.bcebos.com/models/resnet101.pdparams",
        "02f35f034ca3858e1e54d4036443c92d"),
    "resnet152": (
        "https://paddle-hapi.bj.bcebos.com/models/resnet152.pdparams",
        "7ad16a2f1e7333859ff986138630fd7a"),
    "resnext50_32x4d": (
        "https://paddle-hapi.bj.bcebos.com/models/resnext50_32x4d.pdparams",
        "dc47483169be7d6f018fcbb7baf8775d"),
    "resnext50_64x4d": (
        "https://paddle-hapi.bj.bcebos.com/models/resnext50_64x4d.pdparams",
        "063d4b483e12b06388529450ad7576db"),
    "resnext101_32x4d": (
        "https://paddle-hapi.bj.bcebos.com/models/resnext101_32x4d.pdparams",
        "967b090039f9de2c8d06fe994fb9095f"),
    "resnext101_64x4d": (
        "https://paddle-hapi.bj.bcebos.com/models/resnext101_64x4d.pdparams",
        "98e04e7ca616a066699230d769d03008"),
    "resnext152_32x4d": (
        "https://paddle-hapi.bj.bcebos.com/models/resnext152_32x4d.pdparams",
        "18ff0beee21f2efc99c4b31786107121"),
    "resnext152_64x4d": (
        "https://paddle-hapi.bj.bcebos.com/models/resnext152_64x4d.pdparams",
        "77c4af00ca42c405fa7f841841959379"),
    "wide_resnet50_2": (
        "https://paddle-hapi.bj.bcebos.com/models/wide_resnet50_2.pdparams",
        "0282f804d73debdab289bd9fea3fa6dc"),
    "wide_resnet101_2": (
        "https://paddle-hapi.bj.bcebos.com/models/wide_resnet101_2.pdparams",
        "d4360a2d23657f059216f5d5a1a9ac93"),
}


def load_pretrained(model, arch, urls=None):
    """Install published weights (delegates to models._utils; resnet's
    table is the default for backward compatibility)."""
    from ._utils import load_pretrained as _lp
    return _lp(model, arch, model_urls if urls is None else urls)


def _resnet(block, depth, pretrained=False, arch=None, **kwargs):
    model = ResNet(block, depth, **kwargs)
    if pretrained:
        load_pretrained(model, arch or f"resnet{depth}")
    return model


def resnet18(pretrained=False, **kwargs):
    return _resnet(BasicBlock, 18, pretrained, **kwargs)


def resnet34(pretrained=False, **kwargs):
    return _resnet(BasicBlock, 34, pretrained, **kwargs)


def resnet50(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 50, pretrained, **kwargs)


def resnet101(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 101, pretrained, **kwargs)


def resnet152(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 152, pretrained, **kwargs)


def wide_resnet50_2(pretrained=False, **kwargs):
    kwargs["width"] = 128
    return _resnet(BottleneckBlock, 50, pretrained,
                   arch="wide_resnet50_2", **kwargs)


def wide_resnet101_2(pretrained=False, **kwargs):
    kwargs["width"] = 128
    return _resnet(BottleneckBlock, 101, pretrained,
                   arch="wide_resnet101_2", **kwargs)


def _resnext(depth, groups, pretrained, **kwargs):
    kwargs["groups"] = groups
    kwargs["width"] = 4
    return _resnet(BottleneckBlock, depth, pretrained,
                   arch=f"resnext{depth}_{groups}x4d", **kwargs)


def resnext50_32x4d(pretrained=False, **kwargs):
    return _resnext(50, 32, pretrained, **kwargs)


def resnext50_64x4d(pretrained=False, **kwargs):
    return _resnext(50, 64, pretrained, **kwargs)


def resnext101_32x4d(pretrained=False, **kwargs):
    return _resnext(101, 32, pretrained, **kwargs)


def resnext101_64x4d(pretrained=False, **kwargs):
    return _resnext(101, 64, pretrained, **kwargs)


def resnext152_32x4d(pretrained=False, **kwargs):
    return _resnext(152, 32, pretrained, **kwargs)


def resnext152_64x4d(pretrained=False, **kwargs):
    return _resnext(152, 64, pretrained, **kwargs)
