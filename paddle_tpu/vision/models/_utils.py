"""Shared pretrained-weight loader for the vision model zoo.

ref: each reference zoo file's pretrained branch (vision/models/
resnet.py etc.: get_weights_path_from_url + set_dict). One loader +
one arch-key normalization here instead of ten hand-built f-strings —
the per-file variants produced key-mismatch bugs (squeezenet '1.0' vs
'1_0', integer scale '1' vs '1.0')."""
from __future__ import annotations

__all__ = ["load_pretrained", "scale_suffix"]


def scale_suffix(scale) -> str:
    """Canonical textual form of a width multiplier: 1 / 1.0 -> '1.0',
    0.25 -> '0.25' (the form the published artifact names use)."""
    return str(float(scale))


def load_pretrained(model, arch, urls):
    """Fetch (or resolve via PADDLE_TPU_PRETRAINED_DIR) the published
    weights for ``arch`` from the zoo's ``urls`` table and install them,
    failing loudly on a missing arch or any mismatched key."""
    if arch not in urls:
        raise ValueError(
            f"{arch} has no published pretrained weights; set "
            f"pretrained=False (available: {sorted(urls)})")
    from ... import framework
    from ...utils.download import get_weights_path_from_url
    path = get_weights_path_from_url(urls[arch][0], urls[arch][1])
    state = framework.io.load(path, return_numpy=True)
    missing, unexpected = model.set_state_dict(state)
    if missing or unexpected:
        raise ValueError(
            f"pretrained weights for {arch} do not match the model: "
            f"missing={list(missing)[:5]}, "
            f"unexpected={list(unexpected)[:5]}")
    return model
