"""SqueezeNet 1.0/1.1. ref: python/paddle/vision/models/squeezenet.py:251
(factory surface); Fire-module architecture per the SqueezeNet paper."""
from __future__ import annotations

from ... import concat, nn

__all__ = ["SqueezeNet", "squeezenet1_0", "squeezenet1_1"]


class Fire(nn.Layer):
    def __init__(self, in_ch, squeeze, expand1x1, expand3x3):
        super().__init__()
        self.squeeze = nn.Conv2D(in_ch, squeeze, 1)
        self.relu = nn.ReLU()
        self.expand1x1 = nn.Conv2D(squeeze, expand1x1, 1)
        self.expand3x3 = nn.Conv2D(squeeze, expand3x3, 3, padding=1)

    def forward(self, x):
        x = self.relu(self.squeeze(x))
        return concat([self.relu(self.expand1x1(x)),
                       self.relu(self.expand3x3(x))], axis=1)


class SqueezeNet(nn.Layer):
    def __init__(self, version: str = "1.0", num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__()
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2),
                Fire(96, 16, 64, 64), Fire(128, 16, 64, 64),
                Fire(128, 32, 128, 128),
                nn.MaxPool2D(3, stride=2),
                Fire(256, 32, 128, 128), Fire(256, 48, 192, 192),
                Fire(384, 48, 192, 192), Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, stride=2),
                Fire(512, 64, 256, 256),
            )
        elif version == "1.1":
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2),
                Fire(64, 16, 64, 64), Fire(128, 16, 64, 64),
                nn.MaxPool2D(3, stride=2),
                Fire(128, 32, 128, 128), Fire(256, 32, 128, 128),
                nn.MaxPool2D(3, stride=2),
                Fire(256, 48, 192, 192), Fire(384, 48, 192, 192),
                Fire(384, 64, 256, 256), Fire(512, 64, 256, 256),
            )
        else:
            raise ValueError(f"unsupported SqueezeNet version {version!r}")
        self.num_classes = num_classes
        self.with_pool = with_pool
        if num_classes > 0:
            self.classifier_conv = nn.Conv2D(512, num_classes, 1)
            self.dropout = nn.Dropout(0.5)
            self.relu = nn.ReLU()
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = self.relu(self.classifier_conv(self.dropout(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
        return x


model_urls = {
    "squeezenet1_0": (
        "https://paddle-imagenet-models-name.bj.bcebos.com/dygraph/"
        "SqueezeNet1_0_pretrained.pdparams",
        "30b95af60a2178f03cf9b66cd77e1db1"),
    "squeezenet1_1": (
        "https://paddle-imagenet-models-name.bj.bcebos.com/dygraph/"
        "SqueezeNet1_1_pretrained.pdparams",
        "a11250d3a1f91d7131fd095ebbf09eee"),
}


def _squeezenet(version, pretrained, **kwargs):
    model = SqueezeNet(version, **kwargs)
    if pretrained:
        from ._utils import load_pretrained
        load_pretrained(model,
                        f"squeezenet{str(version).replace('.', '_')}",
                        urls=model_urls)
    return model


def squeezenet1_0(pretrained: bool = False, **kwargs) -> SqueezeNet:
    return _squeezenet("1.0", pretrained, **kwargs)


def squeezenet1_1(pretrained: bool = False, **kwargs) -> SqueezeNet:
    return _squeezenet("1.1", pretrained, **kwargs)
