"""DenseNet 121/161/169/201/264. ref: python/paddle/vision/models/
densenet.py:400-539 (factory surface); dense-block architecture per the
DenseNet paper (bn_size=4 bottlenecks, halving transitions)."""
from __future__ import annotations

from ... import concat, nn

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201", "densenet264"]

_CONFIGS = {
    121: (64, 32, (6, 12, 24, 16)),
    161: (96, 48, (6, 12, 36, 24)),
    169: (64, 32, (6, 12, 32, 32)),
    201: (64, 32, (6, 12, 48, 32)),
    264: (64, 32, (6, 12, 64, 48)),
}


class _DenseLayer(nn.Layer):
    def __init__(self, in_ch, growth_rate, bn_size, dropout):
        super().__init__()
        self.bn1 = nn.BatchNorm2D(in_ch)
        self.conv1 = nn.Conv2D(in_ch, bn_size * growth_rate, 1,
                               bias_attr=False)
        self.bn2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3,
                               padding=1, bias_attr=False)
        self.relu = nn.ReLU()
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x):
        out = self.conv1(self.relu(self.bn1(x)))
        out = self.conv2(self.relu(self.bn2(out)))
        if self.dropout is not None:
            out = self.dropout(out)
        return concat([x, out], axis=1)


class _Transition(nn.Layer):
    def __init__(self, in_ch, out_ch):
        super().__init__()
        self.bn = nn.BatchNorm2D(in_ch)
        self.relu = nn.ReLU()
        self.conv = nn.Conv2D(in_ch, out_ch, 1, bias_attr=False)
        self.pool = nn.AvgPool2D(2, stride=2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.bn(x))))


class DenseNet(nn.Layer):
    def __init__(self, layers: int = 121, bn_size: int = 4,
                 dropout: float = 0.0, num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__()
        if layers not in _CONFIGS:
            raise ValueError(
                f"layers must be one of {sorted(_CONFIGS)}, got {layers}")
        num_init, growth, block_cfg = _CONFIGS[layers]
        self.stem = nn.Sequential(
            nn.Conv2D(3, num_init, 7, stride=2, padding=3, bias_attr=False),
            nn.BatchNorm2D(num_init), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, padding=1),
        )
        blocks = []
        ch = num_init
        for bi, n_layers in enumerate(block_cfg):
            for _ in range(n_layers):
                blocks.append(_DenseLayer(ch, growth, bn_size, dropout))
                ch += growth
            if bi != len(block_cfg) - 1:
                blocks.append(_Transition(ch, ch // 2))
                ch //= 2
        self.blocks = nn.Sequential(*blocks)
        self.bn_final = nn.BatchNorm2D(ch)
        self.relu = nn.ReLU()
        self.num_classes = num_classes
        self.with_pool = with_pool
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Linear(ch, num_classes)

    def forward(self, x):
        x = self.relu(self.bn_final(self.blocks(self.stem(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


model_urls = {
    "densenet121": ("https://paddle-imagenet-models-name.bj.bcebos.com/"
                    "dygraph/DenseNet121_pretrained.pdparams",
                    "db1b239ed80a905290fd8b01d3af08e4"),
    "densenet161": ("https://paddle-imagenet-models-name.bj.bcebos.com/"
                    "dygraph/DenseNet161_pretrained.pdparams",
                    "62158869cb315098bd25ddbfd308a853"),
    "densenet169": ("https://paddle-imagenet-models-name.bj.bcebos.com/"
                    "dygraph/DenseNet169_pretrained.pdparams",
                    "82cc7c635c3f19098c748850efb2d796"),
    "densenet201": ("https://paddle-imagenet-models-name.bj.bcebos.com/"
                    "dygraph/DenseNet201_pretrained.pdparams",
                    "16ca29565a7712329cf9e36e02caaf58"),
    "densenet264": ("https://paddle-imagenet-models-name.bj.bcebos.com/"
                    "dygraph/DenseNet264_pretrained.pdparams",
                    "3270ce516b85370bba88cfdd9f60bff4"),
}


def _densenet(layers, pretrained, **kwargs):
    model = DenseNet(layers, **kwargs)
    if pretrained:
        from ._utils import load_pretrained
        load_pretrained(model, f"densenet{layers}", urls=model_urls)
    return model


def densenet121(pretrained: bool = False, **kwargs) -> DenseNet:
    return _densenet(121, pretrained, **kwargs)


def densenet161(pretrained: bool = False, **kwargs) -> DenseNet:
    return _densenet(161, pretrained, **kwargs)


def densenet169(pretrained: bool = False, **kwargs) -> DenseNet:
    return _densenet(169, pretrained, **kwargs)


def densenet201(pretrained: bool = False, **kwargs) -> DenseNet:
    return _densenet(201, pretrained, **kwargs)


def densenet264(pretrained: bool = False, **kwargs) -> DenseNet:
    return _densenet(264, pretrained, **kwargs)
