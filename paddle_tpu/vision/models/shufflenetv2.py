"""ShuffleNetV2 family. ref: python/paddle/vision/models/shufflenetv2.py:
388-610 (factory surface incl. the swish variant); channel-split/shuffle
units per the ShuffleNetV2 paper."""
from __future__ import annotations

from ... import concat, nn

__all__ = [
    "ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
    "shufflenet_v2_x0_5", "shufflenet_v2_x1_0", "shufflenet_v2_x1_5",
    "shufflenet_v2_x2_0", "shufflenet_v2_swish",
]

_STAGE_OUT = {
    0.25: (24, 24, 48, 96, 512),
    0.33: (24, 32, 64, 128, 512),
    0.5: (24, 48, 96, 192, 1024),
    1.0: (24, 116, 232, 464, 1024),
    1.5: (24, 176, 352, 704, 1024),
    2.0: (24, 244, 488, 976, 2048),
}
_REPEATS = (4, 8, 4)


def _act(name):
    return nn.Swish() if name == "swish" else nn.ReLU()


class _ShuffleUnit(nn.Layer):
    """stride-1 unit: channel split, transform right half, shuffle."""

    def __init__(self, ch, act):
        super().__init__()
        branch = ch // 2
        self.branch = nn.Sequential(
            nn.Conv2D(branch, branch, 1, bias_attr=False),
            nn.BatchNorm2D(branch), _act(act),
            nn.Conv2D(branch, branch, 3, padding=1, groups=branch,
                      bias_attr=False),
            nn.BatchNorm2D(branch),
            nn.Conv2D(branch, branch, 1, bias_attr=False),
            nn.BatchNorm2D(branch), _act(act),
        )
        self.shuffle = nn.ChannelShuffle(2)

    def forward(self, x):
        c = x.shape[1] // 2
        left, right = x[:, :c], x[:, c:]
        out = concat([left, self.branch(right)], axis=1)
        return self.shuffle(out)


class _ShuffleDownUnit(nn.Layer):
    """stride-2 unit: both branches transform, output doubles channels."""

    def __init__(self, in_ch, out_ch, act):
        super().__init__()
        branch = out_ch // 2
        self.left = nn.Sequential(
            nn.Conv2D(in_ch, in_ch, 3, stride=2, padding=1, groups=in_ch,
                      bias_attr=False),
            nn.BatchNorm2D(in_ch),
            nn.Conv2D(in_ch, branch, 1, bias_attr=False),
            nn.BatchNorm2D(branch), _act(act),
        )
        self.right = nn.Sequential(
            nn.Conv2D(in_ch, branch, 1, bias_attr=False),
            nn.BatchNorm2D(branch), _act(act),
            nn.Conv2D(branch, branch, 3, stride=2, padding=1, groups=branch,
                      bias_attr=False),
            nn.BatchNorm2D(branch),
            nn.Conv2D(branch, branch, 1, bias_attr=False),
            nn.BatchNorm2D(branch), _act(act),
        )
        self.shuffle = nn.ChannelShuffle(2)

    def forward(self, x):
        out = concat([self.left(x), self.right(x)], axis=1)
        return self.shuffle(out)


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale: float = 1.0, act: str = "relu",
                 num_classes: int = 1000, with_pool: bool = True):
        super().__init__()
        if scale not in _STAGE_OUT:
            raise ValueError(
                f"scale must be one of {sorted(_STAGE_OUT)}, got {scale}")
        chans = _STAGE_OUT[scale]
        self.conv1 = nn.Sequential(
            nn.Conv2D(3, chans[0], 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(chans[0]), _act(act),
        )
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        stages = []
        in_ch = chans[0]
        for out_ch, repeat in zip(chans[1:4], _REPEATS):
            units = [_ShuffleDownUnit(in_ch, out_ch, act)]
            units += [_ShuffleUnit(out_ch, act) for _ in range(repeat - 1)]
            stages.append(nn.Sequential(*units))
            in_ch = out_ch
        self.stages = nn.Sequential(*stages)
        self.conv_last = nn.Sequential(
            nn.Conv2D(in_ch, chans[4], 1, bias_attr=False),
            nn.BatchNorm2D(chans[4]), _act(act),
        )
        self.num_classes = num_classes
        self.with_pool = with_pool
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(chans[4], num_classes)

    def forward(self, x):
        x = self.conv_last(self.stages(self.maxpool(self.conv1(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


model_urls = {
    "shufflenet_v2_x0_25": (
        "https://paddle-hapi.bj.bcebos.com/models/"
        "shufflenet_v2_x0_25.pdparams",
        "1e509b4c140eeb096bb16e214796d03b"),
    "shufflenet_v2_x0_33": (
        "https://paddle-hapi.bj.bcebos.com/models/"
        "shufflenet_v2_x0_33.pdparams",
        "3d7b3ab0eaa5c0927ff1026d31b729bd"),
    "shufflenet_v2_x0_5": (
        "https://paddle-hapi.bj.bcebos.com/models/"
        "shufflenet_v2_x0_5.pdparams",
        "5e5cee182a7793c4e4c73949b1a71bd4"),
    "shufflenet_v2_x1_0": (
        "https://paddle-hapi.bj.bcebos.com/models/"
        "shufflenet_v2_x1_0.pdparams",
        "122d42478b9e81eb49f8a9ede327b1a4"),
    "shufflenet_v2_x1_5": (
        "https://paddle-hapi.bj.bcebos.com/models/"
        "shufflenet_v2_x1_5.pdparams",
        "faced5827380d73531d0ee027c67826d"),
    "shufflenet_v2_x2_0": (
        "https://paddle-hapi.bj.bcebos.com/models/"
        "shufflenet_v2_x2_0.pdparams",
        "cd3dddcd8305e7bcd8ad14d1c69a5784"),
    "shufflenet_v2_swish": (
        "https://paddle-hapi.bj.bcebos.com/models/"
        "shufflenet_v2_swish.pdparams",
        "adde0aa3b023e5b0c94a68be1c394b84"),
}


def _shufflenet(scale, act, pretrained, arch=None, **kwargs):
    model = ShuffleNetV2(scale, act, **kwargs)
    if pretrained:
        from ._utils import load_pretrained
        load_pretrained(model, arch or "?", urls=model_urls)
    return model


def shufflenet_v2_x0_25(pretrained=False, **kw):
    return _shufflenet(0.25, "relu", pretrained,
                       arch="shufflenet_v2_x0_25", **kw)


def shufflenet_v2_x0_33(pretrained=False, **kw):
    return _shufflenet(0.33, "relu", pretrained,
                       arch="shufflenet_v2_x0_33", **kw)


def shufflenet_v2_x0_5(pretrained=False, **kw):
    return _shufflenet(0.5, "relu", pretrained,
                       arch="shufflenet_v2_x0_5", **kw)


def shufflenet_v2_x1_0(pretrained=False, **kw):
    return _shufflenet(1.0, "relu", pretrained,
                       arch="shufflenet_v2_x1_0", **kw)


def shufflenet_v2_x1_5(pretrained=False, **kw):
    return _shufflenet(1.5, "relu", pretrained,
                       arch="shufflenet_v2_x1_5", **kw)


def shufflenet_v2_x2_0(pretrained=False, **kw):
    return _shufflenet(2.0, "relu", pretrained,
                       arch="shufflenet_v2_x2_0", **kw)


def shufflenet_v2_swish(pretrained=False, **kw):
    return _shufflenet(1.0, "swish", pretrained,
                       arch="shufflenet_v2_swish", **kw)
