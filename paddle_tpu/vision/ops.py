"""Vision ops: roi_align, nms, box utils.

ref: python/paddle/vision/ops.py (roi_align, nms, deform_conv2d...).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..core.autograd import apply_op

from .detection_ops import (  # noqa: F401
    DeformConv2D, PSRoIPool, RoIAlign, RoIPool, decode_jpeg,
    deform_conv2d, distribute_fpn_proposals, generate_proposals,
    matrix_nms, prior_box, psroi_pool, read_file, roi_pool, yolo_box,
    yolo_loss)

__all__ = ["nms", "box_coder", "roi_align", "yolo_loss", "yolo_box",
           "prior_box", "deform_conv2d", "DeformConv2D",
           "distribute_fpn_proposals", "generate_proposals",
           "read_file", "decode_jpeg", "roi_pool", "RoIPool",
           "psroi_pool", "PSRoIPool", "RoIAlign", "matrix_nms"]


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """ref: vision/ops.py nms. Host-side implementation (data-dependent
    output size is inherently host logic on TPU)."""
    b = np.asarray(boxes._data if isinstance(boxes, Tensor) else boxes)
    s = (np.asarray(scores._data if isinstance(scores, Tensor) else scores)
         if scores is not None else np.arange(len(b), 0, -1, dtype=np.float32))
    order = np.argsort(-s)
    keep = []
    suppressed = np.zeros(len(b), dtype=bool)
    areas = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    for i_ in order:
        if suppressed[i_]:
            continue
        keep.append(int(i_))
        xx1 = np.maximum(b[i_, 0], b[:, 0])
        yy1 = np.maximum(b[i_, 1], b[:, 1])
        xx2 = np.minimum(b[i_, 2], b[:, 2])
        yy2 = np.minimum(b[i_, 3], b[:, 3])
        w = np.maximum(0.0, xx2 - xx1)
        h = np.maximum(0.0, yy2 - yy1)
        inter = w * h
        iou = inter / (areas[i_] + areas - inter + 1e-10)
        suppressed |= iou > iou_threshold
        suppressed[i_] = True
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(np.asarray(keep, dtype=np.int64)))


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0):
    raise NotImplementedError("box_coder lands with the detection suite")


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """ref: vision/ops.py roi_align — average-pool ROI crops; static-shape
    friendly bilinear sampling."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def f(feat, bxs):
        n_rois = bxs.shape[0]
        c = feat.shape[1]
        h, w = feat.shape[2], feat.shape[3]
        off = 0.5 if aligned else 0.0
        x1 = bxs[:, 0] * spatial_scale - off
        y1 = bxs[:, 1] * spatial_scale - off
        x2 = bxs[:, 2] * spatial_scale - off
        y2 = bxs[:, 3] * spatial_scale - off
        bin_h = (y2 - y1) / oh
        bin_w = (x2 - x1) / ow
        ys = y1[:, None] + (jnp.arange(oh) + 0.5) * bin_h[:, None]
        xs = x1[:, None] + (jnp.arange(ow) + 0.5) * bin_w[:, None]
        y0 = jnp.clip(jnp.floor(ys), 0, h - 1).astype(jnp.int32)
        x0 = jnp.clip(jnp.floor(xs), 0, w - 1).astype(jnp.int32)
        y1i = jnp.clip(y0 + 1, 0, h - 1)
        x1i = jnp.clip(x0 + 1, 0, w - 1)
        wy = jnp.clip(ys, 0, h - 1) - y0
        wx = jnp.clip(xs, 0, w - 1) - x0
        img = feat[0]  # single image per batch of rois (batch handled by boxes_num upstream)
        def gather(yi, xi):
            return img[:, yi][:, :, xi]  # [c, n, oh, n, ow] -> careful
        # vectorized bilinear: [n_rois, c, oh, ow]
        f00 = img[:, y0[:, :, None], x0[:, None, :]]
        f01 = img[:, y0[:, :, None], x1i[:, None, :]]
        f10 = img[:, y1i[:, :, None], x0[:, None, :]]
        f11 = img[:, y1i[:, :, None], x1i[:, None, :]]
        wy_ = wy[:, :, None][None]
        wx_ = wx[:, None, :][None]
        out = (f00 * (1 - wy_) * (1 - wx_) + f01 * (1 - wy_) * wx_
               + f10 * wy_ * (1 - wx_) + f11 * wy_ * wx_)
        return jnp.transpose(out, (1, 0, 2, 3))

    return apply_op(f, x, boxes, op_name="roi_align")
