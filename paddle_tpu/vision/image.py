"""Image IO backend selection.

ref: python/paddle/vision/image.py (set_image_backend /
get_image_backend / image_load): datasets return either PIL images
('pil', default) or numpy/cv2 arrays ('cv2')."""
from __future__ import annotations

import numpy as np

__all__ = ["set_image_backend", "get_image_backend", "image_load"]

_BACKEND = "pil"


def set_image_backend(backend: str):
    """Pick the decode backend used by image_load and the vision
    datasets. 'cv2' is honored when OpenCV is installed; otherwise the
    cv2 setting still returns numpy HWC-BGR arrays decoded via PIL (the
    array contract, without the native dependency)."""
    global _BACKEND
    if backend not in ("pil", "cv2"):
        raise ValueError(
            f"image backend must be 'pil' or 'cv2', got {backend!r}")
    _BACKEND = backend


def get_image_backend() -> str:
    return _BACKEND


def image_load(path: str, backend: str | None = None):
    """Load an image file. 'pil' -> PIL.Image; 'cv2' -> numpy uint8
    HWC in BGR channel order (cv2's convention)."""
    b = backend or _BACKEND
    if b not in ("pil", "cv2"):
        raise ValueError(
            f"image backend must be 'pil' or 'cv2', got {b!r}")
    if b == "cv2":
        try:
            import cv2
            return cv2.imread(path)
        except ImportError:
            from PIL import Image
            arr = np.asarray(Image.open(path).convert("RGB"))
            return arr[:, :, ::-1].copy()  # RGB -> BGR, cv2 contract
    from PIL import Image
    return Image.open(path)
