"""Built-in datasets.

ref: python/paddle/vision/datasets/ (MNIST, CIFAR, Flowers...). This build
has zero network egress, so real downloads are unavailable; each dataset
class accepts local files when present and otherwise generates a
deterministic synthetic sample set with the real shapes/dtypes — enough
for train-loop and benchmark plumbing.
"""
from __future__ import annotations

import numpy as np

from ..io.dataset import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "Flowers",
           "VOC2012"]


class _SyntheticImageDataset(Dataset):
    IMAGE_SHAPE = (1, 28, 28)
    NUM_CLASSES = 10
    NUM_SAMPLES = 1024

    def __init__(self, mode="train", transform=None, backend=None,
                 image_path=None, label_path=None, data_file=None,
                 download=True):
        self.mode = mode
        self.transform = transform
        rng = np.random.default_rng(0 if mode == "train" else 1)
        n = self.NUM_SAMPLES if mode == "train" else self.NUM_SAMPLES // 4
        self.images = rng.integers(
            0, 256, size=(n,) + self.IMAGE_SHAPE[1:] +
            ((self.IMAGE_SHAPE[0],) if self.IMAGE_SHAPE[0] > 1 else ()),
            dtype=np.uint8)
        self.labels = rng.integers(0, self.NUM_CLASSES, size=(n, 1),
                                   dtype=np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype(np.float32)
            if img.ndim == 2:
                img = img[None]
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class MNIST(_SyntheticImageDataset):
    """ref: vision/datasets/mnist.py."""
    IMAGE_SHAPE = (1, 28, 28)
    NUM_CLASSES = 10


class FashionMNIST(MNIST):
    pass


class Cifar10(_SyntheticImageDataset):
    """ref: vision/datasets/cifar.py."""
    IMAGE_SHAPE = (3, 32, 32)
    NUM_CLASSES = 10


class Cifar100(Cifar10):
    NUM_CLASSES = 100


class Flowers(_SyntheticImageDataset):
    """ref: vision/datasets/flowers.py (102-category Oxford flowers)."""
    IMAGE_SHAPE = (3, 96, 96)
    NUM_CLASSES = 102
    NUM_SAMPLES = 512


class VOC2012(Dataset):
    """ref: vision/datasets/voc2012.py — segmentation pairs (image,
    label-mask). Synthetic shapes: [3, H, W] uint8 image, [H, W] int64
    mask over 21 classes (20 + background)."""
    NUM_CLASSES = 21

    def __init__(self, mode="train", transform=None, backend=None,
                 data_file=None, download=True):
        self.mode = mode
        self.transform = transform
        rng = np.random.default_rng(0 if mode == "train" else 1)
        n = 128 if mode == "train" else 32
        self.images = rng.integers(0, 256, size=(n, 3, 64, 64),
                                   dtype=np.uint8)
        self.masks = rng.integers(0, self.NUM_CLASSES, size=(n, 64, 64),
                                  dtype=np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype(np.float32)
        return img, self.masks[idx]

    def __len__(self):
        return len(self.images)


_IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm",
                   ".tif", ".tiff", ".webp")


def _scan_files(root, extensions, is_valid_file):
    """Sorted recursive file scan shared by DatasetFolder/ImageFolder:
    is_valid_file wins when given, else the extension allowlist."""
    import os

    exts = tuple(e.lower() for e in (extensions or _IMG_EXTENSIONS))
    found = []
    for dirpath, _, files in sorted(os.walk(root)):
        for fname in sorted(files):
            path = os.path.join(dirpath, fname)
            ok = (is_valid_file(path) if is_valid_file
                  else fname.lower().endswith(exts))
            if ok:
                found.append(path)
    return found


class DatasetFolder(Dataset):
    """Directory-per-class dataset (ref:
    vision/datasets/folder.py DatasetFolder): root/<class>/<file>,
    classes sorted alphabetically, loaded via the configured image
    backend (PIL here)."""

    def __init__(self, root, loader=None, extensions=None,
                 transform=None, is_valid_file=None):
        import os

        self.root = root
        self.transform = transform
        self.classes = sorted(
            d for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(self.classes)}
        self.samples = []
        for c in self.classes:
            for path in _scan_files(os.path.join(root, c), extensions,
                                    is_valid_file):
                self.samples.append((path, self.class_to_idx[c]))
        if not self.samples:
            raise RuntimeError(f"found no valid files under {root}")
        self.loader = loader or self._pil_loader

    @staticmethod
    def _pil_loader(path):
        from PIL import Image
        with open(path, "rb") as f:
            return Image.open(f).convert("RGB")

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Flat image-file dataset, no labels (ref:
    vision/datasets/folder.py ImageFolder)."""

    def __init__(self, root, loader=None, extensions=None,
                 transform=None, is_valid_file=None):
        self.root = root
        self.transform = transform
        self.samples = _scan_files(root, extensions, is_valid_file)
        if not self.samples:
            raise RuntimeError(f"found no valid files under {root}")
        self.loader = loader or DatasetFolder._pil_loader

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)


__all__ += ["DatasetFolder", "ImageFolder"]
