"""Detection op suite: YOLO decode/loss, SSD priors, ROI pooling
variants, deformable conv, FPN routing, RPN proposals, matrix NMS,
image IO.

ref: python/paddle/vision/ops.py (yolo_loss :69, yolo_box :277,
prior_box :438, deform_conv2d :766, distribute_fpn_proposals :1175,
read_file :1345, decode_jpeg :1388, psroi_pool :1441, roi_pool :1572,
matrix_nms, generate_proposals). Design split: dense decode/loss math
runs on device (jnp, differentiable); ops with data-dependent output
sizes (proposal generation, FPN routing, NMS) are host-side like the
rest of this build's ragged ops.
"""
from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.autograd import apply_op
from ..core.tensor import Tensor
from ..nn.layer import Layer

__all__ = [
    "yolo_loss", "yolo_box", "prior_box", "deform_conv2d",
    "DeformConv2D", "distribute_fpn_proposals", "generate_proposals",
    "read_file", "decode_jpeg", "roi_pool", "RoIPool", "psroi_pool",
    "PSRoIPool", "RoIAlign", "matrix_nms",
]


def _np_of(t):
    return np.asarray(t.numpy() if isinstance(t, Tensor) else t)


# ---------------------------------------------------------------------------
# YOLO
# ---------------------------------------------------------------------------

def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5):
    """Decode a YOLOv3 head [N, S*(5+C), H, W] into boxes + scores
    (ref: ops.py yolo_box). Returns (boxes [N, H*W*S, 4] in xyxy image
    coords, scores [N, H*W*S, C]); predictions below conf_thresh get
    zeroed scores."""
    s = len(anchors) // 2

    def f(xa, imgs):
        n, _, h, w = xa.shape
        an = jnp.asarray(anchors, jnp.float32).reshape(s, 2)
        if iou_aware:
            ioup, xa_ = xa[:, :s], xa[:, s:]
        else:
            ioup, xa_ = None, xa
        p = xa_.reshape(n, s, 5 + class_num, h, w)
        gx = jnp.arange(w, dtype=jnp.float32)
        gy = jnp.arange(h, dtype=jnp.float32)
        bias = 0.5 * (scale_x_y - 1.0)
        cx = (jax.nn.sigmoid(p[:, :, 0]) * scale_x_y - bias
              + gx[None, None, None, :]) / w
        cy = (jax.nn.sigmoid(p[:, :, 1]) * scale_x_y - bias
              + gy[None, None, :, None]) / h
        bw = (jnp.exp(p[:, :, 2]) * an[None, :, 0, None, None]
              / (w * downsample_ratio))
        bh = (jnp.exp(p[:, :, 3]) * an[None, :, 1, None, None]
              / (h * downsample_ratio))
        conf = jax.nn.sigmoid(p[:, :, 4])
        if iou_aware:
            iou_p = jax.nn.sigmoid(ioup.reshape(n, s, h, w))
            conf = conf ** (1 - iou_aware_factor) * \
                iou_p ** iou_aware_factor
        cls = jax.nn.sigmoid(p[:, :, 5:]) * conf[:, :, None]
        im_h = imgs[:, 0].astype(jnp.float32)[:, None, None, None]
        im_w = imgs[:, 1].astype(jnp.float32)[:, None, None, None]
        x1 = (cx - bw / 2) * im_w
        y1 = (cy - bh / 2) * im_h
        x2 = (cx + bw / 2) * im_w
        y2 = (cy + bh / 2) * im_h
        if clip_bbox:
            x1 = jnp.clip(x1, 0, im_w - 1)
            y1 = jnp.clip(y1, 0, im_h - 1)
            x2 = jnp.clip(x2, 0, im_w - 1)
            y2 = jnp.clip(y2, 0, im_h - 1)
        keep = conf > conf_thresh                   # [N, S, H, W]
        boxes = jnp.stack([x1, y1, x2, y2], axis=2)  # [N, S, 4, H, W]
        boxes = jnp.where(keep[:, :, None], boxes, 0.0)
        cls = jnp.where(keep[:, :, None], cls, 0.0)
        # [N, S, 4, H, W] -> [N, H*W*S, 4]
        boxes = jnp.transpose(boxes, (0, 3, 4, 1, 2)).reshape(n, -1, 4)
        cls = jnp.transpose(cls, (0, 3, 4, 1, 2)).reshape(
            n, -1, class_num)
        return boxes, cls

    return apply_op(f, x, img_size, op_name="yolo_box")


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 training loss (ref: ops.py yolo_loss): per-anchor box
    regression (BCE on sigmoid x/y, L1 on w/h), objectness BCE with an
    ignore region above ``ignore_thresh`` IoU, and class BCE. gt_box is
    [N, B, 4] (cx, cy, w, h in image units), gt_label [N, B]; ground
    truths are matched to the best-IoU anchor of this head's mask."""
    s = len(anchor_mask)
    all_an = np.asarray(anchors, np.float32).reshape(-1, 2)
    mask_an = all_an[np.asarray(anchor_mask)]

    def f(xa, gb, gl, *maybe_score):
        n, _, h, w = xa.shape
        p = xa.reshape(n, s, 5 + class_num, h, w)
        stride = downsample_ratio
        img_w = w * stride
        img_h = h * stride
        an = jnp.asarray(mask_an)
        # ground-truth grid placement
        gx = gb[..., 0] / img_w          # [N, B] in [0,1]
        gy = gb[..., 1] / img_h
        gw = gb[..., 2] / img_w
        gh = gb[..., 3] / img_h
        valid = (gw > 0) & (gh > 0)
        gi = jnp.clip((gx * w).astype(jnp.int32), 0, w - 1)
        gj = jnp.clip((gy * h).astype(jnp.int32), 0, h - 1)
        # best anchor (across the FULL anchor set, matched into this
        # mask — the YOLOv3 assignment rule)
        awh = jnp.asarray(all_an) / jnp.asarray(
            [img_w, img_h], jnp.float32)
        inter = (jnp.minimum(gw[..., None], awh[None, None, :, 0])
                 * jnp.minimum(gh[..., None], awh[None, None, :, 1]))
        union = (gw * gh)[..., None] + awh[:, 0] * awh[:, 1] - inter
        an_iou = inter / jnp.maximum(union, 1e-10)
        best = jnp.argmax(an_iou, axis=-1)              # [N, B]
        mask_arr = jnp.asarray(np.asarray(anchor_mask))
        in_mask = (best[..., None] == mask_arr).any(-1) & valid
        slot = jnp.argmax(
            (best[..., None] == mask_arr).astype(jnp.int32), -1)

        # build dense targets via scatter (B is small)
        obj_t = jnp.zeros((n, s, h, w))
        tx = jnp.zeros((n, s, h, w))
        ty = jnp.zeros((n, s, h, w))
        tw = jnp.zeros((n, s, h, w))
        th = jnp.zeros((n, s, h, w))
        tcls = jnp.zeros((n, s, class_num, h, w))
        tscale = jnp.zeros((n, s, h, w))
        bidx = jnp.arange(n)[:, None] * jnp.ones_like(gi)
        wgt = maybe_score[0] if maybe_score else jnp.ones_like(gx)
        sel = (bidx, slot, gj, gi)
        upd = lambda t, v: t.at[sel].add(  # noqa: E731
            jnp.where(in_mask, v, 0.0))
        obj_t = upd(obj_t, jnp.ones_like(gx) * wgt)
        tx = upd(tx, gx * w - gi)
        ty = upd(ty, gy * h - gj)
        tw = upd(tw, jnp.log(jnp.maximum(
            gw * img_w / jnp.maximum(an[slot, 0], 1e-6), 1e-6)))
        th = upd(th, jnp.log(jnp.maximum(
            gh * img_h / jnp.maximum(an[slot, 1], 1e-6), 1e-6)))
        tscale = upd(tscale, 2.0 - gw * gh)
        cls_sel = (bidx, slot, gl.astype(jnp.int32), gj, gi)
        tcls = tcls.at[cls_sel].add(jnp.where(in_mask, 1.0, 0.0))
        obj_mask = (obj_t > 0).astype(jnp.float32)

        # ignore mask: predictions whose best IoU with any gt exceeds
        # the threshold are not penalized as background
        px = (jax.nn.sigmoid(p[:, :, 0])
              + jnp.arange(w, dtype=jnp.float32)) / w
        py = (jax.nn.sigmoid(p[:, :, 1])
              + jnp.arange(h, dtype=jnp.float32)[:, None]) / h
        pw = jnp.exp(jnp.clip(p[:, :, 2], -10, 10)) * \
            an[None, :, 0, None, None] / img_w
        ph = jnp.exp(jnp.clip(p[:, :, 3], -10, 10)) * \
            an[None, :, 1, None, None] / img_h

        def box_iou(ax, ay, aw2, ah2, bx, by, bw2, bh2):
            ax1, ax2 = ax - aw2 / 2, ax + aw2 / 2
            ay1, ay2 = ay - ah2 / 2, ay + ah2 / 2
            bx1, bx2 = bx - bw2 / 2, bx + bw2 / 2
            by1, by2 = by - bh2 / 2, by + bh2 / 2
            iw = jnp.maximum(
                jnp.minimum(ax2, bx2) - jnp.maximum(ax1, bx1), 0.0)
            ih = jnp.maximum(
                jnp.minimum(ay2, by2) - jnp.maximum(ay1, by1), 0.0)
            inter2 = iw * ih
            return inter2 / jnp.maximum(
                aw2 * ah2 + bw2 * bh2 - inter2, 1e-10)

        ious = box_iou(px[..., None], py[..., None], pw[..., None],
                       ph[..., None],
                       gx[:, None, None, None, :],
                       gy[:, None, None, None, :],
                       gw[:, None, None, None, :],
                       gh[:, None, None, None, :])
        ious = jnp.where(valid[:, None, None, None, :], ious, 0.0)
        best_iou = jnp.max(ious, axis=-1)
        noobj_mask = ((best_iou < ignore_thresh).astype(jnp.float32)
                      * (1.0 - obj_mask))

        def bce(logit, target):
            return jnp.maximum(logit, 0) - logit * target + \
                jnp.log1p(jnp.exp(-jnp.abs(logit)))

        delta = 0.1 / class_num if (use_label_smooth
                                    and class_num > 1) else 0.0
        tcls_s = tcls * (1.0 - delta) + delta / max(class_num, 1)
        loss_xy = jnp.sum(
            (bce(p[:, :, 0], tx) + bce(p[:, :, 1], ty))
            * obj_mask * tscale, axis=(1, 2, 3))
        loss_wh = jnp.sum(
            (jnp.abs(p[:, :, 2] - tw) + jnp.abs(p[:, :, 3] - th))
            * obj_mask * tscale, axis=(1, 2, 3))
        loss_obj = jnp.sum(
            bce(p[:, :, 4], obj_t) * (obj_mask + noobj_mask),
            axis=(1, 2, 3))
        loss_cls = jnp.sum(
            bce(p[:, :, 5:], tcls_s)
            * obj_mask[:, :, None], axis=(1, 2, 3, 4))
        return loss_xy + loss_wh + loss_obj + loss_cls

    args = (x, gt_box, gt_label) + ((gt_score,)
                                    if gt_score is not None else ())
    return apply_op(f, *args, op_name="yolo_loss")


# ---------------------------------------------------------------------------
# SSD priors
# ---------------------------------------------------------------------------

def prior_box(input, image, min_sizes, max_sizes=None,
              aspect_ratios=(1.0,), variance=(0.1, 0.1, 0.2, 0.2),
              flip=False, clip=False, steps=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False, name=None):
    """SSD prior (anchor) generation (ref: ops.py prior_box). Returns
    (boxes [H, W, P, 4] normalized xyxy, variances same shape)."""
    feat = input._data if isinstance(input, Tensor) else jnp.asarray(input)
    img = image._data if isinstance(image, Tensor) else jnp.asarray(image)
    h, w = int(feat.shape[2]), int(feat.shape[3])
    im_h, im_w = int(img.shape[2]), int(img.shape[3])
    if isinstance(min_sizes, (int, float)):
        min_sizes = [min_sizes]
    if isinstance(max_sizes, (int, float)):
        max_sizes = [max_sizes]
    if isinstance(aspect_ratios, (int, float)):
        aspect_ratios = [aspect_ratios]
    ars = [1.0]
    for ar in aspect_ratios:
        if all(abs(ar - e) > 1e-6 for e in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    step_w = steps[0] or im_w / w
    step_h = steps[1] or im_h / h
    whs = []
    for k, ms in enumerate(min_sizes):
        if min_max_aspect_ratios_order:
            whs.append((ms, ms))
            if max_sizes:
                big = math.sqrt(ms * max_sizes[k])
                whs.append((big, big))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((ms * math.sqrt(ar), ms / math.sqrt(ar)))
        else:
            for ar in ars:
                whs.append((ms * math.sqrt(ar), ms / math.sqrt(ar)))
            if max_sizes:
                big = math.sqrt(ms * max_sizes[k])
                whs.append((big, big))
    whs_np = np.asarray(whs, np.float32)
    cx = (np.arange(w, dtype=np.float32) + offset) * step_w
    cy = (np.arange(h, dtype=np.float32) + offset) * step_h
    cxg, cyg = np.meshgrid(cx, cy)                   # [H, W]
    boxes = np.empty((h, w, len(whs), 4), np.float32)
    boxes[..., 0] = (cxg[:, :, None] - whs_np[:, 0] / 2) / im_w
    boxes[..., 1] = (cyg[:, :, None] - whs_np[:, 1] / 2) / im_h
    boxes[..., 2] = (cxg[:, :, None] + whs_np[:, 0] / 2) / im_w
    boxes[..., 3] = (cyg[:, :, None] + whs_np[:, 1] / 2) / im_h
    if clip:
        boxes = boxes.clip(0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          boxes.shape).copy()
    return Tensor(jnp.asarray(boxes)), Tensor(jnp.asarray(var))


# ---------------------------------------------------------------------------
# deformable convolution
# ---------------------------------------------------------------------------

def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable conv v1/v2 (ref: ops.py deform_conv2d; Dai et al.
    2017 / Zhu et al. 2019): each kernel tap samples the input at its
    grid position plus a learned offset (bilinear), optionally
    modulated by ``mask``; the result contracts with the weights as a
    dense matmul — gather + MXU, no scatter."""
    sh, sw = (stride, stride) if isinstance(stride, int) else stride
    ph, pw = (padding, padding) if isinstance(padding, int) else padding
    dh, dw = (dilation, dilation) if isinstance(dilation, int) \
        else dilation
    if groups != 1 or deformable_groups != 1:
        raise NotImplementedError(
            "deform_conv2d: groups/deformable_groups > 1 unsupported")

    def f(xa, off, wgt, *rest):
        n, c, h, w = xa.shape
        co, ci, kh, kw = wgt.shape
        oh = (h + 2 * ph - dh * (kh - 1) - 1) // sh + 1
        ow = (w + 2 * pw - dw * (kw - 1) - 1) // sw + 1
        xp = jnp.pad(xa, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
        hp, wp = h + 2 * ph, w + 2 * pw
        off_r = off.reshape(n, kh * kw, 2, oh, ow)
        base_y = (jnp.arange(oh) * sh)[None, :, None]
        base_x = (jnp.arange(ow) * sw)[None, None, :]
        ky = (jnp.arange(kh) * dh).repeat(kw)[:, None, None]
        kx = jnp.tile(jnp.arange(kw) * dw, kh)[:, None, None]
        ys = base_y + ky + off_r[:, :, 0]           # [N, K, OH, OW]
        xs = base_x + kx + off_r[:, :, 1]
        y0 = jnp.floor(ys)
        x0 = jnp.floor(xs)
        wy = ys - y0
        wx = xs - x0
        valid = ((ys > -1) & (ys < hp) & (xs > -1) & (xs < wp))

        def gather(yy, xx):
            yc = jnp.clip(yy, 0, hp - 1).astype(jnp.int32)
            xc = jnp.clip(xx, 0, wp - 1).astype(jnp.int32)
            # per-image gather -> [N, C, K, OH, OW]
            return jax.vmap(
                lambda img, yv, xv: img[:, yv, xv])(xp, yc, xc)

        v00 = gather(y0, x0)
        v01 = gather(y0, x0 + 1)
        v10 = gather(y0 + 1, x0)
        v11 = gather(y0 + 1, x0 + 1)
        wy_ = wy[:, None]
        wx_ = wx[:, None]
        sampled = (v00 * (1 - wy_) * (1 - wx_) + v01 * (1 - wy_) * wx_
                   + v10 * wy_ * (1 - wx_) + v11 * wy_ * wx_)
        sampled = jnp.where(valid[:, None], sampled, 0.0)
        if rest:  # v2 modulation mask [N, K, OH, OW]
            m = rest[0].reshape(n, 1, kh * kw, oh, ow)
            sampled = sampled * m
        # contract [N, C, K, OH, OW] x [CO, C, K] -> [N, CO, OH, OW]
        wk = wgt.reshape(co, ci * kh * kw)
        cols = sampled.reshape(n, c * kh * kw, oh * ow)
        out = jnp.einsum("ok,nkp->nop", wk, cols).reshape(n, co, oh, ow)
        return out

    args = [x, offset, weight]
    if mask is not None:
        args.append(mask)
    out = apply_op(f, *args, op_name="deform_conv2d")
    if bias is not None:
        b = bias if isinstance(bias, Tensor) else Tensor(jnp.asarray(bias))
        out = out + b.reshape([1, -1, 1, 1])
    return out


class DeformConv2D(Layer):
    """Layer wrapper over deform_conv2d (ref: ops.py DeformConv2D)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        from .. import nn
        kh, kw = (kernel_size, kernel_size) if isinstance(
            kernel_size, int) else kernel_size
        bound = 1.0 / math.sqrt(in_channels * kh * kw)
        init = nn.initializer.Uniform(-bound, bound)
        from ..core.tensor import Parameter
        self.weight = Parameter(init(
            (out_channels, in_channels // groups, kh, kw), jnp.float32))
        self.bias = (Parameter(jnp.zeros((out_channels,), jnp.float32))
                     if bias_attr is not False else None)
        self._cfg = dict(stride=stride, padding=padding,
                         dilation=dilation,
                         deformable_groups=deformable_groups,
                         groups=groups)

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias,
                             mask=mask, **self._cfg)


# ---------------------------------------------------------------------------
# ROI pooling family
# ---------------------------------------------------------------------------

def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
             name=None):
    """Max-pool ROI bins (ref: ops.py roi_pool). Bins are sampled on a
    fixed dense grid then max-reduced — static shapes for XLA; exact
    when the grid resolution covers every integer cell, near-exact
    otherwise."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    samples = 4  # sub-samples per bin edge

    def f(feat, bxs):
        img = feat[0]
        c, h, w = img.shape
        x1 = jnp.round(bxs[:, 0] * spatial_scale)
        y1 = jnp.round(bxs[:, 1] * spatial_scale)
        x2 = jnp.maximum(jnp.round(bxs[:, 2] * spatial_scale), x1 + 1)
        y2 = jnp.maximum(jnp.round(bxs[:, 3] * spatial_scale), y1 + 1)
        bh = (y2 - y1) / oh
        bw = (x2 - x1) / ow
        sy = (jnp.arange(oh * samples) + 0.5) / samples
        sx = (jnp.arange(ow * samples) + 0.5) / samples
        ys = y1[:, None] + sy[None, :] * bh[:, None]   # [R, OH*S]
        xs = x1[:, None] + sx[None, :] * bw[:, None]
        yi = jnp.clip(ys.astype(jnp.int32), 0, h - 1)
        xi = jnp.clip(xs.astype(jnp.int32), 0, w - 1)
        vals = img[:, yi[:, :, None], xi[:, None, :]]  # [C,R,OHS,OWS]
        r = vals.shape[1]
        vals = vals.reshape(c, r, oh, samples, ow, samples)
        out = jnp.max(vals, axis=(3, 5))               # [C, R, OH, OW]
        return jnp.transpose(out, (1, 0, 2, 3))

    return apply_op(f, x, boxes, op_name="roi_pool")


class RoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size,
                        self.spatial_scale)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive ROI average pooling (ref: ops.py psroi_pool;
    R-FCN): input channels C = out_c * oh * ow; bin (i, j) of output
    channel k averages input channel k*oh*ow + i*ow + j."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    samples = 4

    def f(feat, bxs):
        img = feat[0]
        c, h, w = img.shape
        out_c = c // (oh * ow)
        x1 = bxs[:, 0] * spatial_scale
        y1 = bxs[:, 1] * spatial_scale
        x2 = bxs[:, 2] * spatial_scale
        y2 = bxs[:, 3] * spatial_scale
        bh = (y2 - y1) / oh
        bw = (x2 - x1) / ow
        sy = (jnp.arange(oh * samples) + 0.5) / samples
        sx = (jnp.arange(ow * samples) + 0.5) / samples
        ys = y1[:, None] + sy[None, :] * bh[:, None]
        xs = x1[:, None] + sx[None, :] * bw[:, None]
        yi = jnp.clip(ys.astype(jnp.int32), 0, h - 1)
        xi = jnp.clip(xs.astype(jnp.int32), 0, w - 1)
        vals = img[:, yi[:, :, None], xi[:, None, :]]
        r = vals.shape[1]
        vals = vals.reshape(c, r, oh, samples, ow, samples)
        avg = jnp.mean(vals, axis=(3, 5))              # [C, R, OH, OW]
        # pick the position-sensitive channel per output bin
        avg = avg.reshape(out_c, oh, ow, r, oh, ow)
        ii = jnp.arange(oh)
        jj = jnp.arange(ow)
        out = avg[:, ii[:, None], jj[None, :], :,
                  ii[:, None], jj[None, :]]
        # [OH, OW, OUT_C, R] -> [R, OUT_C, OH, OW]
        return jnp.transpose(out, (3, 2, 0, 1))

    return apply_op(f, x, boxes, op_name="psroi_pool")


class PSRoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self.output_size,
                          self.spatial_scale)


class RoIAlign(Layer):
    """Layer wrapper over roi_align (ref: ops.py RoIAlign)."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        from .ops import roi_align
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale)


# ---------------------------------------------------------------------------
# host-side proposal machinery
# ---------------------------------------------------------------------------

def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    """Route ROIs to FPN levels by scale (ref: ops.py
    distribute_fpn_proposals): level = floor(refer_level +
    log2(sqrt(area) / refer_scale)). Host-side (ragged outputs)."""
    rois = _np_of(fpn_rois)
    off = 1.0 if pixel_offset else 0.0
    ws = rois[:, 2] - rois[:, 0] + off
    hs = rois[:, 3] - rois[:, 1] + off
    scale = np.sqrt(np.maximum(ws * hs, 1e-12))
    lvl = np.floor(refer_level + np.log2(scale / refer_scale + 1e-12))
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    outs, restore_parts = [], []
    nums = []
    for level in range(min_level, max_level + 1):
        idx = np.nonzero(lvl == level)[0]
        outs.append(Tensor(jnp.asarray(rois[idx])))
        nums.append(Tensor(jnp.asarray(
            np.asarray([len(idx)], np.int32))))
        restore_parts.append(idx)
    order = np.concatenate(restore_parts) if restore_parts else \
        np.empty(0, np.int64)
    restore = np.empty_like(order)
    restore[order] = np.arange(len(order))
    return outs, Tensor(jnp.asarray(restore.reshape(-1, 1))), nums


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=True,
                       name=None):
    """RPN proposal generation (ref: ops.py generate_proposals): decode
    anchor deltas, clip to image, filter small, NMS, top-k. Host-side
    ragged op; single-image (N=1) like the build's other proposal ops."""
    from .ops import nms as nms_op
    sc = _np_of(scores)
    bd = _np_of(bbox_deltas)
    im = _np_of(img_size)
    an = _np_of(anchors).reshape(-1, 4)
    var = _np_of(variances).reshape(-1, 4)
    n = sc.shape[0]
    all_rois, all_scores, all_nums = [], [], []
    off = 1.0 if pixel_offset else 0.0
    for i in range(n):
        s = sc[i].transpose(1, 2, 0).reshape(-1)
        d = bd[i].reshape(-1, 4, *bd.shape[2:]) if False else \
            bd[i].transpose(1, 2, 0).reshape(-1, 4)
        order = np.argsort(-s)[:int(pre_nms_top_n)]
        s, d, a, v = s[order], d[order], an[order % len(an)], \
            var[order % len(var)]
        aw = a[:, 2] - a[:, 0] + off
        ah = a[:, 3] - a[:, 1] + off
        acx = a[:, 0] + aw * 0.5
        acy = a[:, 1] + ah * 0.5
        cx = v[:, 0] * d[:, 0] * aw + acx
        cy = v[:, 1] * d[:, 1] * ah + acy
        bw = aw * np.exp(np.minimum(v[:, 2] * d[:, 2], 10.0))
        bh = ah * np.exp(np.minimum(v[:, 3] * d[:, 3], 10.0))
        boxes = np.stack([cx - bw / 2, cy - bh / 2,
                          cx + bw / 2 - off, cy + bh / 2 - off], axis=1)
        ih, iw = im[i, 0], im[i, 1]
        boxes[:, 0::2] = boxes[:, 0::2].clip(0, iw - off)
        boxes[:, 1::2] = boxes[:, 1::2].clip(0, ih - off)
        keep_sz = ((boxes[:, 2] - boxes[:, 0] + off >= min_size)
                   & (boxes[:, 3] - boxes[:, 1] + off >= min_size))
        boxes, s = boxes[keep_sz], s[keep_sz]
        keep = _np_of(nms_op(Tensor(jnp.asarray(boxes)),
                             iou_threshold=nms_thresh,
                             scores=Tensor(jnp.asarray(s))))
        keep = keep[:int(post_nms_top_n)]
        all_rois.append(boxes[keep])
        all_scores.append(s[keep])
        all_nums.append(len(keep))
    rois = np.concatenate(all_rois) if all_rois else np.empty((0, 4))
    rois_t = Tensor(jnp.asarray(rois.astype(np.float32)))
    scores_out = Tensor(jnp.asarray(
        np.concatenate(all_scores).astype(np.float32)
        if all_scores else np.empty(0, np.float32)))
    if return_rois_num:
        return rois_t, scores_out, Tensor(jnp.asarray(
            np.asarray(all_nums, np.int32)))
    return rois_t, scores_out


def matrix_nms(bboxes, scores, score_threshold, post_threshold,
               nms_top_k, keep_top_k, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               return_index=False, return_rois_num=True, name=None):
    """Matrix NMS (ref: ops.py matrix_nms; SOLOv2): instead of hard
    suppression, each box's score decays by its IoU with higher-scored
    boxes of the same class. Host-side."""
    b = _np_of(bboxes)
    s = _np_of(scores)
    n, num_cls = s.shape[0], s.shape[1]
    outs, idxs, nums = [], [], []
    for i in range(n):
        dets = []
        for c in range(num_cls):
            if c == background_label:
                continue
            sc = s[i, c]
            sel = np.nonzero(sc > score_threshold)[0]
            if len(sel) == 0:
                continue
            order = sel[np.argsort(-sc[sel])][:int(nms_top_k)]
            bs, ss = b[i][order], sc[order]
            x1 = np.maximum(bs[:, None, 0], bs[None, :, 0])
            y1 = np.maximum(bs[:, None, 1], bs[None, :, 1])
            x2 = np.minimum(bs[:, None, 2], bs[None, :, 2])
            y2 = np.minimum(bs[:, None, 3], bs[None, :, 3])
            off = 0.0 if normalized else 1.0
            iw = np.maximum(x2 - x1 + off, 0)
            ih = np.maximum(y2 - y1 + off, 0)
            inter = iw * ih
            area = ((bs[:, 2] - bs[:, 0] + off)
                    * (bs[:, 3] - bs[:, 1] + off))
            iou = inter / np.maximum(
                area[:, None] + area[None, :] - inter, 1e-10)
            iou = np.triu(iou, 1)                 # j suppressed by i<j
            # compensation per suppressor i = its own max IoU with
            # higher-scored boxes (column max; iou_max[0] == 0, which
            # also bounds the min-decay below at <= 1)
            comp = iou.max(axis=0)
            if use_gaussian:
                decay = np.exp(-(iou ** 2 - comp[:, None] ** 2)
                               / gaussian_sigma).min(axis=0)
            else:
                decay = ((1 - iou) / np.maximum(1 - comp[:, None],
                                                1e-10)).min(axis=0)
            decay = np.minimum(decay, 1.0)
            new_s = ss * decay
            keep = new_s > post_threshold
            for j in np.nonzero(keep)[0]:
                dets.append((c, new_s[j], *bs[j], order[j]))
        dets.sort(key=lambda t: -t[1])
        if keep_top_k > 0:
            dets = dets[:int(keep_top_k)]
        outs.append(np.asarray([d[:6] for d in dets], np.float32)
                    if dets else np.empty((0, 6), np.float32))
        idxs.append(np.asarray([d[6] for d in dets], np.int64)
                    if dets else np.empty(0, np.int64))
        nums.append(len(dets))
    out = Tensor(jnp.asarray(np.concatenate(outs)
                             if outs else np.empty((0, 6), np.float32)))
    rois_num = Tensor(jnp.asarray(np.asarray(nums, np.int32)))
    index = Tensor(jnp.asarray(np.concatenate(idxs)
                               if idxs else np.empty(0, np.int64)))
    if return_index:
        return (out, index, rois_num) if return_rois_num else \
            (out, index)
    return (out, None, rois_num) if return_rois_num else (out, None)


# ---------------------------------------------------------------------------
# image IO
# ---------------------------------------------------------------------------

def read_file(filename, name=None):
    """File bytes as a uint8 tensor (ref: ops.py read_file)."""
    with open(filename, "rb") as f:
        data = np.frombuffer(f.read(), np.uint8)
    return Tensor(jnp.asarray(data))


def decode_jpeg(x, mode="unchanged", name=None):
    """JPEG bytes -> CHW uint8 tensor (ref: ops.py decode_jpeg; the
    reference uses nvjpeg — PIL serves the host-side role here)."""
    import io

    from PIL import Image
    data = _np_of(x).tobytes()
    img = Image.open(io.BytesIO(data))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))
