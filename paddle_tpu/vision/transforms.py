"""Image transforms (host-side, numpy).

ref: python/paddle/vision/transforms/transforms.py. These run in the input
pipeline on CPU — device work stays on the TPU.
"""
from __future__ import annotations

import numbers
import random
from typing import List, Sequence

import numpy as np

__all__ = [
    "Compose", "ToTensor", "Normalize", "Resize", "CenterCrop", "RandomCrop",
    "RandomHorizontalFlip", "RandomVerticalFlip", "Transpose", "Pad",
]


class Compose:
    def __init__(self, transforms: List):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(np.asarray(img))


class ToTensor(BaseTransform):
    """HWC uint8 -> CHW float32 in [0,1] (ref: transforms.py ToTensor)."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def _apply_image(self, img):
        img = img.astype(np.float32) / 255.0
        if img.ndim == 2:
            img = img[:, :, None]
        if self.data_format == "CHW":
            img = img.transpose(2, 0, 1)
        return img


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean = np.asarray(mean, dtype=np.float32)
        self.std = np.asarray(std, dtype=np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        img = img.astype(np.float32)
        if self.data_format == "CHW":
            return (img - self.mean[:, None, None]) / self.std[:, None, None]
        return (img - self.mean) / self.std


def _resize_np(img, size):
    """Nearest-neighbor resize without external deps."""
    if isinstance(size, int):
        h, w = img.shape[:2]
        if h < w:
            size = (size, int(size * w / h))
        else:
            size = (int(size * h / w), size)
    oh, ow = size
    h, w = img.shape[:2]
    ys = (np.arange(oh) * (h / oh)).astype(np.int64).clip(0, h - 1)
    xs = (np.arange(ow) * (w / ow)).astype(np.int64).clip(0, w - 1)
    return img[ys][:, xs]


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear"):
        self.size = size

    def _apply_image(self, img):
        return _resize_np(img, self.size)


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        h, w = img.shape[:2]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return img[i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=0):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        if self.padding:
            pad = [(self.padding, self.padding), (self.padding, self.padding)]
            if img.ndim == 3:
                pad.append((0, 0))
            img = np.pad(img, pad)
        h, w = img.shape[:2]
        th, tw = self.size
        i = random.randint(0, max(h - th, 0))
        j = random.randint(0, max(w - tw, 0))
        return img[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return img[:, ::-1].copy()
        return img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return img[::-1].copy()
        return img


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def _apply_image(self, img):
        if img.ndim == 2:
            img = img[:, :, None]
        return img.transpose(self.order)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.padding = padding
        self.fill = fill

    def _apply_image(self, img):
        p = self.padding
        if isinstance(p, int):
            p = (p, p, p, p)
        pad = [(p[1], p[3]), (p[0], p[2])]
        if img.ndim == 3:
            pad.append((0, 0))
        return np.pad(img, pad, constant_values=self.fill)
