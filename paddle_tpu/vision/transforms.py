"""Image transforms (host-side, numpy).

ref: python/paddle/vision/transforms/transforms.py. These run in the input
pipeline on CPU — device work stays on the TPU.
"""
from __future__ import annotations

import numbers
import random
from typing import List, Sequence

import numpy as np

__all__ = [
    "Compose", "ToTensor", "Normalize", "Resize", "CenterCrop", "RandomCrop",
    "RandomHorizontalFlip", "RandomVerticalFlip", "Transpose", "Pad",
]


class Compose:
    def __init__(self, transforms: List):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(np.asarray(img))


class ToTensor(BaseTransform):
    """HWC uint8 -> CHW float32 in [0,1] (ref: transforms.py ToTensor)."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def _apply_image(self, img):
        img = img.astype(np.float32) / 255.0
        if img.ndim == 2:
            img = img[:, :, None]
        if self.data_format == "CHW":
            img = img.transpose(2, 0, 1)
        return img


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean = np.asarray(mean, dtype=np.float32)
        self.std = np.asarray(std, dtype=np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        img = img.astype(np.float32)
        if self.data_format == "CHW":
            return (img - self.mean[:, None, None]) / self.std[:, None, None]
        return (img - self.mean) / self.std


def _resize_np(img, size):
    """Nearest-neighbor resize without external deps."""
    if isinstance(size, int):
        h, w = img.shape[:2]
        if h < w:
            size = (size, int(size * w / h))
        else:
            size = (int(size * h / w), size)
    oh, ow = size
    h, w = img.shape[:2]
    ys = (np.arange(oh) * (h / oh)).astype(np.int64).clip(0, h - 1)
    xs = (np.arange(ow) * (w / ow)).astype(np.int64).clip(0, w - 1)
    return img[ys][:, xs]


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear"):
        self.size = size

    def _apply_image(self, img):
        return _resize_np(img, self.size)


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        h, w = img.shape[:2]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return img[i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=0):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        if self.padding:
            pad = [(self.padding, self.padding), (self.padding, self.padding)]
            if img.ndim == 3:
                pad.append((0, 0))
            img = np.pad(img, pad)
        h, w = img.shape[:2]
        th, tw = self.size
        i = random.randint(0, max(h - th, 0))
        j = random.randint(0, max(w - tw, 0))
        return img[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return img[:, ::-1].copy()
        return img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return img[::-1].copy()
        return img


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def _apply_image(self, img):
        if img.ndim == 2:
            img = img[:, :, None]
        return img.transpose(self.order)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.padding = padding
        self.fill = fill

    def _apply_image(self, img):
        p = self.padding
        if isinstance(p, int):
            p = (p, p, p, p)
        pad = [(p[1], p[3]), (p[0], p[2])]
        if img.ndim == 3:
            pad.append((0, 0))
        return np.pad(img, pad, constant_values=self.fill)


# ---------------------------------------------------------------------------
# functional API (ref: python/paddle/vision/transforms/functional.py) —
# host-side numpy; images are HWC (or HW) arrays like the class
# transforms above
# ---------------------------------------------------------------------------

def to_tensor(pic, data_format="CHW"):
    """HWC uint8/float image -> normalized float32 tensor array
    (ref: functional.py to_tensor)."""
    return ToTensor(data_format)(pic)


def hflip(img):
    return np.asarray(img)[:, ::-1].copy()


def vflip(img):
    return np.asarray(img)[::-1].copy()


def _bilinear_sample(img, ys, xs, fill=0.0):
    """Sample img (HWC) at fractional (ys, xs) grids with bilinear
    interpolation; out-of-bounds reads produce ``fill``."""
    img = np.asarray(img)
    squeeze = img.ndim == 2
    if squeeze:
        img = img[:, :, None]
    h, w, c = img.shape
    y0 = np.floor(ys).astype(np.int64)
    x0 = np.floor(xs).astype(np.int64)
    y1, x1 = y0 + 1, x0 + 1
    wy = (ys - y0)[..., None]
    wx = (xs - x0)[..., None]
    valid = ((ys >= 0) & (ys <= h - 1) & (xs >= 0)
             & (xs <= w - 1))[..., None]
    imgf = img.astype(np.float32)

    def at(yy, xx):
        yc = np.clip(yy, 0, h - 1)
        xc = np.clip(xx, 0, w - 1)
        return imgf[yc, xc]

    out = ((1 - wy) * (1 - wx) * at(y0, x0)
           + (1 - wy) * wx * at(y0, x1)
           + wy * (1 - wx) * at(y1, x0)
           + wy * wx * at(y1, x1))
    out = np.where(valid, out, np.float32(fill))
    if np.issubdtype(img.dtype, np.integer):
        out = np.clip(np.round(out), 0, 255).astype(img.dtype)
    else:
        out = out.astype(img.dtype)
    return out[:, :, 0] if squeeze else out


def resize(img, size, interpolation="bilinear"):
    """ref: functional.py resize; bilinear (default) or nearest."""
    img = np.asarray(img)
    h, w = img.shape[:2]
    if isinstance(size, int):
        if h < w:
            size = (size, int(size * w / h))
        else:
            size = (int(size * h / w), size)
    oh, ow = size
    if interpolation == "nearest":
        return _resize_np(img, (oh, ow))
    ys = (np.arange(oh) + 0.5) * (h / oh) - 0.5
    xs = (np.arange(ow) + 0.5) * (w / ow) - 0.5
    gy, gx = np.meshgrid(ys, xs, indexing="ij")
    return _bilinear_sample(img, np.clip(gy, 0, h - 1),
                            np.clip(gx, 0, w - 1))


def pad(img, padding, fill=0, padding_mode="constant"):
    """ref: functional.py pad; padding int or (l, t, r, b)."""
    img = np.asarray(img)
    p = padding
    if isinstance(p, int):
        p = (p, p, p, p)
    elif len(p) == 2:
        p = (p[0], p[1], p[0], p[1])
    cfg = [(p[1], p[3]), (p[0], p[2])] + \
        ([(0, 0)] if img.ndim == 3 else [])
    mode = {"constant": "constant", "edge": "edge",
            "reflect": "reflect", "symmetric": "symmetric"}[padding_mode]
    if mode == "constant":
        return np.pad(img, cfg, mode, constant_values=fill)
    return np.pad(img, cfg, mode)


def crop(img, top, left, height, width):
    return np.asarray(img)[top:top + height, left:left + width].copy()


def center_crop(img, output_size):
    img = np.asarray(img)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    th, tw = output_size
    h, w = img.shape[:2]
    return crop(img, max((h - th) // 2, 0), max((w - tw) // 2, 0), th, tw)


def _inverse_affine_grid(h, w, matrix):
    """Output-pixel grid mapped through the INVERSE 2x3 affine matrix
    (center-origin convention, like the reference's cv2/PIL path)."""
    cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
    yy, xx = np.meshgrid(np.arange(h, dtype=np.float64),
                         np.arange(w, dtype=np.float64), indexing="ij")
    xr, yr = xx - cx, yy - cy
    a, b, tx, c, d, ty = matrix
    xs = a * xr + b * yr + tx + cx
    ys = c * xr + d * yr + ty + cy
    return ys, xs


def _affine_inverse(angle, translate, scale, shear):
    """Inverse of the affine transform built from rotate/translate/
    scale/shear (degrees), as a flat 2x3 (a, b, tx, c, d, ty)."""
    import math as _m
    rot = _m.radians(angle)
    sx, sy = (_m.radians(s) for s in shear)
    # forward: M = R(rot) * Shear(sx, sy) * scale, then + translate
    a = _m.cos(rot - sy) / _m.cos(sy)
    b = -(_m.cos(rot - sy) * _m.tan(sx) / _m.cos(sy) + _m.sin(rot))
    c = _m.sin(rot - sy) / _m.cos(sy)
    d = -(_m.sin(rot - sy) * _m.tan(sx) / _m.cos(sy) - _m.cos(rot))
    fwd = np.array([[scale * a, scale * b, translate[0]],
                    [scale * c, scale * d, translate[1]],
                    [0.0, 0.0, 1.0]])
    inv = np.linalg.inv(fwd)
    return (inv[0, 0], inv[0, 1], inv[0, 2],
            inv[1, 0], inv[1, 1], inv[1, 2])


def affine(img, angle, translate, scale, shear, interpolation="bilinear",
           fill=0, center=None):
    """ref: functional.py affine — rotate/translate/scale/shear about
    the image center, inverse-mapped with bilinear sampling."""
    img = np.asarray(img)
    if isinstance(shear, numbers.Number):
        shear = (shear, 0.0)
    h, w = img.shape[:2]
    m = _affine_inverse(angle, translate, scale, tuple(shear))
    ys, xs = _inverse_affine_grid(h, w, m)
    return _bilinear_sample(img, ys, xs, fill=fill)


def rotate(img, angle, interpolation="bilinear", expand=False, center=None,
           fill=0):
    """ref: functional.py rotate (expand=False keeps the input size)."""
    return affine(img, angle, (0.0, 0.0), 1.0, (0.0, 0.0),
                  interpolation, fill, center)


def perspective(img, startpoints, endpoints, interpolation="bilinear",
                fill=0):
    """ref: functional.py perspective — warp mapping ``startpoints`` to
    ``endpoints`` (4 corner points each, (x, y))."""
    img = np.asarray(img)
    h, w = img.shape[:2]
    # solve the 8-dof homography sending endpoints -> startpoints
    # (inverse mapping: output pixel -> input location)
    A, bvec = [], []
    for (xe, ye), (xs_, ys_) in zip(endpoints, startpoints):
        A.append([xe, ye, 1, 0, 0, 0, -xs_ * xe, -xs_ * ye])
        A.append([0, 0, 0, xe, ye, 1, -ys_ * xe, -ys_ * ye])
        bvec.extend([xs_, ys_])
    coef = np.linalg.solve(np.asarray(A, np.float64),
                           np.asarray(bvec, np.float64))
    a, b, c, d, e, f, g, hh = coef
    yy, xx = np.meshgrid(np.arange(h, dtype=np.float64),
                         np.arange(w, dtype=np.float64), indexing="ij")
    den = g * xx + hh * yy + 1.0
    xs = (a * xx + b * yy + c) / den
    ys = (d * xx + e * yy + f) / den
    return _bilinear_sample(img, ys, xs, fill=fill)


def to_grayscale(img, num_output_channels=1):
    """ITU-R 601-2 luma (ref: functional.py to_grayscale)."""
    img = np.asarray(img)
    lum = (0.299 * img[..., 0] + 0.587 * img[..., 1]
           + 0.114 * img[..., 2])
    if np.issubdtype(img.dtype, np.integer):
        lum = np.clip(np.round(lum), 0, 255).astype(img.dtype)
    else:
        lum = lum.astype(img.dtype)
    return np.stack([lum] * num_output_channels, axis=-1)


def _blend(img, other, factor):
    out = (img.astype(np.float32) * factor
           + other.astype(np.float32) * (1.0 - factor))
    if np.issubdtype(np.asarray(img).dtype, np.integer):
        return np.clip(np.round(out), 0, 255).astype(np.asarray(img).dtype)
    return out.astype(np.asarray(img).dtype)


def adjust_brightness(img, brightness_factor):
    """ref: functional.py adjust_brightness: blend with black."""
    img = np.asarray(img)
    return _blend(img, np.zeros_like(img), brightness_factor)


def adjust_contrast(img, contrast_factor):
    """ref: functional.py adjust_contrast: blend with the mean gray."""
    img = np.asarray(img)
    gray = to_grayscale(img)[..., 0].astype(np.float32)
    mean = np.full_like(img, gray.mean(), dtype=np.float32)
    return _blend(img, mean, contrast_factor)


def adjust_saturation(img, saturation_factor):
    """ref: functional.py adjust_saturation: blend with grayscale."""
    img = np.asarray(img)
    gray = np.broadcast_to(to_grayscale(img), img.shape)
    return _blend(img, gray, saturation_factor)


def adjust_hue(img, hue_factor):
    """Shift hue in HSV space by hue_factor (in [-0.5, 0.5]); ref:
    functional.py adjust_hue."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    img = np.asarray(img)
    is_int = np.issubdtype(img.dtype, np.integer)
    x = img.astype(np.float32) / (255.0 if is_int else 1.0)
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    mx = np.max(x[..., :3], axis=-1)
    mn = np.min(x[..., :3], axis=-1)
    diff = mx - mn
    safe = np.where(diff == 0, 1.0, diff)
    hr = np.where(mx == r, ((g - b) / safe) % 6.0, 0.0)
    hg = np.where((mx == g) & (mx != r), (b - r) / safe + 2.0, 0.0)
    hb = np.where((mx == b) & (mx != r) & (mx != g),
                  (r - g) / safe + 4.0, 0.0)
    hcombined = (hr + hg + hb) / 6.0
    hue = np.where(diff == 0, 0.0, hcombined)
    sat = np.where(mx == 0, 0.0, diff / np.where(mx == 0, 1.0, mx))
    val = mx
    hue = (hue + hue_factor) % 1.0
    i = np.floor(hue * 6.0)
    f = hue * 6.0 - i
    p = val * (1.0 - sat)
    q = val * (1.0 - f * sat)
    t = val * (1.0 - (1.0 - f) * sat)
    i = i.astype(np.int64) % 6
    r2 = np.choose(i, [val, q, p, p, t, val])
    g2 = np.choose(i, [t, val, val, q, p, p])
    b2 = np.choose(i, [p, p, t, val, val, q])
    out = np.stack([r2, g2, b2], axis=-1)
    if is_int:
        return np.clip(np.round(out * 255.0), 0, 255).astype(img.dtype)
    return out.astype(img.dtype)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    """ref: functional.py normalize."""
    img = np.asarray(img).astype(np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        return (img - mean[:, None, None]) / std[:, None, None]
    return (img - mean) / std


def erase(img, i, j, h, w, v, inplace=False):
    """Erase the region [i:i+h, j:j+w] with value(s) v (ref:
    functional.py erase; works on HWC or CHW arrays)."""
    img = np.asarray(img)
    out = img if inplace else img.copy()
    if out.ndim == 3 and out.shape[0] in (1, 3) and out.shape[2] not in \
            (1, 3):
        out[:, i:i + h, j:j + w] = v  # CHW
    else:
        out[i:i + h, j:j + w] = v
    return out


# ---------------------------------------------------------------------------
# random / photometric transform classes
# ---------------------------------------------------------------------------

class RandomResizedCrop(BaseTransform):
    """Random area+aspect crop resized to ``size``
    (ref: transforms.py RandomResizedCrop)."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        import math as _m
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * random.uniform(*self.scale)
            log_r = (_m.log(self.ratio[0]), _m.log(self.ratio[1]))
            ar = _m.exp(random.uniform(*log_r))
            cw = int(round(_m.sqrt(target * ar)))
            ch = int(round(_m.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                i = random.randint(0, h - ch)
                j = random.randint(0, w - cw)
                return resize(crop(img, i, j, ch, cw), self.size,
                              self.interpolation)
        return resize(center_crop(img, min(h, w)), self.size,
                      self.interpolation)


class BrightnessTransform(BaseTransform):
    """ref: transforms.py BrightnessTransform(value): factor uniform in
    [max(0, 1-value), 1+value]."""

    def __init__(self, value, keys=None):
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = random.uniform(max(0.0, 1 - self.value), 1 + self.value)
        return adjust_brightness(img, f)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        if value < 0:
            raise ValueError("contrast value must be non-negative")
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = random.uniform(max(0.0, 1 - self.value), 1 + self.value)
        return adjust_contrast(img, f)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = random.uniform(max(0.0, 1 - self.value), 1 + self.value)
        return adjust_saturation(img, f)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        if not 0 <= value <= 0.5:
            raise ValueError("hue value must be in [0, 0.5]")
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        return adjust_hue(img, random.uniform(-self.value, self.value))


class ColorJitter(BaseTransform):
    """Random brightness/contrast/saturation/hue in random order
    (ref: transforms.py ColorJitter)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        self.ts = [BrightnessTransform(brightness),
                   ContrastTransform(contrast),
                   SaturationTransform(saturation), HueTransform(hue)]

    def _apply_image(self, img):
        order = list(range(4))
        random.shuffle(order)
        for k in order:
            img = self.ts[k]._apply_image(img)
        return img


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.fill = fill

    def _apply_image(self, img):
        return rotate(img, random.uniform(*self.degrees), fill=self.fill)


class RandomAffine(BaseTransform):
    """ref: transforms.py RandomAffine(degrees, translate, scale,
    shear)."""

    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.fill = fill

    def _apply_image(self, img):
        h, w = img.shape[:2]
        angle = random.uniform(*self.degrees)
        if self.translate is not None:
            tx = random.uniform(-self.translate[0], self.translate[0]) * w
            ty = random.uniform(-self.translate[1], self.translate[1]) * h
        else:
            tx = ty = 0.0
        sc = random.uniform(*self.scale) if self.scale else 1.0
        if self.shear is None:
            shear = (0.0, 0.0)
        elif isinstance(self.shear, numbers.Number):
            shear = (random.uniform(-self.shear, self.shear), 0.0)
        elif len(self.shear) == 2:
            shear = (random.uniform(self.shear[0], self.shear[1]), 0.0)
        else:
            shear = (random.uniform(self.shear[0], self.shear[1]),
                     random.uniform(self.shear[2], self.shear[3]))
        return affine(img, angle, (tx, ty), sc, shear, fill=self.fill)


class RandomPerspective(BaseTransform):
    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.fill = fill

    def _apply_image(self, img):
        if random.random() >= self.prob:
            return img
        h, w = img.shape[:2]
        d = self.distortion_scale
        hw, hh = int(w * d / 2), int(h * d / 2)
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        end = [(random.randint(0, hw), random.randint(0, hh)),
               (w - 1 - random.randint(0, hw), random.randint(0, hh)),
               (w - 1 - random.randint(0, hw),
                h - 1 - random.randint(0, hh)),
               (random.randint(0, hw), h - 1 - random.randint(0, hh))]
        return perspective(img, start, end, fill=self.fill)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)


class RandomErasing(BaseTransform):
    """ref: transforms.py RandomErasing(prob, scale, ratio, value)."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value
        self.inplace = inplace

    def _apply_image(self, img):
        import math as _m
        if random.random() >= self.prob:
            return img
        chw = img.ndim == 3 and img.shape[0] in (1, 3) and \
            img.shape[2] not in (1, 3)
        h, w = (img.shape[1:3] if chw else img.shape[:2])
        area = h * w
        for _ in range(10):
            target = area * random.uniform(*self.scale)
            ar = random.uniform(*self.ratio)
            eh = int(round(_m.sqrt(target * ar)))
            ew = int(round(_m.sqrt(target / ar)))
            if eh < h and ew < w:
                i = random.randint(0, h - eh)
                j = random.randint(0, w - ew)
                if self.value == "random":
                    v = np.random.normal(
                        size=((img.shape[0], eh, ew) if chw
                              else (eh, ew) + img.shape[2:]))
                else:
                    v = self.value
                return erase(img, i, j, eh, ew, v, self.inplace)
        return img


__all__ += [
    "RandomResizedCrop", "BrightnessTransform", "SaturationTransform",
    "ContrastTransform", "HueTransform", "ColorJitter", "RandomAffine",
    "RandomRotation", "RandomPerspective", "Grayscale", "RandomErasing",
    "to_tensor", "hflip", "vflip", "resize", "pad", "affine", "rotate",
    "perspective", "to_grayscale", "crop", "center_crop",
    "adjust_brightness", "adjust_contrast", "adjust_saturation",
    "adjust_hue", "normalize", "erase",
]
