"""Device management.

TPU-native analog of the reference's Place/DeviceContext machinery
(ref: paddle/phi/backends/device_manager.h, paddle/phi/common/place.h).
On TPU the runtime (PJRT, via JAX) owns streams/allocators, so this layer is a
thin facade: named places, device listing, and the default-device switch.
"""
from __future__ import annotations

import jax


class Place:
    """A device place, e.g. Place('tpu', 0). ref: paddle/phi/common/place.h"""

    def __init__(self, device_type: str, device_id: int = 0):
        self.device_type = device_type
        self.device_id = device_id

    def __repr__(self):
        return f"Place({self.device_type}:{self.device_id})"

    def __eq__(self, other):
        return (isinstance(other, Place)
                and self.device_type == other.device_type
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def jax_device(self):
        devs = [d for d in jax.devices() if _platform_of(d) == self.device_type]
        if not devs:
            devs = jax.devices()
        return devs[self.device_id % len(devs)]


def TPUPlace(device_id: int = 0) -> Place:
    return Place("tpu", device_id)


def CPUPlace(device_id: int = 0) -> Place:
    return Place("cpu", device_id)


def _platform_of(d) -> str:
    p = d.platform
    # axon tunnel exposes the real chip under an experimental platform name
    return "tpu" if p in ("tpu", "axon") else p


_current_device: Place | None = None


def set_device(device: str) -> Place:
    """set_device('tpu') / 'tpu:0' / 'cpu'. ref: python/paddle/device/__init__.py"""
    global _current_device
    if ":" in device:
        kind, idx = device.split(":", 1)
        _current_device = Place(kind, int(idx))
    else:
        _current_device = Place(device, 0)
    return _current_device


def get_device() -> str:
    p = _get_place()
    return f"{p.device_type}:{p.device_id}"


def _get_place() -> Place:
    global _current_device
    if _current_device is None:
        plat = _platform_of(jax.devices()[0])
        _current_device = Place(plat, 0)
    return _current_device


def device_count(device_type: str | None = None) -> int:
    if device_type is None:
        return len(jax.devices())
    return len([d for d in jax.devices() if _platform_of(d) == device_type])


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return any(_platform_of(d) == "tpu" for d in jax.devices())
