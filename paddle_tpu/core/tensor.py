"""Tensor: the user-facing eager array.

TPU-native analog of the reference's ``paddle::Tensor``
(ref: paddle/phi/api/include/tensor.h:82) + AutogradMeta
(ref: paddle/fluid/eager/autograd_meta.h:61). The device buffer is a
``jax.Array`` (PJRT-owned); autograd metadata is a (GradNode, out_index)
edge recorded by ``core.autograd.apply_op``.

Under jit tracing the same class wraps JAX tracers, so layer code written
against this API runs unchanged in both eager and compiled modes.
"""
from __future__ import annotations

import threading
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import dtype as dtype_mod
from . import fusion as fusion_mod
from ..observability import flight as _flight
from .autograd import apply_op, backward as _backward, is_grad_enabled


def _cast_impl(a, dtype=None):
    return a.astype(dtype)


# `fusable: true` + parametric (target dtype rides the program key): the
# trailing cast of a bf16 epilogue — act(x @ w + b).astype(...) — fuses
# into the same executable instead of a full-tensor second pass
fusion_mod.register_param_impl("cast", _cast_impl)


# SOT (dy2static) hooks: the graph-break tracer installs these to observe
# host-value materializations (guards) and in-place buffer mutations.
_materialize_hook = None
_mutation_hook = None

# Analysis-auditor hook (paddle_tpu.analysis.auditor): notified of every
# device->host materialization — numpy()/item()/tolist()/__array__ —
# with (tensor, kind). Separate from _materialize_hook so SOT tracing
# and a capture audit can observe the same step simultaneously. None
# outside an audit: one global read per host read.
_sync_hook = None


# Tensors sharing a device buffer with another live handle (today:
# ``detach()``). Buffer-DONATION sites (the fused optimizer step, the
# AMP batched unscale) consult this and copy such a leaf instead of
# donating it — XLA deletes donated buffers, and the eager loop's
# replace-don't-mutate semantics promise a detached snapshot stays
# readable, frozen at its point-in-time value. Outer key: id(array);
# inner: id(alias Tensor) -> Tensor weakly, so entries vanish with the
# last alias (a live alias pins the array, so its id can't be reused).
# _alias_lock guards the structural sweeps: detach() on one thread
# while a fused step's donation gate prunes on another would otherwise
# mutate the dict mid-iteration (found by the PTL003 lint rule).
_buffer_aliases: dict = {}
_alias_lock = threading.Lock()


def _register_alias(arr, t) -> None:
    import weakref
    with _alias_lock:
        if len(_buffer_aliases) > 64:
            # amortized sweep: inner dicts empty themselves when the
            # last alias dies, but the outer entry would otherwise
            # persist — without this a detach-per-step loop leaks one
            # entry per call
            for k in [k for k, d in _buffer_aliases.items()
                      if not len(d)]:
                del _buffer_aliases[k]
        d = _buffer_aliases.get(id(arr))
        if d is None:
            d = _buffer_aliases[id(arr)] = weakref.WeakValueDictionary()
        d[id(t)] = t


def buffer_has_alias(arr) -> bool:
    """True when another live Tensor handle shares ``arr`` — the caller
    must not donate it. ~Free when no aliases exist anywhere."""
    if not _buffer_aliases:
        return False
    with _alias_lock:
        d = _buffer_aliases.get(id(arr))
        if d is None:
            return False
        if not len(d):
            del _buffer_aliases[id(arr)]  # last alias died: prune
            return False
        return True


class Tensor:
    __slots__ = ("_buf", "_lazy", "stop_gradient", "grad", "_node",
                 "_out_index", "_retain_grads", "_hooks", "_hook_counter",
                 "name", "trainable", "__weakref__", "_dist_attr",
                 "_static_feed_name", "_static_rng")

    def __init__(self, data, stop_gradient: bool = True, node=None,
                 out_index: int = 0, name: Optional[str] = None):
        self._buf = data
        self._lazy = None
        self.stop_gradient = stop_gradient
        self.grad = None
        self._node = node
        self._out_index = out_index
        self._retain_grads = False
        self._hooks = {}
        self._hook_counter = 0
        self.name = name or ""
        self.trainable = False
        self._dist_attr = None

    # -- lazy-eager fusion seam ---------------------------------------------
    # ``_data`` is the universal flush point: any consumer that needs the
    # concrete device buffer (host reads, non-fusable ops, backward,
    # mutation) reads this property, and a pending fused chain
    # materializes exactly there. Shape/dtype introspection below stays
    # lazy — it answers from the inferred aval without forcing the chain.
    @property
    def _data(self):
        if self._lazy is not None:
            from . import fusion
            fusion.materialize_tensor(self, "host_read")
        return self._buf

    @_data.setter
    def _data(self, value):
        self._lazy = None  # rebinding the buffer discards a pending chain
        self._buf = value

    # -- basic properties ---------------------------------------------------
    @property
    def data(self):
        return self

    @property
    def shape(self):
        lz = self._lazy
        if lz is not None:
            return list(lz.shape)
        return list(self._buf.shape)

    @property
    def ndim(self):
        lz = self._lazy
        if lz is not None:
            return len(lz.shape)
        return self._buf.ndim

    @property
    def size(self):
        shape = tuple(self._lazy.shape) if self._lazy is not None \
            else self._buf.shape
        return int(np.prod(shape)) if shape else 1

    @property
    def dtype(self):
        lz = self._lazy
        if lz is not None:
            return np.dtype(lz.dtype)
        return np.dtype(self._buf.dtype)

    @property
    def place(self):
        from .device import _get_place
        return _get_place()

    @property
    def is_leaf(self):
        lz = self._lazy
        if lz is not None and lz.rg:
            return False  # the pending fused chain will attach a node
        return self._node is None

    def numel(self):
        return self.size

    def dim(self):
        return self.ndim

    # -- host interop -------------------------------------------------------
    def numpy(self):
        if _materialize_hook is not None:
            _materialize_hook(self, "numpy")
        if _sync_hook is not None:
            _sync_hook(self, "numpy")
        _flight.record("host", "sync", kind="numpy")
        return np.asarray(self._data)

    def item(self, *args):
        if _materialize_hook is not None:
            _materialize_hook(self, "item")
        if _sync_hook is not None:
            _sync_hook(self, "item")
        _flight.record("host", "sync", kind="item")
        if args:
            return np.asarray(self._data).item(*args)
        return np.asarray(self._data).item()

    def tolist(self):
        if _materialize_hook is not None:
            _materialize_hook(self, "numpy")
        if _sync_hook is not None:
            _sync_hook(self, "tolist")
        _flight.record("host", "sync", kind="tolist")
        return np.asarray(self._data).tolist()

    def __array__(self, dtype=None):
        if _materialize_hook is not None:
            _materialize_hook(self, "numpy")
        if _sync_hook is not None:
            _sync_hook(self, "__array__")
        _flight.record("host", "sync", kind="__array__")
        a = np.asarray(self._data)
        return a.astype(dtype) if dtype is not None else a

    def __float__(self):
        return float(self.item())

    def __int__(self):
        return int(self.item())

    def __bool__(self):
        return bool(self.item())

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self.shape[0]

    def __repr__(self):
        grad_str = "" if self.stop_gradient else ", stop_gradient=False"
        return (f"Tensor(shape={self.shape}, dtype={dtype_mod.dtype_name(self.dtype)}"
                f"{grad_str},\n       {np.asarray(self._data)!r})")

    def __hash__(self):
        return id(self)

    # -- autograd -----------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        _backward([self], None if grad_tensor is None else [grad_tensor],
                  retain_graph=retain_graph)

    def clear_grad(self):
        self.grad = None

    def clear_gradient(self, set_to_zero: bool = False):
        if set_to_zero and self.grad is not None:
            self.grad = Tensor(jnp.zeros_like(self.grad._data))
        else:
            self.grad = None

    def retain_grads(self):
        # a pending fused chain has no per-tensor tape node to retain a
        # grad at; flush so this tensor becomes a grad-graph boundary
        if self._lazy is not None:
            from . import fusion
            fusion.materialize_tensor(self, "retain_grads")
        self._retain_grads = True

    def register_hook(self, hook):
        """ref: tensor_patch_methods.py register_hook; returns removable handle."""
        if self._lazy is not None:
            # hooks observe the gradient flowing INTO this tensor, which
            # requires it to sit on a tape edge — flush the fused chain
            # so subsequent ops consume it as a concrete grad leaf
            from . import fusion
            fusion.materialize_tensor(self, "hook")
        hook_id = self._hook_counter
        self._hook_counter += 1
        self._hooks[hook_id] = hook

        class _Handle:
            def remove(_self):
                self._hooks.pop(hook_id, None)

        return _Handle()

    def detach(self):
        t = Tensor(self._data, stop_gradient=True)
        _register_alias(self._data, t)
        return t

    def detach_(self):
        if self._lazy is not None:
            # flush first: a later chain flush would re-attach the fused
            # node, resurrecting the edge detach_ is meant to sever
            from . import fusion
            fusion.materialize_tensor(self, "detach")
        self._node = None
        self.stop_gradient = True
        return self

    def clone(self):
        return apply_op(lambda x: x + 0, self, op_name="clone")

    # -- mutation (leaf-only, used by optimizers / state loading) -----------
    def set_value(self, value):
        if _mutation_hook is not None:
            _mutation_hook(self)
        if isinstance(value, Tensor):
            value = value._data
        self._data = jnp.asarray(value, self._data.dtype).reshape(
            self._data.shape)
        return self

    def copy_(self, other, blocking=True):
        return self.set_value(other)

    def fill_(self, value):
        if _mutation_hook is not None:
            _mutation_hook(self)
        self._data = jnp.full_like(self._data, value)
        return self

    def zero_(self):
        if _mutation_hook is not None:
            _mutation_hook(self)
        self._data = jnp.zeros_like(self._data)
        return self

    # -- conversion ---------------------------------------------------------
    def astype(self, dtype):
        d = dtype_mod.convert_dtype(dtype)
        return apply_op(lambda x: _cast_impl(x, dtype=d), self,
                        op_name="cast", fuse_attrs=(("dtype", d),))

    def cast(self, dtype):
        return self.astype(dtype)

    def to(self, *args, **kwargs):
        t = self
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, str) and a in dtype_mod._NAME_TO_DTYPE:
                t = t.astype(a)
            elif isinstance(a, np.dtype):
                t = t.astype(a)
        return t

    def cpu(self):
        return Tensor(jax.device_get(self._data), self.stop_gradient)

    def pin_memory(self):
        return self

    def contiguous(self):
        return self

    def is_contiguous(self):
        return True

    # -- indexing -----------------------------------------------------------
    def __getitem__(self, idx):
        if isinstance(idx, Tensor):
            idx = idx._data
        elif isinstance(idx, tuple):
            idx = tuple(i._data if isinstance(i, Tensor) else i for i in idx)
        return apply_op(lambda x: x[idx], self, op_name="getitem")

    def __setitem__(self, idx, value):
        if isinstance(idx, Tensor):
            idx = idx._data
        elif isinstance(idx, tuple):
            idx = tuple(i._data if isinstance(i, Tensor) else i for i in idx)
        if isinstance(value, Tensor):
            value = value._data
        if _mutation_hook is not None:
            _mutation_hook(self)
        self._data = self._data.at[idx].set(value)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # value_and methods like reshape/matmul/etc are attached by paddle_tpu.ops
    # at import time (monkey-patch pattern mirroring the reference's
    # python/paddle/tensor/tensor_method_patch).


class Parameter(Tensor):
    """Trainable leaf tensor. ref: python/paddle/base/framework.py Parameter"""

    def __init__(self, data, stop_gradient: bool = False, name=None):
        super().__init__(data, stop_gradient=stop_gradient, name=name)
        self.trainable = not stop_gradient

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


def to_tensor(data, dtype=None, place=None, stop_gradient: bool = True):
    """paddle.to_tensor. ref: python/paddle/tensor/creation.py to_tensor"""
    from . import memory as _memory
    d = dtype_mod.convert_dtype(dtype)
    if isinstance(data, Tensor):
        arr = data._data
        if d is not None and arr.dtype != d:
            arr = arr.astype(d)
        _memory.track(arr)
        return Tensor(arr, stop_gradient=stop_gradient)
    if isinstance(data, (jax.Array,)) or hasattr(data, "aval"):
        arr = data
        if d is not None and arr.dtype != d:
            arr = arr.astype(d)
        _memory.track(arr)
        return Tensor(arr, stop_gradient=stop_gradient)
    np_arr = np.asarray(data)
    if d is None:
        if np_arr.dtype == np.float64:
            np_arr = np_arr.astype(dtype_mod.get_default_dtype())
        elif np_arr.dtype == np.int64 and isinstance(data, (int, list)):
            pass  # keep int64 like paddle
    else:
        np_arr = np_arr.astype(d)
    arr = jnp.asarray(np_arr)
    _memory.track(arr)
    return Tensor(arr, stop_gradient=stop_gradient)


def unwrap(x):
    """Tensor -> jax value (identity on non-Tensors)."""
    return x._data if isinstance(x, Tensor) else x


def wrap(x, stop_gradient=True):
    return x if isinstance(x, Tensor) else Tensor(x, stop_gradient=stop_gradient)
