"""Dtype registry for paddle_tpu.

Canonical dtype objects are plain ``jnp.dtype``s so they interop freely with
JAX; the string names mirror the reference framework's public dtype surface
(ref: paddle/phi/common/data_type.h via python/paddle/framework/dtype.py).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Public dtype singletons ----------------------------------------------------
bool_ = np.dtype("bool")
uint8 = np.dtype("uint8")
int8 = np.dtype("int8")
int16 = np.dtype("int16")
int32 = np.dtype("int32")
int64 = np.dtype("int64")
float16 = np.dtype("float16")
bfloat16 = np.dtype(jnp.bfloat16)
float32 = np.dtype("float32")
float64 = np.dtype("float64")
complex64 = np.dtype("complex64")
complex128 = np.dtype("complex128")

_NAME_TO_DTYPE = {
    "bool": bool_,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "bfloat16": bfloat16,
    "float32": float32,
    "float64": float64,
    "complex64": complex64,
    "complex128": complex128,
}

_FLOATING = {float16, bfloat16, float32, float64}
_INTEGRAL = {uint8, int8, int16, int32, int64}

_default_dtype = float32


def convert_dtype(dtype):
    """Normalize a user-supplied dtype (str, np.dtype, jnp type) to np.dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        try:
            return _NAME_TO_DTYPE[dtype]
        except KeyError:
            raise TypeError(f"Unsupported dtype name: {dtype!r}")
    return np.dtype(dtype)


def dtype_name(dtype) -> str:
    d = convert_dtype(dtype)
    for name, v in _NAME_TO_DTYPE.items():
        if v == d:
            return name
    return str(d)


def is_floating_point(dtype) -> bool:
    return convert_dtype(dtype) in _FLOATING


def is_integer(dtype) -> bool:
    return convert_dtype(dtype) in _INTEGRAL


def set_default_dtype(d):
    """ref: python/paddle/framework/framework.py set_default_dtype"""
    global _default_dtype
    d = convert_dtype(d)
    if d not in (float16, bfloat16, float32, float64):
        raise TypeError(
            f"set_default_dtype only supports floating dtypes, got {d}")
    _default_dtype = d


def get_default_dtype():
    return _default_dtype
