"""Runtime flag registry.

Mirrors the reference's exported-flag system (ref: paddle/common/flags.h:336-375,
flags_native.cc): flags are declared with a type + default, overridable from the
environment as ``FLAGS_<name>`` and at runtime via set_flags/get_flags
(ref: python/paddle/base/framework.py set_flags).
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Callable, Dict

_BOOL_TRUE = {"1", "true", "yes", "on"}


def _parse_bool(s: str) -> bool:
    return s.strip().lower() in _BOOL_TRUE


@dataclass
class _FlagInfo:
    name: str
    default: Any
    parser: Callable[[str], Any]
    help: str
    value: Any = None


_registry: Dict[str, _FlagInfo] = {}


def _native_lib():
    """The native registry mirror is best-effort and LAZY: only mirror when
    the extension is already loaded, so `import paddle_tpu` never pays the
    g++ build (paddle_tpu._native compiles on ITS first import, triggered
    by the components that need it: store/profiler). _native/__init__
    back-fills flags defined before it loaded."""
    import sys
    mod = sys.modules.get("paddle_tpu._native")
    return getattr(mod, "lib", None)


def define_flag(name: str, default: Any, help: str = "") -> None:
    if isinstance(default, bool):
        parser: Callable[[str], Any] = _parse_bool
    elif isinstance(default, int):
        parser = int
    elif isinstance(default, float):
        parser = float
    else:
        parser = str
    info = _FlagInfo(name, default, parser, help, default)
    env = os.environ.get(f"FLAGS_{name}")
    if env is not None:
        info.value = parser(env)
    _registry[name] = info
    # mirror into the C++ registry (ref: flags_native.cc ExportedFlagInfoMap)
    # so native components observe the same flags
    lib = _native_lib()
    if lib is not None:
        lib.flag_define(name, str(info.value), help)


def get_flags(flags):
    """get_flags('FLAGS_x') or get_flags(['FLAGS_x', ...]) -> dict"""
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for f in flags:
        key = f[len("FLAGS_"):] if f.startswith("FLAGS_") else f
        if key not in _registry:
            raise ValueError(f"Unknown flag {f}")
        out[f] = _registry[key].value
    return out


def set_flags(flags: Dict[str, Any]) -> None:
    for f, v in flags.items():
        key = f[len("FLAGS_"):] if f.startswith("FLAGS_") else f
        if key not in _registry:
            raise ValueError(f"Unknown flag {f}")
        info = _registry[key]
        info.value = info.parser(v) if isinstance(v, str) else v
        lib = _native_lib()
        if lib is not None:
            lib.flag_set(key, str(info.value))


def flag_value(name: str):
    return _registry[name].value


# Core flags (subset of the reference's ~180; ref: paddle/common/flags.cc)
define_flag("check_nan_inf", False, "Scan op outputs for NaN/Inf in eager mode")
define_flag("pallas_autotune", False,
            "Measure Pallas block-size candidates at first use per shape "
            "and cache the winner (ref: kernels/autotune/cache.h)")
define_flag("check_nan_inf_stride", 1,
            "Ops between host fetches of the batched NaN-check flags. "
            "1 (default) = synchronous per-op raise, reference parity; "
            ">1 amortizes the host sync (one fetch per stride ops; "
            "essential over a high-RTT device link)")
define_flag("eager_delete_tensor_gb", 0.0, "GC threshold (no-op on TPU; XLA owns memory)")
define_flag("eager_fusion",
            _parse_bool(os.environ.get("PADDLE_TPU_EAGER_FUSION", "1")),
            "Lazy-eager elementwise fusion: defer fusable op chains and "
            "compile each chain as ONE jitted executable at the flush "
            "point (host read / non-fusable boundary / backward / chain "
            "cap). Kill switch: FLAGS_eager_fusion=0 or "
            "PADDLE_TPU_EAGER_FUSION=0 restores per-op dispatch")
define_flag("eager_fusion_reduce", True,
            "Reduction terminators in lazy-eager fusion: ops marked "
            "`fusable: reduce` (sum/mean/max/min/prod/logsumexp/...) "
            "join the deferred chain as terminator nodes (axis/keepdim "
            "in the cache key) instead of flushing it at dispatch. "
            "Granular kill switch under FLAGS_eager_fusion; 0 restores "
            "the flush-at-reduction boundary (flush reason "
            "reduce_boundary)")
define_flag("eager_fusion_epilogue", True,
            "Matmul/linear epilogue capture in lazy-eager fusion: ops "
            "marked `fusable: epilogue` defer as contraction nodes so a "
            "following bias-add/activation/cast chain compiles as the "
            "dot's XLA epilogue. Granular kill switch under "
            "FLAGS_eager_fusion; 0 keeps contractions on the per-op "
            "path (flush reason matmul_boundary)")
define_flag("eager_fusion_max_chain", 32,
            "Deferred-op count at which a fusion chain force-flushes; "
            "bounds compile time and the retained expression DAG")
define_flag("eager_fusion_cache", 256,
            "LRU capacity of the fusion program cache (entries keyed by "
            "DAG structure + input shapes/dtypes)")
define_flag("fused_optimizer", True,
            "One-executable optimizer step: flatten the whole parameter "
            "tree (params/grads/moments) and run grad unscale + finite "
            "check, global-norm clip and every per-param update as ONE "
            "jitted, buffer-donated executable (params and optimizer "
            "state update in place in HBM instead of allocating a second "
            "model copy). Per-step dynamic scalars (lr, loss scale) ride "
            "as 0-d device-array arguments so a changing LR schedule "
            "never recompiles. Kill switch: FLAGS_fused_optimizer=0 "
            "restores the per-param eager update loop")
define_flag("fused_optimizer_cache", 32,
            "LRU capacity of the fused optimizer-step program cache "
            "(entries keyed by optimizer type + parameter-tree structure "
            "+ dtypes/shapes + hyperparameter-static config)")
define_flag("fusion_flush_origin", False,
            "Attribute every fusion chain flush to its origin call "
            "site: fusion.flush_sites_total{reason, site} counts "
            "flushes per (reason, file:line), the planning input for "
            "whole-step capture (which code locations break capture, "
            "not just why). Off by default — the stack walk costs ~µs "
            "per flush; paddle_tpu.analysis audits record origins "
            "regardless of this flag")
define_flag("metrics", True,
            "Process-wide telemetry registry (paddle_tpu.observability): "
            "counters/gauges/histograms woven through dispatch, fusion, "
            "collectives, checkpointing and serving. Default ON — the "
            "metrics_overhead bench enforces <=5% dispatch overhead. "
            "FLAGS_metrics=0 is the kill switch: every instrument "
            "mutation becomes one cached flag read + return")
define_flag("serving_block_size", 16,
            "Tokens per KV block in the paged serving cache "
            "(serving.PagedLlamaDecodeEngine): the block pool is "
            "[num_blocks, block_size, KVH, D] per layer and the tiled "
            "decode attention walks each slot's block table one block "
            "at a time. Larger blocks = fewer gather steps but coarser "
            "allocation granularity (internal fragmentation up to "
            "block_size-1 tokens per request)")
define_flag("serving_num_blocks", 0,
            "KV blocks in the paged serving pool, shared by all slots. "
            "0 (default) = auto-size to dense capacity parity "
            "(max_slots x ceil(max_seq/block_size)); smaller values "
            "trade worst-case capacity for HBM, relying on admission "
            "control (requests wait for blocks instead of OOMing)")
define_flag("serving_prefill_chunk", 64,
            "Max prompt tokens a single paged prefill executable "
            "processes: the GenerationServer loop interleaves one "
            "chunk with each decode step so a long prompt never "
            "stalls the in-flight decode batch for more than one "
            "chunk's forward pass")
define_flag("serving_spec_tokens", 4,
            "Draft tokens a speculative decode step proposes per "
            "target step (the speculation window). The target model "
            "verifies the whole window in ONE batched paged-attention "
            "call and commits the accepted prefix; greedy output is "
            "bit-equal to the non-speculative stream regardless of "
            "the window size — this only trades draft work against "
            "acceptance length")
define_flag("serving_spec_draft_layers", 0,
            "Decoder layers in the auto-built truncated-layer draft "
            "model (PagedLlamaDecodeEngine.make_draft): the draft "
            "shares the target's embedding/head/first-N-layer weights "
            "at zero extra weight HBM. 0 (default) = half the target's "
            "layers (min 1)")
define_flag("paged_attention_kernel", True,
            "Use the Pallas block-table paged-attention TPU kernel "
            "behind the serving_cache.paged_attention seam when the "
            "backend supports it; 0 forces the pure-jnp tiled walk "
            "(the CPU/tier-1 numerics oracle) everywhere. "
            "decode/verify/prefill all route through the one seam")
define_flag("serving_admission_policy", "static",
            "Admission policy a GenerationServer builds when none is "
            "passed: 'static' keeps the FLAGS_serving_shed_queue rule "
            "(the fallback policy), 'adaptive' installs "
            "serving_supervisor.AdaptiveAdmissionPolicy — "
            "step-boundary EWMAs of blocks_free/backlog/throughput "
            "driving graceful brownout (speculative window, then "
            "prefill chunk) before hard shedding, plus deadline-aware "
            "rejection at submit")
define_flag("serving_supervisor_backoff", 0.05,
            "Base seconds of the ServingSupervisor's bounded "
            "exponential restart backoff: death N of a streak waits "
            "backoff * 2^(N-1), capped; the streak resets after a "
            "healthy stretch")
define_flag("serving_supervisor_stall_seconds", 0.0,
            "Decode-loop stall watchdog: a loop thread that is alive "
            "but has not heartbeat for this many seconds WHILE "
            "holding work is fenced and restarted like a crash (0 = "
            "stall detection off; an idle loop parked on the empty "
            "queue never counts as stalled)")
define_flag("serving_prefix_cache", True,
            "Content-addressed prefix sharing in the paged serving KV "
            "cache: committed prompt blocks enter a host-side radix "
            "tree keyed by their token ids, admission matches new "
            "prompts against it at block granularity, matched blocks "
            "are aliased into the slot's table with refcount bumps and "
            "their prefill is SKIPPED. Released prefixes stay cached "
            "(refcount 0) and are LRU-evicted under pool pressure. "
            "0 = kill switch: the allocator behaves byte-identically "
            "to the private-blocks-only design")
define_flag("serving_prefix_cache_blocks", 0,
            "Upper bound on KV blocks the prefix radix tree may hold "
            "(shared + cached); committing past the bound evicts "
            "refcount-0 LRU entries first and stops caching when "
            "nothing is evictable. 0 (default) = unbounded within the "
            "pool — the free-list/LRU pressure path is the only limit")
define_flag("serving_shed_queue", 0,
            "Load-shedding queue bound for the paged GenerationServer: "
            "when the KV block pool has no available blocks AND more "
            "than this many admitted-order requests are already "
            "deferred waiting for blocks, submit() rejects new work "
            "immediately (rejected reason=shed) instead of deferring "
            "unboundedly. 0 (default) disables shedding — exhaustion "
            "queues forever, the pre-policy behavior")
define_flag("serving_fleet_heartbeat_seconds", 0.5,
            "Fleet router heartbeat period: every replica's /health "
            "RPC is probed this often on a dedicated short-timeout "
            "connection, and the returned gauges (blocks_free, "
            "backlog, admission pressure level) feed KV-pressure-"
            "aware placement")
define_flag("serving_fleet_heartbeat_misses", 3,
            "Consecutive failed heartbeats before the fleet router "
            "declares a replica dead: its epoch is fenced (late "
            "responses discarded), in-flight requests fail over to "
            "healthy replicas seeded with their committed tokens, "
            "and resurrection begins. A data-plane connection error "
            "fences immediately without waiting for misses")
define_flag("serving_fleet_restart_backoff", 0.05,
            "Base seconds of the fleet router's bounded exponential "
            "resurrection backoff: relaunch attempt N of a dead "
            "replica waits backoff * 2^(N-1) (capped, full-jittered "
            "under FLAGS_backoff_full_jitter) before spawning the "
            "replacement process from the shared executable cache + "
            "warm bundle")
define_flag("serving_fleet_max_restarts", 8,
            "Resurrection attempts per dead replica before the fleet "
            "router gives up on it and degrades to the surviving "
            "replicas (the router itself never crashes; a degraded "
            "slot is journaled and counted)")
define_flag("serving_fleet_retry_after", 1.0,
            "Seconds clients are told to wait (the retry_after hint "
            "on the fleet-shed error) when every live replica reports "
            "admission pressure level 3 — fleet-level shed fires only "
            "after per-replica brownout has already been exhausted "
            "everywhere")
define_flag("use_bf16_matmul", True, "Prefer bfloat16 matmul accumulation defaults")
define_flag("log_level", 0, "Framework verbosity")
define_flag("benchmark", False, "Synchronize after each op for timing")
define_flag("retain_grad_for_all_tensor", False, "Keep .grad on non-leaf tensors")
