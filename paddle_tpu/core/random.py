"""RNG state management.

The reference keeps per-device mutable Philox generators
(ref: paddle/phi/core/generator.h:32). The TPU-native design is JAX's
functional PRNG: a root key advanced by a counter for eager ops, and
``fold_in`` subkeys for parallel determinism (the analog of the reference's
RNGStatesTracker, ref: python/paddle/distributed/fleet/meta_parallel/parallel_layers/random.py).
"""
from __future__ import annotations

import threading

import jax


class Generator:
    """Stateful counter over a functional JAX key."""

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self.manual_seed(seed)

    def manual_seed(self, seed: int):
        with getattr(self, "_lock", threading.Lock()):
            self._seed = int(seed)
            # key creation is LAZY: jax.random.key initializes the XLA
            # backend, and the default generator is built at import time
            # — an eager key here would make `import paddle_tpu` claim
            # the backend before jax.distributed.initialize can run
            # (the multi-controller bootstrap would silently fall back)
            self._key = None
            self._counter = 0
        _bump_seed_epoch()
        return self

    def initial_seed(self) -> int:
        return self._seed

    def _root_key(self):
        if self._key is None:
            self._key = jax.random.key(self._seed)
        return self._key

    def next_key(self):
        """A fresh subkey; each call advances the stream."""
        with self._lock:
            self._counter += 1
            return jax.random.fold_in(self._root_key(), self._counter)

    def get_state(self):
        return (self._seed, self._counter)

    def set_state(self, state):
        self._seed, self._counter = state
        self._key = None
        _bump_seed_epoch()


class TracedKeyStream:
    """A key stream whose root key is a traced value — used when a Layer's
    forward runs under jit so dropout masks differ per step instead of being
    constant-folded. Pushed by paddle_tpu.jit's train/eval step wrappers."""

    def __init__(self, key):
        self._key = key
        self._counter = 0

    def next_key(self):
        self._counter += 1
        return jax.random.fold_in(self._key, self._counter)


_stream_stack = []


class key_stream:
    """Context manager installing a TracedKeyStream as the active source for
    eager random ops during tracing."""

    def __init__(self, key):
        self._stream = TracedKeyStream(key)

    def __enter__(self):
        _stream_stack.append(self._stream)
        return self._stream

    def __exit__(self, *exc):
        _stream_stack.pop()
        return False


# bumped on every re-seed or state restore (manual_seed/set_state/
# set_rng_state): holders of a derived device-side stream (the compiled
# train steps cache a root key + counter on device) compare this to
# know the global stream was reset and they must re-derive. Bumped for
# ANY generator, not just the default — a spurious bump only costs one
# extra key fold, a missed one silently breaks reproducibility.
_seed_epoch = 0


def _bump_seed_epoch():
    global _seed_epoch
    _seed_epoch += 1


_default_generator = Generator(0)


def seed(value: int) -> Generator:
    """Global seed for eager random ops. ref: python/paddle/framework/random.py"""
    _default_generator.manual_seed(value)
    return _default_generator


def seed_epoch() -> int:
    return _seed_epoch


def default_generator() -> Generator:
    return _default_generator


# SOT tracer hook: observes RNG draws during recording (a recorded trace
# that consumed randomness must not be replayed with frozen keys).
_key_observer = None


def next_key():
    if _key_observer is not None:
        _key_observer()
    if _stream_stack:
        return _stream_stack[-1].next_key()
    return _default_generator.next_key()


def derive_seed(key, dtype=None):
    """Fold a PRNG key down to one 32-bit scalar for kernels that take a
    raw seed (Pallas PRNG, hash dropout). Single definition so every
    call site picks the same key word and bitcast; works on concrete and
    traced keys alike."""
    import jax.numpy as jnp
    kd = jax.random.key_data(key)
    return jax.lax.bitcast_convert_type(
        kd.reshape(-1)[-1], dtype or jnp.int32)


def get_rng_state():
    return _default_generator.get_state()


def set_rng_state(state):
    _default_generator.set_state(state)


class RNGStatesTracker:
    """Named RNG streams for parallel determinism.

    TP layers need 'global' vs 'local' (per model-parallel rank) dropout
    streams; we derive them by fold_in on a per-name seed
    (ref: fleet/layers/mpu/random.py RNGStatesTracker).
    """

    def __init__(self):
        self._seeds = {}

    def add(self, name: str, seed: int):
        if name in self._seeds:
            raise ValueError(f"RNG state {name} already exists")
        self._seeds[name] = Generator(seed)

    def rng_state(self, name: str) -> Generator:
        if name not in self._seeds:
            raise ValueError(f"Unknown RNG state {name}")
        return self._seeds[name]

    def next_key(self, name: str):
        return self.rng_state(name).next_key()
