"""Eager autograd engine.

The reference implements define-by-run autograd with generated C++ GradNodes
and a queue-based backward (ref: paddle/fluid/eager/grad_node_info.h:197,
paddle/fluid/eager/backward.cc:105 RunBackward). The TPU-native design keeps
the same user semantics (``stop_gradient``, ``.grad`` accumulation,
``loss.backward()``, hooks) but each op's gradient comes from ``jax.vjp`` of
its pure-JAX implementation taken at forward time — no per-op handwritten
grad kernels, and the residuals live in the vjp closure (the analog of the
reference's TensorWrapper saved-tensor scheme, ref: eager/tensor_wrapper.h).

Under ``jax.jit`` tracing (the performance path) this tape is bypassed
entirely: gradients come from ``jax.grad`` over the functionalized program.
"""
from __future__ import annotations

import os
import threading
import time as _time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import memory as _memory
from .flags import _registry as _flag_registry
from ..observability import metrics as _om

__all__ = [
    "no_grad", "enable_grad", "is_grad_enabled", "set_grad_enabled",
    "GradNode", "apply_op", "backward", "grad", "flush_nan_checks",
]


class _GradState(threading.local):
    def __init__(self):
        self.enabled = True


_state = _GradState()


def is_grad_enabled() -> bool:
    return _state.enabled


def set_grad_enabled(mode: bool):
    _state.enabled = bool(mode)


class _GradModeGuard:
    def __init__(self, mode: bool):
        self._mode = mode

    def __enter__(self):
        self._prev = _state.enabled
        _state.enabled = self._mode
        return self

    def __exit__(self, *exc):
        _state.enabled = self._prev
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with type(self)(self._mode):
                return fn(*args, **kwargs)

        return wrapper


def no_grad(func=None):
    """Context manager / decorator disabling tape recording.
    ref: python/paddle/base/dygraph/base.py no_grad_
    """
    guard = _GradModeGuard(False)
    if func is not None:
        return guard(func)
    return guard


def enable_grad(func=None):
    guard = _GradModeGuard(True)
    if func is not None:
        return guard(func)
    return guard


class GradNode:
    """One recorded op: holds the vjp closure and edges to input tensors.
    ref-analog: paddle/fluid/eager/grad_node_info.h GradNodeBase + Edge.
    """

    __slots__ = ("vjp_fn", "inputs", "out_avals", "name", "fn", "datas",
                 "kwargs", "diff_idx", "__weakref__")

    def __init__(self, vjp_fn, inputs, out_avals, name, fn=None, datas=None,
                 kwargs=None, diff_idx=None):
        self.vjp_fn = vjp_fn
        self.inputs = inputs          # tuple of differentiable input Tensors
        self.out_avals = out_avals    # (shape, dtype) aval per output
        self.name = name
        # Retained for create_graph=True: re-running the op's forward under
        # the tape makes the backward step differentiable w.r.t. primals too
        # (the vjp closure alone only captures the linear cotangent part).
        # ref-analog: eager/backward.cc:439 general_grad (grad-of-grad).
        self.fn = fn
        self.datas = datas            # full positional arg list (raw arrays)
        self.kwargs = kwargs
        self.diff_idx = diff_idx

    def __repr__(self):
        return f"GradNode({self.name})"


class _Aval:
    """Minimal (shape, dtype) aval for GradNode outputs — a
    jax.ShapeDtypeStruct here costs ~5µs/op of checked-__setattr__ on
    the eager hot path for two fields the backward ever reads."""
    __slots__ = ("shape", "dtype")

    def __init__(self, shape, dtype):
        self.shape = shape
        self.dtype = dtype


def _zeros_ct(aval):
    if jnp.issubdtype(aval.dtype, jnp.inexact):
        return jnp.zeros(aval.shape, aval.dtype)
    return np.zeros(aval.shape, jax.dtypes.float0)


_diff_dtype_cache: Dict[Any, bool] = {}


def _is_diff_dtype(x) -> bool:
    # dtype-keyed cache: jnp.result_type costs ~10µs/call on the eager
    # hot path; arrays expose .dtype directly and the distinct dtype
    # population is tiny
    dt = getattr(x, "dtype", None)
    if dt is not None:
        hit = _diff_dtype_cache.get(dt)
        if hit is None:
            hit = _diff_dtype_cache[dt] = bool(
                jnp.issubdtype(dt, jnp.inexact))
        return hit
    return jnp.issubdtype(jnp.result_type(x), jnp.inexact)


# Pending device-side NaN flags: (op_name, out_index, 0-d bool jax.Array).
# Computing `any(~isfinite)` is an async device op; only the *fetch* blocks.
# Batching the fetch every FLAGS_check_nan_inf_stride ops turns N host
# round-trips into one (critical over a ~100ms-RTT tunnel) while keeping
# exact (op, output) attribution on failure.
_nan_pending: List[Tuple[str, int, Any]] = []


def flush_nan_checks() -> None:
    """Fetch all pending NaN flags in one host sync; raise naming the first
    offending op. Called on stride overflow and at backward() boundaries."""
    global _nan_pending
    if not _nan_pending:
        return
    pending, _nan_pending = _nan_pending, []
    flags = np.asarray(jnp.stack([f for _, _, f in pending]))  # one fetch
    if flags.any():
        name, i, _ = pending[int(np.argmax(flags))]
        raise FloatingPointError(
            f"Operator {name} output {i} contains NaN or Inf "
            f"(FLAGS_check_nan_inf is set)")


_nan_flag = None     # resolved Flag objects (registry identity is
_stride_flag = None  # stable) — avoids per-op registry lookups

# FLAGS_benchmark: block on each op's outputs so wall time measures the
# device, not dispatch pipelining. Inline .value read per dispatch (the
# _M_flag idiom) — off costs one attribute load.
_bench_flag = _flag_registry["benchmark"]
# FLAGS_retain_grad_for_all_tensor: every differentiable interior
# tensor accumulates .grad during backward, as if retain_grads() had
# been called on it (ref: the reference's global retain switch)
_retain_all_flag = _flag_registry["retain_grad_for_all_tensor"]


def _benchmark_sync(outs) -> None:
    for o in outs:
        if isinstance(o, jax.Array) and not isinstance(o, jax.core.Tracer):
            o.block_until_ready()


def _maybe_check_nan_inf(name: str, outs) -> None:
    """FLAGS_check_nan_inf per-op scan (ref: eager/nan_inf_utils.h:38 —
    CheckTensorHasNanOrInf after each ad_func). Only active in eager mode
    (concrete arrays); tracing skips it, matching the reference's
    dygraph-only check."""
    global _nan_flag, _stride_flag
    if _nan_flag is None:
        from .flags import _registry
        _nan_flag = _registry["check_nan_inf"]
        _stride_flag = _registry["check_nan_inf_stride"]
    if not _nan_flag.value:
        return
    stride = max(int(_stride_flag.value or 1), 1)
    for i, o in enumerate(outs):
        if isinstance(o, jax.core.Tracer):
            return  # inside jit trace, skip (dygraph-only check)
        if isinstance(o, jax.Array) and jnp.issubdtype(o.dtype, jnp.inexact):
            flag = jnp.any(~jnp.isfinite(o))  # device op, no host sync
            if stride <= 1:
                if bool(flag):
                    raise FloatingPointError(
                        f"Operator {name} output {i} contains NaN or Inf "
                        f"(FLAGS_check_nan_inf is set)")
            else:
                _nan_pending.append((name, i, flag))
    if len(_nan_pending) >= stride:
        flush_nan_checks()


# Per-op dispatch gate backed by the native OpRegistry (the KernelFactory
# analog — ref: phi/core/kernel_factory.cc:267 SelectKernelOrThrowError):
# first dispatch of each op name looks up its descriptor (arity bounds,
# has_vjp) and validates the call; later dispatches are one dict hit.
# has_vjp=False ops (samplers) skip the tape entirely — their outputs are
# not differentiable by contract.
# name -> [has_vjp: bool, dispatch_count: int] (mutated in place)
_op_gate_cache: Dict[str, list] = {}

# -- telemetry (paddle_tpu.observability) ------------------------------------
# dispatch.ops_total is the one REAL hot-path instrument (a counter inc
# per dispatch, kill-switched by FLAGS_metrics — the metrics_overhead
# bench measures exactly this). Per-op attribution rides free: the
# collector below reads the dispatch counts _op_gate already keeps, so
# ops_dispatched_total{op=...} costs the hot loop nothing.
_M_ops = _om.counter(
    "dispatch.ops_total", "Eager op dispatches through apply_op")
_M_flag = _om.flag_info()  # FLAGS_metrics, cached for the inline check
_M_pair_builds = _om.counter(
    "dispatch.jit_pair_builds_total",
    "Jitted (fwd, vjp) pair cache entries built for eager fast dispatch")
_M_pair_hits = _om.counter(
    "dispatch.jit_pair_hits_total",
    "Dispatches served by a cached jitted pair")
_M_pair_misses = _om.counter(
    "dispatch.jit_pair_misses_total",
    "Dispatches that found no cached pair (first sighting or build)")
_M_compile_s = _om.histogram(
    "dispatch.jit_compile_seconds",
    "First-call (trace+compile) seconds of a freshly built jit pair")
_M_nojit = _om.counter(
    "dispatch.nojit_demotions_total",
    "(fn, config) entries pinned to the plain eager path")


def _collect_dispatch():
    return {"dispatch.ops_dispatched_total":
            {name: cell[1] for name, cell in _op_gate_cache.items()}}


_om.register_collector("dispatch", _collect_dispatch)


def _op_gate(name: str, n_args: int) -> bool:
    """Returns has_vjp for the op; validates arity on first dispatch and
    counts dispatches (introspection via op_registry.dispatch_counts)."""
    if _M_flag.value:
        # inline unlabeled-counter bump (see Counter._v): the measured
        # per-dispatch telemetry cost, enforced ≤5% by bench.py's
        # metrics_overhead line
        _M_ops._v += 1
    hit = _op_gate_cache.get(name)
    if hit is not None:
        hit[1] += 1
        return hit[0]
    has_vjp = True
    try:
        from ..ops.op_registry import get_op_info
        info = get_op_info(name)
    except Exception:
        info = None
    if info:
        has_vjp = bool(info.get("has_vjp", True))
        # the descriptor's nargs caps the POSITIONAL surface; attrs may
        # also ride the kernel closure, so there is no lower bound here,
        # and variadic ops (one positional per tensor) have no cap
        hi = max(int(info.get("nargs", 1)), int(info.get("nin", 0)))
        if n_args > hi and not info.get("variadic", False):
            raise TypeError(
                f"op {name!r} dispatched with {n_args} positional args "
                f"but its registry descriptor allows at most {hi} "
                f"(ops.yaml contract)")
    _op_gate_cache[name] = [has_vjp, 1]
    return has_vjp


# -- eager dispatch fast path -------------------------------------------------
# The reference engineers its eager hot loop to sub-10µs/op (generated
# ad_funcs + cached kernel selection, ref: test/cpp/eager/performance_tests/
# benchmark_eager_cuda.cc, SURVEY §3.1). Here the dominant cost is
# jax.vjp's per-call retrace (~1.4 ms/op measured on v5e): this cache keys
# (fn identity, static args, kwargs) to a jitted forward and a jitted vjp
# program, so the steady-state recorded op is two C++-jit-cache dispatches.
# Engaged only for concrete (non-tracer) eager calls; anything unusual
# (unhashable statics, tracers, exotic cotangents) falls back to plain
# jax.vjp with identical semantics.

import weakref as _weakref

_pair_cache_weak: "_weakref.WeakKeyDictionary" = _weakref.WeakKeyDictionary()
_pair_cache_strong: Dict[Any, dict] = {}
_FAST_DISPATCH = os.environ.get(
    "PADDLE_TPU_DISABLE_FAST_DISPATCH", "0") != "1"


def _fn_pair_cache(fn):
    # id-keyed first: jnp ufunc objects define __hash__/__eq__ that cost
    # ~3µs per lookup on the hot path; ufuncs are module-level
    # singletons so identity is the right key (the entry holds fn,
    # keeping the id stable)
    hit = _pair_cache_strong.get(id(fn))
    if hit is not None:
        return hit[1]
    try:
        d = _pair_cache_weak.get(fn)
        if d is None:
            d = {}
            _pair_cache_weak[fn] = d
        elif "_seen" in d:
            # second+ dispatch of the same fn OBJECT: long-lived (a
            # module fn or ufunc) — promote to the id-keyed cache so
            # later dispatches skip fn.__hash__/__eq__ (jnp ufuncs
            # spend ~3µs there per lookup). Bounded by the 1024-clear.
            if len(_pair_cache_strong) > 1024:
                _pair_cache_strong.clear()
            _pair_cache_strong[id(fn)] = (fn, d)
        return d
    except TypeError:  # fn doesn't support weakrefs (e.g. jnp ufunc objs)
        if len(_pair_cache_strong) > 1024:
            _pair_cache_strong.clear()
        d = {}
        _pair_cache_strong[id(fn)] = (fn, d)
        return d


def _freeze(v):
    """Hashable cache-key form of a static value; TypeError if impossible."""
    if isinstance(v, (list, tuple)):
        return (type(v).__name__,) + tuple(_freeze(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _freeze(x)) for k, x in v.items()))
    if isinstance(v, (jax.Array, np.ndarray)):
        raise TypeError("array is not a static value")
    hash(v)
    return v


def _build_pair(fn, kwargs, datas, dyn_idx, diff_idx):
    """(jitted fwd, jitted vjp, meta) for this op configuration. Static
    (non-array) positional args are baked in; dynamic args are passed, so
    jit's own aval-keyed cache handles shape/dtype polymorphism."""
    template = [None if i in dyn_idx else datas[i]
                for i in range(len(datas))]
    dyn_idx_t = tuple(dyn_idx)
    meta = {"multi": False}

    def _call(dyn_args, overrides=()):
        call = list(template)
        for p, i in zip(dyn_args, dyn_idx_t):
            call[i] = p
        for i, p in overrides:
            call[i] = p
        return fn(*call, **kwargs)

    @jax.jit
    def jfwd(*dyn_args):
        res = _call(dyn_args)
        multi = isinstance(res, (tuple, list))
        meta["multi"] = multi  # set at trace time, read after first call
        return tuple(res) if multi else (res,)

    @jax.jit
    def jbwd(dyn_args, cts):
        prims = [datas_i for i, datas_i in zip(dyn_idx_t, dyn_args)
                 if i in diff_idx]

        def g(*ps):
            res = _call(dyn_args, overrides=tuple(zip(diff_idx, ps)))
            return tuple(res) if isinstance(res, (tuple, list)) else (res,)

        return jax.vjp(g, *prims)[1](cts)

    return jfwd, jbwd, meta


_NOJIT = "nojit"  # sentinel: this (fn, config) must not run under jit


def _fast_pair(fn, kwargs, datas, diff_idx):
    """Cache lookup/build; None when this call can't take the fast path.

    Build policy: a pair is only built for an fn OBJECT seen on a second
    dispatch — per-call fresh closures (whose jit compile would cost
    hundreds of ms every call) die with their first sighting marker and
    never compile; module-level fns and ufuncs pay one deferred build.
    """
    if not _FAST_DISPATCH:
        return None
    dyn_idx, static_key = [], []
    try:
        for i, d in enumerate(datas):
            if isinstance(d, jax.core.Tracer):
                return None  # under an outer trace: plain path
            if isinstance(d, (jax.Array, np.ndarray)):
                dyn_idx.append(i)
            elif isinstance(d, (float, np.floating)):
                # python floats are numeric operands (scales, epsilons),
                # not structure: pass them as (weak-typed) jit arguments
                # so a host-varying scalar — `x * lr` in a loop — hits
                # the same compiled pair instead of compiling per value.
                # A fn that branches on the value fails the trace once
                # and is marked nojit (plain path) below.
                dyn_idx.append(i)
            else:
                static_key.append((i, _freeze(d)))
        key = (tuple(diff_idx), tuple(static_key),
               () if not kwargs else _freeze(kwargs))
    except TypeError:
        if _dispatch_observer is not None:
            _dispatch_observer("unhashable_static", fn)
        return None
    cache = _fn_pair_cache(fn)
    pair = cache.get(key)
    if pair is _NOJIT:
        return None
    if pair is not None:
        if _M_flag.value:
            _M_pair_hits._v += 1  # inline fast cell (see _M_ops)
        return pair, tuple(dyn_idx), cache, key
    if _M_flag.value:
        _M_pair_misses._v += 1
    if pair is None:
        if "_seen" not in cache:
            cache["_seen"] = True
            return None
        if len(cache) > 32:
            # static args that keep changing value (novel key per call)
            # would compile a fresh pair every time — stop building; the
            # existing entries keep serving their own keys
            return None
        pair = _build_pair(fn, kwargs, datas, set(dyn_idx), tuple(diff_idx))
        cache[key] = pair
        _M_pair_builds.inc()
        if _dispatch_observer is not None:
            _dispatch_observer("pair_build", fn)
    return pair, tuple(dyn_idx), cache, key


def _mark_nojit(cache, key, exc=None):
    """Pin (fn, config) to the plain eager path — but only for errors
    that prove the fn can't trace (host-side numpy, value-dependent
    control flow). A transient runtime failure (e.g. RESOURCE_EXHAUSTED
    during the one-time compile under memory pressure) must NOT
    permanently demote the op to the ~1.5ms eager path: evict the cache
    entry so the next dispatch retries the jit, bounded to a few
    attempts so a persistently failing config still settles to eager."""
    msg = "" if exc is None else str(exc)
    transient = ("RESOURCE_EXHAUSTED" in msg or "OUT_OF_MEMORY" in msg
                 or "out of memory" in msg)
    # retry counters live in ONE nested dict so bookkeeping can never
    # crowd the len(cache) gate that caps new pair builds in _fast_pair
    rc = cache.get("_retry_counts")
    if not transient:
        if rc:
            rc.pop(key, None)  # settled: drop the bookkeeping slot
        cache[key] = _NOJIT
        _M_nojit.inc()
        return
    if rc is None:
        rc = cache.setdefault("_retry_counts", {})
    retries = rc.get(key, 0)
    if retries >= 3:
        rc.pop(key, None)
        cache[key] = _NOJIT
        _M_nojit.inc()
        return
    rc[key] = retries + 1
    pair = cache.get(key)
    if isinstance(pair, tuple) and pair[2].get("ever_ok"):
        # the pair has executed successfully at least once — the
        # compile is fine, only this execution hit resource pressure.
        # Keep the compiled executable across the WHOLE retry budget
        # (re-tracing under the same pressure would cost hundreds of
        # ms for nothing); a later success re-confirms it (clearing
        # the counter via state), consecutive failures settle above.
        pair[2]["state"] = 0
        return
    cache.pop(key, None)  # failed during initial compile: rebuild


# When paddle_tpu.static is recording (enable_static / program_guard), this
# holds a callable(fn, args, kwargs, outs, name) appending to the Program
# tape; None in the (default) eager mode — one global check per op.
_op_recorder = None

# SOT hook: notified when a backward walk starts (a recorded trace that
# ran autograd internally cannot be replayed as pure forward segments).
_backward_observer = None

# Analysis-auditor hook (paddle_tpu.analysis.auditor): notified of
# dispatch-cache events that signal recompile risk — ("pair_build", fn)
# when a jitted pair compiles, ("unhashable_static", fn) when a call's
# static args can't enter the cache key (the call runs un-jitted every
# time). None outside an audit: one global read on the miss paths only.
_dispatch_observer = None


# resolved on first dispatch (tensor.py/amp import us — a module-level
# import would be circular; a per-call import costs ~1.5µs of the
# measured dispatch budget)
_Tensor = None
_amp_state = None
_maybe_cast_inputs = None
_fusion = None


def apply_op(fn: Callable, *args, op_name: Optional[str] = None,
             fuse_attrs: Optional[tuple] = None, **kwargs):
    """Run ``fn`` (a pure JAX function) on mixed Tensor/raw args, recording a
    GradNode when grad is enabled and any Tensor input requires grad.

    ``fuse_attrs`` marks a parametric fusable dispatch (reduction
    terminator / contraction epilogue): a hashable (key, value) tuple
    the caller guarantees re-expresses everything ``fn`` bakes in beyond
    its array args, so core/fusion.py can defer the op through its
    registered parametric impl (see fusion._PIMPLS) with the attrs
    folded into the program cache key. None (the default) means plain
    dispatch — elementwise fusion still gates on fn identity.

    Returns Tensor or tuple-of-Tensor mirroring fn's output structure.
    This is the analog of a generated ``*_ad_func`` forward
    (ref: fluid/eager/api/manual/eager_manual/forwards/multiply_fwd_func.cc:68).
    """
    global _Tensor, _amp_state, _maybe_cast_inputs, _fusion
    if _Tensor is None:
        from .tensor import Tensor as _T
        from ..amp.auto_cast import _state as _s, maybe_cast_inputs as _m
        from . import fusion as _f
        _Tensor, _amp_state, _maybe_cast_inputs, _fusion = _T, _s, _m, _f
    Tensor = _Tensor

    name = op_name or getattr(fn, "__name__", "op")

    # lazy-eager fusion: fusable ops — elementwise chains, reduction
    # terminators, matmul/linear epilogue hosts — defer into an
    # expression DAG and compile per-chain instead of per-op
    # (core/fusion.py). The _op_gate still runs so arity validation +
    # dispatch_counts see every dispatch; recorders (SOT/static), AMP,
    # and tracers take the plain path untouched.
    if (_op_recorder is None and not _amp_state.enabled
            and not _bench_flag.value and _fusion.enabled()):
        # FLAGS_benchmark disables deferral: "sync after each op" is
        # only meaningful when each op actually dispatches
        fused_out = _fusion.try_fuse(name, fn, args, kwargs, fuse_attrs)
        if fused_out is not None:
            _op_gate(name, len(args))
            return fused_out

    datas = []
    reason = None
    for a in args:
        if isinstance(a, Tensor):
            if a._lazy is not None:
                # a pending chain meets a non-fusable consumer: flush at
                # the op boundary (gather/reshape/...). The reason label
                # distinguishes reductions/contractions that WOULD have
                # deferred with FLAGS_eager_fusion_reduce/_epilogue on
                # (reduce_boundary / matmul_boundary) from plain
                # op_boundary flushes — the bisection taxonomy.
                if reason is None:
                    reason = _fusion.boundary_reason(name)
                _fusion.materialize_tensor(a, reason)
            datas.append(a._buf)
        else:
            datas.append(a)

    # AMP hook (the analog of the generated ad_func AMP block,
    # ref: multiply_fwd_func.cc:49-70)
    record_fn = fn
    if _amp_state.enabled:
        datas = _maybe_cast_inputs(name, datas)
        # recorders (SOT/static tape) must capture the cast too, so a
        # replayed program reproduces the same AMP numerics
        def record_fn(*a, _fn=fn, _name=name, **kw):
            return _fn(*_maybe_cast_inputs(_name, list(a)), **kw)

    has_vjp = _op_gate(name, len(args))
    # _buf, not the _data property: the unwrap loop above already
    # materialized every Tensor arg, so the lazy-flush branch is dead
    # weight on this measured hot path
    diff_idx = [
        i for i, a in enumerate(args)
        if isinstance(a, Tensor) and not a.stop_gradient
        and _is_diff_dtype(a._buf)
    ]
    record = _state.enabled and bool(diff_idx) and has_vjp

    if not record:
        outs = multi = None
        fast = _fast_pair(fn, kwargs, datas, ())
        if fast is not None:
            (jfwd, _, meta), dyn_idx, cache, ckey = fast
            # an unconfirmed pair's first call pays trace+compile: time
            # it into the registry (steady-state calls skip the clock)
            fresh = meta.get("state") != 1
            if fresh:
                t0 = _time.perf_counter()
            try:
                outs = jfwd(*(datas[i] for i in dyn_idx))
                multi = meta["multi"]
                if fresh:
                    # first success (or first after a transient retry):
                    # confirm the pair and clear the failure counter
                    meta["state"] = 1
                    meta["ever_ok"] = True
                    _M_compile_s.observe(_time.perf_counter() - t0)
                    rc = cache.get("_retry_counts")
                    if rc:
                        rc.pop(ckey, None)
            except FloatingPointError:
                raise
            except Exception as e:
                # fn isn't jittable here (host-side numpy, value-dependent
                # control flow): run it eagerly from now on — unless the
                # failure was transient (resource), which retries
                _mark_nojit(cache, ckey, e)
                outs = None
        if outs is None:
            out = fn(*datas, **kwargs)
            multi = isinstance(out, (tuple, list))
            outs = tuple(out) if multi else (out,)
        _maybe_check_nan_inf(name, outs)
        if _bench_flag.value:
            _benchmark_sync(outs)
        for o in outs:
            _memory.track(o)
        wrapped = tuple(Tensor(o, stop_gradient=True) for o in outs)
        if _op_recorder is not None:
            _op_recorder(record_fn, args, kwargs, wrapped, name)
        return wrapped if multi else wrapped[0]

    outs = None
    fast = _fast_pair(fn, kwargs, datas, diff_idx)
    if fast is not None:
        (jfwd, jbwd, meta), dyn_idx, cache, ckey = fast
        dyn_args = tuple(datas[i] for i in dyn_idx)
        fresh = meta.get("state") != 1
        if fresh:
            t0 = _time.perf_counter()
        try:
            outs = jfwd(*dyn_args)
            multi = meta["multi"]
            if fresh:
                meta["state"] = 1
                meta["ever_ok"] = True
                _M_compile_s.observe(_time.perf_counter() - t0)
                rc = cache.get("_retry_counts")
                if rc:
                    rc.pop(ckey, None)
        except FloatingPointError:
            raise
        except Exception as e:
            _mark_nojit(cache, ckey, e)
            outs = None
        else:
            def vjp_fn(cts, _dyn=dyn_args, _jb=jbwd):
                try:
                    return _jb(_dyn, cts)
                except FloatingPointError:
                    raise
                except Exception:
                    # exotic cotangent (float0/sparse) the jitted vjp
                    # can't take as an argument: one plain retrace
                    def f2(*primals):
                        call = list(datas)
                        for i, p in zip(diff_idx, primals):
                            call[i] = p
                        res = fn(*call, **kwargs)
                        return (tuple(res)
                                if isinstance(res, (tuple, list))
                                else (res,))
                    return jax.vjp(
                        f2, *[datas[i] for i in diff_idx])[1](cts)
    if outs is None:
        struct = {"multi": False}

        def f(*primals):
            call = list(datas)
            for i, p in zip(diff_idx, primals):
                call[i] = p
            res = fn(*call, **kwargs)
            struct["multi"] = isinstance(res, (tuple, list))
            return tuple(res) if struct["multi"] else (res,)

        primals = [datas[i] for i in diff_idx]
        outs, vjp_fn = jax.vjp(f, *primals)
        multi = struct["multi"]
    _maybe_check_nan_inf(name, outs)
    if _bench_flag.value:
        _benchmark_sync(outs)
    for o in outs:
        _memory.track(o)

    out_avals = tuple(_Aval(o.shape, o.dtype) for o in outs)
    node = GradNode(vjp_fn, tuple(args[i] for i in diff_idx), out_avals, name,
                    fn=fn, datas=datas, kwargs=kwargs, diff_idx=diff_idx)

    wrapped = tuple(
        Tensor(o, stop_gradient=False, node=node, out_index=k)
        for k, o in enumerate(outs))
    if _op_recorder is not None:
        _op_recorder(record_fn, args, kwargs, wrapped, name)
    if not multi:
        return wrapped[0]
    return wrapped


def _ensure_jnp(g, aval):
    if g is None:
        return _zeros_ct(aval)
    from .tensor import Tensor
    if isinstance(g, Tensor):
        g = g._data
    if not isinstance(g, (jax.Array, np.ndarray, int, float)):
        return g  # structured cotangent (e.g. sparse BCOO): pass through
    return jnp.asarray(g, aval.dtype) if jnp.issubdtype(
        aval.dtype, jnp.inexact) else g


def _topo_order(root_node: GradNode) -> List[GradNode]:
    """Reverse postorder over the node DAG: every consumer precedes its
    producers, so cotangents are fully accumulated before a node runs."""
    order: List[GradNode] = []
    visited = set()
    stack: List[Tuple[GradNode, int]] = [(root_node, 0)]
    # iterative DFS with explicit postorder
    while stack:
        node, phase = stack.pop()
        if phase == 0:
            if id(node) in visited:
                continue
            visited.add(id(node))
            stack.append((node, 1))
            for t in node.inputs:
                child = t._node
                if child is not None and id(child) not in visited:
                    stack.append((child, 0))
        else:
            order.append(node)
    order.reverse()
    return order


def _node_backward_taped(node: GradNode, ct_tensors):
    """Run one node's backward step *through the tape* so the produced grads
    are themselves differentiable (w.r.t. both the node's primal inputs and
    the incoming cotangents). Used by create_graph=True.
    ref-analog: eager/backward.cc:439 general_grad."""
    if node.datas is None:
        raise RuntimeError(
            f"create_graph backward through {node.name}: the node's "
            f"forward inputs were already freed by a previous "
            f"backward(); pass retain_graph=True to the first backward "
            f"if you need grad-of-grad afterwards")
    nprim = len(node.diff_idx)

    def node_grad_fn(*flat):
        primals, cts = flat[:nprim], flat[nprim:]

        def f(*ps):
            call = list(node.datas)
            for i, p in zip(node.diff_idx, ps):
                call[i] = p
            res = node.fn(*call, **node.kwargs)
            return tuple(res) if isinstance(res, (tuple, list)) else (res,)

        _, vjp = jax.vjp(f, *primals)
        return tuple(vjp(tuple(cts)))

    out = apply_op(node_grad_fn, *node.inputs, *ct_tensors,
                   op_name=node.name + "_grad")
    return out if isinstance(out, tuple) else (out,)


def _run_backward(roots, root_grads, accumulate_into_grad: bool,
                  wanted: Optional[Sequence] = None,
                  create_graph: bool = False,
                  retain_graph: bool = False):
    """Core backward walk shared by Tensor.backward() and paddle.grad().

    ref-analog: eager/backward.cc RunBackward — queue-based topological walk
    routing grads along edges into GradTensorHolder accumulators.

    With ``create_graph=True`` cotangents travel as Tensors and every
    backward step is recorded via apply_op, so returned grads compose for
    grad-of-grad.
    """
    from .tensor import Tensor
    if _backward_observer is not None:
        _backward_observer()

    def _add(a, b):
        if create_graph and (isinstance(a, Tensor) or isinstance(b, Tensor)):
            return apply_op(lambda x, y: x + y, _as_t(a), _as_t(b),
                            op_name="grad_add")
        return a + b

    def _as_t(g):
        return g if isinstance(g, Tensor) else Tensor(g, stop_gradient=True)

    node_cts: Dict[int, List[Any]] = {}
    node_by_id: Dict[int, GradNode] = {}
    results: Dict[int, Any] = {}
    wanted_ids = {id(t) for t in wanted} if wanted is not None else None

    def seed(node, idx, g):
        node_by_id[id(node)] = node
        cts = node_cts.setdefault(id(node), [None] * len(node.out_avals))
        cts[idx] = g if cts[idx] is None else _add(cts[idx], g)

    order: List[GradNode] = []
    seen = set()
    for t, g in zip(roots, root_grads):
        if t._node is None:
            # a leaf root: its grad is just the seed
            _accumulate_leaf(t, g, accumulate_into_grad, results, wanted_ids)
            continue
        seed(t._node, t._out_index, g)
        # a retained non-leaf ROOT gets its seed as .grad (ref parity:
        # loss.grad == ones after backward under retain_grads / the
        # retain-all flag) — the interior loop below can't see roots
        if t._retain_grads or _retain_all_flag.value \
                or (wanted_ids and id(t) in wanted_ids):
            _accumulate_leaf(t, g, accumulate_into_grad, results,
                             wanted_ids, force=True, add=_add)
        for n in _topo_order(t._node):
            if id(n) not in seen:
                seen.add(id(n))
                order.append(n)

    # In multi-root cases, a merged order must still satisfy consumer-before-
    # producer; re-sort by a global DFS from a virtual root.
    if len([t for t in roots if t._node is not None]) > 1:
        virt = GradNode(None, tuple(t for t in roots if t._node is not None),
                        (), "virtual_root")
        order = [n for n in _topo_order(virt) if n is not virt]

    for node in order:
        cts = node_cts.get(id(node))
        if cts is None:
            continue  # unreachable from seeds
        if create_graph:
            full = tuple(
                _as_t(_zeros_ct(a)) if c is None else _as_t(c)
                for c, a in zip(cts, node.out_avals))
            in_grads = _node_backward_taped(node, full)
        else:
            full = tuple(
                _ensure_jnp(c, a) for c, a in zip(cts, node.out_avals))
            in_grads = node.vjp_fn(full)
            if not retain_graph:
                # release the retained forward inputs (kept for potential
                # create_graph re-differentiation) once the node is
                # consumed — the eager-training memory profile then
                # matches the plain vjp-residual tape
                node.fn = node.datas = node.kwargs = None
        for t, g in zip(node.inputs, in_grads):
            if not create_graph:
                if isinstance(g, np.ndarray) and g.dtype == jax.dtypes.float0:
                    continue
                g = _apply_hooks(t, g)
            elif t._hooks:
                # hooks receive the live taped Tensor so a hook built from
                # paddle ops stays differentiable for grad-of-grad
                for hook in list(t._hooks.values()):
                    r = hook(g)
                    if r is not None:
                        g = r if isinstance(r, Tensor) else _as_t(r)
            if t._node is not None:
                seed(t._node, t._out_index, g)
                if t._retain_grads or _retain_all_flag.value \
                        or (wanted_ids and id(t) in wanted_ids):
                    _accumulate_leaf(t, g, accumulate_into_grad, results,
                                     wanted_ids, force=True, add=_add)
            else:
                _accumulate_leaf(t, g, accumulate_into_grad, results,
                                 wanted_ids, add=_add)
        # free residuals as we go unless the caller wants to re-run
        node_cts.pop(id(node), None)
    return results


def _apply_hooks(t, g):
    from .tensor import Tensor
    if t._hooks:
        tg = Tensor(g, stop_gradient=True)
        for hook in list(t._hooks.values()):
            r = hook(tg)
            if r is not None:
                tg = r if isinstance(r, Tensor) else Tensor(r, stop_gradient=True)
        g = tg._data
    return g


def _accumulate_leaf(t, g, accumulate_into_grad, results, wanted_ids,
                     force=False, add=None):
    from .tensor import Tensor
    is_wanted = wanted_ids is not None and id(t) in wanted_ids
    if wanted_ids is not None and not is_wanted and not force:
        return
    if is_wanted or force:
        prev = results.get(id(t))
        if prev is None:
            results[id(t)] = g
        else:
            results[id(t)] = add(prev, g) if add is not None else prev + g
    if accumulate_into_grad and not t.stop_gradient:
        # ref-analog: GradNodeAccumulation writing param.grad
        gd = g._data if isinstance(g, Tensor) else g
        if t.grad is None:
            t.grad = Tensor(gd, stop_gradient=True)
        else:
            t.grad = Tensor(t.grad._data + gd, stop_gradient=True)


def backward(tensors, grad_tensors=None, retain_graph=False):
    """paddle.autograd.backward. ref: python/paddle/autograd/autograd.py"""
    from .tensor import Tensor
    flush_nan_checks()  # drain forward-pass flags before walking the tape
    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if _fusion is not None:
        for t in tensors:
            if t._lazy is not None:  # flush pending chains: the walk
                _fusion.materialize_tensor(t, "backward")  # needs nodes
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]
    seeds = []
    for t, g in zip(tensors, grad_tensors):
        if g is None:
            if t.size != 1:
                raise RuntimeError(
                    "grad must be provided for non-scalar backward root")
            g = jnp.ones(t.shape, t.dtype)
        else:
            g = g._data if isinstance(g, Tensor) else jnp.asarray(g)
        seeds.append(g)
    _run_backward(tensors, seeds, accumulate_into_grad=True,
                  retain_graph=retain_graph)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """Functional gradient API. ref: python/paddle/base/dygraph/base.py grad

    With ``create_graph=True`` the backward pass is itself recorded on the
    tape (each grad step re-runs the op's forward under jax.vjp via
    apply_op), so the returned grads compose for grad-of-grad.
    ref: paddle/fluid/eager/backward.cc:439 general_grad.
    """
    from .tensor import Tensor
    if isinstance(outputs, Tensor):
        outputs = [outputs]
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    if _fusion is not None:
        for t in list(outputs) + list(inputs):
            if t._lazy is not None:
                _fusion.materialize_tensor(t, "backward")
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    elif isinstance(grad_outputs, Tensor):
        grad_outputs = [grad_outputs]
    seeds = []
    for t, g in zip(outputs, grad_outputs):
        if g is None:
            g = jnp.ones(t.shape, t.dtype)
        elif isinstance(g, Tensor):
            g = g if create_graph else g._data
        else:
            g = jnp.asarray(g)
        seeds.append(g)
    results = _run_backward(outputs, seeds, accumulate_into_grad=False,
                            wanted=inputs, create_graph=create_graph,
                            retain_graph=bool(retain_graph) or create_graph)
    out = []
    for t in inputs:
        g = results.get(id(t))
        if g is None:
            if not allow_unused:
                raise RuntimeError(
                    "One of the differentiated tensors appears unused; "
                    "pass allow_unused=True to return None for it")
            out.append(None)
        elif isinstance(g, Tensor):
            out.append(g)
        else:
            out.append(Tensor(g, stop_gradient=create_graph is False))
    return out
