"""Lazy-eager fusion runtime: elementwise chains, reduction terminators,
matmul epilogues.

The eager hot path dispatches one jitted pair per op (core/autograd
apply_op), so an N-op elementwise chain costs N host dispatches and N
HBM round-trips — the locality problem operator-fusion compilers
(Neptune, FlashFuser; the reference's CINN pass) attack at the graph
level. Here the same win is taken WITHOUT leaving eager semantics:

* Ops flagged ``fusable: true`` in ``ops/ops.yaml`` do not execute at
  dispatch. ``apply_op`` routes them here; each builds a ``LazyExpr``
  node over its inputs and returns a real ``Tensor`` whose ``_data``
  materializes on demand (the handle is indistinguishable to user code).
* Ops flagged ``fusable: reduce`` (sum/mean/max/min/prod/logsumexp/...)
  are NOT flush boundaries either: they join the DAG as reduction
  terminator nodes, with their attrs (axis/keepdim/dtype) folded into
  the structural cache key — ``mean((x*y+z)**2)`` compiles and runs as
  ONE executable with no intermediate materialization. Fusable consumers
  may keep chaining past a terminator (softmax-style
  ``exp(x - max(x)) / sum(exp(x - max(x)))`` fuses whole).
* Ops flagged ``fusable: epilogue`` (matmul/linear) defer the same way
  as contraction nodes, so a following bias-add + activation (+ cast)
  chain compiles INTO the dot's program and executes as an XLA epilogue
  of the contraction instead of a second full-tensor pass. A held
  requires-grad matmul handle stays a real tape edge (the chain cuts
  there, exactly like any live fused intermediate), so the epilogue only
  captures contractions with no other live grad consumers.
* The expression DAG flushes at materialization points — a host read
  (``.numpy()``/``item``/``__array__``), a non-fusable op consuming the
  tensor (gather/reshape/...), ``backward()``, an in-place mutation,
  a gradient hook, or the chain-length cap — by compiling the WHOLE
  reachable chain as ONE jitted executable.
* Compiled programs live in an LRU cache keyed by (DAG structure + node
  attrs, input shapes/dtypes/weak-types, diff pattern, live outputs), so
  steady-state loops hit the cache and dispatch once per chain.
* Gradients: the flush records ONE GradNode against the fused program's
  VJP (``jax.vjp`` of the generated pure function), with per-edge
  ``stop_gradient`` inserts reproducing exactly the dispatch-time
  stop_gradient/no_grad semantics the per-op tape would have had.

Kill switch: ``FLAGS_eager_fusion=0`` (or env ``PADDLE_TPU_EAGER_FUSION=0``)
restores the exact pre-fusion dispatch path; ``FLAGS_eager_fusion_reduce``
and ``FLAGS_eager_fusion_epilogue`` turn off just the reduction-terminator
or matmul-epilogue capture for bisection. Observability: ``fusion.stats()``
— chains built, cache hits/misses, flush reasons (incl. the granular
``reduce_boundary``/``matmul_boundary`` labels the kill switches re-create),
reductions/epilogues fused, ops-per-chain histogram.
"""
from __future__ import annotations

import math as _math
import threading
import time as _time
import weakref
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import autograd as _ag
from . import memory as _memory
from .flags import _registry as _flag_registry
from ..observability import flight as _flight
from ..observability import metrics as _om

__all__ = ["stats", "reset_stats", "clear_cache", "register_impl",
           "register_param_impl", "enabled", "materialize_tensor",
           "boundary_reason", "infer_output_aval", "capture_handoff"]

_INT32_MIN, _INT32_MAX = -(2 ** 31), 2 ** 31

# python scalar -> weak-typed device array, interned so a recurring
# literal (the `0.25` in a loop's `add(t, 0.25)`) is the SAME jax.Array
# every dispatch: the fused executable then takes only committed arrays
# (pjit's C++ fast path; a raw python scalar argument re-uploads a fresh
# scalar buffer per call) and identity-dedup collapses repeats to one
# program slot. jnp.asarray keeps python scalars weak-typed, so
# promotion semantics match the eager `jnp.add(x, 0.25)` exactly.
_scalar_cache: Dict[tuple, Any] = {}

# Live handles of pending (unflushed) chains. Buffer DONATION sites
# (the fused optimizer step, the AMP batched unscale) must flush these
# first: a pending chain captured its input buffers at dispatch time,
# and donating one to XLA deletes it under the chain's feet. Keyed by
# id() — a WeakSet would route bucket collisions through Tensor's
# elementwise __eq__ and die on bool(array).
_pending_tensors = weakref.WeakValueDictionary()

# -- telemetry: the registry IS the storage; fusion.stats() below is a
# view reconstructing the legacy dict shape from these instruments
_M_flag = _om.flag_info()
_M = _om.scope("fusion")
_M_deferred = _M.counter("ops_deferred_total",
                         "Fusable dispatches deferred into expression DAGs")
_M_chains = _M.counter("chains_flushed_total", "Fused programs executed")
_M_ops_fused = _M.counter("ops_fused_total",
                          "Ops executed through fused programs")
_M_hits = _M.counter("cache_hits_total",
                     "Flushes served by a cached executable")
_M_misses = _M.counter("cache_misses_total",
                       "Flushes that compiled a new program")
_M_uncompiled = _M.counter("uncompiled_runs_total",
                           "First-sighting flushes run un-jitted")
_M_fallbacks = _M.counter("jit_fallbacks_total",
                          "Flushes that fell back to un-jitted eval")
_M_flushes = _M.counter("flushes_total", "Chain flushes by reason")
_M_chain_len = _M.counter("chain_length", "Ops-per-chain distribution")
_M_reduce_fused = _M.counter(
    "reductions_fused_total",
    "Reduction terminator nodes flushed WITH their producer chain "
    "(the input edge was an interior node of the same fused program)")
_M_epi_fused = _M.counter(
    "epilogues_fused_total",
    "Contraction (matmul/linear) nodes flushed with at least one "
    "consumer in the same fused program — the epilogue actually fused")
_M_compile_s = _M.histogram(
    "compile_seconds", "First execution (trace+compile) of a freshly "
    "built fused program, labeled by program kind "
    "(elementwise/reduce/epilogue)")
_M_flush_sites = _M.counter(
    "flush_sites_total",
    "Chain flushes by (reason, origin call site) — the Fusion III "
    "planning input: which code locations break whole-step capture, "
    "not just why. Populated when FLAGS_fusion_flush_origin=1 (stack "
    "attribution costs ~µs/flush) or during an analysis audit")
_om.default_registry().gauge(
    "fusion.cache_size",
    "Live fused-program cache entries").set_function(
        lambda: len(_cache))


def _intern_scalar(v):
    key = (type(v), v)
    if v == 0 and isinstance(v, float):
        # 0.0 == -0.0 hash-collide but differ for sign-sensitive ops
        # (copysign/atan2/1/x): key the sign in explicitly
        key = (type(v), v, _math.copysign(1.0, v))
    hit = _scalar_cache.get(key)  # lock-free hit: dict get is atomic
    if hit is None:
        # miss path under the fusion lock: an unguarded check-then-clear
        # could drop a scalar another thread JUST interned (and whose
        # identity a pending chain already captured), and two concurrent
        # misses on one value would intern two distinct arrays — either
        # breaks the committed-array identity dedup. Evict oldest
        # entries instead of clearing so live recent literals survive.
        with _cache_lock:
            hit = _scalar_cache.get(key)
            if hit is None:
                while len(_scalar_cache) > 4096:
                    _scalar_cache.pop(next(iter(_scalar_cache)))
                hit = _scalar_cache[key] = jnp.asarray(v)
    return hit

# op name -> canonical pure-JAX implementation. Registration (from
# ops/math.py, ops/extra_math.py) pins a STRONG reference, so the fn's
# identity is stable for the lifetime of the process: a dispatch fuses
# only when its fn IS the registered object, which makes the structural
# cache key (op names) a faithful key for the generated program.
_IMPLS: Dict[str, Any] = {}

# op name -> canonical PARAMETRIC implementation ``fn(*arrays, **attrs)``
# for reduction terminators and contraction/epilogue ops: the dispatch
# wrapper bakes its attrs (axis/keepdim/dtype, transpose flags) into a
# per-call closure for the eager path, so fn identity can't gate fusion
# here — instead the wrapper passes the SAME attrs explicitly
# (apply_op's fuse_attrs) and codegen rebuilds the node from this
# registry + the attrs folded into the structural signature. Contract
# (held by the in-tree call sites): fn(*arrays, **dict(attrs)) is
# semantically identical to the eager closure it rides along with.
_PIMPLS: Dict[str, Any] = {}

# name -> False | True ("elementwise") | "reduce" | "epilogue": ops.yaml
# `fusable` class gate (resolved lazily; ops.yaml loads after the op
# modules that register impls)
_YAML_OK: Dict[str, Any] = {}

_flag = _flag_registry["eager_fusion"]
_reduce_flag = _flag_registry["eager_fusion_reduce"]
_epilogue_flag = _flag_registry["eager_fusion_epilogue"]
_max_chain = _flag_registry["eager_fusion_max_chain"]
_cache_cap = _flag_registry["eager_fusion_cache"]
_nan_flag = _flag_registry["check_nan_inf"]
_origin_flag = _flag_registry["fusion_flush_origin"]

# Analysis-auditor hooks (paddle_tpu.analysis). _flush_observer, when
# set, receives (reason, nops, pkind, origin) after every chain flush;
# _program_observer receives (sig, event) with event in
# "hit"/"compile"/"first" from the program cache. Both are None outside
# an audit — the hot path pays one global read.
_flush_observer = None
_program_observer = None

# frames skipped when attributing a flush to its origin call site: the
# fusion/dispatch machinery itself can never be the planning-relevant
# location
_ORIGIN_SKIP = ("core/fusion.py", "core/tensor.py", "core/autograd.py",
                "analysis/auditor.py", "analysis/locks.py")


def _flush_origin() -> str:
    """``pkg/file.py:line`` of the nearest stack frame outside the
    fusion machinery — the call site whose host read / op boundary
    forced this flush."""
    import sys
    f = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename.replace("\\", "/")
        if not fn.endswith(_ORIGIN_SKIP):
            parts = fn.split("/")
            short = "/".join(parts[-2:]) if len(parts) > 1 else fn
            return f"{short}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>"

# cardinality cap for flush_sites_total's site label: a long-lived
# process under FLAGS_fusion_flush_origin must not grow one counter
# cell per distinct call site forever — the long tail collapses into
# "<other>" (audits are unaffected; they record raw events)
_MAX_FLUSH_SITES = 128
_seen_flush_sites: set = set()

_Tensor = None  # resolved on first dispatch (core.tensor imports us)

# hot-path type handles: jax.Array/jax.core.Tracer lookups go through
# module __getattr__ shims, and jax.Array isinstance is an ABC walk —
# cache the names once and the concrete ArrayImpl type for a one-check
# fast path (it covers every committed eager buffer). _ArrayImpl is
# resolved on FIRST DISPATCH, not at import: `type(jnp.zeros(()))` here
# would initialize the JAX backend when `import paddle_tpu` runs —
# grabbing the exclusive TPU from every subprocess and pinning the
# platform before user code can override it.
_Tracer = jax.core.Tracer
_JaxArray = jax.Array
_ArrayImpl = None


def register_impl(name: str, fn) -> None:
    """Declare ``fn`` the canonical implementation of op ``name`` for
    fusion codegen. First registration wins (e.g. math.tanh vs the
    nn.functional wrapper): later dispatches of a DIFFERENT fn object
    under the same name simply fall back to the eager path."""
    _IMPLS.setdefault(name, fn)


def register_param_impl(name: str, fn) -> None:
    """Declare ``fn(*arrays, **attrs)`` the canonical parametric
    implementation of reduction/contraction op ``name`` (see _PIMPLS).
    First registration wins."""
    _PIMPLS.setdefault(name, fn)


def enabled() -> bool:
    # check_nan_inf wants per-op NaN attribution — a debug mode where
    # chain-level deferral would blur the blame; turn fusion off with it
    return bool(_flag.value) and not _nan_flag.value


def _yaml_class(name: str):
    """ops.yaml fusable class for ``name``: False, True (elementwise),
    "reduce", or "epilogue" (contraction)."""
    ok = _YAML_OK.get(name)
    if ok is None:
        try:
            from ..ops.op_registry import OP_TABLE
            info = OP_TABLE.get(name)
            ok = False
            if info and info.get("has_vjp", True):
                f = info.get("fusable")
                if f in (True, "reduce", "epilogue"):
                    ok = f
        except Exception:
            ok = False
        _YAML_OK[name] = ok
    return ok


# op name -> flush-reason label for apply_op's non-fusable-consumer
# branch: a pending chain flushed by a reduction/contraction consumer
# that DIDN'T defer (granular flag off, impl unregistered, odd call
# shape) is labeled reduce_boundary/matmul_boundary so stats() shows
# exactly the flushes the fusion flags would have avoided.
_BOUNDARY_REASON: Dict[str, str] = {}


def boundary_reason(name: str) -> str:
    r = _BOUNDARY_REASON.get(name)
    if r is None:
        cls = _yaml_class(name)
        r = ("reduce_boundary" if cls == "reduce" else
             "matmul_boundary" if cls == "epilogue" else "op_boundary")
        _BOUNDARY_REASON[name] = r
    return r


# ---------------------------------------------------------------------------
# expression DAG
# ---------------------------------------------------------------------------

class LazyExpr:
    """One deferred fusable op.

    ``args`` entries are LazyExpr (unmaterialized producer), Tensor
    (concrete leaf, strong ref — the GradNode-input analog), raw array,
    or a python scalar. ``adiff[i]`` captures, at dispatch time, whether
    gradient flows through edge i (grad mode on AND the input was
    differentiable then) — the fused program inserts
    ``lax.stop_gradient`` on every adiff=False edge, reproducing the
    per-op tape's stop_gradient semantics edge-exactly.
    """

    __slots__ = ("op", "args", "bufs", "adiff", "shape", "dtype", "weak",
                 "rg", "nops", "val", "anchor", "tref", "attrs", "kind")

    def __init__(self, op, args, bufs, adiff, shape, dtype, weak, nops,
                 attrs=None, kind="e"):
        self.op = op
        self.args = args
        # per-arg buffer captured AT DISPATCH for Tensor leaves (None for
        # expr children / raw arrays): jax arrays are immutable, so an
        # in-place mutation of the leaf later (set_value/zero_/[...]=)
        # only REBINDS t._buf — the flush must compute from the
        # dispatch-time value, exactly as the eager op would have
        self.bufs = bufs
        self.adiff = adiff
        self.shape = shape
        self.dtype = dtype
        self.weak = weak
        self.rg = any(adiff)
        self.nops = nops
        # parametric node state: attrs is the hashable (key, value) tuple
        # folded into the structural cache key (axis/keepdim/dtype for
        # reductions, transpose flags for contractions); None marks a
        # plain elementwise node. kind: "e" elementwise / "r" reduction
        # terminator / "c" contraction (epilogue host).
        self.attrs = attrs
        self.kind = kind
        self.val = None      # set at flush for live outputs
        self.anchor = None   # strong Tensor ref after flush (grad chaining)
        self.tref = None     # weakref to the owning Tensor


# (op, input descriptors) -> (shape, dtype, weak_type); jax.eval_shape
# costs ~100µs, a dict hit ~100ns — steady-state chains never re-infer
_aval_cache: Dict[tuple, tuple] = {}


def _infer_aval(name, fn, descs, entries, attrs=None):
    key = ((name, attrs) if attrs is not None else (name,)) + descs
    hit = _aval_cache.get(key)
    if hit is not None:
        return hit
    if len(_aval_cache) > 8192:  # bound it like the other fusion caches
        # a lock would guard nothing: get/insert run lock-free and a
        # racing insert lost to the eviction just re-infers
        _aval_cache.clear()  # lint-allow: PTL003 GIL-atomic memo eviction
    if attrs is not None:
        # infer through the registered parametric impl + attrs — exactly
        # what codegen will run — not through the per-call eager closure
        fn = _param_fn(name, attrs)
    try:
        eval_args = []
        for d, e in zip(descs, entries):
            if d[0] == "a":
                try:
                    s = jax.ShapeDtypeStruct(d[1], d[2], weak_type=d[3])
                except TypeError:  # older jax: no weak_type kwarg
                    s = jax.ShapeDtypeStruct(d[1], d[2])
                eval_args.append(s)
            else:
                eval_args.append(e)  # python scalar, passed verbatim
        out = jax.eval_shape(fn, *eval_args)
        if isinstance(out, (tuple, list)):
            return None  # fusable ops are single-output by contract
        aval = (tuple(out.shape), np.dtype(out.dtype),
                bool(getattr(out, "weak_type", False)))
    except Exception:
        return None
    _aval_cache[key] = aval
    return aval


def infer_output_aval(name, avals, attrs=None):
    """Live-impl ground truth for the analysis plane's shape/dtype
    abstract interpreter (analysis/shapes.py): the output
    ``(shape, dtype, weak_type)`` of fusable op ``name`` applied to
    abstract inputs ``avals`` (an iterable of ``(shape, dtype)`` or
    ``(shape, dtype, weak)`` tuples), computed by ``jax.eval_shape`` of
    the REGISTERED fusion impl through the same ``_aval_cache`` memo the
    flush path uses — so spec validation grades against exactly what
    codegen will run. ``attrs`` is the hashable attr tuple for
    parametric ops (reductions/contractions/cast). Returns None when no
    impl is registered or the impl rejects the avals."""
    if attrs is None:
        if _IMPLS.get(name) is None:
            return None
    elif name not in _PIMPLS:
        return None
    descs = tuple(
        ("a", tuple(a[0]), np.dtype(a[1]),
         bool(a[2]) if len(a) > 2 else False)
        for a in avals)
    # entries are only consulted for non-"a" descs (python scalars) —
    # every abstract input is an array desc here
    return _infer_aval(name, _IMPLS.get(name), descs,
                       (None,) * len(descs), attrs)


def _param_fn(op, attrs):
    """Evaluation callable for a parametric node: the registered impl
    with the node's attrs baked in (identity for attr-less nodes, e.g.
    bias-less linear or squared_l2_norm)."""
    base = _PIMPLS[op]
    if not attrs:
        return base
    kw = dict(attrs)

    def call(*vals):
        return base(*vals, **kw)

    return call


def _new_lazy_tensor(expr: LazyExpr):
    t = _Tensor.__new__(_Tensor)
    t._buf = None
    t._lazy = expr
    t.stop_gradient = not expr.rg
    t.grad = None
    t._node = None
    t._out_index = 0
    t._retain_grads = False
    t._hooks = {}
    t._hook_counter = 0
    t.name = ""
    t.trainable = False
    t._dist_attr = None
    expr.tref = weakref.ref(t)
    _pending_tensors[id(t)] = t
    return t


def try_fuse(name: str, fn, args, kwargs, attrs=None):
    """Defer one fusable dispatch; returns the handle Tensor, or None to
    take the normal eager path. Hot path: isinstance dispatch is ordered
    Tensor -> exact scalar types -> arrays, and input descriptors are
    built inline so nothing is touched twice.

    ``attrs`` is None for plain elementwise ops (fn identity gates the
    fuse) and a hashable (key, value) tuple for parametric dispatches
    (reductions / contractions) — then the op's ops.yaml class plus its
    registered parametric impl gate instead, and kwargs (which the eager
    ``fn`` may still need, e.g. matmul's transpose flags) are trusted to
    be exactly re-expressed by ``attrs`` (the in-tree wrapper contract,
    see _PIMPLS)."""
    global _Tensor, _ArrayImpl
    if attrs is None:
        if kwargs or _IMPLS.get(name) is not fn or \
                _yaml_class(name) is not True:
            return None
        kind = "e"
    else:
        cls = _yaml_class(name)
        if cls == "reduce":
            if not _reduce_flag.value:
                return None
            kind = "r"
        elif cls == "epilogue":
            if not _epilogue_flag.value:
                return None
            kind = "c"
        elif cls is True:
            # parametric elementwise (gelu's approximate, cast's dtype):
            # attrs ride the structural key like any other node attrs
            kind = "e"
        else:
            return None
        if name not in _PIMPLS:
            return None
        try:
            hash(attrs)  # attrs enter the structural cache key
        except TypeError:
            return None
    if _Tensor is None:
        from .tensor import Tensor as _T
        _Tensor = _T
        _ArrayImpl = type(jnp.zeros(()))
    grad_on = _ag._state.enabled
    entries: List[Any] = []
    bufs: List[Any] = []
    adiff: List[bool] = []
    descs: List[tuple] = []
    nops = 1
    for a in args:
        if isinstance(a, _Tensor):
            lz = a._lazy
            if lz is not None and lz.val is None:
                d = grad_on and not a.stop_gradient \
                    and _ag._is_diff_dtype(lz)
                if not (d and not lz.rg):
                    entries.append(lz)
                    bufs.append(None)
                    adiff.append(d)
                    descs.append(("a", lz.shape, lz.dtype, lz.weak))
                    nops += lz.nops
                    continue
                # stop_gradient was flipped to False on a chain built
                # under no_grad: eager semantics make this tensor a grad
                # LEAF (grads accumulate here, not through its history) —
                # flush it so it enters the new chain as a concrete leaf
                materialize_tensor(a, "grad_leaf")
            buf = a._buf
            if type(buf) is _ArrayImpl:
                weak = buf.weak_type
            elif isinstance(buf, np.ndarray):
                weak = False
            elif isinstance(buf, _JaxArray) and \
                    not isinstance(buf, _Tracer):
                weak = bool(getattr(buf, "weak_type", False))
            else:
                return None
            entries.append(a)
            bufs.append(buf)  # dispatch-time snapshot (mutation safety)
            adiff.append(grad_on and not a.stop_gradient
                         and _ag._is_diff_dtype(buf))
            descs.append(("a", buf.shape, buf.dtype, weak))
        else:
            ta = type(a)
            if ta is float or ta is int or ta is bool:
                # huge python ints overflow the weak-int32 coercion;
                # bail to the eager path rather than fail at trace time
                if ta is int and not (_INT32_MIN <= a < _INT32_MAX):
                    return None
                s = _intern_scalar(a)
                entries.append(s)
                bufs.append(None)
                adiff.append(False)
                descs.append(("a", (), s.dtype, True))
            elif isinstance(a, (_JaxArray, np.ndarray)):
                if isinstance(a, _Tracer):
                    return None
                entries.append(a)
                bufs.append(None)
                adiff.append(False)
                descs.append(("a", tuple(a.shape), a.dtype,
                              bool(getattr(a, "weak_type", False))))
            elif isinstance(a, (bool, int, float)):  # np scalar subclasses
                s = _intern_scalar(a)
                entries.append(s)
                bufs.append(None)
                adiff.append(False)
                descs.append(("a", (), s.dtype, bool(s.weak_type)))
            else:
                return None
    aval = _infer_aval(name, fn, tuple(descs), entries, attrs)
    if aval is None:
        return None
    expr = LazyExpr(name, tuple(entries), tuple(bufs), tuple(adiff),
                    aval[0], aval[1], aval[2], nops, attrs, kind)
    t = _new_lazy_tensor(expr)
    if _M_flag.value:
        _M_deferred._v += 1  # inline fast cell: per-deferral hot path
    if nops >= max(int(_max_chain.value or 32), 2):
        _flush(expr, "cap")
    return t


# ---------------------------------------------------------------------------
# program cache + codegen
# ---------------------------------------------------------------------------

_cache: "OrderedDict[tuple, tuple]" = OrderedDict()
_cache_lock = threading.Lock()


def _build_pure(sig):
    """Decode a structural signature into the pure fused function. It is
    rebuilt from the signature alone — the impl registries map op names
    (+ node attrs for reduction/contraction nodes) back to their
    canonical jnp callables — so one program serves every flush with the
    same structure."""
    nodes, leaf_descs, out_idx, diff_idx = sig
    impls = tuple(_IMPLS[op] if attrs is None else _param_fn(op, attrs)
                  for op, _, attrs in nodes)

    def fused(*leaf_vals):
        env: List[Any] = []
        for (op, children, _attrs), impl in zip(nodes, impls):
            vals = []
            for kind, j, ad in children:
                v = env[j] if kind == "n" else leaf_vals[j]
                if not ad:
                    v = jax.lax.stop_gradient(v)
                vals.append(v)
            env.append(impl(*vals))
        return tuple(env[i] for i in out_idx)

    return fused


def _build_program(sig):
    """(pure fn, jitted fwd, jitted vjp) for a chain structure."""
    from ..jit.warmup import ensure_executable_cache
    ensure_executable_cache()  # fusion programs persist across boots too
    diff_idx = sig[3]
    fused = _build_pure(sig)
    jfwd = jax.jit(fused)

    def bwd(leaf_vals, cts):
        prims = [leaf_vals[i] for i in diff_idx]

        def g(*ps):
            call = list(leaf_vals)
            for i, p in zip(diff_idx, ps):
                call[i] = p
            return fused(*call)

        return jax.vjp(g, *prims)[1](cts)

    jbwd = jax.jit(bwd)
    return fused, jfwd, jbwd


_SEEN = object()  # first-sighting marker: structure noted, not compiled


def _trace_compile_span(pkind: str, dt: float) -> None:
    """Land the first-call (trace+compile) window in the host tracer as
    a ``fusion_compile[kind]`` span when the native tracer is live, so
    ``export_chrome_tracing`` step traces attribute the first-call spike
    to fusion compilation instead of an anonymous gap. Lazy module
    lookup only — never triggers the native build."""
    import sys
    mod = sys.modules.get("paddle_tpu._native")
    lib = getattr(mod, "lib", None)
    if lib is None:
        return
    try:
        if lib.tracer_enabled():
            now = lib.tracer_now()
            lib.tracer_record(f"fusion_compile[{pkind}]",
                              now - dt * 1e6, now)
    except Exception:
        pass


def _timed_first_call(jf, pkind):
    """Wrap a freshly built jitted forward so its FIRST execution (the
    one that traces+compiles) lands in fusion.compile_seconds — labeled
    by program kind (elementwise/reduce/epilogue) — and, when the host
    tracer is recording, as a chrome-trace span; later calls pay one
    flag check."""
    done = [False]

    def wrapper(*a):
        if done[0]:
            return jf(*a)
        t0 = _time.perf_counter()
        out = jf(*a)
        done[0] = True
        dt = _time.perf_counter() - t0
        _M_compile_s.observe(dt, kind=pkind)
        _trace_compile_span(pkind, dt)
        return out

    return wrapper


def _get_program(sig, pkind):
    """Compile policy mirrors autograd's pair cache: a chain structure
    only compiles on its SECOND sighting. One-off chains (test suites,
    cold paths) run un-jitted — op-by-op jnp cost, no XLA compile — and
    steady-state loops compile once on iteration two and hit the cache
    thereafter. Returns (pure fn, jfwd|None, jbwd|None)."""
    with _cache_lock:
        entry = _cache.get(sig)
        if entry is not None and entry is not _SEEN:
            _cache.move_to_end(sig)
            _M_hits.inc()
            if _program_observer is not None:
                _program_observer(sig, "hit")
            return entry
    if entry is _SEEN:
        _M_misses.inc()
        _flight.record("fusion", "compile", kind=pkind)
        if _program_observer is not None:
            _program_observer(sig, "compile")
        built = _build_program(sig)
        built = (built[0], _timed_first_call(built[1], pkind), built[2])
        with _cache_lock:
            _cache[sig] = built
            cap = max(int(_cache_cap.value or 256), 8)
            while len(_cache) > cap:
                _cache.popitem(last=False)
        return built
    _M_uncompiled.inc()
    if _program_observer is not None:
        _program_observer(sig, "first")
    with _cache_lock:
        _cache[sig] = _SEEN
        cap = max(int(_cache_cap.value or 256), 8)
        while len(_cache) > cap:
            _cache.popitem(last=False)
    return _build_pure(sig), None, None


# ---------------------------------------------------------------------------
# flush
# ---------------------------------------------------------------------------

def has_pending() -> bool:
    """Any live unflushed chains? Cheap gate for donation sites."""
    return len(_pending_tensors) > 0


def flush_pending(reason: str = "donation") -> int:
    """Flush EVERY pending chain. Called by buffer-donation sites
    (fused optimizer step, AMP batched unscale) so no deferred program
    can later read a buffer XLA just invalidated. Returns the number
    of chains flushed."""
    n = 0
    for t in list(_pending_tensors.values()):
        _pending_tensors.pop(id(t), None)
        if t._lazy is not None:
            materialize_tensor(t, reason)
            n += 1
    return n


def capture_handoff() -> int:
    """Whole-step capture boundary (jit/sot.py): flush every pending
    eager chain with reason ``sot_capture`` before a captured
    executable donates its inputs — a deferred chain may have snapshot
    buffers the donation is about to invalidate. These flushes are the
    segment handoff INTO the captured program, so the capture planner
    classifies the ``sot_capture`` reason capture-compatible (it is the
    capture boundary, not a break). Returns the number of chains
    flushed; a steady-state captured step flushes zero."""
    if not _pending_tensors:
        return 0
    return flush_pending("sot_capture")


def materialize_tensor(t, reason: str = "host_read") -> None:
    """Flush the chain the lazy tensor ``t`` heads (no-op if concrete)."""
    lz = t._lazy
    if lz is None:
        return
    if lz.val is not None:  # flushed via a shared DAG; just bind
        t._lazy = None
        if t._buf is None:
            t._buf = lz.val
        return
    _flush(lz, reason)


def _flush(root: LazyExpr, reason: str) -> None:
    # -- collect the reachable unmaterialized DAG (postorder) ------------
    order: List[LazyExpr] = []
    node_index: Dict[int, int] = {}
    leaf_vals: List[Any] = []
    leaf_tensors: List[Optional[Any]] = []
    leaf_descs: List[tuple] = []
    leaf_index: Dict[int, int] = {}
    sig_nodes: List[tuple] = []

    def leaf_slot(a, buf):
        # scalars were interned to arrays at dispatch, so every leaf is
        # LazyExpr (materialized earlier) / Tensor / raw array
        if type(a) is LazyExpr:
            key, val, tens = id(a), a.val, a.anchor
        elif buf is not None:
            # Tensor leaf: use the dispatch snapshot. Key by BOTH the
            # buffer and the tensor: same tensor mutated between
            # dispatches -> distinct slots (different bufs), while two
            # tensors SHARING one buffer (x and x.detach()) also stay
            # distinct — merging them would let the first-seen tensor's
            # grad identity swallow the other's cotangent
            key, val, tens = (id(buf), id(a)), buf, a
        else:
            key, val, tens = id(a), a, None
        idx = leaf_index.get(key)
        if idx is None:
            idx = leaf_index[key] = len(leaf_vals)
            leaf_vals.append(val)
            leaf_tensors.append(tens)
            leaf_descs.append(("a", val.shape, val.dtype,
                               bool(getattr(val, "weak_type", False))))
        return idx

    seen = set()
    stack: List[Tuple[LazyExpr, int]] = [(root, 0)]
    while stack:
        e, phase = stack.pop()
        if phase == 0:
            if id(e) in seen:
                continue
            seen.add(id(e))
            stack.append((e, 1))
            for a in e.args:
                if isinstance(a, LazyExpr) and a.val is None and \
                        id(a) not in seen:
                    stack.append((a, 0))
        else:
            children = []
            for a, buf, ad in zip(e.args, e.bufs, e.adiff):
                if isinstance(a, LazyExpr) and a.val is None:
                    children.append(("n", node_index[id(a)], ad))
                else:
                    children.append(("l", leaf_slot(a, buf), ad))
            node_index[id(e)] = len(order)
            order.append(e)
            sig_nodes.append((e.op, tuple(children), e.attrs))

    # -- outputs: every node whose Tensor handle is still alive ----------
    out_idx = []
    out_tensors = []
    for i, e in enumerate(order):
        t = e.tref() if e.tref is not None else None
        # the handle must still OWN this expr: a direct `t._data = ...`
        # rebind discarded the chain for t, and binding here would
        # silently revert the user's buffer to the stale fused value.
        # (The expr itself stays valid for OTHER pending consumers,
        # which by eager semantics see the dispatch-time value.)
        if t is not None and t._lazy is e:
            out_idx.append(i)
            out_tensors.append(t)

    # Live requires-grad INTERIOR tensors must sit on real tape edges —
    # eager users inspect them later (paddle.grad(loss, [y]), post-hoc
    # retain_grads()/register_hook()), and a single fused GradNode only
    # exposes the chain's leaves. Cut the chain there: flush each such
    # producer first (its own GradNode, producers-before-consumers via
    # the postorder), then re-walk — the cut points re-enter as concrete
    # anchored leaves. Hot loops never hit this: their intermediates are
    # dead by flush time.
    root_i = node_index[id(root)]
    cuts = [order[i] for i in out_idx if i != root_i and order[i].rg]
    if cuts:
        for e in cuts:
            if e.val is None:
                _flush(e, reason)
        _flush(root, reason)
        return

    if not out_idx:  # root's handle died mid-flush; nothing observes it
        out_idx = [root_i]
        out_tensors = [None]

    diff_set = set()
    for op, children, _attrs in sig_nodes:
        for kind, j, ad in children:
            if kind == "l" and ad:
                diff_set.add(j)
    diff_idx = tuple(sorted(diff_set))

    # program kind for compile-seconds attribution: a contraction makes
    # it an epilogue program, else a terminator makes it a reduce one
    pkind = "elementwise"
    for e in order:
        if e.kind == "c":
            pkind = "epilogue"
            break
        if e.kind == "r":
            pkind = "reduce"

    sig = (tuple(sig_nodes), tuple(leaf_descs), tuple(out_idx), diff_idx)
    fused, jfwd, jbwd = _get_program(sig, pkind)

    if jfwd is None:  # first sighting of this structure: run un-jitted
        outs = fused(*leaf_vals)
    else:
        try:
            outs = jfwd(*leaf_vals)
        except FloatingPointError:
            raise
        except Exception:
            # jit-specific failure (e.g. resource pressure during the
            # compile): the un-jitted trace has identical semantics
            _M_fallbacks.inc()
            outs = fused(*leaf_vals)

    # -- grad wiring: ONE GradNode over the fused program ----------------
    node = None
    if diff_idx and any(order[i].rg for i in out_idx):
        diff_tensors = tuple(leaf_tensors[i] for i in diff_idx)
        out_avals = tuple(_ag._Aval(o.shape, o.dtype) for o in outs)
        datas = list(leaf_vals)

        def vjp_fn(cts, _lv=tuple(leaf_vals), _jb=jbwd):
            if _jb is not None:
                try:
                    return _jb(_lv, cts)
                except FloatingPointError:
                    raise
                except Exception:
                    pass  # exotic cotangent (float0/sparse): retrace
            # un-compiled first sighting, or jitted-vjp bail: one plain
            # jax.vjp retrace with identical semantics
            prims = [_lv[i] for i in diff_idx]

            def g(*ps):
                call = list(_lv)
                for i, p in zip(diff_idx, ps):
                    call[i] = p
                return fused(*call)

            return jax.vjp(g, *prims)[1](cts)

        node = _ag.GradNode(vjp_fn, diff_tensors, out_avals, "fused_chain",
                            fn=fused, datas=datas, kwargs={},
                            diff_idx=list(diff_idx))

    _ag._maybe_check_nan_inf("fused_chain", outs)

    # -- bind results back into the live handles -------------------------
    for k, (i, t) in enumerate(zip(out_idx, out_tensors)):
        if t is None:
            continue  # dead handle: value unobservable, keep expr interior
        e = order[i]
        o = outs[k]
        _memory.track(o)
        e.val = o
        e.anchor = t  # strong: later chains grad-link through this Tensor
        t._buf = o
        t._lazy = None
        if node is not None and e.rg:
            t._node = node
            t._out_index = k

    _M_chains.inc()
    _M_ops_fused.inc(len(order))
    _M_flushes.inc(reason=reason)
    _M_chain_len.inc(**{"len": len(order)})
    _flight.record("fusion", "flush", reason=reason, nops=len(order))
    obs = _flush_observer
    if obs is not None or _origin_flag.value:
        # stack-origin attribution: WHERE capture broke, not just why —
        # the fusion-III planning input. Off the hot path unless the
        # flag or an origin-consuming observer asks for it (the lock
        # checker's chained observer sets needs_origin=False, so pure
        # lock instrumentation skips the walk).
        want = _origin_flag.value or (
            obs is not None and getattr(obs, "needs_origin", True))
        origin = _flush_origin() if want else "<unattributed>"
        if _origin_flag.value:
            site = origin
            if site not in _seen_flush_sites:
                if len(_seen_flush_sites) >= _MAX_FLUSH_SITES:
                    site = "<other>"
                else:
                    _seen_flush_sites.add(site)
            _M_flush_sites.inc(reason=reason, site=site)
        if obs is not None:
            obs(reason, len(order), pkind, origin)
    if pkind != "elementwise":
        # a reduction "fused" when its input chain flushed WITH it (the
        # input edge is an interior node); a contraction's epilogue fused
        # when some node in this program consumes the dot's output
        consumed = set()
        for _op, children, _attrs in sig_nodes:
            for k, j, _ad in children:
                if k == "n":
                    consumed.add(j)
        for i, e in enumerate(order):
            if e.kind == "r":
                if any(k == "n" for k, _j, _ad in sig_nodes[i][1]):
                    _M_reduce_fused.inc()
            elif e.kind == "c" and i in consumed:
                _M_epi_fused.inc()


# ---------------------------------------------------------------------------
# observability
# ---------------------------------------------------------------------------

def stats() -> Dict[str, Any]:
    """Counter snapshot: chains built, cache hits/misses, flush reasons,
    ops-per-chain histogram, live cache size.

    Since the telemetry unification this is a VIEW over the process
    registry (``observability.snapshot()['fusion']`` carries the same
    counters); with ``FLAGS_metrics=0`` the counters freeze."""
    chains = _M_chains.value()
    ops_fused = _M_ops_fused.value()
    snap = {
        "ops_deferred": _M_deferred.value(),
        "chains_flushed": chains,
        "ops_fused": ops_fused,
        "cache_hits": _M_hits.value(),
        "cache_misses": _M_misses.value(),
        "uncompiled_runs": _M_uncompiled.value(),
        "jit_fallbacks": _M_fallbacks.value(),
        "reductions_fused": _M_reduce_fused.value(),
        "epilogues_fused": _M_epi_fused.value(),
        # labeled registry cells back to the legacy dict shapes (label
        # values keep their Python type, so chain lengths come back int)
        "flush_reasons": {k[0][1]: v
                          for k, v in _M_flushes.series().items() if k},
        "chain_length_hist": {k[0][1]: v
                              for k, v in _M_chain_len.series().items()
                              if k},
        "cache_size": len(_cache),
        "avg_ops_per_chain": ops_fused / chains if chains else 0.0,
    }
    return snap


def reset_stats() -> None:
    for m in (_M_deferred, _M_chains, _M_ops_fused, _M_hits, _M_misses,
              _M_uncompiled, _M_fallbacks, _M_flushes, _M_chain_len,
              _M_reduce_fused, _M_epi_fused):
        m.reset()


def clear_cache() -> None:
    with _cache_lock:
        _cache.clear()
        _scalar_cache.clear()
        _aval_cache.clear()
