"""Live device-memory accounting.

TPU-native analog of the reference's allocator stat counters
(ref: paddle/phi/core/memory/stats.h, exposed as
paddle.device.cuda.max_memory_allocated —
ref: python/paddle/device/cuda/__init__.py:233).

On GPU the reference hooks its own allocator, so current/peak are exact
at allocation granularity. Here PJRT owns device memory, so the design
layers three sources:

1. ``device.memory_stats()`` from PJRT — exact allocator counters when
   the platform reports them (real TPU backends do; the axon tunnel and
   the CPU backend return ``None``).
2. An op-boundary tracker (this module): every eager ``apply_op`` output
   and ``to_tensor`` registers its ``jax.Array`` buffer here; a
   ``weakref.finalize`` decrements on buffer death. Current/peak live in
   the native MemStats counters (``_native/native.cpp`` MemStats) when
   the native runtime is built, with a pure-Python fallback.
3. ``jax.live_arrays()`` — an exact on-demand scan used to reconcile the
   tracker (catches arrays created outside the op funnel, e.g. raw jnp
   calls in user code).

jit-internal temporaries never appear in (2)/(3) — they are XLA's, and
are reported per-executable by :func:`program_memory_analysis` over
``Compiled.memory_analysis()`` (bench emits them as peak_hbm_bytes).
"""
from __future__ import annotations

import threading
import weakref
from typing import Dict, Optional

import jax
from jax.sharding import SingleDeviceSharding

from .._native import lib as _native
from ..observability import metrics as _om

_ALLOC = "allocated"

# id(buffer) set currently tracked: dedups multiple Tensor wrappers over
# one jax.Array (detach/alias) — a buffer is counted once.
_tracked: set = set()
_lock = threading.Lock()

# pure-Python fallback counters {key: [current, peak]} when the native
# runtime is unavailable
_py_stats: Dict[str, list] = {}


_key_cache: Dict = {}


def _key(device) -> str:
    k = _key_cache.get(device)
    if k is None:
        k = _key_cache[device] = f"{_ALLOC}.{device.platform}:{device.id}"
    return k


def _update(key: str, delta: int) -> None:
    if _native is not None:
        _native.stat_update(key, int(delta))
        return
    with _lock:
        e = _py_stats.setdefault(key, [0, 0])
        e[0] += delta
        if e[0] > e[1]:
            e[1] = e[0]


def _get(key: str):
    if _native is not None:
        return _native.stat_get(key)
    with _lock:
        e = _py_stats.get(key, [0, 0])
        return e[0], e[1]


def _reset_peak(key: str) -> None:
    if _native is not None:
        _native.stat_reset_peak(key)
        return
    with _lock:
        e = _py_stats.get(key)
        if e is not None:
            e[1] = e[0]


def _set_current(key: str, cur: int) -> None:
    if _native is not None:
        _native.stat_set_current(key, int(cur))
        return
    with _lock:
        e = _py_stats.setdefault(key, [0, 0])
        e[0] = cur
        if e[0] > e[1]:
            e[1] = e[0]


def _per_device_bytes(arr) -> Dict[str, int]:
    """{stat key: bytes} for one array, from sharding math only — never
    materializes per-shard wrapper arrays (``addressable_shards[i].data``
    creates cached ArrayImpls that a live-array scan would then double
    count)."""
    sh = arr.sharding
    shard_elems = 1
    for d in sh.shard_shape(arr.shape):
        shard_elems *= d
    nbytes = shard_elems * arr.dtype.itemsize
    agg: Dict[str, int] = {}
    for dev in sh.addressable_devices:
        k = _key(dev)
        agg[k] = agg.get(k, 0) + nbytes
    return agg


def _on_free(buf_id: int, per_device) -> None:
    with _lock:
        _tracked.discard(buf_id)
    for key, nbytes in per_device:
        try:
            _update(key, -nbytes)
        except Exception:
            pass  # interpreter shutdown


def track(arr) -> None:
    """Register a device buffer with the allocation counters.

    Called from the eager op funnel (core.autograd.apply_op) and
    to_tensor on every concrete ``jax.Array`` output. Tracers and
    already-seen buffers are skipped. Cost is ~1µs (one finalizer);
    this sits inside the measured eager dispatch budget.
    """
    if isinstance(arr, jax.core.Tracer) or not isinstance(arr, jax.Array):
        return
    buf_id = id(arr)
    with _lock:
        if buf_id in _tracked:
            return
        _tracked.add(buf_id)
    try:
        if type(arr.sharding) is SingleDeviceSharding:
            # single-device fast path (the eager hot loop): no
            # shard-shape math, one cached key lookup
            per_device = [(_key(arr.device), arr.nbytes)]
        else:
            per_device = list(_per_device_bytes(arr).items())
    except Exception:
        with _lock:
            _tracked.discard(buf_id)
        return
    for key, nbytes in per_device:
        _update(key, nbytes)
    weakref.finalize(arr, _on_free, buf_id, per_device)


def live_bytes(device=None) -> Dict[str, int]:
    """Exact per-device bytes of all live jax.Arrays (on-demand scan).

    Cached per-shard wrapper arrays (``ArrayImpl._arrays`` members) are
    aliases of their parent's buffers and are excluded; if the internal
    attribute is unavailable no wrappers were ever materialized by this
    module, so the unfiltered sum is already alias-free.
    """
    arrays = jax.live_arrays()
    shard_ids: set = set()
    for a in arrays:
        try:
            for b in (getattr(a, "_arrays", None) or []):
                if b is not a:
                    shard_ids.add(id(b))
        except Exception:
            break
    out: Dict[str, int] = {}
    for a in arrays:
        if id(a) in shard_ids:
            continue
        try:
            for k, nbytes in _per_device_bytes(a).items():
                out[k] = out.get(k, 0) + nbytes
        except Exception:
            continue
    if device is not None:
        k = _key(device)
        return {k: out.get(k, 0)}
    return out


def reconcile(device=None) -> None:
    """Snap tracker current to the exact live-array scan (keeps peak
    monotone: SetCurrent raises peak if the scan exceeds it)."""
    for key, nbytes in live_bytes(device).items():
        _set_current(key, nbytes)


# Per-device peak-reset emulation for PJRT-backed stats: PJRT exposes a
# process-lifetime peak_bytes_in_use with no reset. After a reset we
# report max(watermark of bytes_in_use observed at stats queries since
# the reset, pjrt_peak if it exceeded its value AT the reset — a new
# global maximum can only have happened after the reset).
# {key: [pjrt_peak_at_reset, observed_watermark_since]}
_pjrt_reset: Dict[str, list] = {}


def stats_for(device) -> Optional[Dict[str, int]]:
    """Per-device stat dict, or the PJRT dict when the platform has one."""
    pjrt = None
    try:
        pjrt = device.memory_stats()
    except Exception:
        pjrt = None
    if pjrt:
        key = _key(device)
        cur = int(pjrt.get("bytes_in_use", 0))
        peak = int(pjrt.get("peak_bytes_in_use", 0))
        rst = _pjrt_reset.get(key)
        if rst is not None:
            rst[1] = max(rst[1], cur)
            peak = peak if peak > rst[0] else rst[1]
        return {
            "allocated.current": cur,
            "allocated.peak": peak,
            "reserved.current": int(pjrt.get("bytes_reserved", cur)),
            "reserved.peak": int(pjrt.get("peak_bytes_reserved", peak)),
            "pjrt": dict(pjrt),
        }
    key = _key(device)
    # the live-array scan is ground truth for CURRENT (the op-funnel
    # tracker misses raw jnp arrays in both directions — creation AND
    # death); snap to it unconditionally. PEAK stays a high-water mark:
    # SetCurrent only ever raises it.
    exact = live_bytes(device)[key]
    _set_current(key, exact)
    cur, peak = _get(key)
    return {
        "allocated.current": int(cur),
        "allocated.peak": int(peak),
        "reserved.current": int(cur),
        "reserved.peak": int(peak),
        "pjrt": None,
    }


# snapshot-time registry view over the op-funnel tracker counters —
# nothing added to the per-buffer track() hot path
def _collect_memory():
    cur: Dict[str, int] = {}
    peak: Dict[str, int] = {}
    for key in list({k for k in _key_cache.values()}):
        c, p = _get(key)
        label = key[len(_ALLOC) + 1:]  # "cpu:0", "tpu:3", ...
        cur[label] = int(c)
        peak[label] = int(p)
    out = {}
    if cur:
        out["memory.tracked_bytes"] = cur
        out["memory.tracked_peak_bytes"] = peak
    return out


_om.register_collector("memory", _collect_memory)


def reset_peak(device) -> None:
    key = _key(device)
    _reset_peak(key)
    try:
        pjrt = device.memory_stats()
    except Exception:
        pjrt = None
    if pjrt:
        _pjrt_reset[key] = [int(pjrt.get("peak_bytes_in_use", 0)),
                            int(pjrt.get("bytes_in_use", 0))]
