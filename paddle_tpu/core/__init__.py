from . import autograd, device, dtype, flags, fusion, random  # noqa: F401
from .tensor import Parameter, Tensor, to_tensor  # noqa: F401
