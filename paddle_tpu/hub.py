"""paddle.hub equivalent (ref: python/paddle/hub.py): list/help/load
model entrypoints from a ``hubconf.py``. Local directories work fully;
github/gitee sources need network and fail loudly on this offline
build, naming the local alternative."""
from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]

_HUBCONF = "hubconf.py"


def _load_hubconf(repo_dir: str, source: str):
    if source != "local":
        raise RuntimeError(
            f"hub source {source!r} needs network access (github/gitee "
            f"clone); this build is offline — clone the repo yourself "
            f"and use source='local' with its path")
    path = os.path.join(repo_dir, _HUBCONF)
    if not os.path.isfile(path):
        raise FileNotFoundError(f"no {_HUBCONF} under {repo_dir}")
    spec = importlib.util.spec_from_file_location("paddle_tpu_hubconf",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    sys.path.insert(0, repo_dir)
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.path.remove(repo_dir)
    return mod


def list(repo_dir: str, source: str = "github",
         force_reload: bool = False):  # noqa: A001 - reference name
    """Entrypoint names exposed by the repo's hubconf (ref: hub.py
    list)."""
    mod = _load_hubconf(repo_dir, source)
    return [name for name, v in vars(mod).items()
            if callable(v) and not name.startswith("_")]


def help(repo_dir: str, model: str, source: str = "github",
         force_reload: bool = False):  # noqa: A001 - reference name
    """Docstring of one entrypoint (ref: hub.py help)."""
    mod = _load_hubconf(repo_dir, source)
    fn = getattr(mod, model, None)
    if fn is None or not callable(fn):
        raise RuntimeError(f"no entrypoint {model!r} in {repo_dir}")
    return fn.__doc__


def load(repo_dir: str, model: str, source: str = "github",
         force_reload: bool = False, **kwargs):
    """Build one entrypoint (ref: hub.py load)."""
    mod = _load_hubconf(repo_dir, source)
    fn = getattr(mod, model, None)
    if fn is None or not callable(fn):
        raise RuntimeError(f"no entrypoint {model!r} in {repo_dir}")
    return fn(**kwargs)
